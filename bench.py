"""Benchmark: InLoc-config dense-matching throughput on the flagship model.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The headline workload is the reference's InLoc dense-matching stage
(eval_inloc.py: long side 3200 px -> ~200x150 features, relocalization
maxpool k=2, NeighConsensus 3-3/16-1, both-direction match extraction),
costed the way the pipeline actually runs it: each query's backbone
features are computed once and matched against its 10 shortlisted panos
(eval_inloc.py:124-132 loops 10 panos per query), so one timed block is
1 query-feature pass + 10 pano steps and pairs/s = 10 / block_time.
The reference runs this at roughly 1 pair/s on a V100 (fp16); the
north-star target is >=4x that per chip (BASELINE.md). vs_baseline is
reported against the 1.0 pair/s V100 estimate.
"""

import json
import os
import sys
import time

V100_BASELINE_PAIRS_PER_S = 1.0

_T0 = time.time()


def note(msg):
    """Stage timestamps on stderr: a silent hang is then attributable to a
    specific stage (device dial, compile, execute) instead of opaque."""
    print(f"# [{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)
    # Mirror into the run log when one is active (NCNET_RUN_LOG): each
    # note is a progress marker, so the heartbeat's idle clock measures
    # time since the last *stage*, not since run start.
    try:
        from ncnet_tpu import obs

        obs.event("note", msg=msg)
    except Exception:
        pass


def main():
    import jax

    from ncnet_tpu import obs
    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    setup_compile_cache()

    # Closed-sweep guard (docs/NEXT.md "Consensus roofline verdict"):
    # the dense per-layer strategy-mix sweeps are CLOSED — every
    # explicit mix was measured HBM-infeasible at headline scale and the
    # verdict says don't re-run them. An explicit strategy pin on the
    # dense arm (exactly what a sweep driver materializes per line) now
    # needs NCNET_BENCH_CLOSED_SWEEPS=1, so the autotuner's new-arm
    # enumeration (cp/fft, ops/cp4d.py) can't silently resurrect the
    # dense sweep lines it still carries.
    _mix = os.environ.get("NCNET_CONSENSUS_STRATEGIES")
    _kind = os.environ.get("NCNET_CONSENSUS_KIND") or "dense"
    if (_mix and _kind == "dense"
            and os.environ.get("NCNET_BENCH_CLOSED_SWEEPS") != "1"):
        note(f"refusing dense-only strategy sweep: NCNET_CONSENSUS_"
             f"STRATEGIES={_mix!r} pins a closed sweep (docs/NEXT.md); "
             "set NCNET_BENCH_CLOSED_SWEEPS=1 to re-run it anyway")
        raise SystemExit(2)

    # Run log is OPT-IN here (NCNET_RUN_LOG=<path or dir>): bench's stdout
    # contract is exactly one JSON line, and the default invocation inside
    # tools/tpu_session.py runs main() many times in one process — an
    # unconditional log would stack open runs. The headline JSON doubles
    # as a `bench.headline` event when enabled.
    run_log = None
    log_dest = os.environ.get("NCNET_RUN_LOG", "")
    if log_dest:
        run_log = obs.init_run(
            "bench",
            obs.default_log_path(log_dest, "bench")
            if os.path.isdir(log_dest) else log_dest,
        )

    import jax.numpy as jnp

    from ncnet_tpu.evals import (
        inloc_device_matches,
        inloc_matches_from_consensus,
    )
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import (
        extract_features,
        ncnet_forward_from_features,
    )

    # Backend dial under a watchdog: a wedged TPU tunnel blocks
    # jax.devices() forever (observed on axon when a prior client's lease
    # lingers), and an unavailable tunnel raises. Either way, fall back to
    # a CPU smoke run in a fresh process — an honestly-labeled
    # *_cpu_smoke JSON line beats no benchmark record at all.
    dial_timeout = float(os.environ.get("NCNET_BENCH_DIAL_TIMEOUT", "900"))
    note(f"dialing backend (jax.devices(), watchdog {dial_timeout:.0f}s)...")
    devices = dial_devices(dial_timeout)
    if devices is None:
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            note("CPU backend also unreachable; aborting")
            os._exit(2)
        if os.environ.get("NCNET_BENCH_NO_REEXEC"):
            # In-process callers (tools/tpu_session.py): an execve here
            # would silently replace the whole session with a CPU smoke.
            note("backend dial failed — NCNET_BENCH_NO_REEXEC set, failing")
            raise RuntimeError("bench dial failed (re-exec disabled)")
        note("backend dial failed — re-exec as CPU smoke run")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)  # axon plugin hooks every proc
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    dev = devices[0]
    on_tpu = dev.platform != "cpu"
    note(f"backend up: {dev}")

    # InLoc configuration (SURVEY.md §3.3): nominal 3200x2400 inputs,
    # bucketed exactly the way the eval CLI buckets them (the host resize
    # is outside the timed region either way). NCNET_INLOC_FEAT_UNIT
    # overrides the alignment unit (16 default at this scale -> 3072x2304
    # px, pooled dims multiples of 8; 2 reproduces the reference's exact
    # 200x150 feature dims — the session driver A/Bs both). On CPU smoke
    # runs, shrink (NCNET_BENCH_SMOKE_SIZE overrides the smoke size —
    # used by the bench-contract test to keep the whole path fast).
    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape, resolve_feat_units

    if on_tpu:
        nominal, nom_h, nom_w = 3200, 3200, 2400
    else:
        nominal = nom_h = nom_w = int(
            os.environ.get("NCNET_BENCH_SMOKE_SIZE", "512")
        )
    feat_unit = int(os.environ.get("NCNET_INLOC_FEAT_UNIT", "-1"))
    units = resolve_feat_units(feat_unit, nominal, 2)
    h_a, w_a = inloc_resize_shape(
        nom_h, nom_w, nominal, 2, h_unit=units[0], w_unit=units[1]
    )
    h_b, w_b = h_a, w_a
    note(f"device input {h_a}x{w_a} (nominal {nom_h}x{nom_w}, "
         f"feat units {units})")

    def build(mode: str, extract_impl: str = "auto"):
        """mode: 'auto' (platform dispatch -> Pallas on TPU), 'xla'
        (forced slab-scan fusion — same memory behavior, no Mosaic), or
        'unfused' (materialize + pool). extract_impl: 'auto' = the
        one-read Pallas statistics kernel on TPU, 'xla' = the
        corr_to_matches formulation (the no-Mosaic fallback).

        NCNET_FUSE_MUTUAL_EXTRACT=1 additionally folds the final
        mutual-NN filter into the extraction kernel (pipeline stops after
        consensus; evals.inloc.inloc_matches_from_consensus) — the
        session driver A/Bs this against the default composition."""
        config = NCNetConfig(
            backbone=BackboneConfig(compute_dtype="bfloat16"),
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            use_fused_corr_pool=mode != "unfused",
            fused_impl="xla" if mode == "xla" else "auto",
        )
        note("building params...")
        params = ncnet_init(jax.random.PRNGKey(0), config)

        @jax.jit
        def query_feats(params, src):
            return extract_features(config, params, src)

        # One pano step: pano backbone + (fused) correlation+pool +
        # consensus + both-direction match extraction — the per-pano device
        # program of cli/eval_inloc.py.
        fuse_mutual = os.environ.get("NCNET_FUSE_MUTUAL_EXTRACT") == "1"

        def step(params, feat_a, tgt):
            feat_b = extract_features(config, params, tgt)
            corr, delta = ncnet_forward_from_features(
                config, params, feat_a, feat_b, final_mutual=not fuse_mutual
            )
            if fuse_mutual:
                return inloc_matches_from_consensus(
                    corr, delta4d=delta, k_size=2, impl=extract_impl
                )
            return inloc_device_matches(
                corr, delta4d=delta, k_size=2, impl=extract_impl
            )

        # One query block = ONE device program: query features + a
        # lax.scan over the pano stack. Per-program dispatch through a
        # tunneled backend costs ~50 ms (measured 2026-07-31: four
        # stage-level optimizations moved chained stage times but not the
        # headline — the 10 per-pano dispatches were the bottleneck), and
        # a local runtime pays a smaller but real per-dispatch cost too.
        # The eval CLI exposes the same batching (--pano_batch).
        # Pano-backbone batching (NCNET_PANO_BACKBONE_BATCH=n, trace
        # time): run the pano backbones for the whole stack in batches of
        # n BEFORE the per-pano scan. The round-2 trace shows the batch-1
        # backbone convs at 12-16% MXU utilization (89-130 GB/s — neither
        # compute- nor HBM-bound); batching feeds the MXU while the
        # per-pano scan keeps the HBM-bound corr/consensus tensors at
        # batch-1 size. Features for 10 panos at InLoc shape are ~0.6 GB
        # bf16 — cheap next to the 1.5 GB consensus activations.
        # Default 5 (promoted 2026-08-01, session_1128 bench matrix):
        # bb5 9.69 pairs/s vs default-1 6.09 (+59%; backbone 84 -> 24
        # ms/pair at 46% MFU). bb10 8.14 and bb5+conv1fold 9.24 LOSE —
        # knobs kept, defaults stay off.
        bb = int(os.environ.get("NCNET_PANO_BACKBONE_BATCH", "5") or 5)

        def match_from_feats(params, feat_a, feat_b):
            corr, delta = ncnet_forward_from_features(
                config, params, feat_a, feat_b, final_mutual=not fuse_mutual
            )
            if fuse_mutual:
                return inloc_matches_from_consensus(
                    corr, delta4d=delta, k_size=2, impl=extract_impl
                )
            return inloc_device_matches(
                corr, delta4d=delta, k_size=2, impl=extract_impl
            )

        def probe_of(m):
            # Consume EVERY element of EVERY output array (the
            # chain_reps rule, utils/profiling.py, strengthened to
            # full sums): anything less lets XLA dead-code-eliminate
            # part of the coordinate extraction (whole arrays, or the
            # per-match delta decode behind a single-element probe).
            return sum(jnp.sum(v.astype(jnp.float32)) for v in m)

        # NCNET_BENCH_HIT_PATH=1: every pano is a feature-cache hit (the
        # cross-query cache of cli/eval_inloc.py at steady state) — the
        # stack entries are precomputed FEATURES and the block runs only
        # correlation/consensus/extraction. Upper bound for the cache's
        # headline effect; the session matrix A/Bs it against default.
        if os.environ.get("NCNET_BENCH_HIT_PATH") == "1":
            @jax.jit
            def block_hit(params, src, feats_stack):
                feat_a = query_feats(params, src)

                def body(acc, feat_b):
                    m = match_from_feats(params, feat_a, feat_b)
                    return acc + probe_of(m), None

                acc, _ = jax.lax.scan(body, jnp.float32(0), feats_stack)
                return acc

            @jax.jit
            def prep_feats(params, tgt_stack):
                # bf16, mirroring what the production cache stores (the
                # correlation casts features to bf16 first anyway).
                return jax.lax.map(
                    lambda t: extract_features(
                        config, params, t[None]
                    ).astype(jnp.bfloat16),
                    tgt_stack,
                )

            return params, block_hit, prep_feats

        @jax.jit
        def block(params, src, tgt_stack):
            feat_a = query_feats(params, src)

            if bb > 1:
                from ncnet_tpu.cli.eval_inloc import _bb_group_size

                n = tgt_stack.shape[0]
                # The CLI's one definition of the grouping: the bench
                # must measure exactly the program eval_inloc runs.
                nb = _bb_group_size(n, bb)
                groups = tgt_stack.reshape(
                    n // nb, nb, *tgt_stack.shape[1:]
                )
                feats_b = jax.lax.map(
                    lambda g: extract_features(config, params, g), groups
                )
                feats_b = feats_b.reshape(n, 1, *feats_b.shape[2:])

                def body_f(acc, feat_b):
                    m = match_from_feats(params, feat_a, feat_b)
                    return acc + probe_of(m), None

                acc, _ = jax.lax.scan(body_f, jnp.float32(0), feats_b)
                return acc

            def body(acc, tgt):
                m = step(params, feat_a, tgt[None])
                return acc + probe_of(m), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), tgt_stack)
            return acc

        return params, block, None

    panos_per_query = 10  # eval_inloc.py:124-132: top-10 shortlist per query
    key = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    src = jax.random.normal(k1, (1, 3, h_a, w_a), jnp.float32)
    # Distinct pano contents: honest per-pano work inside the scan (and
    # nothing for the compiler to share across iterations).
    tgt_stack = jax.random.normal(
        k2, (panos_per_query, 3, h_b, w_b), jnp.float32
    )

    # Fallback ladder: both Pallas kernels -> Pallas corr+pool with XLA
    # extraction -> forced XLA slab-scan (same never-materialize memory
    # behavior, no Mosaic dependency) -> fully unfused materialize+pool.
    # The JSON line records which tier ran.
    tiers = (
        ("auto", "auto"),
        ("auto", "xla"),
        ("xla", "xla"),
        ("unfused", "xla"),
    )
    for tier in tiers:
        mode, extract_impl = tier
        name = f"{mode}+extract-{extract_impl}"
        try:
            params, block, prep_feats = build(mode, extract_impl)
            # The image stack stays loop-invariant: a tier fallback must
            # re-extract features from IMAGES, not from a prior tier's
            # feature stack.
            stack = tgt_stack
            if prep_feats is not None:
                # Precompute the pano features OUTSIDE the timed block:
                # hit-path blocks model a steady-state cache (features on
                # device; the eval CLI's H2D of a cached feature overlaps
                # dispatch the same way its decode prefetch does).
                note("hit-path: precomputing pano feature stack...")
                stack = prep_feats(params, tgt_stack)
                jax.block_until_ready(stack)
                name += "+hit-path"
            note(f"compiling+first-run '{name}' block at {h_a}x{w_a} (first "
                 "compile of this shape can take many minutes on a tunneled "
                 "backend)...")
            out = block(params, src, stack)  # warmup/compile
            jax.block_until_ready(out)
            note(f"'{name}' block compiled and ran")
            break
        except Exception as exc:  # noqa: BLE001
            if tier == tiers[-1]:
                raise
            note(f"'{name}' tier unavailable ({type(exc).__name__}: {exc}); "
                 "falling back")
    fused_ran = tier[0] != "unfused"

    # Timing through a scalar fetch: on tunneled backends (axon)
    # block_until_ready can return before execution completes, so each
    # iteration is closed by materializing a tiny host-side scalar — the
    # fetch cannot complete before the block has run. One fetch per block:
    # per-pano float()s would serialize a tunnel round trip (~40 ms on
    # axon) into every step.

    def run_block():
        """One query block: query features + 10 pano steps, one program."""
        return float(block(params, src, stack))

    run_block()  # settle caches/queues
    note("timing...")
    # CPU smoke times 2 blocks: single-block timing showed +/-4% run-to-
    # run scatter (2026-08-02 A/B), which is the size of the r03->r04
    # smoke "regression" — outage-round numbers must be comparable.
    # TPU times 5 (was 3): the round-5 A/B anchors scattered 9.67-9.84
    # (+/-1%) at 3 blocks, comparable to the knob deltas being judged;
    # two more blocks cost ~2 s against a warm cache.
    n_blocks = 5 if on_tpu else 2
    env_blocks = os.environ.get("NCNET_BENCH_BLOCKS", "").strip()
    if env_blocks:
        # Tolerate a malformed override: by this point the expensive
        # compile already happened, and losing the run (and its JSON
        # line) to a ValueError would cost a tunnel window.
        try:
            n_blocks = max(1, int(env_blocks))
        except ValueError:
            note(f"ignoring malformed NCNET_BENCH_BLOCKS={env_blocks!r}")
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        run_block()
    dt = (time.perf_counter() - t0) / (n_blocks * panos_per_query)

    pairs_per_s = 1.0 / dt

    # Cost card of the headline block (obs/costcards.py): AOT-read the
    # compiled program's XLA FLOP/byte totals and cross-check the
    # consensus stack's analytic cost — OUTSIDE the timed region, and
    # a compile-cache hit (the block just ran). NCNET_COSTCARDS=0
    # skips; any failure is noted and the headline survives.
    costcard = None
    if os.environ.get("NCNET_COSTCARDS", "1") != "0":
        try:
            from ncnet_tpu.obs import costcards as _costcards

            captured = _costcards.aot_capture(block, params, src, stack)
            if captured is not None:
                k = 2  # relocalization_k_size of the bench config
                cells = ((h_a // 16 // k) * (w_a // 16 // k)
                         * (h_b // 16 // k) * (w_b // 16 // k))
                model = _costcards.consensus_model(
                    _costcards.consensus_layers(params["neigh_consensus"]),
                    cells, symmetric=True, dtype_bytes=2,
                    applications=panos_per_query)
                card = _costcards.make_card(
                    program="bench_block", q_shape=(h_a, w_a),
                    p_shape=(h_b, w_b), batch=1, mode=name,
                    captured=captured, model=model)
                _costcards.emit_card(card)
                costcard = {
                    "flops": (card.get("xla") or {}).get("flops"),
                    "bytes_accessed": (card.get("xla")
                                       or {}).get("bytes_accessed"),
                    "temp_bytes": (card.get("memory")
                                   or {}).get("temp_bytes"),
                    "flops_per_byte": card.get("flops_per_byte"),
                    "model_ok": card.get("model_ok"),
                }
        except Exception as exc:  # noqa: BLE001 — headline survives
            note(f"cost card capture failed: {type(exc).__name__}: {exc}")

    # Utilization block (VERDICT r3 weak #5): capture ONE traced block and
    # roll the per-op model_flops/bytes_accessed into whole-step and
    # per-stage achieved TFLOP/s, HBM GB/s, and %-of-peak, so MFU
    # regressions show in BENCH_r*.json without a manual trace read. The
    # trace has op metadata only on TPU; a CPU smoke emits null. Fenced:
    # the headline must survive any profiler failure on a flaky tunnel.
    util = None
    if os.environ.get("NCNET_BENCH_MFU", "1") != "0":
        import tempfile

        from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm
        from ncnet_tpu.utils.traceagg import (
            PEAK_HBM_GBS,
            PEAK_TFLOPS_BF16,
            aggregate,
            stage_rollup,
        )

        tdir = None
        trace_ok = False
        try:
            tdir = tempfile.mkdtemp(prefix="ncnet_bench_trace_")
            note("capturing one traced block for the utilization table...")

            traced_wall = [0.0]

            def _traced():
                with jax.profiler.trace(tdir):
                    t0 = time.perf_counter()
                    run_block()
                    traced_wall[0] = time.perf_counter() - t0

            run_with_alarm(300, _traced)
            trace_ok = True
            agg = aggregate(tdir, steps=1)
            if agg is None:
                note("trace has no accelerator op metadata (CPU smoke); "
                     "utilization omitted")
            else:
                # Capture-scaling invariant: attributed device time
                # summed over ONE op line can never exceed the wall of
                # the traced (synced) run. A violation means the
                # aggregation double-counted — session_1128's umbrella
                # row (fixed in traceagg.op_tids) and round-5's nested
                # `while` containers, whose span covers the very body
                # ops emitted on the same line (fixed by self-time
                # aggregation in traceagg.aggregate) — the capture
                # spanned extra work, or the plane carried several
                # concurrent op lines (op_lines below tells which) — in
                # every case the absolute ms are not wall-comparable and
                # the block says so instead of publishing them silently.
                # Relative stage shares stay meaningful.
                scale_ok = (
                    agg["total_ms"] <= traced_wall[0] * 1e3 * 1.05
                )
                util = {
                    "scale_ok": scale_ok,
                    "op_lines": agg.get("op_lines"),
                    "device_ms_per_pair": round(
                        agg["total_ms"] / panos_per_query, 2
                    ),
                    # Wall time of the traced run itself: attributed
                    # device ms EXCEEDING this flags a capture-scaling
                    # artifact (seen 2026-08-01: attributed 3.14 s vs
                    # wall 1.64 s per block at bb1 — docs/NEXT.md); the
                    # relative stage shares stay meaningful either way.
                    "traced_wall_ms_per_pair": round(
                        traced_wall[0] * 1e3 / panos_per_query, 2
                    ),
                    "tflops": round(agg["tflops"], 2),
                    "hbm_gbs": round(agg["gbs"], 1),
                    "mfu": round(agg["mfu"], 4),
                    "hbm_frac": round(agg["hbm_frac"], 4),
                    "peak_tflops_bf16": PEAK_TFLOPS_BF16,
                    "peak_hbm_gbs": PEAK_HBM_GBS,
                    "stages": stage_rollup(agg),
                }
        except AlarmTimeout:
            note("trace capture timed out; utilization omitted")
        except Exception as exc:  # noqa: BLE001
            note(f"utilization capture failed ({type(exc).__name__}: {exc}); "
                 "omitted")
        finally:
            # A full profiler capture is tens-to-hundreds of MB; the
            # round loop re-runs bench many times — don't leak them.
            # NCNET_BENCH_KEEP_TRACE=<dir> preserves the capture there
            # instead (ONE capture per dest: the bench block's scan-
            # batched 'other' stage only exists in THIS trace, so the
            # session keeps the baseline run's copy for
            # tools/trace_optable.py).
            if tdir is not None:
                import shutil

                keep = os.environ.get("NCNET_BENCH_KEEP_TRACE")
                if keep and trace_ok:
                    # A cwd-relative keep path escapes the .gitignore'd
                    # docs/ tree when bench runs from elsewhere — anchor
                    # it to the repo root like the compile cache.
                    if not os.path.isabs(keep):
                        keep = os.path.join(os.path.dirname(
                            os.path.abspath(__file__)), keep)
                    # Only replace a previously kept capture once THIS
                    # capture is safely in place: stage the new one at a
                    # temp sibling first so a failed move can't lose BOTH
                    # the old and the new capture.
                    staged = keep + ".tmp"
                    shutil.rmtree(staged, ignore_errors=True)
                    try:
                        shutil.move(tdir, staged)
                    except OSError as exc:
                        note(f"trace keep failed ({exc}); dropping")
                        shutil.rmtree(tdir, ignore_errors=True)
                        shutil.rmtree(staged, ignore_errors=True)
                    else:
                        try:
                            shutil.rmtree(keep, ignore_errors=True)
                            os.rename(staged, keep)
                            note(f"trace kept at {keep}")
                        except OSError as exc:
                            # The staged dir is now the only complete
                            # capture — leave it for manual recovery.
                            note(f"trace keep rename failed ({exc}); "
                                 f"capture left at {staged}")
                else:
                    shutil.rmtree(tdir, ignore_errors=True)

    # Coarse-to-fine section: (a) consensus-stage A/B at the reference
    # post-pool shape — the c2f replacement (coarse consensus + top-K
    # window refinement) must beat the one-shot consensus stage it
    # displaces, the cell-count arithmetic made wall-clock; (b) a
    # high-res point at 2x the reference feature grid that runs ONLY
    # under c2f — the one-shot 4D tensor at that shape is the memory
    # wall the mode exists to dodge (docs/PERF.md). Both fenced: the
    # headline must survive any c2f failure. NCNET_BENCH_C2F=0 skips.
    c2f_fields = {
        "coarse_factor": None, "topk": None,
        "consensus_oneshot_ms": None, "consensus_c2f_ms": None,
        "c2f_pairs_s": None, "c2f_hires_input": None,
    }
    if os.environ.get("NCNET_BENCH_C2F", "1") != "0":
        try:
            from ncnet_tpu.models.ncnet import (
                c2f_raw_matches_from_features,
                c2f_stride,
                extract_features as _extract_features,
            )
            from ncnet_tpu.ops.c2f import refine_consensus
            from ncnet_tpu.ops.conv4d import neigh_consensus_apply
            from ncnet_tpu.ops.mutual import mutual_matching
            from ncnet_tpu.utils.profiling import timed_steady

            c2f_config = NCNetConfig(
                backbone=BackboneConfig(compute_dtype="bfloat16"),
                ncons_kernel_sizes=(3, 3),
                ncons_channels=(16, 1),
                relocalization_k_size=2,
                half_precision=True,
                use_fused_corr_pool=tier[0] != "unfused",
                fused_impl="xla" if tier[0] == "xla" else "auto",
                mode="c2f",
            )
            stride = c2f_stride(c2f_config)  # coarse factor x reloc k
            c2f_fields["coarse_factor"] = c2f_config.c2f_coarse_factor
            c2f_fields["topk"] = c2f_config.c2f_topk
            # Reference feature grid (backbone 1/16 scale), snapped to
            # the c2f stride so the coarse/fine shapes are the ones the
            # engine would actually bucket this input into.
            fh = max((h_a // 16) // stride * stride, stride)
            fw = max((w_a // 16) // stride * stride, stride)
            ph, pw = fh // 2, fw // 2            # post reloc-pool (k=2)
            cph, cpw = fh // stride, fw // stride  # coarse post-pool
            kk = min(c2f_config.c2f_topk, cph * cpw)
            wbh = min((2 * c2f_config.c2f_radius + 1) * stride, fh)
            wbw = min((2 * c2f_config.c2f_radius + 1) * stride, fw)
            cons = params["neigh_consensus"]
            ka, kb, kc = jax.random.split(jax.random.PRNGKey(7), 3)
            corr_os = jax.random.normal(
                ka, (1, 1, ph, pw, ph, pw), jnp.float32
            ).astype(jnp.bfloat16)
            corr_coarse = jax.random.normal(
                kb, (1, 1, cph, cpw, cph, cpw), jnp.float32
            ).astype(jnp.bfloat16)
            # Two window stacks (per-B + per-A refinement directions),
            # f32 as ops.c2f.window_correlation produces them.
            wins = jax.random.normal(
                kc, (2, kk, 1, stride, stride, wbh, wbw), jnp.float32
            )

            @jax.jit
            def oneshot_stage(cons, c):
                c = mutual_matching(c)
                c = neigh_consensus_apply(cons, c, symmetric=True)
                return jnp.sum(mutual_matching(c).astype(jnp.float32))

            @jax.jit
            def c2f_stage(cons, c, wins):
                c = mutual_matching(c)
                c = neigh_consensus_apply(cons, c, symmetric=True)
                acc = jnp.sum(mutual_matching(c).astype(jnp.float32))
                for w in (wins[0], wins[1]):
                    acc = acc + jnp.sum(
                        refine_consensus(cons, w, corr_dtype=jnp.bfloat16)
                    )
                return acc

            note(f"c2f consensus A/B: oneshot [1,1,{ph},{pw},{ph},{pw}] "
                 f"vs coarse [1,1,{cph},{cpw},{cph},{cpw}] + 2x[{kk},1,"
                 f"{stride},{stride},{wbh},{wbw}] windows")
            _, dt_os, _ = timed_steady(oneshot_stage, cons, corr_os,
                                       iters=3)
            _, dt_c2f, _ = timed_steady(c2f_stage, cons, corr_coarse,
                                        wins, iters=3)
            c2f_fields["consensus_oneshot_ms"] = round(dt_os * 1e3, 3)
            c2f_fields["consensus_c2f_ms"] = round(dt_c2f * 1e3, 3)
            note(f"consensus stage: oneshot {dt_os * 1e3:.1f} ms, c2f "
                 f"{dt_c2f * 1e3:.1f} ms ("
                 f"{'c2f faster' if dt_c2f < dt_os else 'c2f NOT faster'})")

            try:
                # >=2x the reference grid, pixel dims snapped to
                # 16*stride so the fine grid divides the c2f stride.
                unit = 16 * stride
                hi_h = max(unit, int(round(2 * h_a / unit)) * unit)
                hi_w = max(unit, int(round(2 * w_a / unit)) * unit)
                note(f"c2f high-res point: {hi_h}x{hi_w} images "
                     f"({hi_h // 16}x{hi_w // 16} feature grid; the "
                     "one-shot 4D tensor is never materialized here)")
                k3, k4 = jax.random.split(jax.random.PRNGKey(8))
                src_hi = jax.random.normal(
                    k3, (1, 3, hi_h, hi_w), jnp.float32)
                tgt_hi = jax.random.normal(
                    k4, (1, 3, hi_h, hi_w), jnp.float32)

                @jax.jit
                def c2f_pair(params, src, tgt):
                    fa = _extract_features(c2f_config, params, src)
                    fb = _extract_features(c2f_config, params, tgt)
                    outs = c2f_raw_matches_from_features(
                        c2f_config, params, fa, fb, both_directions=True
                    )
                    return sum(
                        jnp.sum(o.astype(jnp.float32)) for o in outs)

                _, dt_hi, _ = timed_steady(
                    c2f_pair, params, src_hi, tgt_hi, iters=2)
                c2f_fields["c2f_pairs_s"] = round(1.0 / dt_hi, 4)
                c2f_fields["c2f_hires_input"] = [hi_h, hi_w]
                note(f"c2f high-res pair: {dt_hi * 1e3:.0f} ms/pair "
                     f"({1.0 / dt_hi:.2f} pairs/s)")
            except Exception as exc:  # noqa: BLE001
                note(f"c2f high-res point failed ({type(exc).__name__}: "
                     f"{exc}); omitted")
        except Exception as exc:  # noqa: BLE001
            note(f"c2f section failed ({type(exc).__name__}: {exc}); "
                 "omitted")

    # The consensus plan the measured program actually traced (recorded
    # by neigh_consensus_apply at trace time): makes BENCH_r0*.json
    # trajectories attributable to plan changes — fused? strategies?
    # fold? autotune cache hit? — not just code drift. Snapshotted HERE,
    # before the algebraic A/B below traces the cp/fft arms and
    # overwrites the last-plan record with an arm that is not the
    # headline program's.
    from ncnet_tpu.ops import consensus_last_plan

    consensus_plan = consensus_last_plan()

    # Algebraic consensus A/B (the cp/fft arms, ops/cp4d.py): time the
    # SAME mutual->consensus->mutual stage the c2f section's one-shot
    # anchor times, once per enumerated algebraic arm plus an explicit
    # dense anchor, and record per-arm ms + output agreement vs dense.
    # The winner's kind/rank/agreement land in the headline (the fields
    # tools/bench_trend.py passes through) with a model-checked cost
    # card. Fenced: the headline survives any arm failure.
    # NCNET_BENCH_CONSENSUS_AB=0 skips.
    arm_fields = {
        "consensus_arms": None, "consensus_plan_kind": None,
        "cp_rank": None, "cp_agreement": None,
        "consensus_arm_card": None,
    }
    if os.environ.get("NCNET_BENCH_CONSENSUS_AB", "1") != "0":
        try:
            from ncnet_tpu.ops import autotune as _autotune
            from ncnet_tpu.ops import cp4d as _cp4d
            from ncnet_tpu.ops.conv4d import (
                neigh_consensus_apply as _nca,
            )
            from ncnet_tpu.ops.mutual import mutual_matching as _mutual
            from ncnet_tpu.utils.profiling import timed_steady as _timed

            cons = params["neigh_consensus"]
            # Floor 8, not 4: at 4^4 cells every arm is ~0.5 ms of
            # dispatch overhead and the comparison is noise; 8^4 is the
            # smallest grid where arm differences resolve (and the c2f
            # coarse window of the default 512px smoke).
            aph, apw = max(h_a // 16 // 2, 8), max(w_a // 16 // 2, 8)
            corr_ab = jax.random.normal(
                jax.random.PRNGKey(11), (1, 1, aph, apw, aph, apw),
                jnp.float32).astype(jnp.bfloat16)
            arms = [{"kind": "dense", "cp_rank": 0}] + [
                p for p in _autotune.enumerate_plans(
                    cons, symmetric=True, kl_folds=(0,), chunks=(0,))
                if p["kind"] in ("cp", "fft")
            ]
            note(f"algebraic consensus A/B at [1,1,{aph},{apw},{aph},"
                 f"{apw}]: {[p['kind'] for p in arms]}")
            dense_out = None
            table = {}
            best = None
            for plan in arms:
                kind, rank = plan["kind"], plan["cp_rank"]
                label = ("dense" if kind == "dense"
                         else _autotune.plan_label(plan))

                def arm_stage(c, _k=kind, _r=rank):
                    c = _mutual(c)
                    c = _nca(cons, c, symmetric=True, kind=_k,
                             cp_rank=_r or None)
                    return jnp.sum(_mutual(c).astype(jnp.float32))

                try:
                    _, dt_arm, _ = _timed(jax.jit(arm_stage), corr_ab,
                                          iters=10)
                    out = _nca(cons, corr_ab, symmetric=True, kind=kind,
                               cp_rank=rank or None)
                except Exception as exc:  # noqa: BLE001 — arm fence
                    note(f"arm '{label}' failed ({type(exc).__name__}: "
                         f"{exc}); skipped")
                    continue
                entry = {"ms": round(dt_arm * 1e3, 3)}
                if kind == "dense":
                    dense_out = out
                elif dense_out is not None:
                    entry["agreement"] = round(
                        _cp4d.output_agreement(dense_out, out), 4)
                table[label] = entry
                note(f"arm {label:12s} {entry['ms']:8.2f} ms"
                     + (f"  agreement={entry['agreement']:.4f}"
                        if "agreement" in entry else ""))
                if best is None or entry["ms"] < best[2]["ms"]:
                    best = (label, plan, entry)
            if table:
                arm_fields["consensus_arms"] = table
            if best is not None:
                label, plan, entry = best
                arm_fields["consensus_plan_kind"] = plan["kind"]
                arm_fields["cp_rank"] = plan["cp_rank"]
                arm_fields["cp_agreement"] = entry.get("agreement")
                card = _autotune.winner_card(
                    cons, corr_ab, True, plan, entry["ms"])
                if card is not None:
                    arm_fields["consensus_arm_card"] = {
                        "plan_label": card.get("plan_label"),
                        "model_ok": card.get("model_ok"),
                        "flops": (card.get("xla") or {}).get("flops"),
                    }
        except Exception as exc:  # noqa: BLE001
            note(f"algebraic consensus A/B failed ({type(exc).__name__}"
                 f": {exc}); omitted")

    headline = {
        "metric": "inloc_dense_match_pairs_per_s_per_chip"
        + ("" if on_tpu else "_cpu_smoke"),
        "value": round(pairs_per_s, 4),
        "unit": "pairs/s/chip",
        "vs_baseline": round(pairs_per_s / V100_BASELINE_PAIRS_PER_S, 4),
        "fused": fused_ran,
        "path": name,
        "util": util,
        **c2f_fields,
        **arm_fields,
        "consensus_plan": consensus_plan,
        "costcard": costcard,
    }
    if run_log is not None:
        # The same dict BENCH_r*.json archives, queryable from the run
        # log; the gauge makes it diffable by tools/obs_report.py.
        obs.gauge("bench.pairs_per_s").set(pairs_per_s)
        run_log.event("bench.headline", **headline)
        run_log.close("ok")
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
