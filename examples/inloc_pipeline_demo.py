"""Self-contained InLoc pipeline demo: matching -> PnP -> rate curve.

Runs the ENTIRE indoor-localization stack (the reference needs Matlab for
the second half; here it is one command with zero downloads):

    cli.eval_inloc   dense NCNet matching -> per-query matches .mat
    cli.localize     P3P LO-RANSAC poses -> rate-vs-threshold curve

on a synthetic scene built in-process: a textured plane observed by a
database camera at the identity pose, with the query being the same view —
so ground truth is the identity pose and a correct pipeline localizes at
~zero error. The NeighConsensus weights are hand-crafted center-tap
(identity) kernels: untrained weights would scramble the consensus stage,
and the real trained checkpoint needs the (non-downloadable) datasets; the
demo demonstrates PLUMBING, not learned matching quality.

Usage:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python examples/inloc_pipeline_demo.py --out /tmp/inloc_demo
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_identity_consensus_checkpoint(out_dir, kernel_sizes=(3, 3),
                                       channels=(16, 1)):
    """Checkpoint whose consensus stack is the identity map (center taps)."""
    import jax

    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.training.checkpoint import save_checkpoint

    config = NCNetConfig(
        backbone=BackboneConfig(cnn="vgg"),
        ncons_kernel_sizes=tuple(kernel_sizes),
        ncons_channels=tuple(channels),
    )
    params = jax.tree.map(np.asarray, ncnet_init(jax.random.PRNGKey(0), config))
    cin = 1
    for layer, k, cout in zip(params["neigh_consensus"], kernel_sizes, channels):
        w = np.zeros((k, k, k, k, cin, cout), np.float32)
        c = k // 2
        w[c, c, c, c, 0, 0] = 1.0  # channel 0 carries the tensor through
        layer["weight"] = w
        layer["bias"] = np.zeros(cout, np.float32)
        cin = cout
    return save_checkpoint(out_dir, params, config, epoch=0)


def build_scene(root, size, depth=4.0):
    """Textured plane + its XYZcut; query == database view (GT = identity)."""
    from PIL import Image
    from scipy.io import savemat

    rng = np.random.default_rng(0)
    # Smooth random texture: distinctive local appearance without aliasing.
    tex = rng.random((size // 8, size // 8, 3))
    tex = np.kron(tex, np.ones((8, 8, 1)))[:size, :size]
    img = (tex * 255).astype("uint8")

    os.makedirs(os.path.join(root, "query"), exist_ok=True)
    os.makedirs(os.path.join(root, "pano"), exist_ok=True)
    os.makedirs(os.path.join(root, "cutouts"), exist_ok=True)
    Image.fromarray(img).save(os.path.join(root, "query", "q0.jpg"), quality=95)
    Image.fromarray(img).save(os.path.join(root, "pano", "cutout1.jpg"), quality=95)

    # Back-project every db pixel center through K=[fl,0,S/2;...], identity
    # pose, onto the z=depth plane.
    fl = float(size)
    vv, uu = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
    x = (uu + 0.5 - size / 2.0) * depth / fl
    y = (vv + 0.5 - size / 2.0) * depth / fl
    xyz = np.stack([x, y, np.full_like(x, depth)], axis=-1)
    savemat(
        os.path.join(root, "cutouts", "cutout1.jpg.mat"),
        {"XYZcut": xyz},
        do_compression=True,
    )

    img_list = np.zeros((1, 1), dtype=[("queryname", "O"), ("topNname", "O")])
    img_list[0, 0]["queryname"] = "q0.jpg"
    img_list[0, 0]["topNname"] = np.array(["cutout1.jpg"], dtype=object).reshape(1, -1)
    savemat(os.path.join(root, "shortlist.mat"), {"ImgList": img_list})

    gt = np.hstack([np.eye(3), np.zeros((3, 1))])
    np.savez(
        os.path.join(root, "gt.npz"),
        queries=np.array(["q0.jpg"]),
        poses=np.stack([gt]),
    )
    return fl


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/inloc_pipeline_demo")
    p.add_argument("--size", type=int, default=256, help="scene image size")
    p.add_argument("--image_size", type=int, default=0,
                   help="matcher resize (default: same as --size)")
    p.add_argument("--ransac_iters", type=int, default=1000)
    args = p.parse_args(argv)

    if args.size % 8:
        # The texture is built in 8x8 blocks; a ragged size would shrink the
        # images while fl/XYZcut stay at the requested size, silently
        # breaking the geometry.
        args.size -= args.size % 8
        print(f"--size rounded down to {args.size} (multiple of 8)")

    root = args.out
    os.makedirs(root, exist_ok=True)
    fl = build_scene(root, args.size)
    ckpt = make_identity_consensus_checkpoint(os.path.join(root, "ckpt"))
    print(f"scene + identity-consensus checkpoint under {root}")

    from ncnet_tpu.cli import eval_inloc, localize

    eval_inloc.main([
        "--checkpoint", ckpt,
        "--inloc_shortlist", os.path.join(root, "shortlist.mat"),
        "--query_path", os.path.join(root, "query"),
        "--pano_path", os.path.join(root, "pano"),
        "--output_dir", os.path.join(root, "matches"),
        "--image_size", str(args.image_size or args.size),
        "--n_queries", "1", "--n_panos", "1", "--k_size", "2",
    ])
    # Newest experiment dir: re-runs into the same --out with different
    # settings create siblings, and listdir order is unspecified.
    exp = max(
        os.listdir(os.path.join(root, "matches")),
        key=lambda d: os.path.getmtime(os.path.join(root, "matches", d)),
    )
    print(f"matches written: matches/{exp}/1.mat")

    localize.main([
        "--matches_dir", os.path.join(root, "matches", exp),
        "--shortlist", os.path.join(root, "shortlist.mat"),
        "--cutout_dir", os.path.join(root, "cutouts"),
        "--query_dir", os.path.join(root, "query"),
        "--output_dir", os.path.join(root, "out"),
        "--focal_length", str(fl),
        "--score_thr", "0.0",  # demo weights are not trained: keep all
        "--ransac_iters", str(args.ransac_iters),
        "--top_n", "1",
        "--gt_poses", os.path.join(root, "gt.npz"),
    ])

    with np.load(os.path.join(root, "out", "poses.npz"), allow_pickle=True) as z:
        P = z["poses"][0]
    err_pos = float(np.linalg.norm(P[:, 3]))
    print(json.dumps({
        "recovered_pose_translation_err_m": round(err_pos, 4),
        "curve": os.path.join(root, "out", "localization_curve.png"),
    }))
    return 0 if err_pos < 0.25 else 1


if __name__ == "__main__":
    sys.exit(main())
