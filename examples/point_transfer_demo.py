"""Point-transfer demo (parity target: point_transfer_demo.ipynb).

Loads a model (reference .pth.tar or native checkpoint, or random weights
when none is given), runs one image pair through the NCNet forward,
extracts soft-argmax matches, transfers a set of target keypoints into
the source image, and writes a side-by-side visualization.

Usage:
    python examples/point_transfer_demo.py \
        --checkpoint trained_models/ncnet_pfpascal.pth.tar \
        --source_image a.jpg --target_image b.jpg --out demo.png
Without --source/--target a synthetic warped pair is generated, so the
demo runs with no datasets downloaded.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    p = argparse.ArgumentParser(description="NCNet-TPU point-transfer demo")
    p.add_argument("--checkpoint", default="", help=".pth.tar or native checkpoint dir")
    p.add_argument("--source_image", default="")
    p.add_argument("--target_image", default="")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--n_points", type=int, default=12, help="grid keypoints to transfer")
    p.add_argument("--out", default="point_transfer_demo.png")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.cli.common import build_model
    from ncnet_tpu.data.image_io import load_and_resize_chw
    from ncnet_tpu.data.normalization import normalize_image
    from ncnet_tpu.geometry.coords import unnormalize_axis
    from ncnet_tpu.models.ncnet import ncnet_forward
    from ncnet_tpu.ops import corr_to_matches
    from ncnet_tpu.ops.matches import bilinear_point_transfer
    from ncnet_tpu.utils.plot import plot_matches_horizontal

    size = args.image_size
    config, params = build_model(checkpoint=args.checkpoint)

    if args.source_image and args.target_image:
        src_raw, _ = load_and_resize_chw(args.source_image, size, size)
        tgt_raw, _ = load_and_resize_chw(args.target_image, size, size)
        src_raw, tgt_raw = src_raw / 255.0, tgt_raw / 255.0  # to [0, 1]
    else:
        # Synthetic pair: smooth random texture and an affine-warped copy.
        print("no images given - generating a synthetic warped pair")
        from ncnet_tpu.geometry.grid import affine_transform

        rng = np.random.default_rng(0)
        base = rng.uniform(0, 1, (1, 3, size // 8, size // 8)).astype(np.float32)
        base = jnp.asarray(base)
        base = jax.image.resize(base, (1, 3, size, size), "bilinear")
        theta = jnp.asarray([[[1.15, 0.1, 0.05], [-0.08, 0.9, -0.03]]])
        warped = affine_transform(base, theta, size, size)
        src_raw = np.asarray(base[0])
        tgt_raw = np.asarray(warped[0])

    src = jnp.asarray(normalize_image(src_raw))[None]
    tgt = jnp.asarray(normalize_image(tgt_raw))[None]

    @jax.jit
    def run(params, src, tgt):
        corr, _ = ncnet_forward(config, params, src, tgt)
        return corr_to_matches(corr, do_softmax=True)

    xa, ya, xb, yb, score = run(params, src, tgt)

    # Keypoints: a regular grid over the target image (the notebook uses the
    # PF-Pascal annotations; a grid keeps the demo dataset-free).
    g = int(np.ceil(np.sqrt(args.n_points)))
    lin = np.linspace(-0.7, 0.7, g)
    gx, gy = np.meshgrid(lin, lin)
    pts_norm = np.stack([gx.reshape(-1), gy.reshape(-1)])[None, :, : args.n_points]

    warped_norm = bilinear_point_transfer((xa, ya, xb, yb), jnp.asarray(pts_norm))

    def to_px(pts):
        return np.stack(
            [
                np.asarray(unnormalize_axis(pts[0, 0], size)),
                np.asarray(unnormalize_axis(pts[0, 1], size)),
            ],
            axis=1,
        )

    src_px = to_px(np.asarray(warped_norm))
    tgt_px = to_px(pts_norm)

    plot_matches_horizontal(
        np.transpose(src_raw, (1, 2, 0)),
        np.transpose(tgt_raw, (1, 2, 0)),
        src_px,
        tgt_px,
        args.out,
    )
    print(f"transferred {tgt_px.shape[0]} keypoints; mean match score "
          f"{float(np.asarray(score).mean()):.4f}; wrote {args.out}")


if __name__ == "__main__":
    main()
