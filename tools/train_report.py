"""Training-run report: runlog -> loss curve / throughput / step-time.

Reads one training run's ``runlog-train-*.jsonl`` (rotated segments
included) and prints ONE JSON line (the house tool contract) with the
run's headline numbers; the human-readable loss-curve / throughput /
step-time table goes to stderr::

    python tools/train_report.py out/runlog-train-20260807-1.jsonl
    {"metric": "train_report", "value": 0.412, "unit": "loss",
     "steps": 120, "epochs": 3, "divergence_events": 0, ...}

The report is assembled from the records the training observatory
(ncnet_tpu/obs/train_watch.py) writes:

- ``train_step`` events -> per-step loss / grad-norm series,
- ``train.step`` span records -> step-time distribution (the same
  tree tools/trace_export.py renders),
- ``epoch`` events -> per-epoch loss + pairs/s throughput table,
- ``train_divergence`` events -> divergence count,
- the final ``metrics`` snapshot -> ``train.*`` histogram totals.

``--strict`` turns the report into a regression gate against a
committed reference curve (default
``tests/data/train_reference_curve.json``): the run must have booked
at least ``min_steps`` steps, its final train loss must not sit more
than ``loss_margin`` above the reference's (absolute margin — losses
from the weak-supervision objective can be negative, so a relative
check would flip sign), no divergence events past
``max_divergence_events``, and the observatory's evidence must be
present (``train.step`` spans, a non-empty ``train.step_time_s``
histogram, a grad-norm series). Exit 1 with the failed checks named
on stderr; the JSON carries ``"strict"`` either way.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DEFAULT_REFERENCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "data", "train_reference_curve.json")


def load_run(path: str) -> List[dict]:
    """All complete JSON records, rotated segments included (same
    tolerance as tools/obs_report.py: a truncated final line is a
    crash artifact, not an error)."""
    from ncnet_tpu.obs.events import runlog_segments

    records = []
    for seg in runlog_segments(path):
        if not os.path.exists(seg):
            continue
        with open(seg, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return records


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def summarize(records: List[dict]) -> dict:
    """Fold a run's records into the report dict (no gating here)."""
    steps = [r for r in records if r.get("event") == "train_step"]
    epochs = [r for r in records if r.get("event") == "epoch"]
    divergences = [r for r in records
                   if r.get("event") == "train_divergence"]
    step_spans = [r for r in records
                  if r.get("event") == "train.step"
                  and r.get("kind") == "span"]
    losses = [r["loss"] for r in steps
              if isinstance(r.get("loss"), (int, float))
              and math.isfinite(r["loss"])]
    grad_norms = [r["grad_norm"] for r in steps
                  if isinstance(r.get("grad_norm"), (int, float))
                  and math.isfinite(r["grad_norm"])]
    durs = sorted(float(r.get("dur_s", 0.0)) for r in step_spans)

    # The LAST metrics snapshot is the run's final state (flush_metrics
    # runs per epoch and again at close).
    snapshot: Dict = {}
    for r in records:
        if r.get("event") == "metrics" and isinstance(
                r.get("snapshot"), dict):
            snapshot = r["snapshot"]
    hists = snapshot.get("histograms") or {}
    step_hist = hists.get("train.step_time_s") or {}

    report = {
        "metric": "train_report",
        "value": round(losses[-1], 6) if losses else None,
        "unit": "loss",
        "steps": len(steps),
        "epochs": len(epochs),
        "divergence_events": len(divergences),
        "spans": len(step_spans),
        "first_loss": round(losses[0], 6) if losses else None,
        "final_loss": round(losses[-1], 6) if losses else None,
        "grad_norm_points": len(grad_norms),
        "final_grad_norm": round(grad_norms[-1], 6) if grad_norms
        else None,
        "step_time_hist_count": int(step_hist.get("count", 0)),
        "step_p50_s": round(_percentile(durs, 0.50), 4),
        "step_p95_s": round(_percentile(durs, 0.95), 4),
    }
    if epochs:
        last = epochs[-1]
        report["final_epoch_train_loss"] = last.get("train_loss")
        report["pairs_per_s"] = last.get("pairs_per_s")
    report["_epochs_table"] = epochs  # stripped before printing
    return report


def render_table(report: dict, out) -> None:
    epochs = report.get("_epochs_table") or []
    print(f"steps={report['steps']}  spans={report['spans']}  "
          f"divergences={report['divergence_events']}  "
          f"step p50={report['step_p50_s']}s "
          f"p95={report['step_p95_s']}s", file=out)
    if not epochs:
        return
    print(f"{'epoch':>5} {'train_loss':>12} {'val_loss':>12} "
          f"{'pairs/s':>9} {'dur_s':>8}", file=out)
    for e in epochs:
        def num(key, nd=4):
            v = e.get(key)
            return f"{v:.{nd}f}" if isinstance(v, (int, float)) else "-"
        print(f"{e.get('epoch', '?'):>5} {num('train_loss'):>12} "
              f"{num('val_loss'):>12} {num('pairs_per_s', 1):>9} "
              f"{num('dur_s', 1):>8}", file=out)


def strict_gate(report: dict, reference: dict) -> dict:
    """Every check named, every verdict recorded — the gate's JSON
    must show WHAT was compared, not just pass/fail."""
    checks = {}
    min_steps = int(reference.get("min_steps", 1))
    checks["min_steps"] = report["steps"] >= min_steps
    ref_loss = reference.get("final_train_loss")
    margin = float(reference.get("loss_margin", 0.05))
    if ref_loss is not None and report["final_loss"] is not None:
        checks["final_loss_vs_reference"] = (
            report["final_loss"] <= float(ref_loss) + margin)
    else:
        checks["final_loss_vs_reference"] = report["final_loss"] is not None
    max_div = int(reference.get("max_divergence_events", 0))
    checks["divergence_events"] = report["divergence_events"] <= max_div
    # Observatory evidence: the run must have been INSTRUMENTED, not
    # merely finished — a green curve with no spans or histograms means
    # the telemetry silently fell off.
    checks["train_step_spans"] = report["spans"] > 0
    checks["step_time_histogram"] = report["step_time_hist_count"] > 0
    checks["grad_norm_series"] = report["grad_norm_points"] > 0
    return checks


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("runlog", help="training runlog path (base path of "
                    "a rotated set)")
    ap.add_argument("--strict", action="store_true",
                    help="gate against the committed reference curve; "
                         "exit 1 on any failed check")
    ap.add_argument("--reference", default=DEFAULT_REFERENCE,
                    help="reference-curve JSON (default "
                         "tests/data/train_reference_curve.json)")
    args = ap.parse_args(argv)

    records = load_run(args.runlog)
    if not records:
        print(json.dumps({"metric": "train_report",
                          "error": f"no records in {args.runlog}"}))
        print(f"no records in {args.runlog}", file=sys.stderr)
        return 1
    report = summarize(records)
    render_table(report, sys.stderr)
    report.pop("_epochs_table", None)

    rc = 0
    if args.strict:
        try:
            with open(args.reference, encoding="utf-8") as fh:
                reference = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(json.dumps({"metric": "train_report",
                              "error": f"bad reference: {exc}"}))
            print(f"cannot read reference {args.reference}: {exc}",
                  file=sys.stderr)
            return 1
        checks = strict_gate(report, reference)
        report["strict"] = checks
        report["ok"] = all(checks.values())
        for name, ok in checks.items():
            if not ok:
                print(f"STRICT FAIL: {name}", file=sys.stderr)
        rc = 0 if report["ok"] else 1
    print(json.dumps(report))
    return rc


if __name__ == "__main__":
    sys.exit(main())
