"""Stage-cumulative backbone timing at the InLoc image size.

The first real-TPU profile put the ResNet-101 backbone at ~108 ms for a
3200x2400 bf16 forward — ~9 % MXU efficiency against the ~1.8 TFLOP of
conv work, so the backbone is a real optimization target once the corr
pipeline stops dominating. This tool times cumulative truncations at
layer1/layer2/layer3 (the `last_layer` knob) so the slow stage is
identifiable without a profiler trace (stage cost = difference between
consecutive rows; the stem conv+pool is inside the layer1 row).

Usage:
    python tools/bench_backbone.py [--scale 1.0] [--reps 3] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import dataclasses

    import jax.numpy as jnp

    from ncnet_tpu.models.backbone import (
        BackboneConfig,
        backbone_apply,
        backbone_init,
    )

    h = int(3200 * args.scale) // 32 * 32
    w = int(2400 * args.scale) // 32 * 32
    log(f"image {h}x{w} bf16, reps={args.reps}")
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, h, w), jnp.float32)

    base = BackboneConfig(compute_dtype="bfloat16")
    params = backbone_init(jax.random.PRNGKey(1), base)

    for cut in ("layer1", "layer2", "layer3"):
        cfg = dataclasses.replace(base, last_layer=cut)
        try:
            first, dt, _ = timed_steady(
                chain_reps(
                    lambda a, p, cfg=cfg: backbone_apply(cfg, p, a), args.reps
                ),
                x, params, iters=args.iters,
            )
            log(f"-> {cut:8s} cumulative first={first:6.2f}s "
                f"{dt * 1000 / args.reps:7.1f}ms/app")
        except Exception as exc:  # noqa: BLE001
            log(f"-> {cut:8s} FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")


if __name__ == "__main__":
    main()
