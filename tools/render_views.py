"""Orbit-view renderer for .obj meshes (parity target: tools/render_blender.py).

The reference drives Blender to render N orbit views of an object plus
depth / normal / albedo passes, as a synthetic-data side tool. This is a
dependency-free numpy software rasterizer producing the same outputs
(RGB shaded view, depth map, normal map, albedo) without Blender:
triangle z-buffer rasterization with barycentric interpolation and
Lambertian shading.

Usage:
    python tools/render_views.py model.obj --views 8 --size 256 --output_folder out/
Writes view_###.png, depth_###.png, normal_###.png, albedo_###.png.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def load_obj(path: str):
    """Minimal .obj reader: v / f records (faces triangulated by fanning)."""
    verts, faces = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if parts[0] == "v":
                verts.append([float(x) for x in parts[1:4]])
            elif parts[0] == "f":
                idx = [int(tok.split("/")[0]) - 1 for tok in parts[1:]]
                for k in range(1, len(idx) - 1):
                    faces.append([idx[0], idx[k], idx[k + 1]])
    return np.asarray(verts, dtype=np.float64), np.asarray(faces, dtype=np.int64)


def normalize_mesh(verts: np.ndarray, scale: float = 1.0) -> np.ndarray:
    """Center at origin and fit in the unit sphere (times `scale`)."""
    c = (verts.max(axis=0) + verts.min(axis=0)) / 2.0
    v = verts - c
    r = np.linalg.norm(v, axis=1).max()
    return v / (r if r > 0 else 1.0) * scale


def look_at(eye: np.ndarray, target: np.ndarray, up=(0.0, 0.0, 1.0)):
    """World->camera [R|t] with -z... +z forward (camera looks along +z)."""
    fwd = target - eye
    fwd = fwd / np.linalg.norm(fwd)
    right = np.cross(fwd, np.asarray(up, dtype=np.float64))
    if np.linalg.norm(right) < 1e-9:
        right = np.cross(fwd, np.array([0.0, 1.0, 0.0]))
    right /= np.linalg.norm(right)
    down = np.cross(fwd, right)
    R = np.stack([right, down, fwd])
    t = -R @ eye
    return R, t


def render_mesh(
    verts: np.ndarray,
    faces: np.ndarray,
    R: np.ndarray,
    t: np.ndarray,
    size: int = 256,
    focal: float | None = None,
    light_dir=(0.3, -0.5, -0.8),
):
    """Rasterize one view. Returns dict with rgb/depth/normal/albedo arrays."""
    focal = focal if focal is not None else size * 1.2
    K = np.array([[focal, 0, size / 2.0], [0, focal, size / 2.0], [0, 0, 1.0]])

    cam = verts @ R.T + t  # [n, 3]
    tri = cam[faces]  # [f, 3, 3]

    # Face normals in camera space; backface culling.
    n = np.cross(tri[:, 1] - tri[:, 0], tri[:, 2] - tri[:, 0])
    norm_len = np.linalg.norm(n, axis=1, keepdims=True)
    ok = (norm_len[:, 0] > 1e-12) & (tri[:, :, 2].min(axis=1) > 1e-6)
    n = np.where(norm_len > 1e-12, n / np.maximum(norm_len, 1e-12), 0.0)
    facing = n[:, 2] < 0  # normal towards the camera (camera looks +z)
    keep = ok & facing
    tri, n = tri[keep], n[keep]

    light = np.asarray(light_dir, dtype=np.float64)
    light /= np.linalg.norm(light)
    albedo_face = np.full((tri.shape[0], 3), 0.7)
    shade = np.clip(-(n @ light), 0.1, 1.0)

    proj = tri @ K.T
    uv = proj[:, :, :2] / proj[:, :, 2:3]  # [f, 3, 2]

    depth = np.full((size, size), np.inf)
    rgb = np.zeros((size, size, 3))
    normal_map = np.zeros((size, size, 3))
    albedo_map = np.zeros((size, size, 3))

    for f in range(tri.shape[0]):
        p = uv[f]
        zs = tri[f, :, 2]
        xmin = max(int(np.floor(p[:, 0].min())), 0)
        xmax = min(int(np.ceil(p[:, 0].max())) + 1, size)
        ymin = max(int(np.floor(p[:, 1].min())), 0)
        ymax = min(int(np.ceil(p[:, 1].max())) + 1, size)
        if xmin >= xmax or ymin >= ymax:
            continue
        xs, ys = np.meshgrid(np.arange(xmin, xmax) + 0.5, np.arange(ymin, ymax) + 0.5)
        # Barycentric coordinates via the edge-function determinants.
        d = (p[1, 1] - p[2, 1]) * (p[0, 0] - p[2, 0]) + (p[2, 0] - p[1, 0]) * (p[0, 1] - p[2, 1])
        if abs(d) < 1e-12:
            continue
        w0 = ((p[1, 1] - p[2, 1]) * (xs - p[2, 0]) + (p[2, 0] - p[1, 0]) * (ys - p[2, 1])) / d
        w1 = ((p[2, 1] - p[0, 1]) * (xs - p[2, 0]) + (p[0, 0] - p[2, 0]) * (ys - p[2, 1])) / d
        w2 = 1.0 - w0 - w1
        inside = (w0 >= 0) & (w1 >= 0) & (w2 >= 0)
        if not inside.any():
            continue
        # Perspective-correct depth: interpolate 1/z.
        zinv = w0 / zs[0] + w1 / zs[1] + w2 / zs[2]
        z = 1.0 / np.maximum(zinv, 1e-12)
        yy, xx = np.nonzero(inside)
        gy, gx = yy + ymin, xx + xmin
        zf = z[inside]
        closer = zf < depth[gy, gx]
        gy, gx, zf = gy[closer], gx[closer], zf[closer]
        depth[gy, gx] = zf
        rgb[gy, gx] = albedo_face[f] * shade[f]
        normal_map[gy, gx] = (-n[f] + 1.0) / 2.0  # [-1,1] -> [0,1], camera-facing
        albedo_map[gy, gx] = albedo_face[f]

    mask = np.isfinite(depth)
    return {"rgb": rgb, "depth": depth, "normal": normal_map, "albedo": albedo_map, "mask": mask}


def orbit_views(n_views: int, radius: float = 2.5, elevation_deg: float = 20.0):
    """Camera (R, t) for N equally-spaced azimuths at fixed elevation."""
    out = []
    el = np.deg2rad(elevation_deg)
    for i in range(n_views):
        az = 2.0 * np.pi * i / n_views
        eye = radius * np.array([np.cos(az) * np.cos(el), np.sin(az) * np.cos(el), np.sin(el)])
        out.append(look_at(eye, np.zeros(3)))
    return out


def _save_png(path: str, arr: np.ndarray) -> None:
    from PIL import Image

    Image.fromarray((np.clip(arr, 0, 1) * 255).astype(np.uint8)).save(path)


def main(argv=None):
    p = argparse.ArgumentParser(description="Render orbit views of an .obj (no Blender)")
    p.add_argument("obj")
    p.add_argument("--views", type=int, default=30)
    p.add_argument("--output_folder", default="")
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--depth_scale", type=float, default=1.4)
    args = p.parse_args(argv)

    out_dir = args.output_folder or os.path.splitext(args.obj)[0] + "_views"
    os.makedirs(out_dir, exist_ok=True)

    verts, faces = load_obj(args.obj)
    verts = normalize_mesh(verts, args.scale)
    for i, (R, t) in enumerate(orbit_views(args.views)):
        view = render_mesh(verts, faces, R, t, size=args.size)
        _save_png(os.path.join(out_dir, f"view_{i:03d}.png"), view["rgb"])
        d = view["depth"].copy()
        finite = np.isfinite(d)
        dn = np.zeros_like(d)
        if finite.any():
            dmin, dmax = d[finite].min(), d[finite].max()
            dn[finite] = 1.0 - (d[finite] - dmin) / max((dmax - dmin) * args.depth_scale / 1.4, 1e-9)
        _save_png(os.path.join(out_dir, f"depth_{i:03d}.png"), np.repeat(dn[:, :, None], 3, 2))
        _save_png(os.path.join(out_dir, f"normal_{i:03d}.png"), view["normal"])
        _save_png(os.path.join(out_dir, f"albedo_{i:03d}.png"), view["albedo"])
        print(f"rendered view {i + 1}/{args.views}", flush=True)
    print(f"wrote {args.views} views to {out_dir}")


if __name__ == "__main__":
    main()
