"""One-command commit gate: tier-1 tests + lint + bench trend.

Runs the three checks every PR must pass, in order, and prints ONE
aggregated JSON line (the house tool contract)::

    python tools/ci_gate.py
    {"metric": "ci_gate", "value": 1, "ok": true, "checks": {
        "tier1": {"ok": true, "rc": 0, "s": 412.3, ...},
        "lint":  {"ok": true, "rc": 0, ...},
        "bench_trend": {"ok": true, "rc": 0, ...}}}

The checks:

- ``tier1``: the ROADMAP.md tier-1 pytest lane (``-m 'not slow'``,
  CPU-forced, collection errors tolerated per-file) — the same command
  the PR driver enforces, so a green gate here predicts a green driver.
- ``lint``: ``tools/ncnet_lint.py --changed-only`` — the unified
  static-analysis pass over files changed vs the merge base (full-repo
  rules still see everything).
- ``bench_trend``: ``tools/bench_trend.py --strict`` — the committed
  BENCH_r*.json trend; regression vs best prior same-metric round
  fails the gate.

OPTIONAL checks ride behind flags: ``--with-full-lint`` runs
``tools/ncnet_lint.py`` over the WHOLE repo (every rule, no
``--changed-only`` narrowing — the run that must stay clean for the
shared-state race rule's empty-baseline contract). ``--with-tenant-flood`` runs the
multi-tenant QoS chaos contract (``tools/chaos_serving.py
--tenant_flood`` — victims stay 100% available while a flood tenant
bursts 10x), and ``--with-session-chaos`` runs the streaming-session
chaos contract (``tools/chaos_serving.py --session_stream`` — a
mid-stream replica kill must re-seed, never kill the session or drop
a frame). ``--with-quality-report`` runs the match-quality comparator
self-test (``tools/quality_report.py --smoke --strict`` — a tiny
self-hosted server shadow-re-runs every response; rung-0 agreement
must be 1.0 bitwise). ``--with-trace-join`` runs the multi-runlog
trace-assembly self-test (``tools/trace_export.py --selftest`` —
synthetic client + skewed server logs must join into ONE tree with
the clock skew recovered). ``--with-localize-smoke`` runs the
/v1/localize fan-out chaos contract (``tools/chaos_serving.py
--localize_fanout`` — a mid-fan-out replica kill must redispatch the
dead replica's legs, join them into the query trace, and still answer
200 with zero silent pano drops). ``--with-cp-parity`` runs the
algebraic-consensus parity self-test (``python -m ncnet_tpu.ops.cp4d
--selftest`` on CPU — rank-full CP bitwise vs conv4d_reference, the
truncated-rank declared agreement floor, and FFT relative-error
parity). ``--with-train-smoke`` runs a tiny CPU training-throughput
smoke (``tools/bench_train.py --backbone vgg --image-size 48 --batch 2
--iters 2`` — the jitted train step must complete and emit its
one-JSON-line headline). ``--with-elastic-chaos`` runs the elastic
multi-host training chaos gate (``tools/chaos_train.py`` — a 3-host
CPU fleet with one host SIGKILLed mid-epoch; survivors must evict it,
bump the membership generation, resume from the last committed
checkpoint within the step budget, lose no step silently per the
ledger audit, and the surviving curve must pass ``train_report
--strict``). All are off by default because they serve
live traffic for several seconds (or, for trace_join, are covered by
tier-1); a default run still RECORDS them as
``{"skipped": true, "optional": true}`` so the JSON never reads as if
the contract were exercised when it was not.

``--skip NAME`` (repeatable) drops a check — skipped checks are
recorded as ``{"skipped": true}`` and do NOT fail the gate, but the
JSON says so; nothing is silently green. Child stdout/stderr stream to
stderr live (the gate's own stdout stays one JSON line). Exit 0 iff
every non-skipped check passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tier-1 must run CPU-side: this box's sitecustomize auto-dials the
# axon TPU tunnel unless the pool env is dropped (verify skill,
# "Platform gotcha").
_CPU_ENV = {"JAX_PLATFORMS": "cpu"}
_CPU_DROP = ("PALLAS_AXON_POOL_IPS",)

CHECKS = ("tier1", "lint", "bench_trend")
# Opt-in checks: never run by default, never silently green — a
# default run records them as {"skipped": true, "optional": true}.
OPTIONAL_CHECKS = ("full_lint", "tenant_flood", "session_chaos",
                   "quality_report", "trace_join", "localize_smoke",
                   "cp_parity", "train_smoke", "elastic_chaos")


def _run(cmd, timeout_s, cpu_env=False) -> dict:
    env = dict(os.environ)
    if cpu_env:
        env.update(_CPU_ENV)
        for k in _CPU_DROP:
            env.pop(k, None)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        rc, out = proc.returncode, proc.stdout
    except subprocess.TimeoutExpired as exc:
        rc = -1
        out = (exc.stdout or b"").decode("utf-8", "replace") \
            if isinstance(exc.stdout, bytes) else (exc.stdout or "")
        out += f"\n[ci_gate] TIMEOUT after {timeout_s}s"
    sys.stderr.write(out if out.endswith("\n") or not out else out + "\n")
    sys.stderr.flush()
    return {"ok": rc == 0, "rc": rc, "cmd": " ".join(cmd),
            "s": round(time.monotonic() - t0, 1),
            "tail": out.strip().splitlines()[-1] if out.strip() else ""}


def run_tier1(timeout_s: float) -> dict:
    return _run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
         "--continue-on-collection-errors", "-p", "no:cacheprovider",
         "-p", "no:xdist", "-p", "no:randomly"],
        timeout_s, cpu_env=True)


def run_lint(timeout_s: float) -> dict:
    return _run(
        [sys.executable, os.path.join("tools", "ncnet_lint.py"),
         "--changed-only"], timeout_s)


def run_bench_trend(timeout_s: float) -> dict:
    return _run(
        [sys.executable, os.path.join("tools", "bench_trend.py"),
         "--strict"], timeout_s)


def run_full_lint(timeout_s: float) -> dict:
    # The whole-repo pass: every rule over every file, no merge-base
    # narrowing — what the race rule's "exit 0 with an EMPTY baseline"
    # acceptance criterion means in CI terms.
    return _run(
        [sys.executable, os.path.join("tools", "ncnet_lint.py")],
        timeout_s)


def run_tenant_flood(timeout_s: float) -> dict:
    # Short-duration flavor of the chaos contract: same violation
    # rules and self-calibrated rates as the full run, sized so the
    # gate adds seconds, not minutes.
    return _run(
        [sys.executable, os.path.join("tools", "chaos_serving.py"),
         "--tenant_flood", "--duration_s", "6"],
        timeout_s, cpu_env=True)


def run_session_chaos(timeout_s: float) -> dict:
    # Short flavor of the re-seed-not-die contract: 2 replicas, 2
    # streams, and a kill window over EACH replica in turn — whichever
    # replica holds a stream's seed gets killed at some point, so the
    # "a kill window must produce at least one re-seed" violation rule
    # is deterministic, not a coin flip on seed placement.
    return _run(
        [sys.executable, os.path.join("tools", "chaos_serving.py"),
         "--session_stream", "--replicas", "2", "--sessions", "2",
         "--duration_s", "14",
         "--fault", "kill_replica:0@3.0-6.0",
         "--fault", "kill_replica:1@8.0-11.0"],
        timeout_s, cpu_env=True)


def run_quality_report(timeout_s: float) -> dict:
    # The comparator self-test: a self-hosted smoke server with the
    # shadow sampler wide open; --strict fails on any rung-0 re-run
    # that is not 1.0 bitwise (the engine is deterministic) and on a
    # run that recorded no comparisons at all.
    return _run(
        [sys.executable, os.path.join("tools", "quality_report.py"),
         "--smoke", "--strict"],
        timeout_s, cpu_env=True)


def run_localize_smoke(timeout_s: float) -> dict:
    # Short flavor of the localize fan-out chaos contract: 2 replicas,
    # a mid-window replica kill, and the gate's violation rules (zero
    # silent pano drops, redispatched legs joined into the query
    # trace, every query still 200).
    return _run(
        [sys.executable, os.path.join("tools", "chaos_serving.py"),
         "--localize_fanout", "--duration_s", "6", "--panos", "4"],
        timeout_s, cpu_env=True)


def run_cp_parity(timeout_s: float) -> dict:
    # The algebraic-consensus parity self-test (ops/cp4d.py): rank-full
    # CP must be BITWISE equal to conv4d_reference in f32, rank-8 must
    # hold its declared agreement floor, and the FFT arm must match
    # direct convolution to f32 tolerance — all on CPU, no device.
    return _run(
        [sys.executable, "-m", "ncnet_tpu.ops.cp4d", "--selftest"],
        timeout_s, cpu_env=True)


def run_train_smoke(timeout_s: float) -> dict:
    # The smallest real train step that still exercises the full path:
    # VGG backbone at 48 px, batch 2, two timed iterations on CPU. A
    # pass means the jitted two-pass correlation step + Adam update
    # compile and run; the pairs/s headline feeds bench_trend's
    # train_step_pairs_per_s pass-through.
    return _run(
        [sys.executable, os.path.join("tools", "bench_train.py"),
         "--backbone", "vgg", "--image-size", "48", "--batch", "2",
         "--iters", "2"],
        timeout_s, cpu_env=True)


def run_elastic_chaos(timeout_s: float) -> dict:
    # The elastic-training chaos gate: 3 single-process CPU "hosts"
    # under one filesystem membership plane, victim SIGKILLed once its
    # ledger shows mid-epoch progress. Exit 0 iff every check in the
    # tool's one-JSON-line verdict holds (eviction, generation bump,
    # resume-within-budget, zero non-finite losses, ledger tiling,
    # strict curve).
    return _run(
        [sys.executable, os.path.join("tools", "chaos_train.py"),
         "--hosts", "3"],
        timeout_s, cpu_env=True)


def run_trace_join(timeout_s: float) -> dict:
    # The distributed-trace assembly self-test: two synthetic runlogs
    # (client, server skewed +30s) must export as ONE joined tree with
    # the skew recovered by client-send/server-receive pairing.
    return _run(
        [sys.executable, os.path.join("tools", "trace_export.py"),
         "--selftest"],
        timeout_s, cpu_env=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--skip", action="append", default=[],
                    choices=list(CHECKS),
                    help="drop a check (recorded as skipped, not green)")
    ap.add_argument("--tier1-timeout-s", type=float, default=870.0,
                    help="tier-1 pytest wall-clock fence (ROADMAP's "
                         "870 s default)")
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="per-check fence for lint / bench_trend")
    ap.add_argument("--with-full-lint", action="store_true",
                    help="also run ncnet_lint over the whole repo (all "
                         "rules, not --changed-only); off by default, "
                         "recorded as skipped when off")
    ap.add_argument("--with-tenant-flood", action="store_true",
                    help="also run the multi-tenant QoS chaos contract "
                         "(tools/chaos_serving.py --tenant_flood); off "
                         "by default, recorded as skipped when off")
    ap.add_argument("--with-session-chaos", action="store_true",
                    help="also run the streaming-session chaos contract "
                         "(tools/chaos_serving.py --session_stream with "
                         "a mid-stream replica kill); off by default, "
                         "recorded as skipped when off")
    ap.add_argument("--with-quality-report", action="store_true",
                    help="also run the match-quality comparator "
                         "self-test (tools/quality_report.py --smoke "
                         "--strict); off by default, recorded as "
                         "skipped when off")
    ap.add_argument("--with-trace-join", action="store_true",
                    help="also run the multi-runlog trace-assembly "
                         "self-test (tools/trace_export.py --selftest); "
                         "off by default, recorded as skipped when off")
    ap.add_argument("--with-localize-smoke", action="store_true",
                    help="also run the /v1/localize fan-out chaos "
                         "contract (tools/chaos_serving.py "
                         "--localize_fanout, short duration); off by "
                         "default, recorded as skipped when off")
    ap.add_argument("--with-cp-parity", action="store_true",
                    help="also run the algebraic-consensus parity "
                         "self-test (python -m ncnet_tpu.ops.cp4d "
                         "--selftest on CPU); off by default, recorded "
                         "as skipped when off")
    ap.add_argument("--with-train-smoke", action="store_true",
                    help="also run the CPU training-step smoke "
                         "(tools/bench_train.py, tiny VGG config); off "
                         "by default, recorded as skipped when off")
    ap.add_argument("--with-elastic-chaos", action="store_true",
                    help="also run the elastic-training chaos gate "
                         "(tools/chaos_train.py: 3-host CPU fleet, one "
                         "host SIGKILLed mid-epoch, survivors must "
                         "resume with zero silent step loss); off by "
                         "default, recorded as skipped when off")
    ap.add_argument("--chaos-timeout-s", type=float, default=300.0,
                    help="wall-clock fence for the optional chaos checks")
    args = ap.parse_args(argv)

    runners = {
        "tier1": lambda: run_tier1(args.tier1_timeout_s),
        "lint": lambda: run_lint(args.timeout_s),
        "bench_trend": lambda: run_bench_trend(args.timeout_s),
        "full_lint": lambda: run_full_lint(args.timeout_s),
        "tenant_flood": lambda: run_tenant_flood(args.chaos_timeout_s),
        "session_chaos": lambda: run_session_chaos(args.chaos_timeout_s),
        "quality_report": lambda: run_quality_report(
            args.chaos_timeout_s),
        "trace_join": lambda: run_trace_join(args.timeout_s),
        "localize_smoke": lambda: run_localize_smoke(
            args.chaos_timeout_s),
        "cp_parity": lambda: run_cp_parity(args.timeout_s),
        "train_smoke": lambda: run_train_smoke(args.chaos_timeout_s),
        "elastic_chaos": lambda: run_elastic_chaos(args.chaos_timeout_s),
    }
    enabled = {"full_lint": args.with_full_lint,
               "tenant_flood": args.with_tenant_flood,
               "session_chaos": args.with_session_chaos,
               "quality_report": args.with_quality_report,
               "trace_join": args.with_trace_join,
               "localize_smoke": args.with_localize_smoke,
               "cp_parity": args.with_cp_parity,
               "train_smoke": args.with_train_smoke,
               "elastic_chaos": args.with_elastic_chaos}
    checks = {}
    for name in CHECKS + OPTIONAL_CHECKS:
        if name in args.skip or not enabled.get(name, True):
            print(f"[ci_gate] {name}: SKIPPED", file=sys.stderr)
            checks[name] = {"skipped": True}
            if name in OPTIONAL_CHECKS:
                checks[name]["optional"] = True
            continue
        print(f"[ci_gate] {name}: running...", file=sys.stderr)
        checks[name] = runners[name]()
        verdict = "ok" if checks[name]["ok"] else "FAIL"
        print(f"[ci_gate] {name}: {verdict} "
              f"(rc={checks[name]['rc']}, {checks[name]['s']}s)",
              file=sys.stderr)

    ok = all(c.get("ok", True) for c in checks.values())
    print(json.dumps({
        "metric": "ci_gate",
        "value": 1 if ok else 0,
        "unit": "pass",
        "ok": ok,
        "skipped": sorted(args.skip),
        "checks": checks,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
