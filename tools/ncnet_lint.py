"""Run the unified static-analysis pass (docs/ANALYSIS.md) over the repo.

Repo tool convention: stdout carries EXACTLY ONE machine-readable JSON
line (the contract tested in tests/test_bench_contract.py style)::

    {"findings": N, "new": M, "rules": [...], ...}

Finding detail goes to stderr. Exit status is nonzero iff there are
*new* (non-baselined, non-pragma'd) findings — the tier-1 gate and any
session script can consume the exit code directly.

Usage::

    python tools/ncnet_lint.py                  # full repo, all rules
    python tools/ncnet_lint.py --rule lock-order --rule trace-purity
    python tools/ncnet_lint.py --format text    # human-readable findings
    python tools/ncnet_lint.py --changed-only   # only files changed vs
                                                # git merge-base (repo-wide
                                                # rules still see all files)
    python tools/ncnet_lint.py --write-baseline # snapshot findings into
                                                # analysis/baseline.json
                                                # (fill in the reasons!)
    python tools/ncnet_lint.py --write-docs     # regenerate the generated
                                                # lock-order + shared-state
                                                # tables in docs/ANALYSIS.md

The baseline is for deliberate, commented exceptions only — fix real
violations (or pragma them with a justification) instead of baselining.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.analysis import Baseline, Repo, get_rules, run_rules
from ncnet_tpu.analysis.rules import rule_ids
from ncnet_tpu.analysis.rules import lock_order, races


def _changed_files(root: str, base: str) -> Optional[List[str]]:
    """Repo-relative ncnet_tpu/*.py files changed vs the merge-base
    with ``base`` (plus untracked), or None when git can't answer —
    the caller falls back to the full file set, never a silent skip."""

    def git(*args: str) -> str:
        return subprocess.check_output(
            ("git", "-C", root) + args, text=True,
            stderr=subprocess.DEVNULL)

    try:
        mb = git("merge-base", "HEAD", base).strip()
        changed = git("diff", "--name-only", mb).splitlines()
        changed += git("ls-files", "--others",
                       "--exclude-standard").splitlines()
    except (OSError, subprocess.CalledProcessError):
        return None
    return sorted({
        p for p in changed
        if p.startswith("ncnet_tpu/") and p.endswith(".py")
    })


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="unified static-analysis pass (docs/ANALYSIS.md)")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="ID",
                        help=f"run only this rule (repeatable); known: "
                             f"{', '.join(rule_ids())}")
    parser.add_argument("--format", choices=("json", "text"),
                        default="json",
                        help="json: one summary line on stdout, detail "
                             "on stderr; text: findings on stdout")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs the git "
                             "merge-base (repo-wide rules still see "
                             "every file)")
    parser.add_argument("--base", default="main",
                        help="merge-base ref for --changed-only "
                             "(default: main)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current findings into "
                             "analysis/baseline.json (add reasons "
                             "before committing)")
    parser.add_argument("--write-docs", action="store_true",
                        help="regenerate the generated lock-order and "
                             "shared-state tables in docs/ANALYSIS.md, "
                             "then lint")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="baseline file (default: "
                             "ncnet_tpu/analysis/baseline.json)")
    parser.add_argument("--root", default=_REPO,
                        help="repo root to lint (default: this repo; "
                             "fixture repos in tests use this)")
    args = parser.parse_args(argv)

    t0 = time.time()
    selected = None
    if args.changed_only:
        selected = _changed_files(args.root, args.base)
        if selected is None:
            print("ncnet_lint: git unavailable; linting the full repo",
                  file=sys.stderr)
    repo = Repo(root=args.root, selected=selected)

    docs_updated = False
    if args.write_docs:
        docs_updated = lock_order.write_docs_block(repo)
        docs_updated = races.write_docs_block(repo) or docs_updated

    try:
        rules = get_rules(args.rule)
    except KeyError as exc:
        print(f"ncnet_lint: {exc.args[0]}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or Baseline.default_path(repo)
    baseline = Baseline.load(baseline_path)
    report = run_rules(repo, rules, baseline)

    if args.write_baseline:
        Baseline.from_findings(report.findings).save(baseline_path)
        # Re-split against the fresh baseline: everything just written
        # is by definition no longer "new".
        report = run_rules(repo, rules, Baseline.load(baseline_path))

    out = report.to_dict()
    out["duration_s"] = round(time.time() - t0, 3)
    if args.changed_only:
        out["changed_only"] = True
    if args.write_docs:
        out["docs_updated"] = docs_updated
    if args.write_baseline:
        out["baseline_written"] = baseline_path

    detail = sys.stdout if args.format == "text" else sys.stderr
    for f in report.findings:
        marker = "NEW " if f in report.new else "baselined "
        print(f"{marker}{f.rule} {f.location()} {f.message}", file=detail)
    if args.format == "json":
        print(json.dumps(out, sort_keys=True))
    else:
        print(f"{out['findings']} finding(s), {out['new']} new, "
              f"{out['suppressed']} pragma-suppressed, "
              f"{out['files']} file(s), rules: {', '.join(out['rules'])}",
              file=sys.stdout)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
