"""Generic headline A/B over trace-time env knobs (one dial, fenced runs).

Sibling of bench_strategies_ab.py with the runs supplied on the command
line — for quick hardware windows where editing a matrix in code wastes
tunnel minutes:

    python tools/bench_knob_ab.py \
        "chunk25=NCNET_CONSENSUS_CHUNK_I:25" \
        "ss=NCNET_CONSENSUS_STRATEGIES:conv2d_stacked,conv2d_stacked" \
        "combo=NCNET_PANO_BACKBONE_BATCH:6;NCNET_BENCH_HIT_PATH:1" \
        "anchor="

Each arg is label=VAR:value[;VAR:value...] — ';' separates pairs so
comma-valued knobs (the strategy lists) pass through. Empty env = an
all-defaults anchor. Every run emits bench.py's one-line JSON to stdout.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()

# Knobs any run may set; stripped before each run so combos never leak
# between lines (mirrors tpu_session.py's matrix hygiene).
KNOBS = (
    "NCNET_CONSENSUS_STRATEGIES", "NCNET_FUSE_MUTUAL_EXTRACT",
    "NCNET_FUSE_CORR_MAXES", "NCNET_CONSENSUS_KL_FOLD",
    "NCNET_INLOC_FEAT_UNIT", "NCNET_BACKBONE_NHWC",
    "NCNET_CONSENSUS_CL", "NCNET_CONSENSUS_CHUNK_I",
    "NCNET_PANO_BACKBONE_BATCH", "NCNET_BACKBONE_CONV1_FOLD",
    "NCNET_BENCH_HIT_PATH", "NCNET_BENCH_KEEP_TRACE",
    "NCNET_PALLAS_TILE_B_CELLS", "NCNET_PALLAS_CORR_IMPL",
    "NCNET_PALLAS_GRID_ORDER", "NCNET_EXTRACT_IMPL",
)


def log(msg):
    print(f"[ab {time.time() - _T0:7.1f}s] {msg}", flush=True)


def parse_runs(specs):
    """label=VAR:value[;VAR:value...] specs -> [(label, env_dict)].

    ';' separates pairs (not ',': strategy-list knobs are comma-valued).
    Unknown knobs SystemExit before any dial — a typo'd variable must
    not silently bench the default configuration under its label.
    """
    runs = []
    for spec in specs:
        label, sep, envspec = spec.partition("=")
        if not sep:
            # A forgotten '=' would otherwise bench plain defaults
            # under the typo'd label; an anchor run must say so with an
            # explicit trailing '='.
            raise SystemExit(f"missing '=' in run spec {spec!r}")
        env = {}
        for pair in filter(None, envspec.split(";")):
            var, _, val = pair.partition(":")
            if var not in KNOBS:
                raise SystemExit(f"unknown knob {var!r} in {spec!r}")
            if ":" in val:
                # ',' used between pairs folds the next VAR:value into
                # this value (split is on ';'), silently leaving later
                # knobs unset; no legal knob value contains ':'.
                raise SystemExit(
                    f"':' inside value {val!r} in {spec!r} — separate "
                    "pairs with ';'"
                )
            env[var] = val
        runs.append((label, env))
    return runs


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("runs", nargs="+",
                   help="label=VAR:value[;VAR:value...] per run")
    p.add_argument("--dial_timeout", type=float, default=300.0)
    p.add_argument("--fence", type=float, default=1500.0)
    args = p.parse_args(argv)

    runs = parse_runs(args.runs)

    from ncnet_tpu.utils.profiling import run_bench_matrix

    return run_bench_matrix(
        runs, dial_timeout=args.dial_timeout, fence=args.fence,
        knobs=KNOBS, log=log,
    )


if __name__ == "__main__":
    sys.exit(main())
