"""Train -> checkpoint -> eval -> export -> reconvert, as ONE pipeline.

VERDICT r4 missing #2 / next-round #5b: the training loop and the eval
harness are each tested, but no run had produced a checkpoint whose eval
was then recorded, and no trained checkpoint had made the round trip
through the reference .pth.tar format. This tool proves the whole chain
on whatever backend is up (the TPU session runs it as its `train_e2e`
phase; CPU covers the offline test):

  1. build the synthetic affine-warp corpus (known GT correspondences —
     tools/sanity_train_improves_pck.build_dataset);
  2. train the reference recipe shape end-to-end (``cli/train.py``,
     parity: train.py:39-41/191-206) to a best/ checkpoint;
  3. eval PCK@0.1 from that checkpoint (``cli/eval_pf_pascal.py``);
  4. export it to the reference's .pth.tar layout
     (``cli/export_checkpoint.py``), reconvert it back
     (``cli/convert_checkpoint.py``), verify bit-exactness, and re-eval
     from the reconverted copy — the PCK must be identical.

Emits ONE JSON line:
  {"pipeline": "train_eval_export", "backend": ..., "pck": ...,
   "pck_reconverted": ..., "roundtrip_exact": true, ...}

Usage: python tools/train_eval_pipeline.py [--out DIR] [--size 96]
           [--epochs 2] [--image_size 96] [--batch_size 4]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _load_sanity():
    path = os.path.join(os.path.dirname(__file__),
                        "sanity_train_improves_pck.py")
    spec = importlib.util.spec_from_file_location("sanity_pck", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _params_equal(a, b):
    import jax

    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    if len(leaves_a) != len(leaves_b):
        return False
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b)
    )


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/train_eval_pipeline")
    p.add_argument("--size", type=int, default=96,
                   help="synthetic corpus image size")
    p.add_argument("--image_size", type=int, default=96)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--n_train", type=int, default=24)
    p.add_argument("--backbone", type=str, default="vgg",
                   help="vgg keeps the CPU/offline path fast; the TPU "
                   "session can pass resnet101 (the reference default)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import jax

    backend = jax.default_backend()
    sanity = _load_sanity()
    rng = np.random.default_rng(args.seed)
    root = args.out
    t0 = time.time()
    sanity.build_dataset(root, rng, size=args.size,
                         n_train=args.n_train)
    print(f"[pipeline] corpus under {root}", flush=True)

    # 2. Train end-to-end via the real CLI (weak inlier-count loss,
    # checkpoints with config + optimizer state travelling along).
    from ncnet_tpu.cli import train as train_cli

    t_train = time.time()
    train_cli.main([
        "--dataset_image_path", root,
        "--dataset_csv_path", os.path.join(root, "image_pairs"),
        "--num_epochs", str(args.epochs),
        "--batch_size", str(args.batch_size),
        "--image_size", str(args.image_size),
        "--backbone", args.backbone,
        "--ncons_kernel_sizes", "3", "3",
        "--ncons_channels", "16", "1",
        "--result_model_dir", os.path.join(root, "models"),
        "--num_workers", "2",
        "--seed", str(args.seed),
        "--log_interval", "10",
    ])
    train_s = time.time() - t_train
    runs = os.path.join(root, "models")
    run = max(os.listdir(runs),
              key=lambda d: os.path.getmtime(os.path.join(runs, d)))
    best = os.path.join(runs, run, "best")
    print(f"[pipeline] trained checkpoint: {best}", flush=True)

    # 3. Eval PCK from the trained checkpoint.
    pck = sanity.run_pck(root, best, args.image_size)
    print(f"[pipeline] PCK@0.1 from trained checkpoint: {pck:.2f}%",
          flush=True)

    # 4. Export to the reference layout, reconvert, verify, re-eval.
    from ncnet_tpu.cli.convert_checkpoint import main as convert_main
    from ncnet_tpu.cli.export_checkpoint import main as export_main
    from ncnet_tpu.training.checkpoint import load_checkpoint

    # The converters signal verify failure by raising (export: assertion;
    # convert: sys.exit(1)) — catch both so the structured JSON error
    # record is what lands in the TPU session log.
    pth = os.path.join(root, "exported.pth.tar")
    reconv = os.path.join(root, "reconverted")
    for step_name, fn, argv_ in (
        ("export", export_main, [best, pth]),
        ("reconvert", convert_main, [pth, reconv]),
    ):
        try:
            rc = fn(argv_)
        except (SystemExit, Exception) as exc:  # noqa: BLE001
            print(json.dumps({"pipeline": "train_eval_export",
                              "error": f"{step_name}: "
                              f"{type(exc).__name__}: {exc}"}))
            return 1
        if rc not in (0, None):
            print(json.dumps({"pipeline": "train_eval_export",
                              "error": f"{step_name} rc={rc}"}))
            return 1

    params_a = load_checkpoint(best)["params"]
    params_b = load_checkpoint(os.path.join(reconv, "best"))["params"]
    exact = _params_equal(params_a, params_b)
    pck_b = sanity.run_pck(root, os.path.join(reconv, "best"),
                           args.image_size)
    print(f"[pipeline] PCK@0.1 from reconverted checkpoint: {pck_b:.2f}%",
          flush=True)

    rec = {
        "pipeline": "train_eval_export",
        "backend": backend,
        "backbone": args.backbone,
        "epochs": args.epochs,
        "n_train_pairs": args.n_train,
        "image_size": args.image_size,
        "train_s": round(train_s, 1),
        "pck": pck,
        "pck_reconverted": pck_b,
        "roundtrip_exact": bool(exact),
        "total_s": round(time.time() - t0, 1),
    }
    print(json.dumps(rec), flush=True)
    return 0 if (exact and pck == pck_b) else 1


if __name__ == "__main__":
    sys.exit(main())
