"""A/B match-extraction formulations at the InLoc post-consensus shape.

corr_to_matches was the slowest stage of the first real-TPU profile
(754 ms — reductions over a non-minor axis of the 56 M-element tensor);
the minor-axis rewrite landed blind between tunnel windows. This tool
times the current formulation and its pieces so the next regression is
attributable: per-direction cost, the transpose, the softmax logsumexp
pass, and the delta4d relocalization gathers.

Reps are chained inside one jit via lax.scan (see bench_corr_pool.py:
per-call timing through the tunnel has an ~85 ms floor).

Usage:
    python tools/bench_extract.py [--scale 1.0] [--reps 4] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.evals.inloc import (
        inloc_device_matches,
        inloc_matches_from_consensus,
    )
    from ncnet_tpu.ops.matches import corr_to_matches

    ii = max(int(100 * args.scale) // 4 * 4, 8)
    jj = max(int(75 * args.scale) // 4 * 4, 8)
    log(f"corr [1,1,{ii},{jj},{ii},{jj}] bf16, k=2, reps={args.reps}")

    key = jax.random.PRNGKey(0)
    corr = jax.random.normal(
        key, (1, 1, ii, jj, ii, jj), jnp.float32
    ).astype(jnp.bfloat16)
    deltas = tuple(
        jax.random.randint(jax.random.PRNGKey(7 + i), corr.shape, 0, 2)
        for i in range(4)
    )

    from ncnet_tpu.ops.matches import encode_packed_offsets

    packed = encode_packed_offsets(*deltas, 2).astype(jnp.int32)

    def full(c):
        return inloc_device_matches(c, delta4d=deltas, k_size=2, impl="xla")

    def full_packed(c):
        return inloc_device_matches(c, delta4d=packed, k_size=2, impl="xla")

    def full_pallas_stats(c):
        # One-read bidirectional statistics kernel (ops/extract_kernel.py).
        return inloc_device_matches(c, delta4d=packed, k_size=2, impl="pallas")

    def fused_mutual_pallas(c):
        # Final mutual filter evaluated inside the kernel (two reads total).
        return inloc_matches_from_consensus(
            c, delta4d=packed, k_size=2, impl="pallas"
        )

    def mutual_then_extract_xla(c):
        # The materializing equivalent of fused_mutual_pallas: what the
        # default pipeline pays for mutual2 + extraction together.
        return inloc_matches_from_consensus(
            c, delta4d=packed, k_size=2, impl="xla"
        )

    def dir_b2a(c):  # native minor-axis reduction, no transpose
        return corr_to_matches(
            c, delta4d=deltas, k_size=2, do_softmax=True, scale="positive",
            invert_matching_direction=True,
        )

    def dir_a2b(c):  # transposed direction
        return corr_to_matches(
            c, delta4d=deltas, k_size=2, do_softmax=True, scale="positive",
        )

    def dir_a2b_nosoftmax(c):
        return corr_to_matches(
            c, delta4d=deltas, k_size=2, do_softmax=False, scale="positive",
        )

    def dir_b2a_nodelta(c):
        return corr_to_matches(
            c, k_size=2, do_softmax=True, scale="positive",
            invert_matching_direction=True,
        )

    # Pallas candidates first: the XLA formulations are the known compile
    # hazard at this shape (a >20 min remote-compile hang on 2026-07-31
    # starved the whole session queue), so they run last under a fence.
    # The per-direction XLA diagnostics and the decoded-deltas-tuple
    # variant were retired after the 04:27 session: tuple deltas fail the
    # tunnel's remote-compile size cap outright (HTTP 413) and the dir
    # splits burned a 420 s fence each to re-learn what the three kept
    # baselines already show (pallas 16.6 / fused-mutual 17.3 /
    # packed-xla 17.7 ms).
    candidates = {
        "full pallas-stats": full_pallas_stats,
        "fused mutual+extract": fused_mutual_pallas,
        "full packed-deltas": full_packed,
        "mutual+extract (xla)": mutual_then_extract_xla,
    }
    del full, dir_b2a, dir_a2b, dir_a2b_nosoftmax, dir_b2a_nodelta  # retired

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    for name, fn in candidates.items():
        try:
            first, dt, _ = run_with_alarm(
                420,
                timed_steady,
                chain_reps(fn, args.reps),
                corr,
                iters=args.iters,
            )
            log(f"{name:22s} first={first:6.2f}s "
                f"-> {dt * 1000 / args.reps:7.1f}ms/app")
        except AlarmTimeout:
            log(f"{name:22s} TIMED OUT (>420s compile/run)")
        except Exception as exc:  # noqa: BLE001
            log(f"{name:22s} FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")


if __name__ == "__main__":
    main()
