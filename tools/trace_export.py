"""Export a structured run log as Chrome-trace / Perfetto JSON.

Converts the ``runlog-*.jsonl`` span records (schema v2,
docs/OBSERVABILITY.md) into the Chrome trace event format that
``chrome://tracing`` and https://ui.perfetto.dev load directly::

    python tools/trace_export.py out/runlog-serving-*.jsonl -o trace.json

Mapping:

* every ``kind: "span"`` record becomes one complete ("X") event; its
  begin timestamp is ``t_wall - dur_s`` (spans are logged at close);
* each ``trace_id`` gets its own thread row (tid), so one serving
  request's admit → queue_wait → batch_assemble → device → respond
  chain reads as one swimlane; spans without trace ids share an
  "untraced" row;
* other events (``request``, ``compile``, ``stall``, ...) become
  instant ("i") events on their trace's row; bulky payloads
  (``metrics`` snapshots) are elided to a marker;
* process/thread names are emitted as metadata ("M") events.

``--profile_dir`` additionally merges the newest ``jax.profiler``
capture under that directory (the ``<dir>/plugins/profile/<stamp>/``
layout ``utils/profiling.trace_context`` writes) into the same file,
aligned on wall-clock time via the ``profile_capture`` run-log event —
host-side request spans and the device-side XLA op timeline in one
Perfetto view.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: pid of the run-log (host) process row in the exported trace.
RUNLOG_PID = 1

#: Profiler planes keep their own pids, offset past the run-log's.
PROFILE_PID_BASE = 1000

#: Events whose payloads are too bulky to inline as instant-event args.
_ELIDE_ARGS_EVENTS = frozenset({"metrics", "run_start"})


def load_records(path: str) -> List[dict]:
    """All complete JSON records of one run log (same crash tolerance
    as tools/obs_report.load_run)."""
    records = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


_ENVELOPE = frozenset({"v", "run_id", "event", "t_wall", "t_mono",
                       "kind", "dur_s", "trace_id", "span_id",
                       "parent_id"})


def _args_of(rec: dict) -> dict:
    """Scalar non-envelope fields -> Chrome event args."""
    out = {}
    for k, v in rec.items():
        if k in _ENVELOPE:
            continue
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
    for k in ("span_id", "parent_id"):
        if rec.get(k) is not None:
            out[k] = rec[k]
    return out


def records_to_trace(records: List[dict]) -> List[dict]:
    """Run-log records -> Chrome trace events (sorted by ts, metadata
    first; ts is monotone within every (pid, tid))."""
    tids: Dict[Optional[str], int] = {None: 0}

    def tid_of(trace_id: Optional[str]) -> int:
        if trace_id not in tids:
            tids[trace_id] = len(tids)
        return tids[trace_id]

    events: List[dict] = []
    component = None
    for rec in records:
        if rec.get("event") == "run_start" and component is None:
            component = rec.get("component")
        t_wall = rec.get("t_wall")
        if t_wall is None:
            continue
        tid = tid_of(rec.get("trace_id"))
        if rec.get("kind") == "span" and rec.get("dur_s") is not None:
            dur_s = float(rec["dur_s"])
            events.append({
                "name": rec.get("event", "?"),
                "ph": "X",
                "ts": (float(t_wall) - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                "pid": RUNLOG_PID,
                "tid": tid,
                "args": _args_of(rec),
            })
        else:
            name = rec.get("event", "?")
            args = ({} if name in _ELIDE_ARGS_EVENTS else _args_of(rec))
            events.append({
                "name": name,
                "ph": "i",
                "ts": float(t_wall) * 1e6,
                "pid": RUNLOG_PID,
                "tid": tid,
                "s": "t",  # thread-scoped instant marker
                "args": args,
            })
    events.sort(key=lambda e: e["ts"])

    meta: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": RUNLOG_PID,
        "args": {"name": f"runlog {component or '?'}"},
    }]
    for trace_id, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        label = "untraced" if trace_id is None else f"trace {trace_id[:8]}"
        meta.append({
            "name": "thread_name", "ph": "M", "pid": RUNLOG_PID,
            "tid": tid, "args": {"name": label},
        })
    return meta + events


def _import_traceagg():
    try:
        from ncnet_tpu.utils import traceagg
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from ncnet_tpu.utils import traceagg
    return traceagg


def merge_profile(
    trace_events: List[dict],
    profile_dir: str,
    records: List[dict],
) -> Tuple[str, int]:
    """Append the newest jax.profiler capture under ``profile_dir``,
    shifted onto the run log's wall-clock timebase.

    The profiler's ``ts`` values are in its own timebase; the run log's
    ``profile_capture`` (phase=start) event records the wall time the
    capture began, so ``wall_start*1e6 - min(ts)`` is the alignment
    offset. Without that event the capture is appended unshifted — the
    two timelines are still in one file, just not co-registered.
    Returns (capture path, number of merged events).
    """
    traceagg = _import_traceagg()
    path, prof_events = traceagg.load_events(profile_dir)
    start = next(
        (r for r in records
         if r.get("event") == "profile_capture" and r.get("phase") == "start"),
        None,
    )
    offset = 0.0
    ts_vals = [float(e["ts"]) for e in prof_events if "ts" in e]
    if start is not None and ts_vals:
        wall = float(start.get("t_capture_wall", start.get("t_wall", 0.0)))
        offset = wall * 1e6 - min(ts_vals)
    n = 0
    for e in prof_events:
        e = dict(e)
        if "pid" in e:
            e["pid"] = PROFILE_PID_BASE + int(e["pid"])
        if "ts" in e:
            e["ts"] = float(e["ts"]) + offset
        trace_events.append(e)
        n += 1
    return path, n


def export(log_path: str, out_path: str,
           profile_dir: Optional[str] = None) -> dict:
    """Convert one run log (plus optional profiler capture) and write
    the Chrome-trace JSON; returns the trace dict."""
    records = load_records(log_path)
    events = records_to_trace(records)
    if profile_dir:
        merge_profile(events, profile_dir, records)
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return trace


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="run-log JSONL file")
    ap.add_argument("-o", "--out", default="",
                    help="output path (default <log>.trace.json)")
    ap.add_argument("--profile_dir", default="",
                    help="merge the newest jax.profiler capture under "
                         "this directory (plugins/profile/<stamp>/)")
    args = ap.parse_args(argv)
    out = args.out or (os.path.splitext(args.log)[0] + ".trace.json")
    trace = export(args.log, out, profile_dir=args.profile_dir or None)
    n_x = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    n_i = sum(1 for e in trace["traceEvents"] if e.get("ph") == "i")
    print(f"wrote {out}: {len(trace['traceEvents'])} events "
          f"({n_x} spans, {n_i} instants)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
