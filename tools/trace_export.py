"""Export structured run logs as Chrome-trace / Perfetto JSON.

Converts the ``runlog-*.jsonl`` span records (schema v2,
docs/OBSERVABILITY.md) into the Chrome trace event format that
``chrome://tracing`` and https://ui.perfetto.dev load directly::

    python tools/trace_export.py out/runlog-serving-*.jsonl -o trace.json
    python tools/trace_export.py client.jsonl replica0.jsonl \\
        replica1.jsonl -o joined.json

Mapping:

* every ``kind: "span"`` record becomes one complete ("X") event; its
  begin timestamp is ``t_wall - dur_s`` (spans are logged at close);
* each ``trace_id`` gets its own thread row (tid), so one serving
  request's admit → queue_wait → batch_assemble → device → respond
  chain reads as one swimlane; spans without trace ids share an
  "untraced" row;
* other events (``request``, ``compile``, ``stall``, ...) become
  instant ("i") events on their trace's row; bulky payloads
  (``metrics`` snapshots) are elided to a marker;
* process/thread names are emitted as metadata ("M") events.

Multi-runlog join (docs/OBSERVABILITY.md, "Cross-process tracing"):
given N logs — a client's plus the replicas' — each becomes its own
Perfetto process row, spans join across files by ``trace_id`` /
``parent_id`` (the ``X-NCNet-Trace`` propagation makes ids global),
and per-process clock skew is corrected by pairing each remote-edge
child span (server side) with its parent span (client side): the
midpoints of the two spans measure the same instant on two clocks, so
their averaged difference per file pair is that pair's skew. File 0 is
the reference timebase. A rotated log's segment set
(``run.jsonl`` + ``run.00N.jsonl``, obs/events.runlog_segments) is
read transparently — pass the base path.

``--profile_dir`` additionally merges the newest ``jax.profiler``
capture under that directory (the ``<dir>/plugins/profile/<stamp>/``
layout ``utils/profiling.trace_context`` writes) into the same file,
aligned on wall-clock time via the ``profile_capture`` run-log event —
host-side request spans and the device-side XLA op timeline in one
Perfetto view.

Stdout is exactly one JSON line (the bench-contract idiom:
``{"metric": "trace_export", ...}``); the human summary goes to
stderr. ``--selftest`` builds two synthetic runlogs with a known
clock skew, joins them, and verifies the tree + the correction.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple, Union

#: pid of the run-log (host) process row in the exported trace.
RUNLOG_PID = 1

#: Profiler planes keep their own pids, offset past the run-log's.
PROFILE_PID_BASE = 1000

#: Events whose payloads are too bulky to inline as instant-event args.
_ELIDE_ARGS_EVENTS = frozenset({"metrics", "run_start"})


def _segments(path: str) -> List[str]:
    """The (possibly rotated) log's segment set, oldest first — the
    canonical lister lives in ncnet_tpu.obs.events.runlog_segments."""
    try:
        from ncnet_tpu.obs.events import runlog_segments
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from ncnet_tpu.obs.events import runlog_segments
    return runlog_segments(path)


def load_records(path: str) -> List[dict]:
    """All complete JSON records of one run log — reading a rotated
    log's whole segment set (same crash tolerance as
    tools/obs_report.load_run)."""
    records = []
    for seg in _segments(path):
        if not os.path.exists(seg):
            continue
        with open(seg, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return records


_ENVELOPE = frozenset({"v", "run_id", "event", "t_wall", "t_mono",
                       "kind", "dur_s", "trace_id", "span_id",
                       "parent_id"})


def _args_of(rec: dict) -> dict:
    """Scalar non-envelope fields -> Chrome event args."""
    out = {}
    for k, v in rec.items():
        if k in _ENVELOPE:
            continue
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
    for k in ("span_id", "parent_id"):
        if rec.get(k) is not None:
            out[k] = rec[k]
    return out


def records_to_trace(records: List[dict], pid: int = RUNLOG_PID,
                     ts_offset_s: float = 0.0) -> List[dict]:
    """Run-log records -> Chrome trace events (sorted by ts, metadata
    first; ts is monotone within every (pid, tid)).

    ``pid`` is the Perfetto process row this log renders as (each file
    of a multi-log join gets its own); ``ts_offset_s`` is added to
    every wall timestamp — the clock-skew correction onto the
    reference file's timebase (:func:`clock_offsets`)."""
    tids: Dict[Optional[str], int] = {None: 0}

    def tid_of(trace_id: Optional[str]) -> int:
        if trace_id not in tids:
            tids[trace_id] = len(tids)
        return tids[trace_id]

    events: List[dict] = []
    component = None
    for rec in records:
        if rec.get("event") == "run_start" and component is None:
            component = rec.get("component")
        t_wall = rec.get("t_wall")
        if t_wall is None:
            continue
        t_wall = float(t_wall) + ts_offset_s
        tid = tid_of(rec.get("trace_id"))
        if rec.get("kind") == "span" and rec.get("dur_s") is not None:
            dur_s = float(rec["dur_s"])
            events.append({
                "name": rec.get("event", "?"),
                "ph": "X",
                "ts": (t_wall - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                "pid": pid,
                "tid": tid,
                "args": _args_of(rec),
            })
        else:
            name = rec.get("event", "?")
            args = ({} if name in _ELIDE_ARGS_EVENTS else _args_of(rec))
            events.append({
                "name": name,
                "ph": "i",
                "ts": t_wall * 1e6,
                "pid": pid,
                "tid": tid,
                "s": "t",  # thread-scoped instant marker
                "args": args,
            })
    events.sort(key=lambda e: e["ts"])

    meta: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": f"runlog {component or '?'}"},
    }]
    for trace_id, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        label = "untraced" if trace_id is None else f"trace {trace_id[:8]}"
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid,
            "tid": tid, "args": {"name": label},
        })
    return meta + events


def clock_offsets(record_sets: Sequence[List[dict]]) -> List[float]:
    """Per-file wall-clock correction (seconds to ADD to file i's
    timestamps), file 0 the reference at 0.0.

    Every remote edge — a span in file i whose ``parent_id`` resolves
    in file j but not locally — pairs two measurements of (nearly) the
    same instant on two clocks: the child span's midpoint on i's clock
    and its parent's midpoint on j's. Span records carry close-time
    ``t_wall`` and ``dur_s``, so midpoint = ``t_wall - dur_s/2``. The
    per-pair deltas are averaged (network latency is symmetric noise
    around the true skew) and offsets propagate breadth-first from
    file 0; a file with no edge path to the reference keeps 0.0."""
    spans = []  # per file: span_id -> record
    for records in record_sets:
        by_id = {}
        for r in records:
            if r.get("kind") == "span" and r.get("span_id") \
                    and r.get("dur_s") is not None:
                by_id[r["span_id"]] = r
        spans.append(by_id)

    def _mid(rec: dict) -> float:
        return float(rec["t_wall"]) - float(rec["dur_s"]) / 2.0

    # edge (i, j) -> list of (parent_mid_on_j - child_mid_on_i)
    deltas: Dict[Tuple[int, int], List[float]] = {}
    for i, by_id in enumerate(spans):
        for rec in by_id.values():
            parent = rec.get("parent_id")
            if not parent or parent in by_id:
                continue  # local edge (or root): no clock crossing
            for j, other in enumerate(spans):
                if j == i or parent not in other:
                    continue
                deltas.setdefault((i, j), []).append(
                    _mid(other[parent]) - _mid(rec))
                break

    offsets = [0.0] * len(record_sets)
    seen = {0}
    frontier = [0]
    while frontier:
        j = frontier.pop(0)
        for (a, b), ds in deltas.items():
            d = sum(ds) / len(ds)
            # (a, b): child file a is skewed by -d relative to parent
            # file b, so a's correction is b's plus d (and vice versa).
            if b == j and a not in seen:
                offsets[a] = offsets[j] + d
                seen.add(a)
                frontier.append(a)
            elif a == j and b not in seen:
                offsets[b] = offsets[j] - d
                seen.add(b)
                frontier.append(b)
    return offsets


def _import_traceagg():
    try:
        from ncnet_tpu.utils import traceagg
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from ncnet_tpu.utils import traceagg
    return traceagg


def merge_profile(
    trace_events: List[dict],
    profile_dir: str,
    records: List[dict],
) -> Tuple[str, int]:
    """Append the newest jax.profiler capture under ``profile_dir``,
    shifted onto the run log's wall-clock timebase.

    The profiler's ``ts`` values are in its own timebase; the run log's
    ``profile_capture`` (phase=start) event records the wall time the
    capture began, so ``wall_start*1e6 - min(ts)`` is the alignment
    offset. Without that event the capture is appended unshifted — the
    two timelines are still in one file, just not co-registered.
    Returns (capture path, number of merged events).
    """
    traceagg = _import_traceagg()
    path, prof_events = traceagg.load_events(profile_dir)
    start = next(
        (r for r in records
         if r.get("event") == "profile_capture" and r.get("phase") == "start"),
        None,
    )
    offset = 0.0
    ts_vals = [float(e["ts"]) for e in prof_events if "ts" in e]
    if start is not None and ts_vals:
        wall = float(start.get("t_capture_wall", start.get("t_wall", 0.0)))
        offset = wall * 1e6 - min(ts_vals)
    n = 0
    for e in prof_events:
        e = dict(e)
        if "pid" in e:
            e["pid"] = PROFILE_PID_BASE + int(e["pid"])
        if "ts" in e:
            e["ts"] = float(e["ts"]) + offset
        trace_events.append(e)
        n += 1
    return path, n


def export(log_path: Union[str, Sequence[str]], out_path: str,
           profile_dir: Optional[str] = None) -> dict:
    """Convert one or more run logs (plus optional profiler capture)
    and write the Chrome-trace JSON; returns the trace dict.

    A list of paths is the multi-runlog join: file i renders as pid
    ``RUNLOG_PID + i``, clock-skew-corrected onto file 0's timebase
    (:func:`clock_offsets`); ``otherData`` records the inputs and the
    applied offsets."""
    paths = [log_path] if isinstance(log_path, str) else list(log_path)
    record_sets = [load_records(p) for p in paths]
    offsets = (clock_offsets(record_sets) if len(record_sets) > 1
               else [0.0] * len(record_sets))
    events: List[dict] = []
    for i, records in enumerate(record_sets):
        events.extend(records_to_trace(records, pid=RUNLOG_PID + i,
                                       ts_offset_s=offsets[i]))
    if profile_dir:
        merge_profile(events, profile_dir, record_sets[0])
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "logs": paths,
            "clock_offsets_s": {p: round(o, 6)
                                for p, o in zip(paths, offsets)},
        },
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return trace


def _cross_file_traces(record_sets: Sequence[List[dict]]) -> int:
    """How many trace ids have span records in more than one file —
    the joined-tree count the summary line reports."""
    per_file = []
    for records in record_sets:
        per_file.append({r["trace_id"] for r in records
                         if r.get("kind") == "span" and r.get("trace_id")})
    counts: Dict[str, int] = {}
    for ids in per_file:
        for t in ids:
            counts[t] = counts.get(t, 0) + 1
    return sum(1 for n in counts.values() if n > 1)


def _selftest() -> int:
    """Build a synthetic client log + a server log whose clock runs
    30 s ahead, join them, and verify (a) the spans form ONE tree
    rooted at the client span and (b) the correction pulls the server
    span back inside its parent's window. One JSON line on stdout."""
    import tempfile

    skew = 30.0  # server wall clock runs this far ahead
    t0 = 1_700_000_000.0
    client = [
        {"v": 2, "run_id": "c", "event": "run_start", "t_wall": t0,
         "t_mono": 0.0, "component": "client"},
        {"v": 2, "run_id": "c", "event": "client.request", "kind": "span",
         "t_wall": t0 + 1.0, "t_mono": 1.0, "dur_s": 1.0,
         "trace_id": "t" * 16, "span_id": "a" * 16, "parent_id": None},
        {"v": 2, "run_id": "c", "event": "client.attempt", "kind": "span",
         "t_wall": t0 + 0.95, "t_mono": 0.95, "dur_s": 0.9,
         "trace_id": "t" * 16, "span_id": "b" * 16,
         "parent_id": "a" * 16},
    ]
    server = [
        {"v": 2, "run_id": "s", "event": "run_start", "t_wall": t0 + skew,
         "t_mono": 0.0, "component": "serving"},
        {"v": 2, "run_id": "s", "event": "request", "kind": "span",
         "t_wall": t0 + skew + 0.9, "t_mono": 0.9, "dur_s": 0.8,
         "trace_id": "t" * 16, "span_id": "c" * 16,
         "parent_id": "b" * 16, "remote_parent": True,
         "span_kind": "server"},
        {"v": 2, "run_id": "s", "event": "admit", "kind": "span",
         "t_wall": t0 + skew + 0.2, "t_mono": 0.2, "dur_s": 0.1,
         "trace_id": "t" * 16, "span_id": "d" * 16,
         "parent_id": "c" * 16},
    ]
    with tempfile.TemporaryDirectory() as td:
        paths = [os.path.join(td, "client.jsonl"),
                 os.path.join(td, "server.jsonl")]
        for path, recs in zip(paths, (client, server)):
            with open(path, "w", encoding="utf-8") as fh:
                for r in recs:
                    fh.write(json.dumps(r) + "\n")
        out = os.path.join(td, "joined.json")
        trace = export(paths, out)
    spans = {e["args"]["span_id"]: e for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    off = trace["otherData"]["clock_offsets_s"]
    measured = off[paths[1]]
    root = spans["a" * 16]
    remote = spans["c" * 16]
    checks = {
        # one tree: every span reaches the client root by parent links
        "single_tree": all(
            s["args"].get("parent_id") in spans or s is root
            for s in spans.values()),
        # the skew estimate recovered -30 s (midpoint noise is the
        # client/server midpoint mismatch, well under a second here)
        "skew_recovered": abs(measured + skew) < 0.5,
        # after correction, the server span nests inside the client
        # root's [start, end] window
        "nested": (root["ts"] <= remote["ts"]
                   and remote["ts"] + remote["dur"]
                   <= root["ts"] + root["dur"] + 1.0),
        "remote_marked": remote["args"].get("remote_parent") is True,
    }
    ok = all(checks.values())
    print(json.dumps({"metric": "trace_export_selftest", "ok": ok,
                      "clock_offset_s": round(measured, 3), **checks}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", nargs="*",
                    help="run-log JSONL file(s); several = cross-"
                         "process join, first file is the clock "
                         "reference")
    ap.add_argument("-o", "--out", default="",
                    help="output path (default <log>.trace.json)")
    ap.add_argument("--profile_dir", default="",
                    help="merge the newest jax.profiler capture under "
                         "this directory (plugins/profile/<stamp>/)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in join/skew verification "
                         "against synthetic logs and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.log:
        ap.error("at least one run-log path is required")
    out = args.out or (os.path.splitext(args.log[0])[0] + ".trace.json")
    trace = export(args.log, out, profile_dir=args.profile_dir or None)
    n_x = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    n_i = sum(1 for e in trace["traceEvents"] if e.get("ph") == "i")
    record_sets = [load_records(p) for p in args.log]
    print(f"wrote {out}: {len(trace['traceEvents'])} events "
          f"({n_x} spans, {n_i} instants)", file=sys.stderr)
    print(json.dumps({
        "metric": "trace_export",
        "logs": len(args.log),
        "events": len(trace["traceEvents"]),
        "spans": n_x,
        "instants": n_i,
        "joined_traces": _cross_file_traces(record_sets),
        "clock_offsets_s": trace["otherData"]["clock_offsets_s"],
        "out": out,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
