"""Tune the consensus Conv4d plan on the live backend and cache the winner.

Enumerates the legal candidate plans for a consensus config at a given
correlation shape (ncnet_tpu/ops/autotune.py — per-layer strategy mixes
x branch-fused/unfused x KL-fold x chunking), times each with
compiled-call medians (R applies chained in one jit), and persists the
winner to the strategy cache (trained_models/consensus_autotune.json,
override NCNET_STRATEGY_CACHE). After a session runs this once per
(backend, shape bucket), `neigh_consensus_apply` picks the tuned plan at
trace time with no env vars set.

Stdout is EXACTLY ONE JSON line (the driver contract shared with
bench.py / tools/bench_*.py); all diagnostics go to stderr.

Usage:
    python tools/autotune_consensus.py [--shape 1,1,100,75,100,75]
        [--dtype bfloat16] [--kernel_sizes 3 3] [--channels 16 1]
        [--reps 4] [--iters 3] [--max_candidates 0] [--no_save]

NCNET_AUTOTUNE_FAKE_TIMER=1 swaps the device timer for a deterministic
no-device stand-in (CI contract tests; never use for real tuning).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def note(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--shape", type=str, default="1,1,100,75,100,75",
                   help="correlation shape b,c,iA,jA,iB,jB (InLoc "
                        "post-pool default)")
    p.add_argument("--dtype", type=str, default="bfloat16")
    p.add_argument("--kernel_sizes", type=int, nargs="+", default=[3, 3])
    p.add_argument("--channels", type=int, nargs="+", default=[16, 1])
    p.add_argument("--symmetric", type=int, default=1)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--max_candidates", type=int, default=0,
                   help="0 = all; otherwise time only the first N of "
                        "the enumeration (session-budget guard)")
    p.add_argument("--fence", type=int, default=420,
                   help="per-candidate SIGALRM bound, seconds")
    p.add_argument("--no_save", action="store_true",
                   help="measure and report only; leave the cache alone")
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    fake = os.environ.get("NCNET_AUTOTUNE_FAKE_TIMER") == "1"

    from ncnet_tpu.utils.profiling import (
        AlarmTimeout,
        dial_devices,
        run_with_alarm,
        setup_compile_cache,
    )

    if not fake:
        setup_compile_cache()
        devices = dial_devices(args.dial_timeout)
        if devices is None:
            note("backend dial timed out; aborting")
            return 2
        note(f"devices: {devices}")

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.ops import autotune
    from ncnet_tpu.ops.conv4d import neigh_consensus_init

    shape = tuple(int(s) for s in args.shape.split(","))
    if len(shape) != 6:
        note(f"--shape must have 6 dims, got {shape}")
        return 2
    dtype = jnp.dtype(args.dtype)
    params = neigh_consensus_init(
        jax.random.PRNGKey(0), tuple(args.kernel_sizes),
        tuple(args.channels),
    )
    # Timing does not depend on the values; normal data avoids any
    # subnormal slow path.
    corr = jax.random.normal(
        jax.random.PRNGKey(1), shape, jnp.float32
    ).astype(dtype)
    symmetric = bool(args.symmetric)

    plans = autotune.enumerate_plans(params, symmetric=symmetric)
    total = len(plans)
    if args.max_candidates and total > args.max_candidates:
        note(f"capping {total} candidates to first {args.max_candidates}"
             f" (--max_candidates)")
        plans = plans[: args.max_candidates]
    note(f"{len(plans)} candidate plans for shape={shape} "
         f"dtype={dtype.name} sym={symmetric}"
         + (" [FAKE TIMER]" if fake else ""))

    if fake:
        timer = autotune.fake_timer
    else:
        def timer(params_, corr_, sym_, plan, *, reps, iters):
            # Per-candidate fence: one pathological remote compile must
            # cost one candidate, not the session (the bench tools'
            # standing rule). AlarmTimeout is a BaseException, so
            # convert it here — autotune()'s candidate fence catches
            # Exception only, by design.
            try:
                return run_with_alarm(
                    args.fence, autotune.device_timer, params_, corr_,
                    sym_, plan, reps=reps, iters=iters,
                )
            except AlarmTimeout as exc:
                raise RuntimeError(f"candidate fence: {exc}") from None

    best_plan, best_ms, results = autotune.autotune(
        params, corr, symmetric=symmetric, plans=plans,
        reps=args.reps, iters=args.iters, timer=timer,
        save=not args.no_save, log=note,
    )

    measured = [(p_, m) for p_, m in results if m is not None]
    record = {
        "metric": "consensus_autotune_best_ms",
        "value": best_ms,
        "unit": "ms",
        "plan": autotune.normalize_plan(best_plan),
        "plan_label": autotune.plan_label(best_plan),
        "backend": autotune.backend_kind() if not fake else "fake",
        "sig": autotune.shape_signature(shape, dtype, params, symmetric),
        "candidates": len(plans),
        "measured": len(measured),
        "failed": len(results) - len(measured),
        "cache_path": (None if args.no_save else autotune.cache_path()),
        "reps": args.reps,
        "iters": args.iters,
    }
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
