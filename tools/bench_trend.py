"""Trend report over the repo's ``BENCH_r*.json`` benchmark rounds.

Each bench round drops one ``BENCH_r<NN>.json`` (bench.py's contract:
``{n, cmd, rc, parsed}`` with the headline under ``parsed``:
``{metric, value, unit, ...}``). This tool reads every round, groups by
headline metric name — rounds benched on different hardware use
different metric names (the ``_cpu_smoke`` suffix), and cross-hardware
numbers must never be compared — and prints ONE JSON line::

    python tools/bench_trend.py
    {"metric": "...", "rounds": [...], "latest": 9.71, "best_prior": ...,
     "rel_vs_best_prior": ..., "regressed": false, ...}

``--strict`` makes a regression (latest more than ``--threshold``
below the best prior same-metric round, higher-is-better) a nonzero
exit, so a session script can gate on it the same way tier-1 tests
gate a commit. One JSON line on stdout is the whole machine-readable
contract (the bench_serving.py posture); prose goes to stderr.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_rounds(directory: str) -> List[Tuple[int, dict]]:
    """[(round number, record)] for every parseable BENCH_r*.json."""
    rounds = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"skipping {path}: {exc}", file=sys.stderr)
            continue
        rounds.append((int(m.group(1)), rec))
    return sorted(rounds)


def _headline(rec: dict) -> Optional[dict]:
    p = rec.get("parsed")
    if isinstance(p, dict) and "metric" in p and "value" in p:
        return p
    return None


def trend(rounds: List[Tuple[int, dict]], threshold: float) -> dict:
    """Trend of the LATEST round's headline metric vs prior rounds of
    the SAME metric (higher is better — every headline so far is a
    throughput)."""
    parsed = [(n, _headline(rec)) for n, rec in rounds]
    parsed = [(n, h) for n, h in parsed if h is not None]
    if not parsed:
        return {"metric": None, "rounds": [], "latest": None,
                "best_prior": None, "rel_vs_best_prior": None,
                "regressed": False, "n_rounds": 0,
                "threshold": threshold}
    latest_n, latest = parsed[-1]
    metric = latest["metric"]
    same = [(n, h["value"]) for n, h in parsed if h["metric"] == metric]
    series = [{"round": n, "value": v} for n, v in same]
    prior = [v for n, v in same if n != latest_n]
    best_prior = max(prior) if prior else None
    rel = None
    regressed = False
    if best_prior:
        rel = (latest["value"] - best_prior) / best_prior
        regressed = rel < -threshold
    report = {
        "metric": metric,
        "unit": latest.get("unit"),
        "rounds": series,
        "latest": latest["value"],
        "latest_round": latest_n,
        "best_prior": best_prior,
        "rel_vs_best_prior": rel,
        "regressed": regressed,
        "n_rounds": len(parsed),
        "threshold": threshold,
    }
    # Fleet-bench headlines (tools/bench_serving.py --replicas) carry
    # the scaling context a raw pairs/s trend is meaningless without —
    # pass it through so a trend over fleet rounds stays interpretable.
    # Likewise the bulk-pipeline headline (tools/bulk_match.py): a
    # corpus run's trend needs its completion/health counters.
    # And the coarse-to-fine fields (bench.py c2f section +
    # tools/real_parity.py --c2f): a c2f throughput trend is only
    # readable next to the knobs that produced it and the PCK delta
    # that licenses the speed.
    # And the quality-observatory fields (tools/quality_report.py /
    # obs/quality.py): a throughput trend earned by degrading rungs is
    # only honest next to the measured agreement cost and drift state.
    # And the localize-bench fields (tools/bench_serving.py --localize):
    # a localize-QPS trend only means something next to the fan-out
    # width it served and the result-cache hit rate that paid for it.
    # And the algebraic-consensus fields (ops/cp4d.py arms): a consensus
    # trend won by a CP-truncated or spectral plan is only honest next
    # to the plan kind/rank and the measured agreement-vs-dense.
    # And the train-bench fields (tools/bench_train.py
    # train_step_pairs_per_s): a training-throughput trend is only
    # comparable within one device count / batch / remat-accum shape.
    # And the elastic-scaling fields (tools/bench_train.py --hosts
    # train_elastic_scaling): an efficiency trend is only comparable
    # at one host count, and a number earned while the fleet was
    # resuming from evictions is not a steady-state number.
    for key in ("replicas", "single_replica_pairs_per_s", "scaling_x",
                "scaling_efficiency", "pairs_done", "pairs_s",
                "quarantined", "resumes",
                "c2f_pairs_s", "coarse_factor", "topk", "c2f_pck_delta",
                "shadow_agreement", "quality_drift_psi",
                "fanout_width", "rescache_hit_rate", "legs",
                "legs_failed",
                "consensus_plan_kind", "cp_rank", "cp_agreement",
                "step_ms", "devices", "batch", "accum", "remat_policy",
                "hosts", "elastic_resumes"):
        if key in latest:
            report[key] = latest[key]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative drop vs best prior same-metric round "
                         "that counts as a regression (default 0.05)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regression")
    args = ap.parse_args(argv)

    report = trend(load_rounds(args.dir), args.threshold)
    print(json.dumps(report))
    if report["metric"] is None:
        print("no parseable bench rounds found", file=sys.stderr)
    elif report["regressed"]:
        print(
            f"REGRESSION: {report['metric']} {report['latest']:g} is "
            f"{-report['rel_vs_best_prior']:.1%} below best prior "
            f"{report['best_prior']:g}", file=sys.stderr,
        )
    return 1 if (args.strict and report["regressed"]) else 0


if __name__ == "__main__":
    sys.exit(main())
