"""Bulk matcher CLI: crash-safe resumable map over a pair manifest.

Runs ``ncnet_tpu/pipeline/bulk.py`` against a CSV/JSONL manifest of
image pairs on a replica fleet — the paper's benchmark workload
(PF-Pascal / TSS / InLoc are all bulk jobs) run as throughput instead
of latency. Kill it at any point and re-run the same command line: it
resumes from the ledger with zero lost and zero duplicated results.

    # synthesize a corpus, then map it (resumable: re-run to resume)
    python tools/bulk_match.py --synthetic 64@48x64 --out_dir /tmp/bulk \
        --engine echo --replicas 2

    # real model fleet over an existing manifest
    python tools/bulk_match.py --manifest pairs.csv --out_dir out \
        --engine real --replicas 2 --image_size 64

Prints ONE JSON line (the repo's bench stdout contract)::

    {"metric": "bulk_match_pairs_per_s", "value": ..., "unit":
     "pairs/s", "pairs_done": ..., "pairs_s": ..., "quarantined": ...,
     "resumes": ..., ...}

``--chaos`` replays a crash-resume-crash schedule against one corpus:
two subprocess legs die by real SIGKILL at armed ``bulk.commit`` /
``bulk.checkpoint`` failpoints, then an in-process leg resumes with
``engine.device`` + ``bulk.read`` / ``bulk.dispatch`` error faults
armed, kills (and revives) a replica mid-run, and routes
manifest-marked poison pairs through bisection into the quarantine
sidecar. The gate: the final ledger holds every manifest row exactly
once, every poison pair is quarantined with its failure record, and
the exit code is nonzero on any drop, duplicate, or missed poison.
Stage notes go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_serving import note  # noqa: E402


def synth_corpus(corpus_dir, n_pairs, spec="48x64", poison=0, seed=0):
    """Write ``n_pairs`` random JPEG pairs + a JSONL manifest; the last
    ``poison`` rows are marked (EchoMatcher fails them on sight).
    Returns the manifest path. Deterministic in ``seed``."""
    import numpy as np
    from PIL import Image

    h, w = (int(v) for v in spec.split("x"))
    os.makedirs(corpus_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    manifest = os.path.join(corpus_dir, "manifest.jsonl")
    with open(manifest + ".tmp", "w") as fh:
        for i in range(n_pairs):
            paths = []
            for side in ("q", "p"):
                img = Image.fromarray(
                    (rng.random((h, w, 3)) * 255).astype("uint8"))
                path = os.path.join(corpus_dir, f"{side}{i:05d}.jpg")
                img.save(path, format="JPEG")
                paths.append(path)
            rec = {"id": f"synth-{i:05d}", "query": paths[0],
                   "pano": paths[1]}
            if poison and i >= n_pairs - poison:
                rec["poison"] = 1
            fh.write(json.dumps(rec) + "\n")
    os.replace(manifest + ".tmp", manifest)
    return manifest


def _build_fleet(args, model):
    """(fleet, prepare) per --engine; deadlines off on every replica."""
    if args.engine == "echo":
        from ncnet_tpu.pipeline import echo

        fleet, _ = echo.build_echo_fleet(
            n_replicas=args.replicas, max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            delay_s=args.echo_delay_ms / 1e3)
        return fleet, echo.prepare

    from ncnet_tpu.serving.fleet import MatchFleet

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    fleet = MatchFleet.build(
        config, params,
        n_replicas=args.replicas,
        base_id="bulk",
        cache_mb=args.cache_mb,
        engine_kwargs=dict(k_size=2, image_size=args.image_size),
        replica_kwargs=dict(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            default_timeout_s=None,  # bulk mode: no deadline flushes
        ),
    )
    engine = fleet.replicas[0].engine

    def prepare(pair):
        p = engine.prepare({"query_path": pair.query,
                            "pano_path": pair.pano})
        p.meta = {"row": pair.row, **pair.extra}
        return p.bucket_key, p

    return fleet, prepare


def run_once(args, model=None, extra_failpoints=None, on_dispatch=None):
    """One (possibly resuming) bulk pass; returns the run_bulk summary."""
    from ncnet_tpu.pipeline.bulk import run_bulk
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.reliability.retry import RetryBudget, RetryPolicy

    for site, kwargs in (extra_failpoints or {}).items():
        failpoints.registry().set(site, **kwargs)
    fleet, prepare = _build_fleet(args, model)
    fleet.start()
    dispatches = [0]

    def submit(bucket_key, payload):
        dispatches[0] += 1
        if on_dispatch is not None:
            on_dispatch(dispatches[0], fleet)
        return fleet.dispatcher.submit(bucket_key, payload)

    try:
        return run_bulk(
            args.manifest, args.out_dir, prepare, submit,
            shard_size=args.shard_size,
            max_inflight=args.max_inflight,
            checkpoint_every=args.checkpoint_every,
            retry_policy=RetryPolicy(
                max_attempts=args.retries + 1,
                base_delay_s=0.02, max_delay_s=1.0,
                budget=RetryBudget(capacity=100.0, refill_per_success=1.0),
            ),
        )
    finally:
        fleet.close()
        for site in (extra_failpoints or {}):
            failpoints.clear(site)


def chaos(args, model=None):
    """Crash-resume-crash schedule over one corpus; 0 = gate green."""
    from ncnet_tpu.pipeline.bulk import iter_manifest

    if args.engine != "echo":
        note("chaos legs respawn the tool; forcing --engine echo")
        args.engine = "echo"
    if not args.echo_delay_ms:
        # A real per-batch model time gives the kill_replica verb a
        # window with work actually queued on the victim.
        args.echo_delay_ms = 5.0
    rows = list(iter_manifest(args.manifest))
    poison_rows = {p.row for p in rows if p.extra.get("poison")}
    note(f"chaos corpus: {len(rows)} pairs, {len(poison_rows)} poison")

    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--manifest", args.manifest, "--out_dir", args.out_dir,
        "--engine", "echo", "--replicas", str(args.replicas),
        "--max_inflight", "4", "--checkpoint_every", "2",
        "--shard_size", str(args.shard_size),
        "--echo_delay_ms", str(args.echo_delay_ms),
    ]
    kills = 0
    for leg, spec in (("commit-window", "bulk.commit=kill:+1"),
                      ("checkpoint-rename", "bulk.checkpoint=kill:+2")):
        env = dict(os.environ, NCNET_FAILPOINTS=spec)
        note(f"leg {kills + 1}: SIGKILL at {spec} ...")
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              timeout=120)
        if proc.returncode == 0:
            note(f"leg {leg}: expected a mid-run kill but the run "
                 "completed — corpus too small for the schedule")
            return 1, {"error": f"kill never fired in leg {leg}"}
        kills += 1
        note(f"leg {leg}: died rc={proc.returncode} (good)")

    # Final leg, in-process: resume under error faults + replica death.
    def on_dispatch(n, fleet):
        if n == 3 and args.replicas > 1:
            note("chaos: kill_replica mid-run")
            fleet.kill(-1)
        elif n == 9 and args.replicas > 1:
            fleet.revive(-1)

    note("leg 3: resume with engine.device/bulk.read/bulk.dispatch "
         "faults + kill_replica")
    summary = run_once(
        args, model,
        extra_failpoints={
            "engine.device": dict(mode="error", max_fires=2),
            "bulk.read": dict(mode="error", max_fires=2),
            "bulk.dispatch": dict(mode="error", max_fires=2),
        },
        on_dispatch=on_dispatch,
    )

    # -- verify exactly-once + poison quarantine --------------------------
    ledger_rows, statuses = [], {}
    with open(os.path.join(args.out_dir, "ledger.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            ledger_rows.append(rec["row"])
            statuses[rec["row"]] = rec["status"]
    lost = sorted(set(range(len(rows))) - set(ledger_rows))
    dupes = len(ledger_rows) - len(set(ledger_rows))
    quarantined = {}
    qpath = os.path.join(args.out_dir, "quarantine.jsonl")
    if os.path.exists(qpath):
        with open(qpath) as fh:
            for line in fh:
                rec = json.loads(line)
                quarantined[rec["row"]] = rec
    poison_missed = sorted(
        r for r in poison_rows
        if r not in quarantined or not quarantined[r].get("error"))
    wrongly_quarantined = sorted(
        r for r, s in statuses.items()
        if s == "quarantined" and r not in poison_rows)
    ok = not lost and not dupes and not poison_missed \
        and not wrongly_quarantined and kills == 2
    rec = {
        "metric": "bulk_chaos_survival",
        "value": 1.0 if ok else 0.0,
        "unit": "frac",
        "pairs": len(rows),
        "pairs_done": summary["pairs_done"],
        "pairs_s": round(summary["pairs_s"], 3),
        "lost": len(lost),
        "duplicates": dupes,
        "poison_expected": len(poison_rows),
        "poison_quarantined": sum(
            1 for r in poison_rows if r in quarantined),
        "wrongly_quarantined": len(wrongly_quarantined),
        "quarantined": summary["quarantined"],
        "retries": summary["retries"],
        "resumes": summary["resumes"],
        "kills": kills,
    }
    return (0 if ok else 1), rec


def prewarm_results(args, model=None):
    """``--prewarm-results``: run the manifest's pairs and populate a
    match-RESULT cache disk tier (serving/result_cache.py) instead of a
    ledger — the offline half of the serving cache: a nightly sweep
    over tomorrow's expected shortlists turns day-one localize traffic
    into disk hits. Pairs already cached are skipped (resumable by
    construction: the disk tier IS the ledger). Returns (rc, record).
    """
    import time as _time

    import numpy as np

    from ncnet_tpu.pipeline.bulk import iter_manifest
    from ncnet_tpu.serving.feature_store import content_digest
    from ncnet_tpu.serving.result_cache import MatchResultCache

    model_key = args.rescache_model_key
    if not model_key:
        if args.engine == "echo":
            model_key = "echo|res"
        else:
            from ncnet_tpu.evals.feature_cache import model_cache_key

            model_key = model_cache_key("", seed=1) + "|res"
    cache = MatchResultCache(
        max(args.rescache_mb, 1) * 1024 * 1024,
        disk_dir=args.rescache_dir, model_key=model_key)
    fleet, prepare = _build_fleet(args, model)
    fleet.start()
    engine = fleet.replicas[0].engine

    def to_table(matches):
        t = np.asarray(matches)
        if t.ndim == 2:
            return t
        # Echo engine: the digest bytes fold into a deterministic fake
        # [4, 5] table so the prewarm plumbing drills jax-free.
        raw = np.frombuffer(bytes(matches), np.uint8)[:20]
        return raw.astype(np.float32).reshape(4, 5)

    t0 = _time.monotonic()
    stored = warm = failed = 0
    pending = []

    def drain_one():
        nonlocal stored, failed
        key0, pid, fut = pending.pop(0)
        try:
            br = fut.result(timeout=300.0)
            cache.put(key0, to_table(br.result["matches"]))
            stored += 1
        except Exception as exc:  # noqa: BLE001 — skip, count, continue
            note(f"prewarm: pair {pid} failed: {type(exc).__name__}: {exc}")
            failed += 1

    rows = list(iter_manifest(args.manifest))
    for pair in rows:
        try:
            bucket_key, p = prepare(pair)
            op = (engine.result_op_key(p)
                  if hasattr(engine, "result_op_key") else ("echo",))
            key = cache.key(content_digest(pair.query),
                            content_digest(pair.pano), op)
        except (OSError, ValueError) as exc:
            note(f"prewarm: pair {pair.pair_id} unreadable: {exc}")
            failed += 1
            continue
        if cache.get(key) is not None:
            warm += 1
            continue
        pending.append((key, pair.pair_id, fleet.dispatcher.submit(
            bucket_key, p)))
        while len(pending) >= args.max_inflight:
            drain_one()
    while pending:
        drain_one()
    fleet.close()
    dur = _time.monotonic() - t0
    rec = {
        "metric": "bulk_prewarm_results_pairs_per_s",
        "value": round(stored / dur, 3) if dur > 0 else 0.0,
        "unit": "pairs/s",
        "engine": args.engine,
        "pairs": len(rows),
        "stored": stored,
        "already_warm": warm,
        "failed": failed,
        "rescache_dir": args.rescache_dir,
        "duration_s": round(dur, 3),
    }
    return (0 if failed == 0 else 1), rec


def main(argv=None, model=None):
    parser = argparse.ArgumentParser(
        description="crash-safe resumable bulk matcher over a manifest")
    parser.add_argument("--manifest", type=str, default="",
                        help="CSV (query,pano[,id]) or JSONL pair list")
    parser.add_argument("--out_dir", type=str, required=True,
                        help="ledger/checkpoint/quarantine directory")
    parser.add_argument("--engine", choices=("real", "echo"),
                        default="real")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--shard_size", type=int, default=512)
    parser.add_argument("--max_inflight", type=int, default=32)
    parser.add_argument("--checkpoint_every", type=int, default=64)
    parser.add_argument("--retries", type=int, default=4,
                        help="per-pair retry attempts after the first")
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--max_delay_ms", type=float, default=5.0)
    parser.add_argument("--image_size", type=int, default=64)
    parser.add_argument("--cache_mb", type=int, default=0)
    parser.add_argument("--echo_delay_ms", type=float, default=0.0,
                        help="echo engine: simulated model time/batch")
    parser.add_argument("--synthetic", type=str, default="",
                        help="N@HxW: synthesize a corpus + manifest "
                        "under out_dir/corpus")
    parser.add_argument("--poison", type=int, default=0,
                        help="with --synthetic: mark the last N rows "
                        "poison (echo engine fails them)")
    parser.add_argument("--chaos", action="store_true",
                        help="crash-resume-crash gate; nonzero exit on "
                        "any lost/duplicated/unquarantined pair")
    parser.add_argument("--prewarm-results", action="store_true",
                        dest="prewarm_results",
                        help="populate a match-result cache disk tier "
                        "from the manifest's pairs instead of writing a "
                        "ledger (serving caches answer repeat traffic "
                        "from it; already-cached pairs are skipped)")
    parser.add_argument("--rescache_dir", type=str, default="",
                        help="match-result cache disk tier for "
                        "--prewarm-results (give the server the same "
                        "dir via --rescache_dir)")
    parser.add_argument("--rescache_mb", type=int, default=256,
                        help="prewarm-side memory budget (the disk "
                        "tier is what persists)")
    parser.add_argument("--rescache_model_key", type=str, default="",
                        help="cache namespace; MUST match the serving "
                        "side's (default: derived like the server's "
                        "default for this tool's model)")
    parser.add_argument("--run_log", type=str, default="")
    args = parser.parse_args(argv)

    from ncnet_tpu import obs

    if args.run_log:
        obs.init_run("bulk_match", args.run_log, args=args)
    if args.chaos and not args.synthetic and not args.manifest:
        args.synthetic = "24@48x64"
        args.poison = args.poison or 3
    if args.synthetic and not args.manifest:
        n, _, spec = args.synthetic.partition("@")
        args.manifest = synth_corpus(
            os.path.join(args.out_dir, "corpus"),
            int(n), spec or "48x64", poison=args.poison)
        note(f"synthesized corpus manifest: {args.manifest}")
    if not args.manifest:
        parser.error("need --manifest or --synthetic")

    if args.chaos:
        rc, rec = chaos(args, model)
        print(json.dumps(rec), flush=True)
        return rc

    if args.prewarm_results:
        if not args.rescache_dir:
            parser.error("--prewarm-results needs --rescache_dir")
        rc, rec = prewarm_results(args, model)
        print(json.dumps(rec), flush=True)
        return rc

    summary = run_once(args, model)
    rec = {
        "metric": "bulk_match_pairs_per_s",
        "value": round(summary["pairs_s"], 3),
        "unit": "pairs/s",
        "engine": args.engine,
        "replicas": args.replicas,
        "pairs_done": summary["pairs_done"],
        "pairs_this_run": summary["pairs_this_run"],
        "pairs_s": round(summary["pairs_s"], 3),
        "quarantined": summary["quarantined"],
        "retries": summary["retries"],
        "resumes": summary["resumes"],
        "duration_s": round(summary["duration_s"], 3),
        "ledger": summary["ledger"],
    }
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
