"""Mosaic lowering probe: can pltpu.roll express the consensus plane shifts?

The deleted l1 kernel (see ops/conv4d.py) died on lane-UNALIGNED offsets:
its flat [K*LP] layout made a (dk, dl) plane shift a concatenate/slice at
+-1 column, which Mosaic's TC lowering rejects three different ways. The
fused-consensus plan keeps each (k, l) plane 2-D in VMEM and shifts with
`pltpu.roll` (the documented lane/sublane rotate) + iota edge masks —
zero-fill rotation == 'same' zero padding.

This probe compiles and checks ONE grid step of that pattern on real
Mosaic in seconds: a [sk, lp] block, all 9 (dk, dl) shifted copies via
roll+mask, a [sk*lp/? , 9] x [9, c] dot. PASS/FAIL decides whether the
fused consensus kernel is buildable before any real investment (the l1
lesson: interpret-mode green says nothing about TC lowering).

    python tools/probe_roll_kernel.py            # dials the tunnel
    JAX_PLATFORMS=cpu ... --interpret            # CPU sanity of the probe
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=120.0)
    p.add_argument("--interpret", action="store_true")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not args.interpret:
        from ncnet_tpu.utils.profiling import dial_devices

        if dial_devices(args.dial_timeout) is None:
            print("dial timed out")
            return 2

    sk, sl, c = 16, 72, 8  # one (k, l) plane; lp pads 72 -> 128 lanes
    lp = 128

    def kernel(x_ref, w_ref, o_ref):
        x = x_ref[...]  # [sk, lp], L zero-padded
        rows = jax.lax.broadcasted_iota(jnp.int32, (sk, lp), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sk, lp), 1)
        taps = []
        for dk in (-1, 0, 1):
            for dl in (-1, 0, 1):
                # roll + mask the wrap: rotation by (dk, dl) brings
                # row/col (r - dk, c - dl) here; rows/cols whose source
                # fell outside [0, sk) x [0, sl) contribute zero ('same'
                # zero padding).
                y = pltpu.roll(x, dk % sk, 0)
                y = pltpu.roll(y, dl % lp, 1)
                src_r = rows - dk
                src_c = cols - dl
                # Source in-bounds AND destination a real (non-pad)
                # column: source masking alone keeps garbage out of
                # VALID outputs, but a layered kernel wants pad columns
                # exactly zero so no mask subtlety compounds per layer.
                ok = (
                    (src_r >= 0) & (src_r < sk)
                    & (src_c >= 0) & (src_c < sl) & (cols < sl)
                )
                taps.append(jnp.where(ok, y, 0.0))
        a = jnp.stack(taps, axis=-1)  # [sk, lp, 9]
        acc = jax.lax.dot_general(
            a.reshape(sk * lp, 9),
            w_ref[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # f32 probe oracle needs true-f32 MXU passes; the default
            # single-bf16-pass precision shows ~4e-2 error at these
            # magnitudes, which would masquerade as a roll/mask bug.
            precision=jax.lax.Precision.HIGHEST,
        )
        o_ref[...] = acc.reshape(sk, lp, c)

    x = jnp.zeros((sk, lp), jnp.float32).at[:, :sl].set(
        jnp.asarray(np.random.RandomState(0).randn(sk, sl), jnp.float32)
    )
    w = jnp.asarray(np.random.RandomState(1).randn(9, c), jnp.float32)

    run = jax.jit(
        lambda x, w: pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((sk, lp, c), jnp.float32),
            interpret=args.interpret,
        )(x, w)
    )
    t0 = time.perf_counter()
    try:
        got = np.asarray(run(x, w))
    except Exception as exc:  # noqa: BLE001
        print(f"FAIL compile/run ({type(exc).__name__}): {exc}")
        return 1
    dt = time.perf_counter() - t0

    # Oracle: same-padded 3x3 conv over the [sk, sl] plane per channel.
    xf = np.asarray(x)[:, :sl]
    wf = np.asarray(w)
    want = np.zeros((sk, sl, c), np.float32)
    for t, (dk, dl) in enumerate(
        (dk, dl) for dk in (-1, 0, 1) for dl in (-1, 0, 1)
    ):
        shifted = np.zeros_like(xf)
        rs = slice(max(0, -dk), sk - max(0, dk))
        rd = slice(max(0, dk), sk - max(0, -dk))
        cs = slice(max(0, -dl), sl - max(0, dl))
        cd = slice(max(0, dl), sl - max(0, -dl))
        shifted[rd, cd] = xf[rs, cs]
        want += shifted[..., None] * wf[t]
    err = float(np.abs(got[:, :sl] - want).max())
    pads = float(np.abs(got[:, sl:]).max())
    ok = err < 1e-4 and pads == 0.0
    print(
        f"{'PASS' if ok else 'FAIL'} compile+run {dt:.1f}s "
        f"max_abs_err={err:.3g} pad_cols_abs={pads:.3g}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
