"""Mask IoU scoring (parity target: tools/simpleMatch.py).

Note on the reference: its `matchScore` computes "union" as
`(realMask + synMask) > 1`, which is the INTERSECTION again, so the
returned "IoU" is identically 1.0 wherever the masks overlap at all
(tools/simpleMatch.py:13-15). That is a defect, not a behavior to
replicate; this implementation computes the actual intersection over
union.
"""

from __future__ import annotations

import numpy as np


def match_score(real_mask: np.ndarray, syn_mask: np.ndarray, threshold: float = 1.0) -> float:
    """IoU of the two masks binarized at `value > threshold`.

    Returns 0.0 when the union is empty (both masks blank).
    """
    a = np.asarray(real_mask) > threshold
    b = np.asarray(syn_mask) > threshold
    union = np.logical_or(a, b).sum()
    if union == 0:
        return 0.0
    return float(np.logical_and(a, b).sum() / union)


def main(argv=None):
    import argparse

    from PIL import Image

    p = argparse.ArgumentParser(description="IoU of two binarized mask images")
    p.add_argument("real_mask")
    p.add_argument("syn_mask")
    p.add_argument("--threshold", type=float, default=1.0)
    args = p.parse_args(argv)
    a = np.asarray(Image.open(args.real_mask).convert("L"))
    b = np.asarray(Image.open(args.syn_mask).convert("L"))
    print(match_score(a, b, args.threshold))


if __name__ == "__main__":
    main()
