"""Live fleet status: poll N replicas' /metrics into one terminal view.

The dashboard half of obs/aggregate.py: scrape every replica's
``GET /metrics`` each poll, merge the scrapes into a fleet view, and
render a per-replica table to STDERR —

    replica      req/s   err/s   p99 ms   queue  breaker  burn  hbm GB  head%  warm  rung  sess  drift  shad%  resc%
    r0            12.4     0.0     38.2       1   closed   0.1    21.40     33     4     0     3   0.04     99     81
    r1            11.9     0.0     41.7       0   closed   0.2    21.38     33     4     0     1   0.05    100     79
    FLEET         24.3     0.0     40.9       1        -   0.2    42.78     33     8     0     4   0.05     99     80
      tenants: default=112  lowpri=38

req/s and err/s are counter deltas between polls; p99 is exact at the
shared bucket ladder's resolution (merged buckets for the FLEET row,
never an average of per-replica percentiles); breaker decodes the
``breaker_engine_state`` gauge; burn is the availability SLO's
fast-window burn rate (obs/slo.py) — at or above 1.0 the fleet is
spending error budget faster than it earns it. hbm GB / head% read the
``device.hbm.*`` gauges (obs/costcards.py, polled by the server on
/metrics) — bytes in use and percent of the device limit still free
("-" on backends that don't report memory stats, e.g. CPU); warm is
the ``serving.warmup_programs`` counter, how many (bucket, batch,
mode) programs the replica precompiled; rung is the
``serving.qos.rung`` gauge — the QoS controller's current ladder
position ("-" on servers without the multi-tenant QoS layer),
suffixed ``cp<R>`` when the active rung is a CP-decomposed consensus
arm (the ``serving.qos.cp_rank`` gauge — a declared approximation,
not a c2f coarsening); sess is
the ``serving.session.active`` gauge — open streaming sessions on
that front door ("-" before the first session ever opens); drift is
the worst ``serving.quality.drift_psi`` across the replica's
endpoints (obs/quality.py — 0.25+ means the live score distribution
shifted); shad% is the count-weighted mean
``serving.quality.shadow_agreement`` across rungs (serving/shadow.py
— "-" until the shadow sampler has compared something; the per-rung
split lives in tools/quality_report.py); resc% is the lifetime
match-result-cache hit percent, ``serving.rescache.hits`` over
hits+misses (serving/result_cache.py — "-" on replicas running
without the result cache). A ``tenants:`` line breaks
fleet-wide request totals out per ``serving.tenant.requests`` tenant
label.

On exit (``--iterations N``, or Ctrl-C when polling forever) it prints
ONE JSON line to stdout, the house contract every tool in tools/
follows, with fleet totals, per-replica counters, and the unreachable
list — so a session script can watch a rollout and assert on the
result.

Example::

    python tools/fleet_status.py http://127.0.0.1:8123 \
        http://127.0.0.1:8124 --interval_s 2
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ncnet_tpu.obs.aggregate import fleet_view  # noqa: E402

# Scraped series carry Prometheus-sanitized names (dots -> underscores;
# obs/aggregate.parse_prometheus_text docstring).
REQS = "serving_requests"
ERRS = "serving_errors"
LAT = "serving_e2e_latency_s"
QUEUE = "serving_queue_depth"
BREAKER = "breaker_engine_state"
BURN = "slo_availability_burn_fast"
HBM_USE = "device_hbm_bytes_in_use"
HBM_LIM = "device_hbm_limit_bytes"
WARMED = "serving_warmup_programs"
RUNG = "serving_qos_rung"
CP_RANK = "serving_qos_cp_rank"
SESSIONS = "serving_session_active"
TENANT_REQS = "serving_tenant_requests"
DRIFT_PSI = "serving_quality_drift_psi"
SHADOW_AGREE = "serving_quality_shadow_agreement"
RESCACHE_HITS = "serving_rescache_hits"
RESCACHE_MISSES = "serving_rescache_misses"

_BREAKER_STATES = {0.0: "closed", 1.0: "half_open", 2.0: "open"}


def _rung_cell(rung, cp_rank):
    """Decode the rung column: the ladder position, suffixed ``cp<R>``
    when the active rung is a CP-decomposed consensus arm
    (``serving.qos.cp_rank`` gauge) — a declared approximation a
    dashboard must not render identically to a c2f coarsening."""
    if rung is None:
        return None
    cell = f"{rung:.0f}"
    if cp_rank:
        cell += f"cp{cp_rank:.0f}"
    return cell


def _rescache_pct(counters):
    """Lifetime match-result-cache hit percent from the
    ``serving_rescache_{hits,misses}`` counters ("-" on replicas that
    run without the result cache — neither counter ever registers)."""
    hits = counters.get(RESCACHE_HITS)
    misses = counters.get(RESCACHE_MISSES)
    if hits is None and misses is None:
        return None
    total = (hits or 0.0) + (misses or 0.0)
    return (hits or 0.0) / total * 100.0 if total else None


def note(msg):
    print(msg, file=sys.stderr, flush=True)


def _fmt(v, width, prec=1):
    if v is None:
        return "-".rjust(width)
    return f"{v:.{prec}f}".rjust(width)


def _rate(now, prev, key, dt):
    """Counter delta per second between two counter maps (None on the
    first poll, when there is no baseline)."""
    if prev is None or dt <= 0:
        return None
    return max(now.get(key, 0.0) - prev.get(key, 0.0), 0.0) / dt


def _p99_ms(hists, key):
    h = hists.get(key)
    if not h or not h.get("count"):
        return None
    p99 = h.get("p99")
    return p99 * 1e3 if p99 is not None else None


def _headroom_pct(use, lim):
    """Percent of the device HBM limit still free (None when the
    backend doesn't report memory stats — CPU replicas)."""
    if use is None or not lim:
        return None
    return max(0.0, 1.0 - use / lim) * 100.0


def _gauge_sum(view, key):
    """Sum a gauge across replicas (fleet HBM totals — the merged
    entry only carries min/max/mean, but per_replica has every value)."""
    entry = view["gauges"].get(key) or {}
    vals = (entry.get("per_replica") or {}).values()
    vals = [v for v in vals if v is not None]
    return sum(vals) if vals else None


def _family(store, base):
    """A labeled family's children in a flat series map: the bare name
    plus every ``name{...}`` key (drift psi is labeled per endpoint,
    shadow agreement per rung)."""
    return [v for k, v in store.items()
            if k == base or k.startswith(base + "{")]


def _label_max(store, base):
    """Worst (max) value across one gauge family's labeled children —
    the drift column shows the most-drifted endpoint."""
    vals = [v for v in _family(store, base) if v is not None]
    return max(vals) if vals else None


def _hist_family_mean(hists, base):
    """Count-weighted mean across one histogram family's labeled
    children (the per-rung shadow-agreement series fold into one
    fleet-readable number; the per-rung split stays in
    tools/quality_report.py)."""
    tot_sum = tot_n = 0.0
    for h in _family(hists, base):
        tot_sum += float(h.get("sum") or 0.0)
        tot_n += float(h.get("count") or 0.0)
    return tot_sum / tot_n if tot_n else None


def _fleet_gauge_max(view, base):
    """Fleet-wide max over a labeled gauge family (merged gauge entries
    carry min/max/mean per series; take the worst across series)."""
    vals = [(e or {}).get("max") for e in _family(view["gauges"], base)]
    vals = [v for v in vals if v is not None]
    return max(vals) if vals else None


_TENANT_LABEL_RE = re.compile(r'tenant="([^"]*)"')


def _tenant_totals(counters):
    """Per-tenant request totals from the labeled
    ``serving_tenant_requests{tenant=...}`` series ({} on servers
    without the multi-tenant QoS layer)."""
    out = {}
    for key, val in counters.items():
        if not key.startswith(TENANT_REQS + "{"):
            continue
        m = _TENANT_LABEL_RE.search(key)
        if m:
            out[m.group(1)] = out.get(m.group(1), 0.0) + val
    return out


def render(view, prev_counters, dt, out=None):
    """One poll's table; returns {ident: counters} for the next delta."""
    w = (out or sys.stderr).write
    rows = []
    idents = sorted(view["per_replica"])
    for ident in idents:
        rep = view["per_replica"][ident]
        prev = (prev_counters or {}).get(ident)
        state = rep["gauges"].get(BREAKER)
        burn = rep["gauges"].get(BURN)
        use = rep["gauges"].get(HBM_USE)
        lim = rep["gauges"].get(HBM_LIM)
        rows.append((
            ident,
            _rate(rep["counters"], prev, REQS, dt),
            _rate(rep["counters"], prev, ERRS, dt),
            _p99_ms(rep["histograms"], LAT),
            rep["gauges"].get(QUEUE),
            _BREAKER_STATES.get(state, "?") if state is not None else "-",
            burn,
            use / 1e9 if use is not None else None,
            _headroom_pct(use, lim),
            rep["counters"].get(WARMED),
            _rung_cell(rep["gauges"].get(RUNG),
                       rep["gauges"].get(CP_RANK)),
            rep["gauges"].get(SESSIONS),
            _label_max(rep["gauges"], DRIFT_PSI),
            _hist_family_mean(rep["histograms"], SHADOW_AGREE),
            _rescache_pct(rep["counters"]),
        ))
    fleet_prev = (prev_counters or {}).get("FLEET")
    burn_entry = view["gauges"].get(BURN) or {}
    fleet_use = _gauge_sum(view, HBM_USE)
    fleet_lim = _gauge_sum(view, HBM_LIM)
    rows.append((
        "FLEET",
        _rate(view["counters"], fleet_prev, REQS, dt),
        _rate(view["counters"], fleet_prev, ERRS, dt),
        _p99_ms(view["histograms"], LAT),
        (view["gauges"].get(QUEUE) or {}).get("max"),
        "-",
        burn_entry.get("max"),
        fleet_use / 1e9 if fleet_use is not None else None,
        _headroom_pct(fleet_use, fleet_lim),
        view["counters"].get(WARMED),
        _rung_cell((view["gauges"].get(RUNG) or {}).get("max"),
                   (view["gauges"].get(CP_RANK) or {}).get("max")),
        _gauge_sum(view, SESSIONS),
        _fleet_gauge_max(view, DRIFT_PSI),
        _hist_family_mean(view["histograms"], SHADOW_AGREE),
        _rescache_pct(view["counters"]),
    ))
    w(f"{'replica':<12} {'req/s':>8} {'err/s':>8} {'p99 ms':>8} "
      f"{'queue':>6} {'breaker':>9} {'burn':>6} {'hbm GB':>7} "
      f"{'head%':>6} {'warm':>5} {'rung':>5} {'sess':>5} "
      f"{'drift':>6} {'shad%':>6} {'resc%':>6}\n")
    for (ident, rps, eps, p99, q, brk, burn, hbm, head, warm,
         rung, sess, drift, shad, resc) in rows:
        qs = f"{q:.0f}".rjust(6) if q is not None else "-".rjust(6)
        ws_ = f"{warm:.0f}".rjust(5) if warm is not None else "-".rjust(5)
        rg = (rung if rung is not None else "-").rjust(5)
        ss = f"{sess:.0f}".rjust(5) if sess is not None else "-".rjust(5)
        sh = (f"{shad * 100:.0f}".rjust(6) if shad is not None
              else "-".rjust(6))
        rc = (f"{resc:.0f}".rjust(6) if resc is not None
              else "-".rjust(6))
        w(f"{ident:<12} {_fmt(rps, 8)} {_fmt(eps, 8)} {_fmt(p99, 8)} "
          f"{qs} {brk:>9} {_fmt(burn, 6)} {_fmt(hbm, 7, 2)} "
          f"{_fmt(head, 6, 0)} {ws_} {rg} {ss} "
          f"{_fmt(drift, 6, 2)} {sh} {rc}\n")
    tenants = _tenant_totals(view["counters"])
    if tenants:
        w("  tenants: " + "  ".join(
            f"{name}={total:.0f}" for name, total in
            sorted(tenants.items())) + "\n")
    for url, why in sorted(view["errors"].items()):
        w(f"  unreachable {url}: {why}\n")
    nxt = {i: dict(view["per_replica"][i]["counters"]) for i in idents}
    nxt["FLEET"] = dict(view["counters"])
    return nxt


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="poll replicas' /metrics into one live fleet view")
    parser.add_argument("urls", nargs="+",
                        help="replica base URLs (or /metrics endpoints)")
    parser.add_argument("--interval_s", type=float, default=2.0)
    parser.add_argument("--iterations", type=int, default=0,
                        help="polls before exiting (0 = until Ctrl-C)")
    parser.add_argument("--timeout_s", type=float, default=5.0)
    parser.add_argument("--clear", action="store_true",
                        help="clear the terminal between polls (ANSI)")
    args = parser.parse_args(argv)

    prev, last_t = None, None
    view = None
    polls = 0
    try:
        while args.iterations <= 0 or polls < args.iterations:
            if polls and args.interval_s > 0:
                time.sleep(args.interval_s)
            view = fleet_view(args.urls, timeout_s=args.timeout_s)
            now = time.monotonic()
            dt = (now - last_t) if last_t is not None else 0.0
            if args.clear:
                sys.stderr.write("\x1b[2J\x1b[H")
            note(f"poll {polls + 1}: {len(view['sources'])}/"
                 f"{len(args.urls)} replicas up")
            prev = render(view, prev, dt)
            last_t = now
            polls += 1
    except KeyboardInterrupt:
        pass

    if view is None:
        return 1
    # New fields (HBM accounting, warmed programs) are ADDED to the
    # exit record; every pre-existing key keeps its name and meaning —
    # session scripts parsing older outputs keep working.
    replicas = {}
    for ident, rep in sorted(view["per_replica"].items()):
        use = rep["gauges"].get(HBM_USE)
        lim = rep["gauges"].get(HBM_LIM)
        replicas[ident] = {
            "requests": rep["counters"].get(REQS, 0.0),
            "errors": rep["counters"].get(ERRS, 0.0),
            "p99_ms": _p99_ms(rep["histograms"], LAT),
            "hbm_bytes_in_use": use,
            "hbm_headroom_pct": _headroom_pct(use, lim),
            "warmed_programs": rep["counters"].get(WARMED),
            "qos_rung": rep["gauges"].get(RUNG),
            "qos_cp_rank": rep["gauges"].get(CP_RANK),
            "sessions": rep["gauges"].get(SESSIONS),
            "tenants": _tenant_totals(rep["counters"]),
            "drift_psi": _label_max(rep["gauges"], DRIFT_PSI),
            "shadow_agreement": _hist_family_mean(
                rep["histograms"], SHADOW_AGREE),
            "rescache_hit_pct": _rescache_pct(rep["counters"]),
        }
    fleet_use = _gauge_sum(view, HBM_USE)
    fleet_lim = _gauge_sum(view, HBM_LIM)
    rec = {
        "metric": "fleet_status",
        "value": view["counters"].get(REQS, 0.0),
        "unit": "requests",
        "replicas": replicas,
        "fleet": {
            "requests": view["counters"].get(REQS, 0.0),
            "errors": view["counters"].get(ERRS, 0.0),
            "p99_ms": _p99_ms(view["histograms"], LAT),
            "n_sources": view["n_sources"],
            "hbm_bytes_in_use": fleet_use,
            "hbm_limit_bytes": fleet_lim,
            "warmed_programs": view["counters"].get(WARMED),
            "qos_rung": (view["gauges"].get(RUNG) or {}).get("max"),
            "qos_cp_rank": (view["gauges"].get(CP_RANK)
                            or {}).get("max"),
            "sessions": _gauge_sum(view, SESSIONS),
            "tenants": _tenant_totals(view["counters"]),
            "drift_psi": _fleet_gauge_max(view, DRIFT_PSI),
            "shadow_agreement": _hist_family_mean(
                view["histograms"], SHADOW_AGREE),
            "rescache_hit_pct": _rescache_pct(view["counters"]),
        },
        "polls": polls,
        "unreachable": sorted(view["errors"]),
    }
    print(json.dumps(rec), flush=True)
    return 0 if not view["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
