"""Consensus-strategy A/B at the headline workload: kill the layout copies.

Round-5 capture truth (docs/tpu_r05/bench_trace, self-time traceagg):
consensus is the top stage at 502 ms/block, and ~265 ms of that is four
XLA layout copies around the two channels-last convs (conv4d.py:608/653
— the MXU conv wants the 6912 A-cells on lanes `{0,3,2,1}` while the
surrounding concat/slice/pad fusions emit `{1,2,3,0}`). The copies are a
property of the per-layer decomposition mix, so A/B the mixes end to end
in headline units. The default 'auto' is (stacked, outstacked) at the
InLoc (3,3)/(16,1) config (conv4d._auto_pick).

MEASURED VERDICT (2026-08-02, docs/tpu_r05/ab_0401.log): all three
non-auto mixes are HBM-INFEASIBLE at one-shot InLoc scale — layer-1
outstacked and layer-2 stacked each materialize a bf16[6912,96,72,144]
(18.3 GB) intermediate, every bench tier fails to allocate, and 'auto'
remains the only mix that fits. The copies are the price of the only
feasible formulation; see docs/NEXT.md "Consensus roofline verdict".
Kept runnable for regression on future shapes/backends.

Run AFTER tools/tpu_session.py finishes (one jax client at a time):
    python tools/bench_strategies_ab.py [--dial_timeout 300]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[ab {time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=300.0)
    p.add_argument("--keep_trace_dir", default="docs/tpu_r05/ab_trace",
                   help="per-variant trace keep prefix")
    args = p.parse_args(argv)

    base_runs = [
        ("outstacked,outstacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_outstacked,conv2d_outstacked"}),
        ("stacked,stacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_stacked,conv2d_stacked"}),
        ("outstacked,stacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_outstacked,conv2d_stacked"}),
        # Anchor: the promoted default, warm cache, keeps the session
        # comparable run-over-run.
        ("auto anchor", {}),
    ]
    runs = []
    for label, env in base_runs:
        if env:
            # Keep each variant's capture so the copy table is checkable
            # without a re-run (small: one block's device plane).
            env = dict(env, NCNET_BENCH_KEEP_TRACE=(
                args.keep_trace_dir + "_"
                + label.replace(",", "_").replace(" ", "_")
            ))
        runs.append((label, env))

    from ncnet_tpu.utils.profiling import run_bench_matrix

    return run_bench_matrix(
        runs, dial_timeout=args.dial_timeout,
        knobs=("NCNET_CONSENSUS_STRATEGIES", "NCNET_BENCH_KEEP_TRACE"),
        log=log,
    )


if __name__ == "__main__":
    sys.exit(main())
