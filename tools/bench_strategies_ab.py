"""Consensus-strategy A/B at the headline workload: kill the layout copies.

Round-5 capture truth (docs/tpu_r05/bench_trace, self-time traceagg):
consensus is the top stage at 502 ms/block, and ~265 ms of that is four
XLA layout copies around the two channels-last convs (conv4d.py:608/653
— the MXU conv wants the 6912 A-cells on lanes `{0,3,2,1}` while the
surrounding concat/slice/pad fusions emit `{1,2,3,0}`). The copies are a
property of the per-layer decomposition mix, so A/B the mixes end to end
in headline units: layer-1 'conv2d_stacked' pays an input-side concat
copy pair, layer-2 'conv2d_outstacked' pays an output-side copy per
symmetric branch. The default 'auto' is (stacked, outstacked) at the
InLoc (3,3)/(16,1) config (conv4d._auto_pick).

Run AFTER tools/tpu_session.py finishes (one jax client at a time):
    python tools/bench_strategies_ab.py [--dial_timeout 300]
Winner promotion: flip conv4d._auto_pick (and note the measurement in
docs/NEXT.md) if a fixed mix beats 'auto' at the headline.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[ab {time.time() - _T0:7.1f}s] {msg}", flush=True)


def _load_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=300.0)
    p.add_argument("--keep_trace_dir", default="docs/tpu_r05/ab_trace",
                   help="trace of the winning run (set per run below)")
    args = p.parse_args(argv)

    from ncnet_tpu.utils.profiling import (
        AlarmTimeout,
        dial_devices,
        run_with_alarm,
        setup_compile_cache,
    )

    setup_compile_cache()
    log(f"dialing (watchdog {args.dial_timeout:.0f}s)...")
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("dial timed out; aborting")
        return 2
    log(f"devices: {devices}")

    # Hard backstop mirroring tpu_session.py: a remote-compile wait stuck
    # in native code defers SIGALRM indefinitely; hard-exit past fence.
    import threading

    deadline = [None]

    def _watchdog():
        while True:
            time.sleep(30)
            d = deadline[0]
            if d is not None and time.time() > d:
                log("watchdog: alarm never landed; hard-exiting")
                os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()

    os.environ["NCNET_BENCH_DIAL_TIMEOUT"] = "120"
    os.environ["NCNET_BENCH_NO_REEXEC"] = "1"

    # Ordered by information value. Every non-default mix is a fresh XLA
    # program at InLoc shape (disk cache cold) — the documented
    # >20 min compile hang class gets the 1500 s fence + hard exit.
    runs = [
        # Hypothesis 1: outstacked layer-1 removes the input-side concat
        # copy pair (conv4d.py:608, 99 ms/block) without touching the
        # measured-good layer-2.
        ("outstacked,outstacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_outstacked,conv2d_outstacked"}),
        # Hypothesis 2: stacked layer-2 removes the output-side copies
        # (conv4d.py:653, 132 ms/block) at the price of a 144-feature
        # input concat.
        ("stacked,stacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_stacked,conv2d_stacked"}),
        # The remaining mix (auto's mirror image).
        ("outstacked,stacked",
         {"NCNET_CONSENSUS_STRATEGIES":
          "conv2d_outstacked,conv2d_stacked"}),
        # Anchor: the promoted default, warm cache, keeps the session
        # comparable run-over-run.
        ("auto anchor", {}),
    ]
    for label, env in runs:
        os.environ.pop("NCNET_CONSENSUS_STRATEGIES", None)
        os.environ.pop("NCNET_BENCH_KEEP_TRACE", None)
        os.environ.update(env)
        if env:
            # Keep each variant's capture so the copy table is checkable
            # without a re-run (small: one block's device plane).
            os.environ["NCNET_BENCH_KEEP_TRACE"] = (
                args.keep_trace_dir + "_"
                + label.replace(",", "_").replace(" ", "_")
            )
        log(f"=== bench[{label}] env={env} ===")
        deadline[0] = time.time() + 1500 + 180
        try:
            run_with_alarm(1500, _load_bench().main)
        except AlarmTimeout as exc:
            log(f"bench[{label}] TIMED OUT: {exc}")
        except Exception:  # noqa: BLE001
            log(f"bench[{label}] FAILED:\n{traceback.format_exc()}")
        finally:
            deadline[0] = None
            for k in env:
                os.environ.pop(k, None)
    log("A/B DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
