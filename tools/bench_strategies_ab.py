"""Consensus-strategy A/B at the headline workload: kill the layout copies.

Round-5 capture truth (docs/tpu_r05/bench_trace, self-time traceagg):
consensus is the top stage at 502 ms/block, and ~265 ms of that is four
XLA layout copies around the two channels-last convs (conv4d.py:608/653
— the MXU conv wants the 6912 A-cells on lanes `{0,3,2,1}` while the
surrounding concat/slice/pad fusions emit `{1,2,3,0}`). The copies are a
property of the per-layer decomposition mix, so A/B the mixes end to end
in headline units. The default 'auto' is (stacked, outstacked) at the
InLoc (3,3)/(16,1) config (conv4d._auto_pick).

MEASURED VERDICT (2026-08-02, docs/tpu_r05/ab_0401.log): all three
non-auto mixes are HBM-INFEASIBLE at one-shot InLoc scale — layer-1
outstacked and layer-2 stacked each materialize a bf16[6912,96,72,144]
(18.3 GB) intermediate, every bench tier fails to allocate, and 'auto'
remains the only mix that fits. The copies are the price of the only
feasible formulation; see docs/NEXT.md "Consensus roofline verdict".
Kept runnable for regression on future shapes/backends.

The candidate matrix is sourced from the autotuner's enumeration
(ncnet_tpu/ops/autotune.py — the single home shared with
tools/bench_consensus.py and tools/autotune_consensus.py), so it now
includes the branch-fused/unfused axis, the algebraic arms
(cp:rank=R / fft — ops/cp4d.py), and --include_folds extends it with
the KL-fold candidates the enumeration carries. The dense explicit-mix
lines are the CLOSED sweep (docs/NEXT.md verdict: HBM-infeasible at
headline scale) — they are dropped unless NCNET_BENCH_CLOSED_SWEEPS=1,
matching bench.py's own guard.

Stdout is ONE JSON line (per-run headline value + the plan kind/rank/
agreement fields bench_trend passes through); prose goes to stderr.

Run AFTER tools/tpu_session.py finishes (one jax client at a time):
    python tools/bench_strategies_ab.py [--dial_timeout 300]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[ab {time.time() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=300.0)
    p.add_argument("--keep_trace_dir", default="docs/tpu_r05/ab_trace",
                   help="per-variant trace keep prefix")
    p.add_argument("--n_layers", type=int, default=2,
                   help="consensus depth the headline model runs "
                        "(InLoc: 2)")
    p.add_argument("--include_folds", action="store_true",
                   help="also run the KL-fold candidates (off by "
                        "default: each A/B line is a full bench run)")
    p.add_argument("--max_runs", type=int, default=0,
                   help="0 = all; otherwise cap the matrix (session-"
                        "budget guard)")
    args = p.parse_args(argv)

    # Import is device-free: enumerate_plans only needs the layer count,
    # so the backend dial stays inside run_bench_matrix.
    from ncnet_tpu.ops import autotune

    plans = autotune.enumerate_plans(
        [{}] * args.n_layers, symmetric=True,
        kl_folds=(0, 2, 4) if args.include_folds else (0,),
        chunks=(0,),
    )
    # Closed-sweep filter (docs/NEXT.md): dense explicit-mix lines only
    # when the operator re-opens them, mirroring bench.py's guard.
    if os.environ.get("NCNET_BENCH_CLOSED_SWEEPS") != "1":
        open_plans = [pl for pl in plans
                      if pl["kind"] != "dense" or not pl["strategies"]]
        if len(open_plans) != len(plans):
            log(f"dropping {len(plans) - len(open_plans)} dense "
                "explicit-mix lines (closed sweep; "
                "NCNET_BENCH_CLOSED_SWEEPS=1 re-opens)")
        plans = open_plans
    base_runs = [(autotune.plan_label(pl), autotune.plan_env(pl))
                 for pl in plans]
    # Anchor: the promoted default (no knobs at all — heuristic + any
    # populated strategy cache), warm cache, keeps the session
    # comparable run-over-run.
    base_runs.append(("auto anchor", {}))
    if args.max_runs and len(base_runs) > args.max_runs:
        log(f"capping {len(base_runs)} runs to {args.max_runs}")
        base_runs = base_runs[: args.max_runs]

    runs = []
    for label, env in base_runs:
        if env:
            # Keep each variant's capture so the copy table is checkable
            # without a re-run (small: one block's device plane), and
            # disable the strategy cache: a tuned plan filling the
            # knobs a candidate left open would mislabel that line.
            env = dict(env, NCNET_STRATEGY_CACHE="",
                       NCNET_BENCH_KEEP_TRACE=(
                           args.keep_trace_dir + "_"
                           + label.replace(",", "_").replace(" ", "_")
                                  .replace("+", "_")
                       ))
        runs.append((label, env))

    from ncnet_tpu.utils.profiling import run_bench_matrix

    results = []

    def on_result(label, headline):
        rec = {"label": label, "value": None}
        if isinstance(headline, dict):
            for key in ("metric", "value", "unit", "consensus_plan_kind",
                        "cp_rank", "cp_agreement", "consensus_arms"):
                if key in headline:
                    rec[key] = headline[key]
        results.append(rec)

    rc = run_bench_matrix(
        runs, dial_timeout=args.dial_timeout,
        knobs=autotune.PLAN_ENV_KEYS
        + ("NCNET_BENCH_KEEP_TRACE", "NCNET_STRATEGY_CACHE"),
        log=log, on_result=on_result,
    )

    # ONE JSON line (the bench_serving.py posture): the best run's
    # headline value plus the full per-arm table — per-arm ms lives in
    # each run's consensus_arms block, agreement-vs-dense next to it.
    ok = [r for r in results if r["value"] is not None]
    best = max(ok, key=lambda r: r["value"], default=None)
    print(json.dumps({
        "metric": "consensus_ab_best_pairs_per_s",
        "unit": best.get("unit") if best else None,
        "value": None if best is None else best["value"],
        "best_label": None if best is None else best["label"],
        "consensus_plan_kind": (best or {}).get("consensus_plan_kind"),
        "cp_rank": (best or {}).get("cp_rank"),
        "cp_agreement": (best or {}).get("cp_agreement"),
        "runs": results,
        "n_runs": len(results),
        "n_failed": len(results) - len(ok),
    }), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
