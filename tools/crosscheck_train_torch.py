"""Torch-vs-JAX training-dynamics cross-check (VERDICT r2 item 5).

Round 2's `tools/sanity_train_improves_pck.py` found that the weak loss
improves while synthetic-pair PCK degrades (random backbone). Two
hypotheses: (a) a data/loss property (texture-identity shortcut), or
(b) a bug somewhere in THIS repo's training stack (loss, gradients,
optimizer, consensus AD). This tool separates them by training the same
model on the same data in BOTH frameworks and asserting the dynamics
agree:

  * one set of frozen features (tiny conv net over synthetic textured
    pairs, computed once, fed to both sides bit-identically);
  * the JAX side is the SHIPPED stack: ops.feature_correlation ->
    mutual_matching -> neigh_consensus_apply(symmetric) ->
    mutual_matching -> training.loss.weak_loss_from_features ->
    optax.adam — the exact modules cli/train.py runs;
  * the torch side is an INDEPENDENT reimplementation of the same
    semantics (written from this repo's docstrings — the symmetric
    branch uses the literal transpose formulation, deliberately NOT the
    swapped-kernel identity, so the identity itself is under test;
    loss spec parity: reference train.py:110-156);
  * step 0: loss and every consensus gradient must match to f32
    tolerance (this is the bug detector);
  * free-run N steps with per-framework Adam: loss curves must track
    (chaotic drift bounded by a loose per-step tolerance);
  * after training, keypoint-transfer error is measured from both
    frameworks' final corr tensors with one shared numpy argmax
    decoder, and the before/after PCK direction is reported.

Exit codes: 0 = frameworks agree (whatever PCK does — agreement means
the anomaly is a data/loss property, not a stack bug); 1 = mismatch
(a real bug: the step-0 gradient diff localizes it).

Runs on CPU in ~1 min:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python tools/crosscheck_train_torch.py
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

EPS_MUTUAL = 1e-5  # ops/mutual.py EPS
EPS_L2 = 1e-6      # ops/correlation.py feature_l2norm


# ----------------------------------------------------------------- data

def make_pairs(rng, n_pairs, size):
    """Textured source images + translation-warped targets (+ the shift)."""
    from tools.sanity_train_improves_pck import _affine, _texture, _warp

    srcs, tgts, shifts = [], [], []
    for _ in range(n_pairs):
        img = _texture(rng, size)
        M = _affine(rng, size)  # translation-only by default
        srcs.append(img)
        tgts.append(_warp(img, M))
        shifts.append(M[:, 2])  # target->source translation, pixels
    to_f = lambda ims: (
        np.stack(ims).astype(np.float32).transpose(0, 3, 1, 2) / 255.0 - 0.45
    ) / 0.225
    return to_f(srcs), to_f(tgts), np.stack(shifts)


def tiny_features(images, w1, b1, w2, b2):
    """Frozen 2-conv stride-2 backbone + channel L2 norm, in numpy f32.

    One implementation feeds BOTH frameworks, so feature mismatch can
    never masquerade as a training-stack difference.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def conv(x, w, b):
        y = lax.conv_general_dilated(
            x, w, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        return jax.nn.relu(y + b[None, :, None, None])

    x = jnp.asarray(images)
    y = conv(conv(x, jnp.asarray(w1), jnp.asarray(b1)),
             jnp.asarray(w2), jnp.asarray(b2))
    norm = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True) + EPS_L2)
    return np.asarray(y / norm, np.float32)


# ----------------------------------------------------- torch re-implementation

def torch_pipeline(fa, fb, params):
    """corr -> mutual -> symmetric consensus -> mutual, independent torch form.

    Semantics source: ops/correlation.py, ops/mutual.py (exact eps and
    multiplication grouping), ops/conv4d.py neigh_consensus_apply. The
    symmetric branch here literally transposes (A<->B), applies the same
    weights, and transposes back — the formulation this repo's
    swapped-kernel identity replaces.
    """
    import torch

    def mutual(c):
        max_over_a = torch.amax(c, dim=(2, 3), keepdim=True)
        max_over_b = torch.amax(c, dim=(4, 5), keepdim=True)
        return c * ((c / (max_over_b + EPS_MUTUAL))
                    * (c / (max_over_a + EPS_MUTUAL)))

    def conv4d(x, w, bias):
        # [b,cin,I,J,K,L] * [ki,kj,kk,kl,cin,cout]; 'same' zero padding.
        ki, kj, kk, kl, cin, cout = w.shape
        pad = (kl // 2, kl // 2, kk // 2, kk // 2,
               kj // 2, kj // 2, ki // 2, ki // 2)
        xp = torch.nn.functional.pad(x, pad)
        b_, _, si, sj, sk, sl = x.shape
        out = None
        for di in range(ki):
            for dj in range(kj):
                for dk in range(kk):
                    for dl in range(kl):
                        xs = xp[:, :, di:di + si, dj:dj + sj,
                                dk:dk + sk, dl:dl + sl]
                        term = torch.einsum(
                            "bcijkl,co->boijkl", xs, w[di, dj, dk, dl]
                        )
                        out = term if out is None else out + term
        return out + bias[None, :, None, None, None, None]

    def stack(x):
        for li, layer in enumerate(params):
            x = torch.relu(conv4d(x, layer["weight"], layer["bias"]))
        return x

    corr = torch.einsum("bcij,bckl->bijkl", fa, fb)[:, None]
    c = mutual(corr)
    swap = lambda t: t.permute(0, 1, 4, 5, 2, 3)
    c = stack(c) + swap(stack(swap(c)))
    return mutual(c)


def torch_loss(fa, fb, params):
    """Weak loss: score(rolled negatives) - score(positives)."""
    import torch

    def score(c):
        b = c.shape[0]
        fs1, fs2, fs3, fs4 = c.shape[2:]
        nc_b = torch.softmax(c.reshape(b, fs1 * fs2, fs3, fs4), dim=1)
        nc_a = torch.softmax(c.reshape(b, fs1, fs2, fs3 * fs4), dim=3)
        return (torch.amax(nc_a, dim=3).mean()
                + torch.amax(nc_b, dim=1).mean()) / 2

    pos = score(torch_pipeline(fa, fb, params))
    neg = score(torch_pipeline(torch.roll(fa, -1, dims=0), fb, params))
    return neg - pos


# ----------------------------------------------------------- shared decoding

def transfer_error(corr, shifts, stride):
    """Mean argmax keypoint-transfer error in feature cells, numpy.

    corr: [b,1,iA,jA,iB,jB] f32. For each B cell, the argmax A cell
    should sit at B + shift/stride (translation-only pairs).
    """
    b, _, i1, j1, i2, j2 = corr.shape
    flat = corr.reshape(b, i1 * j1, i2, j2)
    am = flat.argmax(axis=1)  # [b, iB, jB] -> A index
    ai, aj = np.unravel_index(am, (i1, j1))
    bi, bj = np.meshgrid(np.arange(i2), np.arange(j2), indexing="ij")
    errs = []
    for k in range(b):
        # target pixel -> source pixel shift is shifts[k] (x, y order)
        exp_i = bi + shifts[k][1] / stride
        exp_j = bj + shifts[k][0] / stride
        e = np.hypot(ai[k] - exp_i, aj[k] - exp_j)
        # Score only cells whose expected source cell is in-image.
        m = (exp_i >= 0) & (exp_i < i1) & (exp_j >= 0) & (exp_j < j1)
        errs.append(e[m])
    return float(np.concatenate(errs).mean())


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--n_pairs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--size", type=int, default=48)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=str, default="")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax
    import torch

    from ncnet_tpu.ops.conv4d import (
        neigh_consensus_apply,
        neigh_consensus_init,
    )
    from ncnet_tpu.ops.correlation import feature_correlation
    from ncnet_tpu.ops.mutual import mutual_matching
    from ncnet_tpu.training.loss import weak_loss_from_features

    torch.manual_seed(args.seed)
    torch.set_num_threads(1)
    rng = np.random.default_rng(args.seed)

    # Data + frozen features (shared bit-identically).
    srcs, tgts, shifts = make_pairs(rng, args.n_pairs, args.size)
    wb = [
        0.3 * rng.standard_normal((8, 3, 3, 3)).astype(np.float32),
        0.1 * rng.standard_normal(8).astype(np.float32),
        0.3 * rng.standard_normal((16, 8, 3, 3)).astype(np.float32),
        0.1 * rng.standard_normal(16).astype(np.float32),
    ]
    feat_a_all = tiny_features(srcs, *wb)
    feat_b_all = tiny_features(tgts, *wb)
    stride = args.size / feat_a_all.shape[2]

    # Identical initial consensus params.
    params0 = neigh_consensus_init(jax.random.PRNGKey(args.seed), (3, 3),
                                   (4, 1))
    params0 = jax.tree.map(lambda t: np.asarray(t, np.float32), params0)

    # --- JAX side: the shipped stack.
    def match(params):
        def fn(fa, fb):
            corr = feature_correlation(fa, fb, compute_dtype=jnp.float32)
            c = mutual_matching(corr)
            c = neigh_consensus_apply(params, c, symmetric=True)
            return mutual_matching(c).astype(jnp.float32)
        return fn

    def loss_jax(params, fa, fb):
        return weak_loss_from_features(match(params), fa, fb, "softmax")

    tx = optax.adam(args.lr)
    jp = jax.tree.map(jnp.asarray, params0)
    opt_state = tx.init(jp)
    grad_fn = jax.jit(jax.value_and_grad(loss_jax))

    # --- torch side.
    tp = [
        {k: torch.tensor(np.asarray(v), requires_grad=True)
         for k, v in layer.items()}
        for layer in params0
    ]
    topt = torch.optim.Adam(
        [t for layer in tp for t in layer.values()], lr=args.lr
    )

    # Fixed batch schedule shared by both loops.
    order = [
        rng.integers(0, args.n_pairs, args.batch) for _ in range(args.steps)
    ]

    # Step-0 check: loss + grads from identical params.
    idx0 = order[0]
    fa0, fb0 = feat_a_all[idx0], feat_b_all[idx0]
    l0_j, g_j = grad_fn(jp, jnp.asarray(fa0), jnp.asarray(fb0))
    l0_t = torch_loss(torch.tensor(fa0), torch.tensor(fb0), tp)
    l0_t.backward()
    grad_diffs = {}
    for li, layer in enumerate(g_j):
        for k in ("weight", "bias"):
            d = float(np.abs(np.asarray(layer[k])
                             - tp[li][k].grad.numpy()).max())
            grad_diffs[f"l{li}.{k}"] = d
    loss0_diff = abs(float(l0_j) - float(l0_t.item()))
    topt.zero_grad()

    # Free-run training, same batches, per-framework Adam.
    curve_j, curve_t = [], []
    for step in range(args.steps):
        idx = order[step]
        fa, fb = feat_a_all[idx], feat_b_all[idx]
        lj, gj = grad_fn(jp, jnp.asarray(fa), jnp.asarray(fb))
        updates, opt_state = tx.update(gj, opt_state, jp)
        jp = optax.apply_updates(jp, updates)
        curve_j.append(float(lj))

        topt.zero_grad()
        lt = torch_loss(torch.tensor(fa), torch.tensor(fb), tp)
        lt.backward()
        topt.step()
        curve_t.append(float(lt.item()))

    curve_j, curve_t = np.array(curve_j), np.array(curve_t)
    curve_diff = float(np.abs(curve_j - curve_t).max())

    # Post-training transfer error from both frameworks' corr tensors,
    # one shared decoder.
    fa_e = feat_a_all[: args.batch]
    fb_e = feat_b_all[: args.batch]
    corr_j = np.asarray(
        match(jp)(jnp.asarray(fa_e), jnp.asarray(fb_e)), np.float32
    )
    with torch.no_grad():
        corr_t = torch_pipeline(
            torch.tensor(fa_e), torch.tensor(fb_e), tp
        ).numpy()
    corr0 = np.asarray(
        match(jax.tree.map(jnp.asarray, params0))(
            jnp.asarray(fa_e), jnp.asarray(fb_e)
        ),
        np.float32,
    )
    err0 = transfer_error(corr0, shifts[: args.batch], stride)
    err_j = transfer_error(corr_j, shifts[: args.batch], stride)
    err_t = transfer_error(corr_t, shifts[: args.batch], stride)

    report = {
        "loss0_diff": loss0_diff,
        "grad_diffs": grad_diffs,
        "curve_diff_max": curve_diff,
        "loss_first": curve_j[0],
        "loss_last_jax": float(curve_j[-1]),
        "loss_last_torch": float(curve_t[-1]),
        "transfer_err_cells_init": err0,
        "transfer_err_cells_jax": err_j,
        "transfer_err_cells_torch": err_t,
    }
    print(json.dumps(report, indent=2))
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        with open(os.path.join(args.out, "crosscheck.json"), "w") as f:
            json.dump(report, f, indent=2)

    ok = (
        loss0_diff < 1e-5
        and max(grad_diffs.values()) < 1e-5
        and curve_diff < 5e-4
        and abs(err_j - err_t) < 0.5
    )
    verdict = (
        "FRAMEWORKS AGREE: training dynamics match torch — the "
        "loss-improves/PCK-degrades finding is a property of the weak "
        "loss + random features, not a bug in this stack."
        if ok else
        "MISMATCH: see grad_diffs/curve_diff — a training-stack bug."
    )
    print(verdict, file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
