#!/bin/bash
# Probe-then-session loop: dial-probe the tunnel with a short subprocess,
# and the moment it answers, run the full one-dial experiment session
# (tools/tpu_session.py). Exactly one JAX client at a time; 300 s between
# probe attempts (a wedged tunnel needs 10-25 min to clear, and hammering
# it with probes extends the wedge).
cd /root/repo || exit 1
OUT=docs/tpu_r05
mkdir -p "$OUT"
# NCNET_LOOP_ATTEMPTS: ~5-7 min per attempt; 80 spans ~8 h. Round 4
# observed the round window outlasting the default — size to the window.
for n in $(seq 1 "${NCNET_LOOP_ATTEMPTS:-80}"); do
  echo "=== session-loop attempt $n $(date -u +%FT%TZ) ===" >> "$OUT/session_loop.log"
  # Transport-layer forensics BEFORE the jax dial: "refused" = the remote
  # tunnel service is down (nothing local helps; observed 12:05-? after
  # the 11:28 session's hard exit), "timeout" = network/lease wedge,
  # "open" + a failed dial = client-visible lease wedge.
  probe_out=$(python - 2>&1 <<'PYEOF'
import os, socket
hp = os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")[0]
if hp:
    # Split host:port only for the two unambiguous forms — bracketed
    # IPv6 '[::1]:8471', or a single-colon host with a numeric tail.
    # A bare IPv6 literal ('::1', 'fe80::1') has >1 colon and is NOT a
    # port split even when its last group is numeric; probing a mangled
    # host would log a misleading DNS error instead of the transport
    # state, defeating the forensic purpose of this line.
    host, port_n = hp, 8471
    if hp.startswith("["):
        br, sep, port = hp.partition("]:")
        if sep and port.isdigit():
            host, port_n = br[1:], int(port)
        elif hp.endswith("]"):
            host = hp[1:-1]
    elif hp.count(":") == 1:
        h, _, port = hp.partition(":")
        if h and port.isdigit():
            host, port_n = h, int(port)
    # create_connection auto-selects the address family (an AF_INET
    # socket would turn every IPv6 literal into a resolver error).
    try:
        socket.create_connection((host, port_n), timeout=5).close()
        print("  tcp: open")
    except socket.timeout:
        print("  tcp: timeout")
    except OSError as e:
        print(f"  tcp: {e.strerror or e}")
PYEOF
  )
  echo "$probe_out" >> "$OUT/session_loop.log"
  # A refused TCP probe means the remote service is down — the 120 s jax
  # dial cannot succeed and only burns CPU against whatever else runs on
  # this box (the round-end driver bench measured a -7% smoke regression
  # under this loop's contention in round 4). Dial only when the probe
  # says open/timeout or could not say (empty endpoint/unknown error).
  case "$probe_out" in
    *"refused"*) sleep 300; continue ;;
  esac
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "=== tunnel up; starting session $(date -u +%FT%TZ) ===" >> "$OUT/session_loop.log"
    # timeout: a tunnel wedge after a successful dial otherwise hangs the
    # session in a device fetch forever (the dial watchdog only bounds the
    # dial); 2 h bounds a full session incl. first-compiles.
    timeout 7200 python tools/tpu_session.py --dial_timeout 300 "$@" \
      > "$OUT/session_$(date -u +%H%M).log" 2>&1
    rc=$?
    echo "=== session rc=$rc $(date -u +%FT%TZ) ===" >> "$OUT/session_loop.log"
    if [ "$rc" -eq 0 ]; then
      # Trimmed session landed — spend the rest of the tunnel window on
      # the FULL measurement session: the bench matrix re-runs first
      # (warm cache, fast) and then the phases the trimmed pass skipped
      # (corr_pool etc.) get their shot.
      if [ "$#" -gt 0 ]; then
        echo "=== chaining full session $(date -u +%FT%TZ) ===" >> "$OUT/session_loop.log"
        timeout 7200 python tools/tpu_session.py --dial_timeout 300 --skip smoke \
          > "$OUT/session_full_$(date -u +%H%M).log" 2>&1
        echo "=== full session rc=$? $(date -u +%FT%TZ) ===" >> "$OUT/session_loop.log"
      fi
      exit 0
    fi
  fi
  sleep 300
done
echo "=== gave up ===" >> "$OUT/session_loop.log"
exit 3
