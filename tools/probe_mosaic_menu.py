"""Mosaic primitive menu probe: which ops can the fused consensus kernel use?

probe_roll_kernel.py proved the basic [sk, 128] roll+mask+dot pattern
lowers. The full fused-consensus kernel has several candidate layouts
whose feasibility turns on specific Mosaic lowerings; this probe compiles
each in isolation on real hardware and prints a PASS/FAIL menu. The
design doc in docs/NEXT.md picks the layout from this table:

  lane_roll_xtile   roll the lane axis of [8, 1024] by 129 (crosses the
                    128-lane tile boundary) — needed by the C-major flat
                    layout ([c, K*LP]) where a (dk, dl) shift is one
                    lane roll by dk*LP + dl.
  sub_roll_big      roll the sublane axis of [1024, 32] by 129 — needed
                    by the flat-M layout ([K*LP, c]) where the shift is
                    a sublane roll.
  sub_concat_odd    concatenate [1, N] rows at sublane offset 1 (build
                    an [81, N] im2col by stacking tap rows).
  reshape_lanes     [M, K*128] -> [M, K, 128] lane retiling (unflatten
                    planes without a copy through HBM).
  roll_rank3        pltpu.roll on axis 1 of [8, 64, 128] (roll a
                    middle/sublane axis of a rank-3 block).
  dyn_scratch       lax.fori_loop with dynamic leading-index load from
                    an input block and accumulating store to a VMEM
                    scratch buffer (the per-j inner loop + out_acc
                    scatter pattern).

Each case checks numerics against numpy, not just compilation.

    python tools/probe_mosaic_menu.py              # dial + run all
    JAX_PLATFORMS=cpu ... --interpret              # CPU sanity
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=120.0)
    p.add_argument("--interpret", action="store_true")
    p.add_argument("--only", default="", help="comma-separated case names")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not args.interpret:
        from ncnet_tpu.utils.profiling import dial_devices

        if dial_devices(args.dial_timeout) is None:
            print("dial timed out")
            return 2

    rng = np.random.RandomState(0)
    results = {}

    def case(name, fn):
        if args.only and name not in args.only.split(","):
            return
        t0 = time.perf_counter()
        try:
            err = float(fn())
            ok = err < 1e-4
            results[name] = (
                f"{'PASS' if ok else 'NUMERIC-FAIL'} "
                f"err={err:.3g} {time.perf_counter() - t0:.1f}s"
            )
        except Exception as exc:  # noqa: BLE001
            msg = str(exc).split("\n")[0][:140]
            results[name] = (
                f"LOWER-FAIL ({type(exc).__name__}) {msg} "
                f"{time.perf_counter() - t0:.1f}s"
            )
        print(f"  {name:16s} {results[name]}", flush=True)

    def run1(kernel, out_sds, *xs):
        return jax.jit(
            lambda *a: pl.pallas_call(
                kernel, out_shape=out_sds, interpret=args.interpret
            )(*a)
        )(*xs)

    # -- lane_roll_xtile: [8, 1024] lanes rolled by 129 --------------------
    def lane_roll_xtile():
        x = jnp.asarray(rng.randn(8, 1024), jnp.float32)

        def k(x_ref, o_ref):
            o_ref[...] = pltpu.roll(x_ref[...], 129, 1)

        got = np.asarray(
            run1(k, jax.ShapeDtypeStruct((8, 1024), jnp.float32), x)
        )
        want = np.roll(np.asarray(x), 129, 1)
        return np.abs(got - want).max()

    case("lane_roll_xtile", lane_roll_xtile)

    # -- sub_roll_big: [1024, 32] sublanes rolled by 129 -------------------
    def sub_roll_big():
        x = jnp.asarray(rng.randn(1024, 32), jnp.float32)

        def k(x_ref, o_ref):
            o_ref[...] = pltpu.roll(x_ref[...], 129, 0)

        got = np.asarray(
            run1(k, jax.ShapeDtypeStruct((1024, 32), jnp.float32), x)
        )
        want = np.roll(np.asarray(x), 129, 0)
        return np.abs(got - want).max()

    case("sub_roll_big", sub_roll_big)

    # -- sub_concat_odd: stack 81 [1, N] rows ------------------------------
    def sub_concat_odd():
        x = jnp.asarray(rng.randn(1, 512), jnp.float32)

        def k(x_ref, o_ref):
            rows = [x_ref[...] * float(i) for i in range(81)]
            o_ref[...] = jnp.concatenate(rows, axis=0)

        got = np.asarray(
            run1(k, jax.ShapeDtypeStruct((81, 512), jnp.float32), x)
        )
        want = np.concatenate(
            [np.asarray(x) * float(i) for i in range(81)], 0
        )
        return np.abs(got - want).max()

    case("sub_concat_odd", sub_concat_odd)

    # -- reshape_lanes: [16, 8*128] -> [16, 8, 128] ------------------------
    def reshape_lanes():
        x = jnp.asarray(rng.randn(16, 1024), jnp.float32)

        def k(x_ref, o_ref):
            o_ref[...] = x_ref[...].reshape(16, 8, 128)

        got = np.asarray(
            run1(k, jax.ShapeDtypeStruct((16, 8, 128), jnp.float32), x)
        )
        want = np.asarray(x).reshape(16, 8, 128)
        return np.abs(got - want).max()

    case("reshape_lanes", reshape_lanes)

    # -- roll_rank3: roll axis 1 of [8, 64, 128] ---------------------------
    def roll_rank3():
        x = jnp.asarray(rng.randn(8, 64, 128), jnp.float32)

        def k(x_ref, o_ref):
            o_ref[...] = pltpu.roll(x_ref[...], 3, 1)

        got = np.asarray(
            run1(k, jax.ShapeDtypeStruct((8, 64, 128), jnp.float32), x)
        )
        want = np.roll(np.asarray(x), 3, 1)
        return np.abs(got - want).max()

    case("roll_rank3", roll_rank3)

    # -- dyn_scratch: fori_loop dynamic load + scratch accumulate ----------
    def dyn_scratch():
        sj, m, n = 12, 64, 128
        x = jnp.asarray(rng.randn(sj, m, n), jnp.float32)

        def k(x_ref, o_ref, acc):
            acc[...] = jnp.zeros_like(acc)

            def body(j, _):
                v = x_ref[j]  # dynamic leading index
                # accumulate into a rolling slot (j mod 3) then fold
                acc[jax.lax.rem(j, 3)] += v
                return 0

            jax.lax.fori_loop(0, sj, body, 0)
            o_ref[...] = acc[0] + acc[1] + acc[2]

        got = np.asarray(
            jax.jit(
                lambda a: pl.pallas_call(
                    k,
                    out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
                    scratch_shapes=[pltpu.VMEM((3, m, n), jnp.float32)],
                    interpret=args.interpret,
                )(a)
            )(x)
        )
        want = np.asarray(x).sum(0)
        return np.abs(got - want).max()

    case("dyn_scratch", dyn_scratch)

    print("menu:", {k: v.split()[0] for k, v in results.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
