"""Sweep the Conv4d strategies at consensus-stack shapes on this backend.

One invocation times every formulation of ncnet_tpu.ops.conv4d (conv2d /
conv3d / conv2d_stacked / conv2d_outstacked / convnd, skipping any the
backend rejects) on the
InLoc consensus layers (post-pool [1,1,100,75,100,75], 3^4 kernels,
1->16->1 channels) and on the PF-Pascal shape (25^4, 5^4 kernels), plus
the full symmetric neigh_consensus_apply. Prints one line per (shape,
strategy) so picking NCNET_CONV4D_STRATEGY for a backend is one run.

Usage:
    python tools/bench_conv4d.py [--scale 1.0] [--iters 5]
    # CPU smoke: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    #   python tools/bench_conv4d.py --scale 0.2 --iters 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

STRATEGIES = ("conv2d", "conv3d", "conv2d_stacked", "conv2d_outstacked",
              "convnd", "auto")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=float, default=1.0,
                   help="scale on the InLoc consensus shape (1.0 = 100x75)")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--reps", type=int, default=4,
                   help="applications chained inside one jit per timing")
    p.add_argument("--dial_timeout", type=float, default=900.0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.ops.conv4d import (
        conv4d_prepadded,
        neigh_consensus_apply,
        neigh_consensus_init,
    )
    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        print("backend dial timed out; aborting", file=sys.stderr)
        os._exit(2)
    print(f"# backend: {devices[0]}")

    ii = max(int(100 * args.scale) // 4 * 4, 8)
    jj = max(int(75 * args.scale) // 4 * 4, 8)
    cases = [
        # (name, shape [b,cin,I,J,K,L], kernel, cout, dtype)
        ("inloc-l1", (1, 1, ii, jj, ii, jj), 3, 16, jnp.bfloat16),
        ("inloc-l2", (1, 16, ii, jj, ii, jj), 3, 1, jnp.bfloat16),
        ("pfpascal-l1", (1, 1, 25, 25, 25, 25), 5, 16, jnp.float32),
        ("pfpascal-l2", (1, 16, 25, 25, 25, 25), 5, 16, jnp.float32),
    ]

    def timed(fn, *xs):
        _, steady, _ = timed_steady(
            chain_reps(fn, args.reps), *xs, iters=args.iters
        )
        return steady / args.reps

    for name, shape, k, cout, dtype in cases:
        b, cin = shape[:2]
        x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype)
        w = jax.random.normal(
            jax.random.PRNGKey(1), (k, k, k, k, cin, cout), jnp.float32
        ) * (1.0 / (cin * k**4) ** 0.5)
        bias = jnp.zeros((cout,), jnp.float32)
        xp = jnp.pad(
            x, ((0, 0), (0, 0), (k // 2, k // 2)) + ((0, 0),) * 3
        )
        for strategy in STRATEGIES:
            try:
                dt = timed(
                    lambda a, ww, bb, s=strategy: conv4d_prepadded(
                        a, ww, bb, strategy=s
                    ),
                    xp, w, bias,
                )
                print(f"{name:14s} {strategy:15s} {dt * 1e3:9.2f} ms")
            except Exception as exc:  # noqa: BLE001
                print(f"{name:14s} {strategy:15s} unsupported "
                      f"({type(exc).__name__})")

    # Full symmetric consensus stack at the InLoc config.
    params = neigh_consensus_init(jax.random.PRNGKey(2), (3, 3), (16, 1))
    corr = jax.random.normal(
        jax.random.PRNGKey(3), (1, 1, ii, jj, ii, jj), jnp.bfloat16
    )
    dt = timed(
        lambda c, p: neigh_consensus_apply(p, c, symmetric=True), corr, params
    )
    print(f"{'consensus-stack':14s} {'(default)':15s} {dt * 1e3:9.2f} ms")


if __name__ == "__main__":
    main()
