"""Program cost-card report: roofline table, diff, and cost gate.

Reads a card set — the ``program_cards.json`` sidecar that warmup /
autotune persist next to the strategy cache (obs/costcards.py), or the
``program_card`` events of a runlog — and renders a per-bucket table to
STDERR with each program's roofline placement:

    key                                  GFLOP    MB acc   FLOP/B  side
    batch_pairs|q64x64|p64x64|b1|oneshot  5.15      83.2      62.0  mem
    ...

``side`` is where the program sits relative to the chip ridge point
(PEAK_TFLOPS_BF16 / PEAK_HBM_GBS, utils/traceagg.py): arithmetic
intensity below the ridge is memory-bound ("mem"), above is
compute-bound ("comp"). On CPU-captured cards the placement still uses
the TPU ridge — the cards exist to predict device behavior.

``--diff OTHER`` compares a second card set key-by-key (relative FLOP
/ bytes / temp deltas). ``--baseline PATH --strict`` turns any shared
card whose flops, bytes_accessed, or temp_bytes grew more than
``--threshold`` (default 10%) over the committed baseline into a
nonzero exit — the bench_trend.py gate posture, applied to compiled
program cost instead of wall clock.

One JSON line on stdout is the whole machine-readable contract; prose
goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ncnet_tpu.utils.traceagg import PEAK_HBM_GBS, PEAK_TFLOPS_BF16  # noqa: E402

RIDGE_FLOPS_PER_BYTE = PEAK_TFLOPS_BF16 * 1e12 / (PEAK_HBM_GBS * 1e9)
DEFAULT_CARDS = os.path.join("trained_models", "program_cards.json")

# The cost axes the gate watches. Growth on any of them past the
# threshold is a regression: more FLOPs or more bytes moved per
# program is slower at fixed roofline, and more temp HBM shrinks the
# batch/bucket headroom warmup accounts for.
GATE_FIELDS = (
    ("flops", ("xla", "flops")),
    ("bytes_accessed", ("xla", "bytes_accessed")),
    ("temp_bytes", ("memory", "temp_bytes")),
)


def _field(card: dict, path) -> Optional[float]:
    node = card
    for part in path:
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return float(node) if node is not None else None


def load_card_set(path: str) -> Dict[str, dict]:
    """Cards keyed by card key, from a sidecar JSON or a runlog JSONL
    (``program_card`` events; the last event per key wins)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        data = json.loads(text)
        if isinstance(data, dict) and "cards" in data:
            return dict(data["cards"] or {})
    except ValueError:
        pass
    cards: Dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("event") == "program_card" and rec.get("key"):
            cards[rec["key"]] = rec
    return cards


def roofline_side(card: dict) -> Optional[str]:
    ai = card.get("flops_per_byte")
    if ai is None:
        return None
    return "comp" if float(ai) >= RIDGE_FLOPS_PER_BYTE else "mem"


def card_plan(card: dict) -> Optional[str]:
    """The consensus arm the card was modeled for — 'cp:rank=N' / 'fft'
    / 'dense' (obs/costcards.py consensus_model kind/cp_rank), None on
    cards with no analytic model."""
    model = card.get("model")
    if not isinstance(model, dict) or "kind" not in model:
        return None
    kind = str(model.get("kind") or "dense")
    if kind == "cp":
        return f"cp:rank={int(model.get('cp_rank') or 0)}"
    return kind


def card_rows(cards: Dict[str, dict]) -> List[dict]:
    rows = []
    for key in sorted(cards):
        card = cards[key]
        rows.append({
            "key": key,
            "program": card.get("program"),
            "flops": _field(card, ("xla", "flops")),
            "bytes_accessed": _field(card, ("xla", "bytes_accessed")),
            "temp_bytes": _field(card, ("memory", "temp_bytes")),
            "flops_per_byte": card.get("flops_per_byte"),
            "model_ok": card.get("model_ok"),
            "plan": card_plan(card),
            "roofline": roofline_side(card),
            "backend": card.get("backend"),
        })
    return rows


def diff_card_sets(cards: Dict[str, dict], other: Dict[str, dict],
                   threshold: float) -> dict:
    """Per-key relative cost deltas of ``cards`` vs ``other`` (the
    baseline). A key regresses when any gate field grew more than
    ``threshold`` relative to the baseline value."""
    shared = sorted(set(cards) & set(other))
    entries, regressions = [], []
    for key in shared:
        entry = {"key": key}
        worst = None
        for name, path in GATE_FIELDS:
            new = _field(cards[key], path)
            old = _field(other[key], path)
            if new is None or old is None or old <= 0:
                continue
            rel = (new - old) / old
            entry[f"{name}_rel"] = round(rel, 6)
            worst = rel if worst is None else max(worst, rel)
        entry["regressed"] = worst is not None and worst > threshold
        if entry["regressed"]:
            regressions.append(key)
        entries.append(entry)
    return {
        "shared": len(shared),
        "only_current": sorted(set(cards) - set(other)),
        "only_baseline": sorted(set(other) - set(cards)),
        "entries": entries,
        "regressions": regressions,
        "threshold": threshold,
    }


def _fmt(v, scale, nd=2) -> str:
    return f"{v / scale:.{nd}f}" if v is not None else "-"


def render_table(rows: List[dict]) -> str:
    width = max([len(r["key"]) for r in rows] + [len("key")])
    lines = [f"{'key':<{width}}  {'GFLOP':>9}  {'MB acc':>9}  "
             f"{'MB tmp':>9}  {'FLOP/B':>7}  {'model':>5}  "
             f"{'plan':>10}  side"]
    for r in rows:
        ai = r["flops_per_byte"]
        model = {True: "ok", False: "FAIL", None: "-"}[r["model_ok"]]
        lines.append(
            f"{r['key']:<{width}}  {_fmt(r['flops'], 1e9):>9}  "
            f"{_fmt(r['bytes_accessed'], 1e6):>9}  "
            f"{_fmt(r['temp_bytes'], 1e6):>9}  "
            f"{(f'{ai:.1f}' if ai is not None else '-'):>7}  "
            f"{model:>5}  {(r['plan'] or '-'):>10}  "
            f"{r['roofline'] or '-'}")
    lines.append(f"ridge: {RIDGE_FLOPS_PER_BYTE:.1f} FLOP/byte "
                 f"({PEAK_TFLOPS_BF16:g} TFLOP/s bf16 / "
                 f"{PEAK_HBM_GBS:g} GB/s HBM)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cards", nargs="?", default=DEFAULT_CARDS,
                    help="card set: sidecar JSON or runlog JSONL "
                         f"(default {DEFAULT_CARDS})")
    ap.add_argument("--diff", metavar="OTHER",
                    help="second card set to diff against (baseline)")
    ap.add_argument("--baseline",
                    help="committed baseline card set for --strict "
                         "(implies a diff against it)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative cost growth vs baseline that counts "
                         "as a regression (default 0.10)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regression vs --baseline/--diff, "
                         "or on any model_ok=false card")
    args = ap.parse_args(argv)

    try:
        cards = load_card_set(args.cards)
    except (OSError, ValueError) as exc:
        print(json.dumps({"cards": None, "error": str(exc)}))
        print(f"cannot read {args.cards}: {exc}", file=sys.stderr)
        return 1 if args.strict else 0

    rows = card_rows(cards)
    report = {
        "source": args.cards,
        "n_cards": len(rows),
        "ridge_flops_per_byte": round(RIDGE_FLOPS_PER_BYTE, 2),
        "cards": rows,
        "model_failures": [r["key"] for r in rows
                           if r["model_ok"] is False],
    }
    if rows:
        print(render_table(rows), file=sys.stderr)
    else:
        print(f"no cards in {args.cards}", file=sys.stderr)

    base_path = args.baseline or args.diff
    if base_path:
        try:
            base = load_card_set(base_path)
        except (OSError, ValueError) as exc:
            report["diff"] = {"error": str(exc), "baseline": base_path}
            print(f"cannot read baseline {base_path}: {exc}",
                  file=sys.stderr)
            print(json.dumps(report))
            return 1 if args.strict else 0
        diff = diff_card_sets(cards, base, args.threshold)
        diff["baseline"] = base_path
        report["diff"] = diff
        for key in diff["regressions"]:
            entry = next(e for e in diff["entries"] if e["key"] == key)
            rels = {k: v for k, v in entry.items()
                    if k.endswith("_rel")}
            print(f"COST REGRESSION: {key} {rels}", file=sys.stderr)

    regressed = bool(report.get("diff", {}).get("regressions"))
    report["regressed"] = regressed
    print(json.dumps(report))
    if args.strict and report["model_failures"]:
        print("model_ok=false card(s): "
              + ", ".join(report["model_failures"]), file=sys.stderr)
        return 1
    return 1 if (args.strict and regressed) else 0


if __name__ == "__main__":
    sys.exit(main())
