"""Threaded open-loop load generator for the online matching service.

Drives ``POST /v1/match`` at a fixed arrival rate (open loop: arrivals
are scheduled on the wall clock, independent of completions — the
honest way to measure a service's latency under load; closed-loop
clients hide queueing collapse by slowing down with the server) and
prints ONE JSON line (the repo's bench stdout contract,
tests/test_bench_contract.py):

    {"metric": "serving_match_throughput_rps", "value": N,
     "unit": "req/s", "latency_ms": {"p50": ..., "p95": ..., "p99": ...},
     "sent": ..., "ok": ..., "rejected": ..., "errors": ...,
     "deadline_exceeded": ..., "batched_frac": ..., "duration_s": ...,
     "slo": {"availability": ..., "availability_objective": ...,
             "availability_met": ..., "deadline_hit_rate": ...,
             "p99_ms": ..., "p99_target_ms": ..., "p99_met": ...,
             "met": ...}}

The ``slo`` block applies obs/slo.py's serving definitions from the
client side (``--slo_availability``, ``--slo_p99_ms``); ``--slo_strict``
turns a missed objective into a nonzero exit, so a bench run can gate a
deploy the way tier-1 tests gate a commit.

Request payloads: ``--query/--pano`` point at server-readable files, or
``--synthetic HxW`` generates random JPEGs once and ships them inline
(base64) — self-contained against any server. Stage notes go to stderr.

Example (CPU smoke)::

    python -m ncnet_tpu.serving.server --port 8123 --image_size 64 &
    python tools/bench_serving.py --url http://127.0.0.1:8123 \
        --synthetic 96x128 --rate 4 --duration_s 5
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import threading
import time


def note(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


def percentile(sorted_vals, q):
    """Nearest-rank percentile on a pre-sorted list (no numpy needed —
    the load generator stays stdlib-only, like serving/client.py)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def synth_jpegs(spec, seed=0):
    """Two random JPEGs (query, pano) at HxW — encoded once, sent inline."""
    import numpy as np
    from PIL import Image

    h, w = (int(v) for v in spec.split("x"))
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(2):
        img = Image.fromarray(
            (rng.random((h, w, 3)) * 255).astype("uint8")
        )
        buf = io.BytesIO()
        img.save(buf, format="JPEG")
        out.append(buf.getvalue())
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="open-loop load generator for the matching service"
    )
    parser.add_argument("--url", type=str, required=True)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="open-loop arrival rate, requests/s")
    parser.add_argument("--duration_s", type=float, default=10.0)
    parser.add_argument("--threads", type=int, default=16,
                        help="worker pool size (bounds in-flight requests)")
    parser.add_argument("--query", type=str, default="",
                        help="server-readable query image path")
    parser.add_argument("--pano", type=str, default="",
                        help="server-readable pano image path")
    parser.add_argument("--synthetic", type=str, default="",
                        help="HxW: generate random images, send inline b64")
    parser.add_argument("--deadline_ms", type=float, default=0.0,
                        help="per-request deadline (0 = server default)")
    parser.add_argument("--max_matches", type=int, default=16)
    parser.add_argument("--no_retry", action="store_true",
                        help="count 503s as rejected instead of retrying")
    parser.add_argument("--slo_availability", type=float, default=0.999,
                        help="availability objective for the SLO summary")
    parser.add_argument("--slo_p99_ms", type=float, default=0.0,
                        help="p99 latency target for the SLO summary "
                             "(0 = no latency gate)")
    parser.add_argument("--slo_strict", action="store_true",
                        help="exit 1 when the run misses its SLOs")
    args = parser.parse_args(argv)
    if bool(args.synthetic) == bool(args.query and args.pano):
        parser.error("pass either --synthetic HxW or both --query/--pano")

    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        ServingError,
    )

    kwargs = {"max_matches": args.max_matches}
    if args.deadline_ms > 0:
        kwargs["deadline_ms"] = args.deadline_ms
    if args.synthetic:
        q_bytes, p_bytes = synth_jpegs(args.synthetic)
        kwargs.update(query_bytes=q_bytes, pano_bytes=p_bytes)
    else:
        kwargs.update(query_path=args.query, pano_path=args.pano)

    client = MatchClient(args.url, retries=0 if args.no_retry else 2)
    health = client.healthz()
    note(f"healthz: {health}")

    n_requests = max(1, int(args.rate * args.duration_s))
    lock = threading.Lock()
    lat_ms, batch_sizes = [], []
    counts = {"sent": 0, "ok": 0, "rejected": 0, "errors": 0,
              "deadline_exceeded": 0}
    # Open loop: request i fires at t0 + i/rate regardless of completions.
    # A schedule index handed out under the lock keeps workers from
    # coordinating on anything but the wall clock.
    sched = {"next": 0}
    t0 = time.monotonic()

    def worker():
        while True:
            with lock:
                i = sched["next"]
                if i >= n_requests:
                    return
                sched["next"] = i + 1
            due = t0 + i / args.rate
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                resp = client.match(**kwargs)
            except OverCapacityError:
                with lock:
                    counts["sent"] += 1
                    counts["rejected"] += 1
                continue
            except (ServingError, OSError) as exc:
                # 504 = the server's DeadlineBatcher gave up honestly;
                # it feeds the deadline-hit SLO, not the error count.
                deadline = getattr(exc, "status", None) == 504
                with lock:
                    counts["sent"] += 1
                    counts["deadline_exceeded" if deadline
                           else "errors"] += 1
                note(f"error on req {i}: {exc}")
                continue
            dt_ms = (time.monotonic() - t_req) * 1e3
            with lock:
                counts["sent"] += 1
                counts["ok"] += 1
                lat_ms.append(dt_ms)
                batch_sizes.append(resp.get("batch_size", 1))

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(min(args.threads, n_requests))
    ]
    note(f"load: {n_requests} requests at {args.rate}/s open-loop, "
         f"{len(threads)} workers")
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0

    lat_ms.sort()
    batched = sum(1 for b in batch_sizes if b > 1)

    # SLO summary — the same definitions obs/slo.default_serving_slos
    # uses, measured from the client side: availability over requests
    # the server owed an answer (200/500/504; shed 503s excluded),
    # deadline-hit over requests that ran, p99 vs an optional target.
    answered = counts["ok"] + counts["errors"] + counts["deadline_exceeded"]
    availability = counts["ok"] / answered if answered else None
    ran = counts["ok"] + counts["deadline_exceeded"]
    deadline_hit_rate = counts["ok"] / ran if ran else None
    p99_ms = percentile(lat_ms, 99) if lat_ms else None
    availability_met = (availability is None
                        or availability >= args.slo_availability)
    p99_met = (args.slo_p99_ms <= 0 or p99_ms is None
               or p99_ms <= args.slo_p99_ms)
    slo = {
        "availability": round(availability, 6)
        if availability is not None else None,
        "availability_objective": args.slo_availability,
        "availability_met": availability_met,
        "deadline_hit_rate": round(deadline_hit_rate, 6)
        if deadline_hit_rate is not None else None,
        "p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
        "p99_target_ms": args.slo_p99_ms if args.slo_p99_ms > 0 else None,
        "p99_met": p99_met,
        "met": availability_met and p99_met,
    }

    rec = {
        "metric": "serving_match_throughput_rps",
        "value": round(counts["ok"] / elapsed, 4) if elapsed > 0 else 0.0,
        "unit": "req/s",
        "latency_ms": {
            "p50": round(percentile(lat_ms, 50), 3) if lat_ms else None,
            "p95": round(percentile(lat_ms, 95), 3) if lat_ms else None,
            "p99": round(percentile(lat_ms, 99), 3) if lat_ms else None,
        },
        "sent": counts["sent"],
        "ok": counts["ok"],
        "rejected": counts["rejected"],
        "errors": counts["errors"],
        "deadline_exceeded": counts["deadline_exceeded"],
        "batched_frac": round(batched / len(batch_sizes), 4)
        if batch_sizes else 0.0,
        "mean_batch_size": round(sum(batch_sizes) / len(batch_sizes), 3)
        if batch_sizes else None,
        "duration_s": round(elapsed, 3),
        "slo": slo,
    }
    print(json.dumps(rec), flush=True)
    if args.slo_strict and not slo["met"]:
        return 1
    return 0 if counts["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
