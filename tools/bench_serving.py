"""Threaded open-loop load generator for the online matching service.

Drives ``POST /v1/match`` at a fixed arrival rate (open loop: arrivals
are scheduled on the wall clock, independent of completions — the
honest way to measure a service's latency under load; closed-loop
clients hide queueing collapse by slowing down with the server) and
prints ONE JSON line (the repo's bench stdout contract,
tests/test_bench_contract.py):

    {"metric": "serving_match_throughput_rps", "value": N,
     "unit": "req/s", "latency_ms": {"p50": ..., "p95": ..., "p99": ...},
     "sent": ..., "ok": ..., "rejected": ..., "errors": ...,
     "deadline_exceeded": ..., "batched_frac": ..., "duration_s": ...,
     "slo": {"availability": ..., "availability_objective": ...,
             "availability_met": ..., "deadline_hit_rate": ...,
             "p99_ms": ..., "p99_target_ms": ..., "p99_met": ...,
             "met": ...}}

The ``slo`` block applies obs/slo.py's serving definitions from the
client side (``--slo_availability``, ``--slo_p99_ms``); ``--slo_strict``
turns a missed objective into a nonzero exit, so a bench run can gate a
deploy the way tier-1 tests gate a commit.

Request payloads: ``--query/--pano`` point at server-readable files, or
``--synthetic HxW`` generates random JPEGs once and ships them inline
(base64) — self-contained against any server. Stage notes go to stderr.

Example (CPU smoke)::

    python -m ncnet_tpu.serving.server --port 8123 --image_size 64 &
    python tools/bench_serving.py --url http://127.0.0.1:8123 \
        --synthetic 96x128 --rate 4 --duration_s 5

**Fleet mode** (``--replicas N``, mutually exclusive with ``--url``):
spins up TWO in-process fleets — a 1-replica baseline at ``--rate``,
then N replicas at ``--rate x N`` (weak scaling: offered load grows
with capacity, so a fleet that keeps up IS the scaling evidence) — and
prints one line with the fleet headline::

    {"metric": "serving_fleet_pairs_per_s", "value": ..., "unit":
     "pairs/s", "replicas": N, "single_replica_pairs_per_s": ...,
     "scaling_x": ..., "scaling_efficiency": ..., "per_replica":
     {"fleet-d0": {"admitted": ..., "batches": ...}, ...}, ...}

``scaling_efficiency`` = scaling_x / N is reported HONESTLY: on a
single-core CPU host the replicas time-slice one core and efficiency
lands near 1/N; the >= 0.75 deployments should gate on needs one real
device per replica (``parallel.serving_devices``).

    python tools/bench_serving.py --replicas 8 --synthetic 96x128 \
        --rate 2 --duration_s 5

**Session mode** (``--session``): one streaming video session (open ->
``--frames`` frames -> close) against a baseline of the same frames as
one-shot ``mode='c2f'`` requests; prints one ``serving_session_fps``
line with the seeded / unseeded / full-coarse latency split and the
seed-hit fraction::

    python tools/bench_serving.py --replicas 1 --session \
        --synthetic 96x128 --frames 16
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import threading
import time


def note(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


def percentile(sorted_vals, q):
    """Nearest-rank percentile on a pre-sorted list (no numpy needed —
    the load generator stays stdlib-only, like serving/client.py)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def synth_jpegs(spec, seed=0, n=2):
    """``n`` random JPEGs at HxW — encoded once, sent inline. The
    default two are the (query, pano) pair; session mode asks for a
    reference plus one image per frame."""
    import numpy as np
    from PIL import Image

    h, w = (int(v) for v in spec.split("x"))
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        img = Image.fromarray(
            (rng.random((h, w, 3)) * 255).astype("uint8")
        )
        buf = io.BytesIO()
        img.save(buf, format="JPEG")
        out.append(buf.getvalue())
    return out


def run_load(client, kwargs, rate, duration_s, threads):
    """Open-loop load against one client: request i fires at t0 + i/rate
    regardless of completions (closed-loop clients hide queueing
    collapse by slowing down with the server). Returns
    ``{counts, lat_ms (sorted), batch_sizes, elapsed, n_requests}`` —
    shared by the URL mode and both fleet-bench phases."""
    from ncnet_tpu.serving.client import OverCapacityError, ServingError

    n_requests = max(1, int(rate * duration_s))
    lock = threading.Lock()
    lat_ms, batch_sizes = [], []
    rungs, degraded = set(), [0]
    counts = {"sent": 0, "ok": 0, "rejected": 0, "throttled": 0,
              "errors": 0, "deadline_exceeded": 0}
    # A schedule index handed out under the lock keeps workers from
    # coordinating on anything but the wall clock.
    sched = {"next": 0}
    t0 = time.monotonic()

    def worker():
        while True:
            with lock:
                i = sched["next"]
                if i >= n_requests:
                    return
                sched["next"] = i + 1
            due = t0 + i / rate
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                resp = client.match(**kwargs)
            except OverCapacityError as exc:
                # Tenant-scoped refusals (429 tenant_budget /
                # tenant_slots) are this tenant throttling at its OWN
                # limits, not service pressure — split them out so a
                # mixed-tenant report doesn't read fairness isolation
                # as an availability problem.
                kind = (exc.payload or {}).get("kind") \
                    if isinstance(exc.payload, dict) else None
                with lock:
                    counts["sent"] += 1
                    counts["throttled" if kind in
                           ("tenant_budget", "tenant_slots")
                           else "rejected"] += 1
                continue
            except (ServingError, OSError) as exc:
                # 504 = the server's DeadlineBatcher gave up honestly;
                # it feeds the deadline-hit SLO, not the error count.
                deadline = getattr(exc, "status", None) == 504
                with lock:
                    counts["sent"] += 1
                    counts["deadline_exceeded" if deadline
                           else "errors"] += 1
                note(f"error on req {i}: {exc}")
                continue
            dt_ms = (time.monotonic() - t_req) * 1e3
            with lock:
                counts["sent"] += 1
                counts["ok"] += 1
                lat_ms.append(dt_ms)
                batch_sizes.append(resp.get("batch_size", 1))
                qv = resp.get("qos")
                if qv:  # QoS-enabled server: audit the rungs visited
                    rungs.add(int(qv.get("rung", 0)))
                    if qv.get("degraded"):
                        degraded[0] += 1

    workers = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(min(threads, n_requests))
    ]
    note(f"load: {n_requests} requests at {rate:g}/s open-loop, "
         f"{len(workers)} workers")
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    lat_ms.sort()
    return {"counts": counts, "lat_ms": lat_ms,
            "batch_sizes": batch_sizes,
            "rungs": sorted(rungs), "degraded": degraded[0],
            "elapsed": time.monotonic() - t0, "n_requests": n_requests}


def tenants_bench(args, kwargs):
    """Mixed multi-tenant load against one server (``--tenants``).

    Each ``name:priority:rate`` spec drives its own open-loop load with
    that tenant's headers, all concurrently; the report is per-tenant
    availability / p99 / rungs visited — the client-side audit of the
    server's QoS ladder (docs/SERVING.md, multi-tenant QoS).
    """
    from ncnet_tpu.serving.client import MatchClient

    specs = []
    for s in args.tenants:
        parts = s.split(":")
        if len(parts) != 3:
            raise SystemExit(
                f"bad --tenants spec {s!r} (want name:priority:rate)")
        specs.append((parts[0], parts[1], float(parts[2])))

    results = {}
    lock = threading.Lock()

    def run_one(name, priority, rate):
        client = MatchClient(args.url, retries=0 if args.no_retry else 2)
        kw = dict(kwargs, tenant=name, priority=priority)
        res = run_load(client, kw, rate, args.duration_s, args.threads)
        with lock:
            results[name] = res

    drivers = [threading.Thread(target=run_one, args=spec, daemon=True)
               for spec in specs]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join()

    per_tenant = {}
    total_ok, elapsed = 0, 0.0
    for name, priority, rate in specs:
        res = results[name]
        counts, lat = res["counts"], res["lat_ms"]
        answered = (counts["ok"] + counts["errors"]
                    + counts["deadline_exceeded"])
        per_tenant[name] = {
            "priority": priority,
            "rate": rate,
            "sent": counts["sent"],
            "ok": counts["ok"],
            "rejected": counts["rejected"],
            "throttled": counts["throttled"],
            "errors": counts["errors"],
            "deadline_exceeded": counts["deadline_exceeded"],
            "availability": round(counts["ok"] / answered, 6)
            if answered else None,
            "p50_ms": round(percentile(lat, 50), 3) if lat else None,
            "p99_ms": round(percentile(lat, 99), 3) if lat else None,
            "rungs_visited": res["rungs"],
            "degraded": res["degraded"],
        }
        total_ok += counts["ok"]
        elapsed = max(elapsed, res["elapsed"])
    rec = {
        "metric": "serving_tenant_mix_rps",
        "value": round(total_ok / elapsed, 4) if elapsed > 0 else 0.0,
        "unit": "req/s",
        "tenants": per_tenant,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    errors = sum(results[n]["counts"]["errors"] for n, _, _ in specs)
    return 0 if errors == 0 else 1


def fleet_bench(args, model=None):
    """Two-phase weak-scaling bench over in-process replica fleets."""
    from ncnet_tpu import obs
    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    q_bytes, p_bytes = synth_jpegs(args.synthetic)
    kwargs = {"query_bytes": q_bytes, "pano_bytes": p_bytes,
              "max_matches": args.max_matches}

    def phase(n_replicas, base_id, rate, duration_s):
        timeout_s = max(duration_s * 4, 60.0)
        fleet = MatchFleet.build(
            config, params,
            n_replicas=n_replicas,
            base_id=base_id,
            cache_mb=0,  # inline-b64 payloads never touch the store
            engine_kwargs=dict(k_size=2, image_size=args.image_size),
            replica_kwargs=dict(
                max_batch=args.max_batch,
                max_delay_s=args.max_delay_ms / 1e3,
                default_timeout_s=timeout_s,
            ),
        )
        # Warm the exact buckets the load hits: the bench must measure
        # serving, not first-request XLA compiles.
        fleet.warmup([(h, w, h, w)],
                     batch_sizes=sorted({1, max(1, args.max_batch // 2),
                                         args.max_batch}))
        rids = [r.replica_id for r in fleet.replicas]
        # Counters are process-cumulative; deltas keep repeated
        # in-process runs (tests call main() directly) honest.
        before = {
            rid: (obs.counter("serving.admitted",
                              labels={"replica": rid}).value,
                  obs.counter("serving.batches",
                              labels={"replica": rid}).value)
            for rid in rids
        }
        redisp0 = obs.counter("serving.redispatched").value
        server = MatchServer(None, port=0, fleet=fleet).start()
        try:
            client = MatchClient(server.url, timeout_s=timeout_s,
                                 retries=0 if args.no_retry else 2)
            res = run_load(client, kwargs, rate, duration_s, args.threads)
        finally:
            server.stop()
        res["per_replica"] = {
            rid: {
                "admitted": obs.counter(
                    "serving.admitted", labels={"replica": rid}
                ).value - before[rid][0],
                "batches": obs.counter(
                    "serving.batches", labels={"replica": rid}
                ).value - before[rid][1],
            }
            for rid in rids
        }
        res["redispatched"] = (
            obs.counter("serving.redispatched").value - redisp0)
        return res

    base_dur = args.baseline_duration_s or args.duration_s
    note(f"phase 1/2: baseline — 1 replica at {args.rate:g}/s")
    base = phase(1, "base", args.rate, base_dur)
    fleet_rate = args.rate * args.replicas
    note(f"phase 2/2: fleet — {args.replicas} replicas at "
         f"{fleet_rate:g}/s (weak scaling)")
    flt = phase(args.replicas, "fleet", fleet_rate, args.duration_s)

    base_tp = (base["counts"]["ok"] / base["elapsed"]
               if base["elapsed"] > 0 else 0.0)
    fleet_tp = (flt["counts"]["ok"] / flt["elapsed"]
                if flt["elapsed"] > 0 else 0.0)
    scaling_x = fleet_tp / base_tp if base_tp > 0 else None
    lat = flt["lat_ms"]
    counts = flt["counts"]
    rec = {
        "metric": "serving_fleet_pairs_per_s",
        "value": round(fleet_tp, 4),
        "unit": "pairs/s",
        "replicas": args.replicas,
        "single_replica_pairs_per_s": round(base_tp, 4),
        "scaling_x": round(scaling_x, 4) if scaling_x is not None else None,
        "scaling_efficiency": round(scaling_x / args.replicas, 4)
        if scaling_x is not None else None,
        "latency_ms": {
            "p50": round(percentile(lat, 50), 3) if lat else None,
            "p95": round(percentile(lat, 95), 3) if lat else None,
            "p99": round(percentile(lat, 99), 3) if lat else None,
        },
        "sent": counts["sent"],
        "ok": counts["ok"],
        "rejected": counts["rejected"],
        "errors": counts["errors"],
        "deadline_exceeded": counts["deadline_exceeded"],
        "redispatched": flt["redispatched"],
        "per_replica": flt["per_replica"],
        "duration_s": round(flt["elapsed"], 3),
    }
    print(json.dumps(rec), flush=True)
    bad = counts["errors"] + base["counts"]["errors"]
    return 0 if bad == 0 else 1


def localize_bench(args, model=None):
    """``--localize``: the localization-as-a-service bench — one query
    against a ``--panos``-wide shortlist, fanned out over an in-process
    2+-replica fleet fronted by a match-result cache.

    Two phases against ONE server: a COLD pass (each distinct query
    once — every leg dispatches and populates the cache) and a
    duration-bound REPLAY pass (the same repeated shortlists — the
    localization traffic shape the cache exists for; steady-state legs
    answer from cache). Prints one ``serving_localize_qps`` JSON line:
    replay-phase queries/s, fan-out width, per-pano-leg cache hit-rate
    on the replay, per-replica admitted deltas (the fan-out proof:
    one query's legs land on BOTH replicas), and both phases' latency.
    """
    from ncnet_tpu import obs
    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.result_cache import MatchResultCache
    from ncnet_tpu.serving.server import MatchServer

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    replicas = max(args.replicas, 2)
    h, w = (int(v) for v in args.synthetic.split("x"))
    imgs = synth_jpegs(args.synthetic, seed=31,
                       n=args.panos + args.localize_queries)
    shortlist, queries = imgs[:args.panos], imgs[args.panos:]
    timeout_s = max(args.duration_s * 4, 60.0)
    fleet = MatchFleet.build(
        config, params,
        n_replicas=replicas,
        base_id="loc",
        cache_mb=0,  # inline-b64 legs never touch the feature store
        engine_kwargs=dict(k_size=2, image_size=args.image_size),
        replica_kwargs=dict(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            default_timeout_s=timeout_s,
        ),
    )
    fleet.warmup([(h, w, h, w)],
                 batch_sizes=sorted({1, max(1, args.max_batch // 2),
                                     args.max_batch}))
    rids = [r.replica_id for r in fleet.replicas]
    before = {rid: obs.counter("serving.admitted",
                               labels={"replica": rid}).value
              for rid in rids}
    cache = MatchResultCache(256 * 1024 * 1024, model_key="bench")
    server = MatchServer(None, port=0, fleet=fleet,
                         result_cache=cache).start()
    lock = threading.Lock()
    stats = {"sent": 0, "ok": 0, "rejected": 0, "errors": 0,
             "legs": 0, "legs_failed": 0, "hit_legs": 0}
    cold_lat, replay_lat = [], []

    def one(client, qb, lat_sink):
        from ncnet_tpu.serving.client import (
            OverCapacityError,
            ServingError,
        )

        with lock:
            stats["sent"] += 1
        t_req = time.monotonic()
        try:
            resp = client.localize(query_bytes=qb,
                                   panos=list(shortlist),
                                   max_matches=args.max_matches)
        except OverCapacityError:
            with lock:
                stats["rejected"] += 1
            return
        except (ServingError, OSError) as exc:
            with lock:
                stats["errors"] += 1
            note(f"localize error: {exc}")
            return
        dt_ms = (time.monotonic() - t_req) * 1e3
        rows = resp.get("panos", [])
        with lock:
            stats["ok"] += 1
            lat_sink.append(dt_ms)
            stats["legs"] += len(rows)
            stats["legs_failed"] += sum(
                1 for r in rows if not r.get("ok"))
            stats["hit_legs"] += sum(
                1 for r in rows
                if r.get("rescache") in ("hit", "coalesced"))

    try:
        client = MatchClient(server.url, timeout_s=timeout_s,
                             retries=0 if args.no_retry else 2)
        note(f"phase 1/2: cold — {len(queries)} distinct queries x "
             f"{args.panos}-pano shortlist over {replicas} replicas")
        for qb in queries:
            one(client, qb, cold_lat)
        cold_legs = stats["legs"]
        cold_hits = stats["hit_legs"]
        note(f"phase 2/2: replay — same shortlists for "
             f"{args.duration_s:g}s ({args.threads} drivers)")
        t0 = time.monotonic()

        def driver(k):
            c = MatchClient(server.url, timeout_s=timeout_s,
                            retries=0 if args.no_retry else 2)
            i = k
            while time.monotonic() - t0 < args.duration_s:
                one(c, queries[i % len(queries)], replay_lat)
                i += 1

        threads = [threading.Thread(target=driver, args=(k,),
                                    daemon=True)
                   for k in range(args.threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        replay_elapsed = time.monotonic() - t0
    finally:
        server.stop()

    per_replica = {
        rid: {"admitted": obs.counter(
            "serving.admitted", labels={"replica": rid}
        ).value - before[rid]}
        for rid in rids
    }
    replay_legs = stats["legs"] - cold_legs
    replay_hits = stats["hit_legs"] - cold_hits
    qps = (len(replay_lat) / replay_elapsed
           if replay_elapsed > 0 else 0.0)
    cold_lat.sort()
    replay_lat.sort()

    def _lat(vals):
        return {
            "p50": round(percentile(vals, 50), 3) if vals else None,
            "p99": round(percentile(vals, 99), 3) if vals else None,
        }

    rec = {
        "metric": "serving_localize_qps",
        "value": round(qps, 4),
        "unit": "qps",
        "replicas": replicas,
        "fanout_width": args.panos,
        "queries": {k: stats[k] for k in
                    ("sent", "ok", "rejected", "errors")},
        "legs": stats["legs"],
        "legs_failed": stats["legs_failed"],
        "rescache_hit_rate": round(replay_hits / replay_legs, 4)
        if replay_legs else None,
        "cold_latency_ms": _lat(cold_lat),
        "replay_latency_ms": _lat(replay_lat),
        "per_replica": per_replica,
        "duration_s": round(replay_elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    return 0 if stats["errors"] == 0 and not stats["legs_failed"] else 1


def session_bench(args, model=None):
    """Streaming-session bench (``--session``): one video-style stream,
    open -> N frames -> close, against a baseline of the SAME frames as
    one-shot ``mode='c2f'`` /v1/match requests. The split answers the
    tentpole question directly: what does frame-to-frame seeding save
    over re-running the coarse pass (and the reference extraction)
    every frame? Prints one ``serving_session_fps`` JSON line.

    Warmup frames are excluded from the latency stats on BOTH sides
    (the first baseline request compiles the c2f programs; the first
    session frames compile the cached-coarse and seeded programs) —
    the bench measures serving, not XLA.
    """
    from ncnet_tpu.serving.client import MatchClient

    n_frames = args.frames
    warm = min(args.warmup_frames, max(0, n_frames - 1))
    imgs = synth_jpegs(args.synthetic, n=n_frames + 1)
    ref, frames = imgs[0], imgs[1:]

    server = None
    if args.replicas > 0:
        from ncnet_tpu.serving.fleet import MatchFleet
        from ncnet_tpu.serving.server import MatchServer

        if model is None:
            from ncnet_tpu.cli.common import build_model

            note("building tiny model (pass model= to reuse one "
                 "in-process)")
            model = build_model(
                ncons_kernel_sizes=(3, 3),
                ncons_channels=(16, 1),
                relocalization_k_size=2,
                half_precision=True,
                backbone_bf16=True,
            )
        config, params = model
        fleet = MatchFleet.build(
            config, params,
            n_replicas=args.replicas,
            base_id="sess",
            cache_mb=0,
            engine_kwargs=dict(k_size=2, image_size=args.image_size,
                               c2f_topk=args.c2f_topk),
            replica_kwargs=dict(
                max_batch=args.max_batch,
                max_delay_s=args.max_delay_ms / 1e3,
                default_timeout_s=600.0,
            ),
        )
        server = MatchServer(None, port=0, fleet=fleet).start()
        url = server.url
    else:
        url = args.url
    client = MatchClient(url, timeout_s=600.0,
                         retries=0 if args.no_retry else 2)
    try:
        # Phase 1: one-shot c2f baseline — every frame pays the full
        # coarse pass AND the reference feature extraction.
        note(f"phase 1/2: {n_frames} one-shot c2f frames (baseline)")
        full_ms, errors = [], 0
        for i, fb in enumerate(frames):
            t = time.monotonic()
            try:
                client.match(query_bytes=fb, pano_bytes=ref, mode="c2f",
                             max_matches=args.max_matches)
            except Exception as exc:  # noqa: BLE001 — counted, reported
                errors += 1
                note(f"baseline error on frame {i}: {exc}")
                continue
            if i >= warm:
                full_ms.append((time.monotonic() - t) * 1e3)

        # Phase 2: the stream — one session, same frames.
        note(f"phase 2/2: session stream, {n_frames} frames")
        seeded_ms, unseeded_ms = [], []
        seeded_n = reseeds = 0
        t0 = time.monotonic()
        with client.session(ref_bytes=ref) as s:
            for i, fb in enumerate(frames):
                t = time.monotonic()
                try:
                    resp = s.frame(query_bytes=fb,
                                   max_matches=args.max_matches)
                except Exception as exc:  # noqa: BLE001
                    errors += 1
                    note(f"session error on frame {i}: {exc}")
                    continue
                dt_ms = (time.monotonic() - t) * 1e3
                sess = resp.get("session", {})
                if sess.get("seeded"):
                    seeded_n += 1
                if i >= warm:
                    (seeded_ms if sess.get("seeded")
                     else unseeded_ms).append(dt_ms)
            elapsed = time.monotonic() - t0
            stats = s.close() or {}
            reseeds = stats.get("reseeds", 0)
    finally:
        if server is not None:
            server.stop()

    full_ms.sort()
    seeded_ms.sort()
    unseeded_ms.sort()
    done = len(seeded_ms) + len(unseeded_ms)

    def _split(vals):
        return {"p50": round(percentile(vals, 50), 3) if vals else None,
                "p99": round(percentile(vals, 99), 3) if vals else None,
                "n": len(vals)}

    seeded_p50 = percentile(seeded_ms, 50) if seeded_ms else None
    full_p50 = percentile(full_ms, 50) if full_ms else None
    rec = {
        "metric": "serving_session_fps",
        "value": round(done / elapsed, 4) if elapsed > 0 else 0.0,
        "unit": "frames/s",
        "frames": n_frames,
        "warmup_frames": warm,
        "seeded_frames": seeded_n,
        "seed_hit_frac": round(seeded_n / n_frames, 4) if n_frames else 0.0,
        "reseeds": reseeds,
        "latency_ms": {
            "seeded": _split(seeded_ms),
            "unseeded": _split(unseeded_ms),
            "full_c2f": _split(full_ms),
        },
        "seeded_speedup_p50": round(full_p50 / seeded_p50, 4)
        if seeded_p50 and full_p50 else None,
        "errors": errors,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    return 0 if errors == 0 else 1


def main(argv=None, model=None):
    parser = argparse.ArgumentParser(
        description="open-loop load generator for the matching service"
    )
    parser.add_argument("--url", type=str, default="",
                        help="target server (mutually exclusive with "
                             "--replicas)")
    parser.add_argument("--replicas", type=int, default=0,
                        help="fleet mode: bench an in-process N-replica "
                             "fleet vs a 1-replica baseline (weak "
                             "scaling; no --url)")
    parser.add_argument("--image_size", type=int, default=64,
                        help="fleet mode: engine bucket image size")
    parser.add_argument("--max_batch", type=int, default=4,
                        help="fleet mode: per-replica batch bound")
    parser.add_argument("--max_delay_ms", type=float, default=50.0,
                        help="fleet mode: per-replica batching delay")
    parser.add_argument("--baseline_duration_s", type=float, default=0.0,
                        help="fleet mode: baseline phase length "
                             "(0 = --duration_s)")
    parser.add_argument("--rate", type=float, default=8.0,
                        help="open-loop arrival rate, requests/s")
    parser.add_argument("--duration_s", type=float, default=10.0)
    parser.add_argument("--threads", type=int, default=16,
                        help="worker pool size (bounds in-flight requests)")
    parser.add_argument("--query", type=str, default="",
                        help="server-readable query image path")
    parser.add_argument("--pano", type=str, default="",
                        help="server-readable pano image path")
    parser.add_argument("--synthetic", type=str, default="",
                        help="HxW: generate random images, send inline b64")
    parser.add_argument("--deadline_ms", type=float, default=0.0,
                        help="per-request deadline (0 = server default)")
    parser.add_argument("--max_matches", type=int, default=16)
    parser.add_argument("--no_retry", action="store_true",
                        help="count 503s as rejected instead of retrying")
    parser.add_argument(
        "--tenants", action="append", default=[],
        help="mixed-load mode (with --url): drive one open-loop load "
        "per name:priority:rate spec, each with its tenant headers, "
        "all concurrently; reports per-tenant availability/p99 and "
        "the QoS rungs visited (repeatable)",
    )
    parser.add_argument("--session", action="store_true",
                        help="streaming-session bench: open one "
                        "/v1/session stream, post --frames frames, "
                        "close; reports seeded vs full-coarse frame "
                        "p50/p99 + seed-hit fraction (one "
                        "serving_session_fps line). Needs --synthetic; "
                        "works with --url or an in-process --replicas "
                        "fleet")
    parser.add_argument("--frames", type=int, default=16,
                        help="session mode: frames per stream")
    parser.add_argument("--warmup_frames", type=int, default=2,
                        help="session mode: leading frames excluded "
                        "from latency stats (compile + first-seed "
                        "cost)")
    parser.add_argument("--c2f_topk", type=int, default=4,
                        help="session mode, in-process fleet: coarse "
                        "survivors refined per frame (keeps the c2f "
                        "path non-degenerate at smoke image sizes)")
    parser.add_argument("--localize", action="store_true",
                        help="localize bench: repeated-shortlist "
                        "/v1/localize queries over an in-process "
                        "2+-replica fleet with a match-result cache "
                        "(one serving_localize_qps line: replay qps, "
                        "fan-out width, per-leg cache hit-rate, "
                        "per-replica admitted deltas). Needs "
                        "--synthetic + --replicas")
    parser.add_argument("--panos", type=int, default=6,
                        help="localize mode: shortlist width per query")
    parser.add_argument("--localize_queries", type=int, default=4,
                        help="localize mode: distinct query images "
                        "(the replay cycles through them)")
    parser.add_argument("--slo_availability", type=float, default=0.999,
                        help="availability objective for the SLO summary")
    parser.add_argument("--slo_p99_ms", type=float, default=0.0,
                        help="p99 latency target for the SLO summary "
                             "(0 = no latency gate)")
    parser.add_argument("--slo_strict", action="store_true",
                        help="exit 1 when the run misses its SLOs")
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.replicas > 0):
        parser.error("pass exactly one of --url or --replicas N")
    if args.tenants and args.replicas > 0:
        parser.error("--tenants is a --url mode (it drives one "
                     "already-running server)")
    if bool(args.synthetic) == bool(args.query and args.pano):
        parser.error("pass either --synthetic HxW or both --query/--pano")

    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    if args.localize:
        if not args.synthetic or args.replicas <= 0:
            parser.error("--localize needs --synthetic HxW and "
                         "--replicas >= 2 (in-process fleet; the "
                         "fan-out proof wants two replicas)")
        return localize_bench(args, model=model)

    if args.session:
        if not args.synthetic:
            parser.error("--session needs --synthetic HxW (frames are "
                         "generated client-side)")
        return session_bench(args, model=model)

    if args.replicas > 0:
        if not args.synthetic:
            parser.error("fleet mode needs --synthetic HxW (inline "
                         "payloads; the in-process servers have no "
                         "shared file gallery)")
        return fleet_bench(args, model=model)

    from ncnet_tpu.serving.client import MatchClient

    kwargs = {"max_matches": args.max_matches}
    if args.deadline_ms > 0:
        kwargs["deadline_ms"] = args.deadline_ms
    if args.synthetic:
        q_bytes, p_bytes = synth_jpegs(args.synthetic)
        kwargs.update(query_bytes=q_bytes, pano_bytes=p_bytes)
    else:
        kwargs.update(query_path=args.query, pano_path=args.pano)

    if args.tenants:
        return tenants_bench(args, kwargs)

    client = MatchClient(args.url, retries=0 if args.no_retry else 2)
    health = client.healthz()
    note(f"healthz: {health}")

    res = run_load(client, kwargs, args.rate, args.duration_s,
                   args.threads)
    counts, lat_ms = res["counts"], res["lat_ms"]
    batch_sizes, elapsed = res["batch_sizes"], res["elapsed"]
    batched = sum(1 for b in batch_sizes if b > 1)

    # SLO summary — the same definitions obs/slo.default_serving_slos
    # uses, measured from the client side: availability over requests
    # the server owed an answer (200/500/504; shed 503s excluded),
    # deadline-hit over requests that ran, p99 vs an optional target.
    answered = counts["ok"] + counts["errors"] + counts["deadline_exceeded"]
    availability = counts["ok"] / answered if answered else None
    ran = counts["ok"] + counts["deadline_exceeded"]
    deadline_hit_rate = counts["ok"] / ran if ran else None
    p99_ms = percentile(lat_ms, 99) if lat_ms else None
    availability_met = (availability is None
                        or availability >= args.slo_availability)
    p99_met = (args.slo_p99_ms <= 0 or p99_ms is None
               or p99_ms <= args.slo_p99_ms)
    slo = {
        "availability": round(availability, 6)
        if availability is not None else None,
        "availability_objective": args.slo_availability,
        "availability_met": availability_met,
        "deadline_hit_rate": round(deadline_hit_rate, 6)
        if deadline_hit_rate is not None else None,
        "p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
        "p99_target_ms": args.slo_p99_ms if args.slo_p99_ms > 0 else None,
        "p99_met": p99_met,
        "met": availability_met and p99_met,
    }

    rec = {
        "metric": "serving_match_throughput_rps",
        "value": round(counts["ok"] / elapsed, 4) if elapsed > 0 else 0.0,
        "unit": "req/s",
        "latency_ms": {
            "p50": round(percentile(lat_ms, 50), 3) if lat_ms else None,
            "p95": round(percentile(lat_ms, 95), 3) if lat_ms else None,
            "p99": round(percentile(lat_ms, 99), 3) if lat_ms else None,
        },
        "sent": counts["sent"],
        "ok": counts["ok"],
        "rejected": counts["rejected"],
        "errors": counts["errors"],
        "deadline_exceeded": counts["deadline_exceeded"],
        "batched_frac": round(batched / len(batch_sizes), 4)
        if batch_sizes else 0.0,
        "mean_batch_size": round(sum(batch_sizes) / len(batch_sizes), 3)
        if batch_sizes else None,
        "duration_s": round(elapsed, 3),
        "slo": slo,
    }
    print(json.dumps(rec), flush=True)
    if args.slo_strict and not slo["met"]:
        return 1
    return 0 if counts["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
