"""Aggregate a JAX/XLA device trace into a per-op / per-stage cost table.

Reads the ``vm.trace.json.gz`` files that ``tools/trace_step.py`` (or any
``jax.profiler.trace``) drops under ``<dir>/plugins/profile/<stamp>/`` and
prints, per step:

  * device time by HLO category (convolution / data formatting / pad / ...)
  * device time by source file:line (the ``source`` metadata XLA attaches)
  * a per-stage rollup with achieved TFLOP/s, HBM GB/s and %-of-peak
  * the top ops with model FLOPs, achieved TFLOP/s, HBM GB/s and MXU %

This is how the round-2 "corr+pool costs 68 ms in-step" mystery was
resolved (VERDICT r2 weak #2): the knockout bisect misattributes because
removing a stage lets XLA dead-code-eliminate backbone work feeding it.
The trace is ground truth; the bisect is only a differential.

The aggregation lives in ``ncnet_tpu.utils.traceagg`` (shared with
``bench.py``'s utilization block); this tool is the human-readable CLI.

Usage:
    python tools/trace_optable.py docs/tpu_r02/trace [--steps 2]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ncnet_tpu.utils.traceagg import (  # noqa: E402
    PEAK_TFLOPS_BF16,
    aggregate,
    stage_rollup,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--steps", type=int, default=2,
                    help="traced step count (durations are divided by this)")
    ap.add_argument("--top", type=int, default=30)
    args = ap.parse_args()

    try:
        agg = aggregate(args.trace_dir, steps=args.steps)
    except FileNotFoundError as exc:
        raise SystemExit(str(exc))
    if agg is None:
        raise SystemExit(
            f"no accelerator plane with op metadata under {args.trace_dir} "
            "(CPU-smoke traces carry none)"
        )
    print(f"# {agg['path']}  (/{agg['steps']} steps)")
    print(
        f"total attributed device time: {agg['total_ms']:.1f} ms/step  "
        f"({agg['tflops']:.1f} TFLOP/s = {agg['mfu'] * 100:.1f}% MXU, "
        f"{agg['gbs']:.0f} GB/s = {agg['hbm_frac'] * 100:.1f}% HBM)\n"
    )
    print("-- by hlo_category (ms/step) --")
    for k, v in sorted(agg["by_cat"].items(), key=lambda kv: -kv[1]):
        print(f"{v:8.2f}  {k}")
    print("\n-- by stage (ms/step, achieved rates) --")
    for name, s in stage_rollup(agg).items():
        print(f"{s['ms']:8.2f}  {name:10s} {s['tflops']:7.2f} TFLOP/s "
              f"({s['mfu'] * 100:4.1f}%)  {s['gbs']:6.0f} GB/s "
              f"({s['hbm_frac'] * 100:4.1f}%)")
    n = agg["steps"]
    print("\n-- by source (ms/step) --")
    rows = sorted(agg["by_src"].items(), key=lambda kv: -kv[1]["us"])
    for k, v in rows[: args.top]:
        print(f"{v['us'] / n / 1000:8.2f}  {k}")
    print("\n-- top ops --")
    print(f"{'ms/step':>8} {'GFLOP':>8} {'TFLOP/s':>8} {'GB/s':>7} "
          f"{'MXU%':>5}  op  [category]  source")
    ops = sorted(agg["ops"].items(), key=lambda kv: -kv[1]["us"])[: args.top]
    for name, v in ops:
        ms = v["us"] / n / 1000
        sec = v["us"] * 1e-6  # all executions; rates use matching sums
        tf = v["flops"] / sec / 1e12 if sec else 0.0
        gbs = v["bytes"] / sec / 1e9 if sec else 0.0
        print(f"{ms:8.2f} {v['flops'] / n / 1e9:8.2f} {tf:8.2f} {gbs:7.0f} "
              f"{tf / PEAK_TFLOPS_BF16 * 100:5.1f}  {name}  "
              f"[{v['cat']}]  {v['src']}")


if __name__ == "__main__":
    main()
