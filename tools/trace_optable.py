"""Aggregate a JAX/XLA device trace into a per-op / per-stage cost table.

Reads the ``vm.trace.json.gz`` files that ``tools/trace_step.py`` (or any
``jax.profiler.trace``) drops under ``<dir>/plugins/profile/<stamp>/`` and
prints, per step:

  * device time by HLO category (convolution / data formatting / pad / ...)
  * device time by source file:line (the ``source`` metadata XLA attaches)
  * the top ops with model FLOPs, achieved TFLOP/s, HBM GB/s and MXU %

This is how the round-2 "corr+pool costs 68 ms in-step" mystery was
resolved (VERDICT r2 weak #2): the knockout bisect misattributes because
removing a stage lets XLA dead-code-eliminate backbone work feeding it.
The trace is ground truth; the bisect is only a differential.

Usage:
    python tools/trace_optable.py docs/tpu_r02/trace [--steps 2]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os

PEAK_TFLOPS_BF16 = 197.0  # v5e per-chip
PEAK_HBM_GBS = 819.0


def load_events(trace_dir: str):
    pats = sorted(
        glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    )
    if not pats:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}/plugins/profile/")
    path = pats[-1]
    with gzip.open(path) as f:
        data = json.load(f)
    return path, data["traceEvents"]


def device_pid(events):
    for e in events:
        if (
            e.get("ph") == "M"
            and e.get("name") == "process_name"
            and "TPU" in e.get("args", {}).get("name", "")
        ):
            return e["pid"]
    return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--steps", type=int, default=2,
                    help="traced step count (durations are divided by this)")
    ap.add_argument("--top", type=int, default=30)
    args = ap.parse_args()

    path, ev = load_events(args.trace_dir)
    pid = device_pid(ev)
    print(f"# {path}  (device pid {pid}, /{args.steps} steps)")

    by_src = collections.Counter()
    by_cat = collections.Counter()
    agg = {}
    tot = 0.0
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") != pid:
            continue
        a = e.get("args") or {}
        if "long_name" not in a:  # umbrella program / host rows
            continue
        d = e["dur"]
        src = a.get("source", "<none>").split("/ncnet_tpu/")[-1]
        by_src[src] += d
        by_cat[a.get("hlo_category", "?")] += d
        tot += d
        key = e["name"]
        if key not in agg:
            agg[key] = dict(
                dur=0.0,
                flops=float(a.get("model_flops", 0) or 0),
                bytes=float(a.get("bytes_accessed", 0) or 0),
                cat=a.get("hlo_category"),
                src=src,
            )
        agg[key]["dur"] += d

    n = args.steps
    print(f"total attributed device time: {tot / n / 1000:.1f} ms/step\n")
    print("-- by hlo_category (ms/step) --")
    for k, v in by_cat.most_common():
        print(f"{v / n / 1000:8.2f}  {k}")
    print("\n-- by source (ms/step) --")
    for k, v in by_src.most_common(args.top):
        print(f"{v / n / 1000:8.2f}  {k}")
    print("\n-- top ops --")
    print(f"{'ms/step':>8} {'GFLOP':>8} {'TFLOP/s':>8} {'GB/s':>7} "
          f"{'MXU%':>5}  op  [category]  source")
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["dur"])[: args.top]
    for name, v in rows:
        ms = v["dur"] / n / 1000
        sec = v["dur"] / n * 1e-6
        tf = v["flops"] / sec / 1e12 if sec else 0.0
        gbs = v["bytes"] / sec / 1e9 if sec else 0.0
        print(f"{ms:8.2f} {v['flops'] / 1e9:8.2f} {tf:8.2f} {gbs:7.0f} "
              f"{tf / PEAK_TFLOPS_BF16 * 100:5.1f}  {name}  "
              f"[{v['cat']}]  {v['src']}")


if __name__ == "__main__":
    main()
