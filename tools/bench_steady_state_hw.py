"""MEASURED steady-state throughput of the cached+batched InLoc path.

BASELINE.md's "blended 10.96 pairs/s/chip" folds the two measured
endpoint rates (cold 9.69 / all-hits 12.39, bench.py) linearly over the
replayed 53% pano hit-rate (tools/cache_steady_state.py). That linear
blend ignores real-path structure that only costs on MIXED queries:

- miss stacks pad to full --pano_batch groups (`_MissGroups.pad`,
  cli/eval_inloc.py): a query with 6 cache hits still pays the full
  5-pano miss program (5 backbones AND 5 consensus/extract scans) for
  its 4 misses — at the replayed schedule, 38% of queries drain at
  least one partial group;
- a mixed block interleaves the hit scan with the batched miss program
  inside one query, a program composition neither endpoint runs.

This tool measures those compositions directly on hardware. The replay
(pose-grounded shortlist structure over the real byte-bounded LRU —
same machinery as cache_steady_state) yields each query's composition
class `(h hits, miss-stack sizes)`; the most frequent classes are built
as bench-convention query blocks (ONE jitted program per class: query
backbone + length-h hit scan + the class's miss stacks with the bf16
feature output the cache store consumes) and timed like bench.py
(scalar-fetch closed, device-resident inputs — transfers are excluded
exactly as in the endpoint numbers, where the CLI overlaps them with
dispatch/decode). Unmeasured rare classes are filled by a least-squares
fit t = t_query + h*t_hit + n_stacks*t_stack + n_slots*t_slot; its
residuals on the measured classes are reported so the linearity
assumption is checked, not assumed.

--ragged additionally evaluates NCNET_RAGGED_MISS_STACKS=1 (partial
groups dispatch at their true size instead of padding to 5), the
candidate default this tool exists to decide.

Output: one JSON line with the measured steady-state pairs/s/chip, the
per-class table, and the fit diagnostics.

Reference workload: eval_inloc.py:124-132 (356 queries x top-10
shortlist); cache path: cli/eval_inloc.py `_run_panos_cached_batched`.

Run (one JAX client at a time — never concurrently with a session):
    python tools/bench_steady_state_hw.py [--ragged] [--classes 6]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import Counter

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PANOS_PER_QUERY = 10
P = 5  # --pano_batch / NCNET_PANO_BACKBONE_BATCH promoted default


def miss_sizes(m: int, ragged: bool) -> tuple:
    """Stack sizes the CLI dispatches for m misses in one query: full
    groups of P as misses decode, the remainder padded (default) or at
    its true size (NCNET_RAGGED_MISS_STACKS=1)."""
    sizes = (P,) * (m // P)
    if m % P:
        sizes += (m % P if ragged else P,)
    return sizes


def schedule_histogram(cache_mb: int, ragged: bool):
    """{(hits, miss_sizes): n_queries} over the pose-grounded replay.

    Same replay as tools/cache_steady_state.py (its documented
    surrogate caveats apply here unchanged); re-derived per run so the
    histogram always matches the current cache/bucketing defaults.
    """
    from cache_steady_state import (
        ENTRY_DTYPE,
        ENTRY_SHAPE,
        REFPOSES_DEFAULT,
        build_scans,
        build_shortlists,
        load_queries,
        synthetic_queries,
    )

    from ncnet_tpu.evals.feature_cache import PanoFeatureCache

    if os.path.exists(REFPOSES_DEFAULT):
        queries = load_queries(REFPOSES_DEFAULT)
    else:  # sandbox without the reference tree: keep the tool runnable
        queries = synthetic_queries()
    lists = build_shortlists(queries, build_scans(queries))
    entry = np.broadcast_to(np.zeros((), ENTRY_DTYPE), ENTRY_SHAPE)
    cache = PanoFeatureCache(cache_mb * 1024 * 1024)
    hist: Counter = Counter()
    for cuts in lists:
        h = 0
        for cut in cuts:
            if cache.get(cut, (3072, 2304)) is not None:
                h += 1
            else:
                cache.put(cut, (3072, 2304), entry)
        hist[(h, miss_sizes(len(cuts) - h, ragged))] += 1
    hit_rate = cache.hits / (cache.hits + cache.misses)
    return hist, hit_rate


def pick_classes(hist: Counter, n: int):
    """The n most frequent classes, extended (within n+2) until every
    distinct stack size in the histogram is covered by some measured
    class — the fit cannot otherwise pin a size's cost."""
    by_freq = sorted(hist.items(), key=lambda kv: -kv[1])
    chosen = [c for c, _ in by_freq[:n]]
    need = {s for (_, sizes) in hist for s in sizes}
    have = {s for (_, sizes) in chosen for s in sizes}
    for c, _ in by_freq[n:]:
        if len(chosen) >= n + 2 or need <= have:
            break
        if set(c[1]) - have:
            chosen.append(c)
            have |= set(c[1])
    return chosen


def fit_features(h: int, sizes: tuple):
    return [1.0, float(h), float(len(sizes)), float(sum(sizes))]


def class_label(h: int, sizes: tuple) -> str:
    return f"h{h}m" + ("-".join(str(s) for s in sizes) or "0")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ragged", action="store_true",
                    help="evaluate NCNET_RAGGED_MISS_STACKS=1 dispatch "
                         "(partial miss groups at true size)")
    ap.add_argument("--classes", type=int, default=6,
                    help="measure the N most frequent composition classes")
    ap.add_argument("--blocks", type=int, default=3,
                    help="timed blocks per class (after warmup)")
    ap.add_argument("--cache_mb", type=int, default=4096)
    ap.add_argument("--dial_timeout", type=float, default=120.0)
    args = ap.parse_args(argv)

    hist, hit_rate = schedule_histogram(args.cache_mb, args.ragged)
    n_queries = sum(hist.values())
    measured_classes = pick_classes(hist, args.classes)
    print(f"# schedule: {n_queries} queries, hit-rate {hit_rate:.3f}, "
          f"{len(hist)} classes; measuring "
          f"{[class_label(*c) for c in measured_classes]}", flush=True)

    import jax

    from ncnet_tpu import obs
    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    # Opt-in run log (NCNET_RUN_LOG=<path or dir>), bench.py convention:
    # the per-class timings and the headline land as structured events.
    run_log = None
    log_dest = os.environ.get("NCNET_RUN_LOG", "")
    if log_dest:
        run_log = obs.init_run(
            "bench_steady_state",
            obs.default_log_path(log_dest, "bench_steady_state")
            if os.path.isdir(log_dest) else log_dest,
            args=args,
        )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        print("dial failed; aborting (this tool needs the accelerator)")
        return 2
    on_tpu = devices[0].platform != "cpu"
    print(f"# backend: {devices[0]}", flush=True)

    import jax.numpy as jnp

    from ncnet_tpu.cli.eval_inloc import (
        _bb_group_size,
        inloc_resize_shape,
        resolve_feat_units,
    )
    from ncnet_tpu.evals import inloc_device_matches
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import (
        extract_features,
        ncnet_forward_from_features,
    )

    # Same configuration/bucketing as bench.py's headline block.
    if on_tpu:
        nominal, nom_h, nom_w = 3200, 3200, 2400
    else:
        nominal = nom_h = nom_w = int(
            os.environ.get("NCNET_BENCH_SMOKE_SIZE", "512")
        )
    units = resolve_feat_units(-1, nominal, 2)
    h_a, w_a = inloc_resize_shape(
        nom_h, nom_w, nominal, 2, h_unit=units[0], w_unit=units[1]
    )
    config = NCNetConfig(
        backbone=BackboneConfig(compute_dtype="bfloat16"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
        use_fused_corr_pool=True,
        fused_impl="auto",
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)

    def match_from_feats(prm, feat_a, feat_b):
        corr, delta = ncnet_forward_from_features(
            config, prm, feat_a, feat_b, final_mutual=True
        )
        return inloc_device_matches(corr, delta4d=delta, k_size=2,
                                    impl="auto")

    def probe_of(m):
        # Full-sum probe (bench.py convention): consume every output
        # element so XLA cannot DCE part of the extraction.
        return sum(jnp.sum(v.astype(jnp.float32)) for v in m)

    def build_block(h, sizes):
        """One query block of composition (h hits, miss stacks of
        `sizes`): the device work `_run_panos_cached_batched` dispatches
        for such a query, as ONE program (the endpoints' convention)."""

        def miss_group(prm, feat_a, acc, stack):
            m = stack.shape[0]
            nb = _bb_group_size(m, P)  # the CLI's one grouping rule
            groups = stack.reshape(m // nb, nb, *stack.shape[1:])
            feats_b = jax.lax.map(
                lambda grp: extract_features(config, prm, grp), groups
            )
            # The store's bf16 rounding is part of the real miss program
            # (pano_matches_batch_with_feats); its sum keeps the cast
            # un-DCE'd (one extra HBM read, ~0.3 ms — negligible next to
            # the backbones).
            f16 = feats_b.astype(jnp.bfloat16)
            fb = feats_b.reshape(m, 1, *feats_b.shape[2:])

            def body_miss(aa, feat_b):
                return aa + probe_of(
                    match_from_feats(prm, feat_a, feat_b)
                ), None

            acc, _ = jax.lax.scan(body_miss, acc, fb)
            return acc + jnp.sum(f16.astype(jnp.float32))

        @jax.jit
        def block(prm, src, feats_stack, tgt_stacks):
            feat_a = extract_features(config, prm, src)
            acc = jnp.float32(0)
            if h:
                def body_hit(a, feat_b):
                    return a + probe_of(
                        match_from_feats(prm, feat_a, feat_b)
                    ), None

                acc, _ = jax.lax.scan(body_hit, acc, feats_stack)
            for stack in tgt_stacks:
                acc = miss_group(prm, feat_a, acc, stack)
            return acc

        return block

    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    src = jax.random.normal(k1, (1, 3, h_a, w_a), jnp.float32)
    fh, fw = h_a // 16, w_a // 16  # backbone stride (SURVEY §2.1)
    # Hit entries: bf16 features, the dtype the cache stores. Distinct
    # per-slot contents (honest per-pano work inside the scan).
    h_max = max(h for h, _ in measured_classes)
    feats_all = jax.random.normal(
        k2, (max(h_max, 1), 1, 1024, fh, fw), jnp.float32
    ).astype(jnp.bfloat16)
    imgs_all = jax.random.normal(k3, (PANOS_PER_QUERY, 3, h_a, w_a),
                                 jnp.float32)

    results = {}
    for h, sizes in measured_classes:
        feats = (feats_all[:h] if h else
                 jnp.zeros((0, 1, 1024, fh, fw), jnp.bfloat16))
        tgts, off = [], 0
        for s in sizes:
            tgts.append(imgs_all[off:off + s])
            off += s
        label = class_label(h, sizes)
        print(f"# compiling block {label}...", flush=True)
        block = build_block(h, sizes)
        t0 = time.perf_counter()
        float(block(params, src, feats, tgts))  # compile + warmup
        print(f"#   compiled+ran in {time.perf_counter() - t0:.1f}s; "
              "timing...", flush=True)
        float(block(params, src, feats, tgts))  # settle queues
        t0 = time.perf_counter()
        for _ in range(args.blocks):
            # Scalar fetch closes each block (tunneled block_until_ready
            # can return early — bench.py convention).
            float(block(params, src, feats, tgts))
        dt = (time.perf_counter() - t0) / args.blocks
        results[(h, sizes)] = dt
        print(f"#   {label}: {dt * 1e3:.1f} ms/block "
              f"({PANOS_PER_QUERY / dt:.3f} pairs/s)", flush=True)
        obs.event("class_timed", label=label, ms_per_block=dt * 1e3,
                  pairs_per_s=PANOS_PER_QUERY / dt)

    # Least-squares fill for unmeasured classes + linearity check on the
    # measured ones. Padded-only data has n_slots = 5*n_stacks
    # (collinear): lstsq's minimum-norm solution still predicts
    # correctly inside that subspace, which is exactly where the
    # unmeasured padded classes live.
    A = np.array([fit_features(h, s) for (h, s) in results])
    y = np.array(list(results.values()))
    coef, *_ = np.linalg.lstsq(A, y, rcond=None)

    def predict(h, sizes):
        return float(np.dot(fit_features(h, sizes), coef))

    fit_err = {
        class_label(h, s): round(predict(h, s) / t - 1.0, 4)
        for (h, s), t in results.items()
    }

    total_time = 0.0
    table = {}
    for (h, sizes), n in sorted(hist.items()):
        t = results.get((h, sizes))
        src_kind = "measured"
        if t is None:
            t = predict(h, sizes)
            src_kind = "fit"
        total_time += n * t
        table[class_label(h, sizes)] = {
            "queries": n,
            "ms_per_block": round(t * 1e3, 1),
            "pairs_per_s": round(PANOS_PER_QUERY / t, 3),
            "source": src_kind,
        }
    measured = PANOS_PER_QUERY * n_queries / total_time

    headline = {
        "metric": "inloc_steady_state_pairs_per_s_per_chip"
        + ("_ragged" if args.ragged else "")
        + ("" if on_tpu else "_cpu_smoke"),
        "value": round(measured, 4),
        "unit": "pairs/s/chip",
        "hit_rate": round(hit_rate, 4),
        "queries": n_queries,
        "classes": table,
        "fit_coef_ms": {
            "t_query": round(float(coef[0]) * 1e3, 1),
            "t_hit": round(float(coef[1]) * 1e3, 1),
            "t_stack": round(float(coef[2]) * 1e3, 1),
            "t_slot": round(float(coef[3]) * 1e3, 1),
        },
        "fit_residuals": fit_err,
    }
    if run_log is not None:
        obs.gauge("bench.steady_state_pairs_per_s").set(measured)
        run_log.event("bench.headline", **headline)
        run_log.close("ok")
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
