"""One-command PF-Pascal real-weights parity runner (VERDICT r3 item 7b).

The day egress exists, quality parity against the published reference
weights is ONE invocation:

    python tools/real_parity.py

which does, in order:
  1. fetch ``ncnet_pfpascal.pth.tar`` (trained_models/download.sh) and the
     PF-Pascal images + split CSVs (datasets/pf-pascal/download.sh +
     datasets/fetch_pair_lists.sh) — skipped for pieces already on disk;
     a failed fetch is recorded VERBATIM and exits 3 (the round log keeps
     the evidence trail the judge asked for);
  2. convert the torch checkpoint through the golden-tested converter
     (ncnet_tpu.cli.convert_checkpoint, forward-verified vs torch);
  3. run the PCK@0.1 eval exactly as the reference harness does
     (``/root/reference/eval_pf_pascal.py:84-89`` semantics: scnet
     procedure, 400 px; our ``cli/eval_pf_pascal.py`` is the parity
     twin);
  4. compare against the paper-reported ≈78.9% PCK@0.1 (BASELINE.md) and
     print one JSON verdict line.

Offline testing: ``--pth`` / ``--dataset_path`` accept pre-staged inputs
(the test suite stages a real torch-serialized surrogate checkpoint and
a synthetic dataset), so the full fetch->convert->eval->compare path is
exercised without egress; ``--expected_pck -1`` skips the comparison.

Usage:
    python tools/real_parity.py [--pth trained_models/ncnet_pfpascal.pth.tar]
        [--dataset_path datasets/pf-pascal] [--expected_pck 0.789]
        [--tolerance 0.02] [--image_size 400] [--alpha 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"[real_parity] {msg}", flush=True)


def _fetch(script, cwd, what):
    """Run a fetch script, echoing its output verbatim (evidence trail)."""
    log(f"fetching {what} via {script} ...")
    try:
        proc = subprocess.run(
            ["bash", script], cwd=cwd, capture_output=True, text=True,
            timeout=1800,
        )
    except subprocess.TimeoutExpired as exc:
        for s in (exc.stdout, exc.stderr):
            if s:
                print(s.decode() if isinstance(s, bytes) else s, flush=True)
        log("FETCH TIMED OUT after 1800 s (blackholed network?) — the "
            "partial output above is the verbatim record.")
        raise SystemExit(3)
    out = (proc.stdout + proc.stderr).strip()
    print(out, flush=True)
    if proc.returncode != 0:
        log(f"FETCH FAILED (rc={proc.returncode}) — no egress? The output "
            "above is the verbatim record; re-run when the network allows.")
        raise SystemExit(3)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fetch -> convert -> eval_pf_pascal -> compare"
    )
    ap.add_argument("--pth", type=str,
                    default=os.path.join(REPO, "trained_models",
                                         "ncnet_pfpascal.pth.tar"))
    ap.add_argument("--dataset_path", type=str,
                    default=os.path.join(REPO, "datasets", "pf-pascal"))
    ap.add_argument("--converted_dir", type=str, default="",
                    help="output dir for the converted checkpoint "
                    "(default: <pth>.converted)")
    ap.add_argument("--expected_pck", type=float, default=0.789,
                    help="paper-reported PCK@0.1 (BASELINE.md); pass -1 "
                    "to skip the comparison")
    ap.add_argument("--tolerance", type=float, default=0.02)
    ap.add_argument("--image_size", type=int, default=400)
    ap.add_argument("--alpha", type=float, default=0.1)
    ap.add_argument("--batch_size", type=int, default=8)
    ap.add_argument("--num_workers", type=int, default=4)
    args = ap.parse_args(argv)

    # 1. Fetch anything missing.
    if not os.path.exists(args.pth):
        _fetch("download.sh", os.path.join(REPO, "trained_models"),
               "published reference weights")
        if not os.path.exists(args.pth):
            log(f"{args.pth} still missing after fetch")
            raise SystemExit(3)
    csv = os.path.join(args.dataset_path, "image_pairs", "test_pairs.csv")
    if not os.path.exists(csv):
        _fetch("fetch_pair_lists.sh", os.path.join(REPO, "datasets"),
               "PF-Pascal split CSVs")
    if not os.path.isdir(os.path.join(args.dataset_path, "PF-dataset-PASCAL")) \
            and not os.path.isdir(os.path.join(args.dataset_path, "images")):
        _fetch("download.sh", args.dataset_path, "PF-Pascal images")
    if not os.path.exists(csv):
        log(f"{csv} still missing after fetch")
        raise SystemExit(3)

    # 2. Convert (golden-tested converter; verifies a forward vs torch).
    converted = args.converted_dir or args.pth + ".converted"
    best = os.path.join(converted, "best")  # converter writes <dst>/best
    if not os.path.exists(os.path.join(best, "params.npz")):
        log(f"converting {args.pth} -> {converted}")
        from ncnet_tpu.cli.convert_checkpoint import main as convert_main

        rc = convert_main([args.pth, converted])
        if rc not in (0, None):
            log(f"converter failed rc={rc}")
            raise SystemExit(1)
    else:
        log(f"using existing conversion {best}")

    # 3. Eval: reference harness semantics (eval_pf_pascal.py:84-89 —
    # scnet PCK procedure, alpha 0.1 as the paper reports).
    log(f"evaluating PCK@{args.alpha} at {args.image_size} px ...")
    from ncnet_tpu.cli.common import build_model
    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFPascalDataset

    config, params = build_model(checkpoint=best)
    dataset = PFPascalDataset(
        csv, args.dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="scnet",
    )
    mean_pck, per_pair = evaluate_pck(
        config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers,
    )

    # 4. Verdict.
    rec = {
        "metric": f"pf_pascal_pck_at_{args.alpha}",
        "value": round(float(mean_pck), 4),
        "n_pairs": int(per_pair.shape[0]),
        "checkpoint": os.path.basename(args.pth),
    }
    if args.expected_pck >= 0:
        rec["expected"] = args.expected_pck
        rec["tolerance"] = args.tolerance
        rec["parity"] = bool(
            abs(float(mean_pck) - args.expected_pck) <= args.tolerance
        )
    print(json.dumps(rec), flush=True)
    if args.expected_pck >= 0 and not rec["parity"]:
        raise SystemExit(1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
