"""One-command real-weights parity runner — ALL FOUR benchmarks.

The day egress exists, quality parity against the published reference
weights is ONE invocation:

    python tools/real_parity.py

which runs four suites (``--suite`` picks a subset):

  pfpascal  fetch ``ncnet_pfpascal.pth.tar`` + PF-Pascal images/CSVs,
            convert through the golden-tested converter, eval PCK@0.1
            exactly as the reference harness does
            (``/root/reference/eval_pf_pascal.py:84-89`` semantics) and
            GATE against the paper-reported ~78.9%.
  pfwillow  same checkpoint, PF-Willow bbox-PCK@0.1
            (``/root/reference/eval_pf_willow.py`` twin). Report-only:
            the reference repo stores no Willow scalar.
  tss       write TSS Middlebury flows (``/root/reference/eval_tss.py``
            twin), then score them against the dataset's own GT
            ``.flo`` where present (mean EPE + flow-PCK@0.05).
            Report-only; the reference defers scoring to the external
            TSS Matlab kit.
  inloc     fetch InLoc + ``ncnet_ivd.pth.tar``, run the full match
            stage (``cli/eval_inloc.py``) then the in-framework
            localization driver (``cli/localize.py`` — the reference
            needs Matlab here) and report rate@{0.25,0.5,1.0}m against
            the reference-committed GT poses
            (``lib_matlab/DUC_refposes_all.mat``). Report-only; the
            reference stores curves, not a scalar.

A suite whose fetch is blocked (no egress) records the failure VERBATIM
(the evidence trail the judge asked for) and the runner CONTINUES to the
next suite, exiting 3 at the end if anything was blocked — so day one of
egress produces every number one invocation can reach.

Offline testing: every suite accepts pre-staged inputs (the test suite
stages torch-serialized surrogate checkpoints and synthetic datasets in
the reference layouts), so each fetch->convert->eval->report path is
exercised without egress; ``--expected_pck -1`` skips the one gate.

Usage:
    python tools/real_parity.py [--suite pfpascal,pfwillow,tss,inloc]
        [--pth trained_models/ncnet_pfpascal.pth.tar]
        [--ivd_pth trained_models/ncnet_ivd.pth.tar]
        [--dataset_path datasets/pf-pascal] [--expected_pck 0.789]
        [--consensus cp:rank=8] ...
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_GT_POSES = "/root/reference/lib_matlab/DUC_refposes_all.mat"

ALL_SUITES = ("pfpascal", "pfwillow", "tss", "inloc")


def log(msg):
    print(f"[real_parity] {msg}", flush=True)


class FetchBlocked(Exception):
    """A download could not complete (no egress / timeout)."""


def _fetch(script, cwd, what):
    """Run a fetch script, echoing its output verbatim (evidence trail)."""
    log(f"fetching {what} via {script} ...")
    try:
        proc = subprocess.run(
            ["bash", script], cwd=cwd, capture_output=True, text=True,
            timeout=1800,
        )
    except (FileNotFoundError, NotADirectoryError) as exc:
        log(f"FETCH IMPOSSIBLE ({exc}) — fetch script dir missing.")
        raise FetchBlocked(what)
    except subprocess.TimeoutExpired as exc:
        for s in (exc.stdout, exc.stderr):
            if s:
                print(s.decode() if isinstance(s, bytes) else s, flush=True)
        log("FETCH TIMED OUT after 1800 s (blackholed network?) — the "
            "partial output above is the verbatim record.")
        raise FetchBlocked(what)
    out = (proc.stdout + proc.stderr).strip()
    print(out, flush=True)
    if proc.returncode != 0:
        log(f"FETCH FAILED (rc={proc.returncode}) — no egress? The output "
            "above is the verbatim record; re-run when the network allows.")
        raise FetchBlocked(what)


def _ensure_pth(pth, what):
    if not os.path.exists(pth):
        _fetch("download.sh", os.path.join(REPO, "trained_models"), what)
        if not os.path.exists(pth):
            log(f"{pth} still missing after fetch")
            raise FetchBlocked(what)


def _ensure_converted(pth, converted_dir=""):
    """Convert a reference .pth.tar once; return the checkpoint dir."""
    converted = converted_dir or pth + ".converted"
    best = os.path.join(converted, "best")  # converter writes <dst>/best
    if not os.path.exists(os.path.join(best, "params.npz")):
        log(f"converting {pth} -> {converted}")
        from ncnet_tpu.cli.convert_checkpoint import main as convert_main

        rc = convert_main([pth, converted])
        if rc not in (0, None):
            log(f"converter failed rc={rc}")
            raise SystemExit(1)
    else:
        log(f"using existing conversion {best}")
    return best


# ---------------------------------------------------------------- suites


def run_pfpascal(args):
    """PCK@0.1 vs the paper-reported 78.9 (the one gated suite)."""
    _ensure_pth(args.pth, "published reference weights (pfpascal)")
    csv = os.path.join(args.dataset_path, "image_pairs", "test_pairs.csv")
    if not os.path.exists(csv):
        _fetch("fetch_pair_lists.sh", os.path.join(REPO, "datasets"),
               "PF-Pascal split CSVs")
    if not os.path.isdir(os.path.join(args.dataset_path,
                                      "PF-dataset-PASCAL")) \
            and not os.path.isdir(os.path.join(args.dataset_path, "images")):
        _fetch("download.sh", args.dataset_path, "PF-Pascal images")
    if not os.path.exists(csv):
        log(f"{csv} still missing after fetch")
        raise FetchBlocked("PF-Pascal split CSVs")

    best = _ensure_converted(args.pth, args.converted_dir)
    log(f"evaluating PF-Pascal PCK@{args.alpha} at {args.image_size} px ...")
    from ncnet_tpu.cli.common import build_model
    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFPascalDataset

    config, params = build_model(checkpoint=best)
    dataset = PFPascalDataset(
        csv, args.dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="scnet",
    )
    mean_pck, per_pair = evaluate_pck(
        config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers,
    )
    rec = {
        "metric": f"pf_pascal_pck_at_{args.alpha}",
        "value": round(float(mean_pck), 4),
        "n_pairs": int(per_pair.shape[0]),
        "checkpoint": os.path.basename(args.pth),
    }
    if args.expected_pck >= 0:
        rec["expected"] = args.expected_pck
        rec["tolerance"] = args.tolerance
        from ncnet_tpu.evals import within_tolerance

        rec["parity"] = within_tolerance(
            float(mean_pck), args.expected_pck, args.tolerance)
    if args.c2f:
        rec.update(_pfpascal_c2f_delta(args, config, params, mean_pck))
    if args.session:
        rec.update(_pfpascal_session_delta(args, config, params))
    if args.consensus:
        rec.update(
            _pfpascal_consensus_delta(args, config, params, mean_pck))
    return rec


def _parse_consensus(spec):
    """'fft' | 'cp:rank=N' -> (kind, rank), the serving ladder grammar
    (serving/qos.parse_ladder) restricted to one rung."""
    s = spec.strip().lower()
    if s == "fft":
        return "fft", 0
    if s.startswith("cp:rank="):
        try:
            return "cp", int(s.split("=", 1)[1])
        except ValueError:
            pass
    raise SystemExit(
        f"--consensus must be 'fft' or 'cp:rank=N', got {spec!r}")


def _pfpascal_consensus_delta(args, config, params, oneshot_pck):
    """A/B an algebraic consensus arm (cp:rank=N / fft) vs dense
    one-shot on PF-Pascal.

    Unlike --c2f this is a GATE for cp arms: a cp rung is a declared
    approximation (ops/cp4d.py), and the PCK drop it costs end-to-end
    must stay within the rank's declared budget
    (cp4d.declared_pck_drop) or the run exits nonzero — the per-rung
    PCK gate the QoS ladder's cp rungs are audited against. fft is
    exact algebra, so it shares the ±0.01 report-only c2f gate.
    """
    import dataclasses

    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFPascalDataset
    from ncnet_tpu.evals import delta_within_gate
    from ncnet_tpu.ops import cp4d

    kind, rank = _parse_consensus(args.consensus)
    arm_config = dataclasses.replace(
        config, consensus_kind=kind, consensus_cp_rank=rank)
    csv = os.path.join(args.dataset_path, "image_pairs", "test_pairs.csv")
    dataset = PFPascalDataset(
        csv, args.dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="scnet",
    )
    log(f"evaluating {args.consensus} consensus PCK@{args.alpha} at "
        f"{args.image_size} px (params baked: the arm factorizes "
        "weights at trace time) ...")
    arm_pck, _ = evaluate_pck(
        arm_config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers, bake_params=True,
    )
    delta = float(arm_pck) - float(oneshot_pck)
    rec = {
        "consensus_arm": args.consensus,
        "consensus_pck": round(float(arm_pck), 4),
        "consensus_pck_delta": round(delta, 4),
    }
    if kind == "cp":
        budget = cp4d.declared_pck_drop(rank)
        rec["consensus_declared_pck_drop"] = budget
        rec["consensus_within_gate"] = delta >= -budget
    else:
        rec["consensus_within_gate"] = delta_within_gate(delta)
    return rec


def _pfpascal_c2f_delta(args, config, params, oneshot_pck):
    """A/B the coarse-to-fine matcher against one-shot on PF-Pascal.

    The c2f quality gate (docs/PERF.md): the default knobs must hold PCK
    within 1 point of one-shot, or the mode stays opt-in. The delta is
    recorded, never hard-failed — c2f IS opt-in, and the number in the
    parity record is exactly what decides whether that changes.

    c2f needs feature grids divisible by the stride on both axes, so the
    eval image size snaps to a multiple of 16*stride — and the one-shot
    baseline re-runs at the SAME snapped size when it differs from
    --image_size, so the delta compares identical inputs.
    """
    import dataclasses

    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFPascalDataset
    from ncnet_tpu.evals import delta_within_gate

    c2f_config = dataclasses.replace(
        config, mode="c2f",
        c2f_coarse_factor=args.c2f_coarse_factor,
        c2f_topk=args.c2f_topk,
        c2f_radius=args.c2f_radius,
    )
    stride = args.c2f_coarse_factor * max(config.relocalization_k_size, 1)
    unit = 16 * stride
    c2f_size = max(unit, int(round(args.image_size / unit)) * unit)
    csv = os.path.join(args.dataset_path, "image_pairs", "test_pairs.csv")
    dataset = PFPascalDataset(
        csv, args.dataset_path, output_size=(c2f_size, c2f_size),
        pck_procedure="scnet",
    )
    base_pck = float(oneshot_pck)
    if c2f_size != args.image_size:
        log(f"c2f grid alignment snaps eval to {c2f_size} px; re-running "
            "the one-shot baseline there for a like-for-like delta ...")
        base_pck, _ = evaluate_pck(
            config, params, dataset, args.batch_size, args.alpha,
            num_workers=args.num_workers,
        )
        base_pck = float(base_pck)
    log(f"evaluating c2f PCK@{args.alpha} at {c2f_size} px (factor="
        f"{args.c2f_coarse_factor}, topk={args.c2f_topk}, "
        f"radius={args.c2f_radius}) ...")
    c2f_pck, _ = evaluate_pck(
        c2f_config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers,
    )
    delta = float(c2f_pck) - base_pck
    return {
        "c2f_pck": round(float(c2f_pck), 4),
        "c2f_baseline_pck": round(base_pck, 4),
        "c2f_pck_delta": round(delta, 4),
        "c2f_image_size": c2f_size,
        "c2f_coarse_factor": args.c2f_coarse_factor,
        "c2f_topk": args.c2f_topk,
        "c2f_radius": args.c2f_radius,
        "c2f_within_gate": delta_within_gate(delta),
    }


def _pfpascal_session_delta(args, config, params):
    """A/B the streaming-session seeded refinement against full c2f.

    Simulates the session steady state on the still-image benchmark:
    per pair, "frame 1" runs the full c2f coarse pass and emits the
    gate (ops/c2f.coarse_gate); "frame 2" is the SAME pair refined
    purely from that seed dilated by --session_seed_radius
    (ops/c2f.refine_from_seed) — the coarse pipeline never touches
    frame 2, exactly what serving/engine.py's seeded program does. The
    PCK delta vs a full c2f eval at the same snapped size is the
    seeded-quality number docs/SERVING.md cites. Recorded, never
    hard-failed — same ±0.01 report-only gate as --c2f.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import DataLoader, PFPascalDataset
    from ncnet_tpu.evals import delta_within_gate, pck_metric
    from ncnet_tpu.models.ncnet import (
        c2f_coarse_from_features,
        c2f_stride,
        extract_features,
    )
    from ncnet_tpu.ops.c2f import coarse_gate, refine_from_seed
    from ncnet_tpu.ops.matches import relocalize_and_coords

    if args.c2f_coarse_factor <= 1:
        return {"session_skipped": "factor<=1 has no coarse stage to "
                                   "seed from"}
    c2f_config = dataclasses.replace(
        config, mode="c2f",
        c2f_coarse_factor=args.c2f_coarse_factor,
        c2f_topk=args.c2f_topk,
        c2f_radius=args.c2f_radius,
    )
    stride = args.c2f_coarse_factor * max(config.relocalization_k_size, 1)
    unit = 16 * stride
    size = max(unit, int(round(args.image_size / unit)) * unit)
    csv = os.path.join(args.dataset_path, "image_pairs", "test_pairs.csv")
    dataset = PFPascalDataset(
        csv, args.dataset_path, output_size=(size, size),
        pck_procedure="scnet",
    )
    log(f"evaluating full c2f PCK@{args.alpha} at {size} px (session "
        "baseline) ...")
    base_pck, _ = evaluate_pck(
        c2f_config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers,
    )
    base_pck = float(base_pck)

    log(f"evaluating seeded PCK@{args.alpha} (seed_radius="
        f"{args.session_seed_radius}) ...")

    @jax.jit
    def step(params, source, target, batch_points):
        def per_pair(feats):
            fa, fb = (f[None] for f in feats)
            coarse4d, _ = c2f_coarse_from_features(
                c2f_config, params, fa, fb)
            # Per-B probe direction (the eval convention): transpose
            # the coarse tensor and swap feature roles.
            coarse_t = jnp.transpose(coarse4d, (0, 1, 4, 5, 2, 3))
            _, cells, cs, mb = coarse_gate(coarse_t, c2f_config.c2f_topk)
            s = c2f_stride(c2f_config)
            hb, wb = fb.shape[2] // s, fb.shape[3] // s
            ha, wa = fa.shape[2] // s, fa.shape[3] // s
            (i_b, j_b, i_a, j_a, score), _gate = refine_from_seed(
                params["neigh_consensus"], cells, cs, mb, fb, fa,
                coarse_shape=(hb, wb, ha, wa), stride=s,
                radius=c2f_config.c2f_radius,
                seed_radius=args.session_seed_radius,
                topk=c2f_config.c2f_topk,
                symmetric=c2f_config.symmetric_mode,
                corr_dtype=c2f_config.corr_dtype,
            )
            fine_shape = (fa.shape[2], fa.shape[3],
                          fb.shape[2], fb.shape[3])
            return relocalize_and_coords(
                i_a, j_a, i_b, j_b, score, None, 1, fine_shape,
                "centered")

        feat_a = extract_features(c2f_config, params, source)
        feat_b = extract_features(c2f_config, params, target)
        outs = jax.lax.map(per_pair, (feat_a, feat_b))
        xa, ya, xb, yb, _ = (o[:, 0] for o in outs)
        return pck_metric(batch_points, (xa, ya, xb, yb), args.alpha)

    loader = DataLoader(dataset, args.batch_size, shuffle=False,
                        num_workers=args.num_workers)
    values = []
    for batch in loader:
        batch_points = {
            k: jnp.asarray(batch[k])
            for k in ("source_points", "target_points", "source_im_size",
                      "target_im_size", "L_pck")
        }
        values.append(np.asarray(step(
            params,
            jnp.asarray(batch["source_image"]),
            jnp.asarray(batch["target_image"]),
            batch_points,
        )))
    per_pair = np.concatenate(values)
    good = np.flatnonzero((per_pair != -1) & ~np.isnan(per_pair))
    sess_pck = float(per_pair[good].mean()) if good.size else float("nan")
    delta = sess_pck - base_pck
    return {
        "session_pck": round(sess_pck, 4),
        "session_baseline_c2f_pck": round(base_pck, 4),
        "session_pck_delta": round(delta, 4),
        "session_image_size": size,
        "session_seed_radius": args.session_seed_radius,
        "session_within_gate": delta_within_gate(delta),
    }


def run_pfwillow(args):
    """PF-Willow bbox-PCK@0.1 with the PF-Pascal checkpoint (the
    reference's eval_pf_willow.py pairing). Report-only."""
    _ensure_pth(args.pth, "published reference weights (pfpascal)")
    csv = os.path.join(args.willow_dataset_path, args.willow_csv)
    if not os.path.exists(csv):
        _fetch("download.sh", args.willow_dataset_path, "PF-Willow dataset")
    if not os.path.exists(csv):
        log(f"{csv} still missing after fetch")
        raise FetchBlocked("PF-Willow dataset")

    best = _ensure_converted(args.pth, args.converted_dir)
    log(f"evaluating PF-Willow PCK@{args.alpha} at {args.image_size} px ...")
    from ncnet_tpu.cli.common import build_model
    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFWillowDataset

    config, params = build_model(checkpoint=best)
    dataset = PFWillowDataset(
        csv, args.willow_dataset_path,
        output_size=(args.image_size, args.image_size),
    )
    mean_pck, per_pair = evaluate_pck(
        config, params, dataset, args.batch_size, args.alpha,
        num_workers=args.num_workers,
    )
    return {
        "metric": f"pf_willow_pck_at_{args.alpha}",
        "value": round(float(mean_pck), 4),
        "n_pairs": int(per_pair.shape[0]),
        "checkpoint": os.path.basename(args.pth),
    }


def run_tss(args):
    """Write TSS flows, then score vs the dataset's GT .flo in-framework
    (mean EPE + flow-PCK@0.05; the reference defers to the TSS Matlab
    kit). Report-only."""
    pth = args.tss_pth or args.pth
    # A distinct conversion dir is only needed when TSS really uses a
    # different checkpoint; the default (tss_pth == pth) shares the
    # pfpascal suite's conversion instead of re-running it.
    tss_converted = (args.converted_dir + ".tss"
                     if args.converted_dir and args.tss_pth else
                     args.converted_dir)
    _ensure_pth(pth, "published reference weights (tss)")
    csv = os.path.join(args.tss_dataset_path, args.tss_csv)
    if not os.path.exists(csv):
        _fetch("download.sh", args.tss_dataset_path, "TSS dataset")
    if not os.path.exists(csv):
        log(f"{csv} still missing after fetch")
        raise FetchBlocked("TSS dataset")

    best = _ensure_converted(pth, tss_converted)
    flow_dir = args.flow_output_dir or os.path.join(
        args.tss_dataset_path, "results")
    log(f"writing TSS flows to {flow_dir} ...")
    from ncnet_tpu.cli.eval_tss import main as tss_main

    tss_main([
        "--checkpoint", best,
        "--eval_dataset_path", args.tss_dataset_path,
        "--csv_file", args.tss_csv,
        "--flow_output_dir", flow_dir,
        "--image_size", str(args.image_size),
        "--batch_size", str(args.batch_size),
        "--num_workers", str(args.num_workers),
    ])

    # Score the written flows against GT flows shipped with the dataset
    # (<pair_dir>/flow<d>.flo). TSS convention: a pixel is correct when
    # the flow endpoint lands within alpha * max(h, w) of GT.
    import pandas as pd

    from ncnet_tpu.geometry.flow_io import read_flo_file

    rows = pd.read_csv(csv)
    epes, pcks, n_scored = [], [], 0
    for _, row in rows.iterrows():
        pair_dir = os.path.dirname(str(row.iloc[0]))
        flow_file = f"flow{int(row.iloc[2])}.flo"
        gt_path = os.path.join(args.tss_dataset_path, pair_dir, flow_file)
        # write_flow_output layout: <flow_dir>/nc/<pair_dir>/<flow_file>
        out_path = os.path.join(flow_dir, "nc", pair_dir, flow_file)
        if not (os.path.exists(gt_path) and os.path.exists(out_path)):
            continue
        gt = read_flo_file(gt_path)
        pred = read_flo_file(out_path)
        if gt.shape != pred.shape:
            continue
        if int(row.iloc[3]):
            # flip_img_A=1: matching ran on the MIRRORED source against
            # the unflipped target (tss_dataset.py:48-50 semantics), so
            # the predicted endpoints are already in the GT target frame
            # but indexed by mirrored source pixels. Re-index to the
            # original source grid: for original x the flipped column is
            # W-1-x, and u_orig = (W-1-x) + u'[y, W-1-x] - x.
            w = pred.shape[1]
            pred = pred[:, ::-1].copy()
            xs = np.arange(w, dtype=pred.dtype)
            pred[..., 0] += (w - 1.0) - 2.0 * xs
        valid = np.isfinite(gt).all(axis=-1) & (np.abs(gt) < 1e9).all(
            axis=-1)
        if not valid.any():
            continue
        err = np.linalg.norm(pred - gt, axis=-1)[valid]
        thr = args.tss_alpha * max(gt.shape[0], gt.shape[1])
        epes.append(float(err.mean()))
        pcks.append(float((err <= thr).mean()))
        n_scored += 1
    rec = {
        "metric": "tss_flow",
        "n_pairs": int(len(rows)),
        "n_scored_vs_gt": n_scored,
        "checkpoint": os.path.basename(pth),
    }
    if n_scored:
        rec["mean_epe_px"] = round(float(np.mean(epes)), 3)
        rec[f"flow_pck_at_{args.tss_alpha}"] = round(
            float(np.mean(pcks)), 4)
    return rec


def run_inloc(args):
    """Full InLoc chain: match stage -> localization driver -> rates vs
    the reference-committed GT poses. Report-only (reference stores
    curves, not a scalar: lib_matlab/ht_plotcurve_WUSTL.m:81-97)."""
    _ensure_pth(args.ivd_pth, "published reference weights (ivd)")
    shortlist = args.inloc_shortlist or os.path.join(
        args.inloc_dataset_path, "densePE_top100_shortlist_cvpr18.mat")
    if not os.path.exists(shortlist):
        _fetch("download.sh", args.inloc_dataset_path, "InLoc dataset")
    if not os.path.exists(shortlist):
        log(f"{shortlist} still missing after fetch")
        raise FetchBlocked("InLoc dataset")

    best = _ensure_converted(args.ivd_pth, args.converted_dir and
                             args.converted_dir + ".ivd")
    # Key the matches root by checkpoint file so two different weights
    # can never share (or --resume into) each other's match files.
    ckpt_tag = os.path.basename(args.ivd_pth).split(".")[0]
    matches_dir = args.inloc_matches_dir or os.path.join(
        REPO, "matches", f"real_parity_{ckpt_tag}")
    log(f"running InLoc match stage -> {matches_dir} ...")
    from ncnet_tpu.cli.eval_inloc import main as inloc_main

    exp_dir = inloc_main([
        "--checkpoint", best,
        "--inloc_shortlist", shortlist,
        "--query_path", args.inloc_query_path or os.path.join(
            args.inloc_dataset_path, "query", "iphone7"),
        "--pano_path", args.inloc_pano_path or os.path.join(
            args.inloc_dataset_path, "pano"),
        "--output_dir", matches_dir,
        "--image_size", str(args.inloc_image_size),
        "--n_queries", str(args.inloc_n_queries),
        "--n_panos", str(args.inloc_n_panos),
    ])

    # eval_inloc returns the experiment subdir it wrote into (named by
    # shortlist/config/checkpoint); the driver consumes that subdir.
    if exp_dir and os.path.exists(os.path.join(exp_dir, "1.mat")):
        matches_dir = exp_dir

    log("running localization driver ...")
    from ncnet_tpu.cli.localize import main as localize_main

    gt = args.inloc_gt_poses
    if gt == "auto":
        gt = REF_GT_POSES if os.path.exists(REF_GT_POSES) else ""
    loc_out = os.path.join(matches_dir, "localization")
    summary = localize_main([
        "--matches_dir", matches_dir,
        "--shortlist", shortlist,
        "--cutout_dir", args.inloc_cutout_path or os.path.join(
            args.inloc_dataset_path, "cutouts"),
        "--query_dir", args.inloc_query_path or os.path.join(
            args.inloc_dataset_path, "query", "iphone7"),
        "--transform_dir", ("" if args.inloc_transform_path == "none"
                            else args.inloc_transform_path or os.path.join(
                                args.inloc_dataset_path, "cutouts")),
        "--output_dir", loc_out,
        "--top_n", str(args.inloc_n_panos),
    ] + (["--gt_poses", gt] if gt else []))
    rec = {
        "metric": "inloc_localization",
        "checkpoint": os.path.basename(args.ivd_pth),
        "matches_dir": matches_dir,
    }
    if summary:
        rec.update(summary)
    else:
        rec["note"] = "no GT poses available; poses written, no rates"
    return rec


SUITE_RUNNERS = {
    "pfpascal": run_pfpascal,
    "pfwillow": run_pfwillow,
    "tss": run_tss,
    "inloc": run_inloc,
}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fetch -> convert -> eval -> report, all four suites"
    )
    ap.add_argument("--suite", type=str, default="all",
                    help="comma list of " + ",".join(ALL_SUITES))
    ap.add_argument("--pth", type=str,
                    default=os.path.join(REPO, "trained_models",
                                         "ncnet_pfpascal.pth.tar"))
    ap.add_argument("--ivd_pth", type=str,
                    default=os.path.join(REPO, "trained_models",
                                         "ncnet_ivd.pth.tar"))
    ap.add_argument("--tss_pth", type=str, default="",
                    help="TSS checkpoint (default: --pth; the reference "
                    "eval_tss.py documents no pairing)")
    ap.add_argument("--dataset_path", type=str,
                    default=os.path.join(REPO, "datasets", "pf-pascal"))
    ap.add_argument("--willow_dataset_path", type=str,
                    default=os.path.join(REPO, "datasets", "pf-willow"))
    ap.add_argument("--willow_csv", type=str, default="test_pairs.csv")
    ap.add_argument("--tss_dataset_path", type=str,
                    default=os.path.join(REPO, "datasets", "tss"))
    ap.add_argument("--tss_csv", type=str, default="test_pairs.csv")
    ap.add_argument("--tss_alpha", type=float, default=0.05)
    ap.add_argument("--flow_output_dir", type=str, default="")
    ap.add_argument("--inloc_dataset_path", type=str,
                    default=os.path.join(REPO, "datasets", "inloc"))
    ap.add_argument("--inloc_shortlist", type=str, default="")
    ap.add_argument("--inloc_query_path", type=str, default="")
    ap.add_argument("--inloc_pano_path", type=str, default="")
    ap.add_argument("--inloc_cutout_path", type=str, default="")
    ap.add_argument("--inloc_transform_path", type=str, default="",
                    help="'' = <inloc_dataset_path>/cutouts, 'none' = "
                    "run without scan transforms")
    ap.add_argument("--inloc_matches_dir", type=str, default="")
    ap.add_argument("--inloc_gt_poses", type=str, default="auto",
                    help="'auto' = the reference-committed "
                    "DUC_refposes_all.mat when present")
    ap.add_argument("--inloc_image_size", type=int, default=3200)
    ap.add_argument("--inloc_n_queries", type=int, default=356)
    ap.add_argument("--inloc_n_panos", type=int, default=10)
    ap.add_argument("--converted_dir", type=str, default="",
                    help="output dir for the converted checkpoint "
                    "(default: <pth>.converted)")
    ap.add_argument("--expected_pck", type=float, default=0.789,
                    help="paper-reported PF-Pascal PCK@0.1 (BASELINE.md); "
                    "pass -1 to skip the comparison")
    ap.add_argument("--tolerance", type=float, default=0.02)
    ap.add_argument("--image_size", type=int, default=400)
    ap.add_argument("--c2f", action="store_true",
                    help="also eval PF-Pascal under mode='c2f' and record "
                    "the PCK delta vs one-shot (the c2f quality gate; "
                    "report-only — the mode is opt-in)")
    ap.add_argument("--c2f_coarse_factor", type=int, default=2)
    ap.add_argument("--c2f_topk", type=int, default=8)
    ap.add_argument("--c2f_radius", type=int, default=1)
    ap.add_argument("--session", action="store_true",
                    help="also eval the streaming-session seeded path "
                    "(frame 1 c2f coarse emits the gate, frame 2 = same "
                    "pair refined from the dilated seed) and record the "
                    "PCK delta vs full c2f (report-only, like --c2f)")
    ap.add_argument("--session_seed_radius", type=int, default=1,
                    help="Chebyshev seed dilation, matching the serving "
                    "engine's --session_seed_radius")
    ap.add_argument("--consensus", type=str, default="",
                    help="also eval PF-Pascal under an algebraic "
                    "consensus arm ('cp:rank=N' or 'fft') and GATE the "
                    "PCK drop against the rank's declared budget "
                    "(ops/cp4d.py DECLARED_PCK_DROP; fft is exact and "
                    "report-only)")
    ap.add_argument("--alpha", type=float, default=0.1)
    ap.add_argument("--batch_size", type=int, default=8)
    ap.add_argument("--num_workers", type=int, default=4)
    args = ap.parse_args(argv)

    suites = (ALL_SUITES if args.suite == "all"
              else tuple(s for s in args.suite.split(",") if s))
    unknown = set(suites) - set(ALL_SUITES)
    if unknown:
        ap.error(f"unknown suite(s): {sorted(unknown)}")

    records = []
    blocked = []
    failed_gate = False
    for suite in suites:
        log(f"=== suite: {suite} ===")
        try:
            rec = SUITE_RUNNERS[suite](args)
        except FetchBlocked as exc:
            blocked.append(suite)
            rec = {"metric": suite, "blocked": str(exc)}
        rec["suite"] = suite
        records.append(rec)
        print(json.dumps(rec), flush=True)
        if rec.get("parity") is False:
            failed_gate = True
        # A cp arm's declared PCK budget is a hard gate (fft/c2f deltas
        # stay report-only — they promise exactness, not a budget).
        if (rec.get("consensus_declared_pck_drop") is not None
                and rec.get("consensus_within_gate") is False):
            failed_gate = True

    if len(suites) > 1:
        print(json.dumps({"summary": True,
                          "suites_run": len(suites) - len(blocked),
                          "suites_blocked": blocked}), flush=True)
    if failed_gate:
        raise SystemExit(1)
    if blocked:
        raise SystemExit(3)
    return 0


if __name__ == "__main__":
    sys.exit(main())
