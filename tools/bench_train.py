"""Training-throughput benchmark: PF-Pascal weak-supervision step, pairs/s.

Secondary perf evidence next to the headline bench.py (InLoc dense
matching). Times the full jitted train step — two correlation passes
(positive + rolled negative), gradient, Adam update — on synthetic batches
at the reference's training configuration (400 px, ResNet-101 layer3,
NeighConsensus 5-5-5/16-16-1, batch 16: reference train.py:36-43), sharded
over all local devices.

Prints one JSON line: {"metric", "value", "unit", "devices", "batch"}.

Usage:
    python tools/bench_train.py [--batch 16] [--image-size 400] [--iters 10]
    # CPU smoke: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    #   python tools/bench_train.py --backbone vgg --image-size 64 --iters 2
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--image-size", type=int, default=400)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--backbone", type=str, default="resnet101")
    p.add_argument("--remat", action="store_true")
    # Gradient accumulation (trainer.make_train_step accum_steps): the
    # round-4 HBM lever to sweep against the remat policies — micro-batch
    # AD memory may allow a cheaper policy at the same global batch.
    p.add_argument("--accum", type=int, default=1)
    p.add_argument(
        "--policies", type=str, default="",
        help="comma-separated NCNET_TRAIN_REMAT_POLICY sweep (e.g. "
        "'full,dots,none'); one JSON line per policy, each fenced so a "
        "pathological compile can't starve the rest (round-3 item 4: "
        "7.8 s/step is recompute-heavy, the policy trade is untried on "
        "hardware). Empty = single run with the inherited env.",
    )
    p.add_argument("--dial_timeout", type=float, default=600.0)
    # Elastic scaling line: run the chaos_train fleet (no kill) at 1
    # host and at N hosts, report scaling efficiency and the measured
    # lease/heartbeat overhead share of step time (< 2% acceptance).
    p.add_argument(
        "--hosts", type=int, default=0,
        help="emit the train_elastic_scaling line for an N-host elastic "
        "CPU fleet instead of the single-process step benchmark")
    p.add_argument("--elastic-steps", type=int, default=24,
                   help="--hosts mode: steps per epoch per fleet run")
    args = p.parse_args(argv)

    if args.hosts:
        return _measure_elastic_scaling(args)

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.parallel import make_mesh
    from ncnet_tpu.training import (
        create_train_state,
        make_train_step,
        replicate_state,
        shard_batch,
    )
    from ncnet_tpu.utils.profiling import setup_compile_cache

    setup_compile_cache()
    # Dial under a watchdog: a wedged axon tunnel blocks jax.devices()
    # forever (same policy as bench.py / the other tools).
    from ncnet_tpu.utils.profiling import dial_devices

    devices = dial_devices(args.dial_timeout)
    if devices is None:
        # One-JSON-line contract even on failure: stdout carries exactly
        # one parseable line, prose goes to stderr (same as bench.py).
        print("backend dial timed out; aborting", file=sys.stderr)
        print(json.dumps({"metric": "train_step_pairs_per_s",
                          "error": "backend dial timed out"}), flush=True)
        return 2
    n_dev = len(devices)
    # Same validation as cli/train.py: fail fast, not inside the jit trace.
    if args.accum > 1 and (
        args.batch % args.accum or args.batch // args.accum < 2
    ):
        msg = (f"--accum {args.accum} needs --batch {args.batch} divisible "
               "by it with a micro-batch >= 2")
        print(msg, file=sys.stderr)
        print(json.dumps({"metric": "train_step_pairs_per_s", "error": msg}),
              flush=True)
        return 2
    # Largest device count dividing the MICRO-batch (same rule as
    # cli/train.py — the accumulated scan shards per micro-batch).
    micro = args.batch // max(args.accum, 1)
    dp = max(d for d in range(1, n_dev + 1) if micro % d == 0)
    mesh = make_mesh((dp,), ("dp",))

    config = NCNetConfig(
        # last_layer stays at its default: BackboneConfig resolves the
        # per-backbone truncation point (layer3 / pool4 / ...).
        backbone=BackboneConfig(cnn=args.backbone),
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)

    key = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    shape = (args.batch, 3, args.image_size, args.image_size)
    batch = shard_batch(
        {
            "source_image": jax.random.normal(k1, shape, jnp.float32),
            "target_image": jax.random.normal(k2, shape, jnp.float32),
        },
        mesh,
    )

    def measure(policy_label):
        # Fresh param buffers per run: train_step donates trainable/opt
        # state, so a shared init pytree would be deleted after the first
        # policy's run.
        state, tx = create_train_state(jax.tree.map(jnp.array, params))
        state = replicate_state(state, mesh)
        train_step, _ = make_train_step(config, tx, remat_backbone=args.remat,
                                        accum_steps=args.accum)
        trainable, opt_state = state.trainable, state.opt_state
        trainable, opt_state, loss, _ = train_step(  # compile + warmup
            trainable, state.frozen, opt_state,
            batch["source_image"], batch["target_image"],
        )
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            trainable, opt_state, loss, _ = train_step(
                trainable, state.frozen, opt_state,
                batch["source_image"], batch["target_image"],
            )
            float(loss)  # per-step sync: the fetch closes the iteration
        dt = (time.perf_counter() - t0) / args.iters
        line = {
            "metric": "train_step_pairs_per_s",
            "value": round(args.batch / dt, 3),
            "unit": "pairs/s",
            "devices": dp,
            "batch": args.batch,
            "step_ms": round(dt * 1e3, 2),
        }
        if policy_label is not None:
            line["remat_policy"] = policy_label
        if args.accum > 1:
            line["accum"] = args.accum
        print(json.dumps(line), flush=True)

    if not args.policies:
        measure(None)
        return
    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    for policy in args.policies.split(","):
        policy = policy.strip()
        os.environ["NCNET_TRAIN_REMAT_POLICY"] = policy
        try:
            # 10 min per policy: an OOMing or pathologically-compiling
            # variant must not starve the sweep.
            run_with_alarm(600, measure, policy)
        except AlarmTimeout:
            print(json.dumps({"metric": "train_step_pairs_per_s",
                              "remat_policy": policy, "timeout": True}),
                  flush=True)
        except Exception as exc:  # noqa: BLE001 — OOM is a data point
            print(json.dumps({"metric": "train_step_pairs_per_s",
                              "remat_policy": policy,
                              "error": str(exc)[:200]}), flush=True)
        finally:
            os.environ.pop("NCNET_TRAIN_REMAT_POLICY", None)


def _measure_elastic_scaling(args):
    """N-host elastic fleet throughput vs a 1-host baseline.

    Both runs go through tools/chaos_train.py with ``--kill none`` (the
    same worker loop the chaos gate audits — leases, step checks,
    commit barriers — minus the kill). The baseline trains the per-host
    slice, the fleet trains N slices of the same global batch, so ideal
    scaling is exactly N× and ``scaling_efficiency`` is their ratio.
    ``lease_overhead_frac`` is the fleet's cumulative
    ``ElasticDriver.step_check`` time over cumulative training time —
    the membership tax on every step, gated < 2%.
    """
    import glob as _glob
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n = max(args.hosts, 1)
    per_host = max(args.batch // n, 1)

    def fleet(n_hosts, batch):
        root = tempfile.mkdtemp(prefix=f"bench_elastic_{n_hosts}_")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "chaos_train.py"),
             "--kill", "none", "--hosts", str(n_hosts), "--epochs", "1",
             "--steps", str(args.elastic_steps), "--batch", str(batch),
             # No rolling saves: the writer's commit-barrier waits would
             # bill checkpoint sync into the throughput number; the
             # scaling line measures the per-step membership tax only.
             "--save-interval", "0", "--dir", root],
            env=env, capture_output=True, text=True, timeout=600)
        sys.stderr.write(proc.stderr[-2000:])
        if proc.returncode != 0:
            raise RuntimeError(
                f"{n_hosts}-host fleet exited {proc.returncode}")
        results = []
        for path in _glob.glob(os.path.join(root, "result-*.json")):
            with open(path, encoding="utf-8") as fh:
                results.append(json.load(fh))
        if len(results) != n_hosts:
            raise RuntimeError(
                f"expected {n_hosts} result files, got {len(results)}")
        wall = max(r["train_time_s"] for r in results)
        return {
            "pairs_per_s": sum(r["pairs"] for r in results)
            / max(wall, 1e-9),
            "check_frac": sum(r["check_time_s"] for r in results)
            / max(sum(r["train_time_s"] for r in results), 1e-9),
            "resumes": sum(r["resumes"] for r in results),
        }

    try:
        base = fleet(1, per_host)
        scaled = fleet(n, per_host * n)
    except (RuntimeError, subprocess.TimeoutExpired, OSError) as exc:
        print(str(exc), file=sys.stderr)
        print(json.dumps({"metric": "train_elastic_scaling",
                          "error": str(exc)[:200]}), flush=True)
        return 2
    efficiency = scaled["pairs_per_s"] / max(n * base["pairs_per_s"], 1e-9)
    line = {
        "metric": "train_elastic_scaling",
        "value": round(efficiency, 4),
        "unit": "scaling_efficiency",
        "hosts": n,
        "batch": per_host * n,
        "scaling_efficiency": round(efficiency, 4),
        "pairs_per_s": round(scaled["pairs_per_s"], 2),
        "baseline_pairs_per_s": round(base["pairs_per_s"], 2),
        "lease_overhead_frac": round(scaled["check_frac"], 5),
        "elastic_resumes": scaled["resumes"],
        "synthetic": True,
    }
    print(json.dumps(line), flush=True)
    # The acceptance line: membership must tax step time under 2%.
    if scaled["check_frac"] >= 0.02:
        print(f"lease overhead {scaled['check_frac']:.4f} >= 2% of step "
              "time", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
