"""Per-rung quality-cost table + drift report from a live server.

Reads one server's ``GET /healthz`` ``quality`` block (obs/quality.py
drift detectors + serving/shadow.py per-rung shadow-agreement
aggregates) and renders the measured degradation cost —

    rung     n   mean agree   min agree   bitwise%   seeded
    0       14       1.0000      1.0000        100        0
    1        9       0.9631      0.9200          0        0
    drift: v1_match psi 0.04 (ok)

Rung 0 is the comparator's self-test: the engine is deterministic, so
a rung-0 shadow re-run must agree 1.0 bitwise — anything else means
the comparison itself is broken, not the ladder. Degraded rungs carry
the number the QoS ladder's knob choices are audited against.

On exit it prints ONE JSON line to stdout (the house tools/ contract;
prose goes to stderr). ``--strict`` makes quality failures a nonzero
exit so a session script (or ci_gate --with-quality-report) can gate
on it:

* any rung's mean agreement below its floor — ``--floor`` for c2f
  rungs; for ``cp:`` rungs (a *declared* approximation,
  ops/cp4d.py) the declared per-rank agreement floor, resolved from
  the /healthz ``qos.ladder`` block, so a deliberately-approximate cp
  rung doesn't fail the c2f floor while still being gated against the
  number it promised;
* rung 0 present but not 100% bitwise (broken comparator);
* no shadow comparisons recorded at all — a report that measured
  nothing must never read as green.

Example::

    python tools/quality_report.py http://127.0.0.1:8123 \
        --strict --floor 0.9

``--smoke`` self-hosts a tiny CPU server (no url needed), drives a
handful of synthetic requests through it with the shadow sampler wide
open and synchronous, and reports on the result — every sample runs at
rung 0, so a green smoke is exactly the comparator self-test: the
deterministic engine re-ran every response and agreed 1.0 bitwise.
This is the flavor ``ci_gate --with-quality-report`` runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

# Runnable as `python tools/quality_report.py` from the repo root: the
# --smoke path imports ncnet_tpu (the scrape path never does).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def note(msg):
    print(msg, file=sys.stderr, flush=True)


def fetch_healthz(url: str, timeout_s: float = 5.0) -> dict:
    if not url.rstrip("/").endswith("/healthz"):
        url = url.rstrip("/") + "/healthz"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def _declared_cp_floor(rank: int, fallback: float = 0.1) -> float:
    """The declared agreement floor for a cp:rank=N rung (the single
    home is ops/cp4d.py DECLARED_AGREEMENT_FLOOR; nearest declared rank
    at or below N). Falls back when ncnet_tpu isn't importable — the
    scrape path must work on report-only hosts without jax."""
    try:
        from ncnet_tpu.ops.cp4d import DECLARED_AGREEMENT_FLOOR
    except Exception:  # noqa: BLE001 — report-only host
        return fallback
    best = None
    for r in sorted(DECLARED_AGREEMENT_FLOOR):
        if r <= rank:
            best = DECLARED_AGREEMENT_FLOOR[r]
    if best is None:
        best = DECLARED_AGREEMENT_FLOOR[min(DECLARED_AGREEMENT_FLOOR)]
    return best


def evaluate(quality: dict, floor: float, ladder=None) -> dict:
    """The report record from one /healthz ``quality`` block.

    ``ok`` reflects the strict gate's three rules; ``failures`` names
    each violated one (empty = clean). ``ladder`` is the /healthz
    ``qos.ladder`` knob list — it tells which rung indices are cp
    rungs, which are gated at their declared per-rank floor instead of
    the c2f ``floor``.
    """
    drift = quality.get("drift") or {}
    shadow = quality.get("shadow") or {}
    rungs = shadow.get("rungs") or {}
    ladder = list(ladder or [])
    failures = []
    rung_floors = {}
    for rung, agg in sorted(rungs.items()):
        mean = agg.get("mean_agreement")
        try:
            idx = int(rung)
        except (TypeError, ValueError):
            idx = 0
        knobs = ladder[idx - 1] if 0 < idx <= len(ladder) else {}
        kind = (knobs or {}).get("kind", "c2f")
        rung_floor = floor
        if kind == "cp":
            rung_floor = _declared_cp_floor(
                int((knobs or {}).get("rank") or 0))
        rung_floors[rung] = {"kind": kind, "floor": rung_floor}
        if mean is not None and mean < rung_floor:
            failures.append(
                f"rung {rung} ({kind}) mean agreement {mean:g} below "
                f"floor {rung_floor:g}")
    zero = rungs.get("0")
    if zero and zero.get("n") and (zero.get("bitwise_frac") or 0.0) < 1.0:
        failures.append(
            f"rung 0 bitwise_frac {zero['bitwise_frac']:g} != 1.0 "
            "(comparator self-test failed)")
    if not any(agg.get("n") for agg in rungs.values()):
        failures.append("no shadow comparisons recorded")
    means = [agg["mean_agreement"] for agg in rungs.values()
             if agg.get("mean_agreement") is not None]
    return {
        "metric": "quality_report",
        "value": min(means) if means else None,
        "unit": "frac",
        "rungs": rungs,
        "drift": drift,
        "drifting": bool(drift.get("drifting")),
        "shadow_enabled": bool(shadow.get("enabled")),
        "sampled": shadow.get("sampled"),
        "skipped": shadow.get("skipped"),
        "shadow_errors": shadow.get("errors"),
        "tau_px": shadow.get("tau_px"),
        "floor": floor,
        "rung_floors": rung_floors,
        "ok": not failures,
        "failures": failures,
    }


def _cell(v, width, prec=4, scale=1.0):
    if v is None:
        return "-".rjust(width)
    return f"{v * scale:.{prec}f}".rjust(width)


def render(rec: dict) -> None:
    rungs = rec["rungs"]
    if rungs:
        note(f"{'rung':<5} {'n':>5} {'mean agree':>11} {'min agree':>10} "
             f"{'bitwise%':>9} {'seeded':>7}")
        for rung, agg in sorted(rungs.items(), key=lambda kv: kv[0]):
            note(f"{rung:<5} {agg.get('n', 0):>5} "
                 f"{_cell(agg.get('mean_agreement'), 11)} "
                 f"{_cell(agg.get('min_agreement'), 10)} "
                 f"{_cell(agg.get('bitwise_frac'), 9, 0, 100.0)} "
                 f"{agg.get('seeded', 0):>7}")
    else:
        note("no shadow comparisons recorded "
             "(shadow sampler off, or nothing sampled yet)")
    for ep, det in sorted((rec["drift"].get("per_endpoint") or {}).items()):
        state = "DRIFTING" if det.get("drifting") else (
            "ok" if det.get("reference_full") else
            f"warming ({det.get('live_n', 0)}/{det.get('window')})")
        note(f"drift: {ep} psi {det.get('psi', 0.0):.3f} ({state})")
    for f in rec["failures"]:
        note(f"FAIL: {f}")


def run_smoke(n_requests: int, model=None) -> dict:
    """Self-hosted comparator self-test; returns the final /healthz.

    Heavy imports stay in here — the scrape path must work without
    jax installed (offline dashboards, report-only hosts).
    """
    import io

    import numpy as np
    from PIL import Image

    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    rng = np.random.default_rng(0)
    imgs = []
    for _ in range(2):
        buf = io.BytesIO()
        Image.fromarray(
            (rng.random((96, 128, 3)) * 255).astype("uint8")
        ).save(buf, format="JPEG")
        imgs.append(buf.getvalue())

    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    engine.warmup([(96, 128, 96, 128)], batch_sizes=(1,))
    # Shadow wide open + synchronous executor: every request is
    # re-dispatched and compared before its response returns, so the
    # final healthz deterministically holds n_requests rung-0 compares.
    server = MatchServer(engine, port=0, max_batch=1, max_delay_s=0.0,
                         default_timeout_s=120.0, shadow_rate=1e6,
                         shadow_executor=lambda fn: fn()).start()
    try:
        client = MatchClient(server.url, timeout_s=120.0)
        for i in range(n_requests):
            client.match(query_bytes=imgs[0], pano_bytes=imgs[1],
                         max_matches=16)
            note(f"smoke request {i + 1}/{n_requests} ok")
        return client.healthz()
    finally:
        server.stop()


def main(argv=None, fetch=None, model=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("url", nargs="?", default="",
                    help="server base URL (or /healthz endpoint)")
    ap.add_argument("--floor", type=float, default=0.9,
                    help="minimum acceptable per-rung mean agreement "
                         "(default 0.9)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any quality failure")
    ap.add_argument("--timeout_s", type=float, default=5.0)
    ap.add_argument("--smoke", action="store_true",
                    help="self-host a tiny CPU server and report on its "
                         "own shadow compares (no url)")
    ap.add_argument("--smoke_requests", type=int, default=4,
                    help="requests the smoke run drives (default 4)")
    args = ap.parse_args(argv)
    if bool(args.smoke) == bool(args.url):
        ap.error("exactly one of url or --smoke is required")

    fetch = fetch or fetch_healthz
    try:
        if args.smoke:
            health = run_smoke(args.smoke_requests, model=model)
        else:
            health = fetch(args.url, args.timeout_s)
    except Exception as exc:  # noqa: BLE001 — report, one exit path
        note(f"{'smoke failed' if args.smoke else 'unreachable'}: {exc}")
        print(json.dumps({"metric": "quality_report", "value": None,
                          "unit": "frac", "ok": False,
                          "failures": [f"unreachable: {exc}"]}))
        return 1
    quality = health.get("quality") or {}
    ladder = (health.get("qos") or {}).get("ladder")
    rec = evaluate(quality, args.floor, ladder=ladder)
    render(rec)
    print(json.dumps(rec), flush=True)
    return 1 if (args.strict and not rec["ok"]) else 0


if __name__ == "__main__":
    sys.exit(main())
