"""Elastic-training chaos gate: SIGKILL a host, survivors must resume.

Boots an N-host CPU training fleet (one process per host, rendezvous
through a shared membership root — ncnet_tpu/parallel/membership.py),
kills one host mid-epoch, and audits the recovery end to end:

- the survivors detect the death (lease TTL), bump the membership
  generation WITHOUT the victim, reload the last committed checkpoint
  and resume within ``--resume-budget-steps`` re-trained steps;
- the per-host step ledgers (``steps-<host>.jsonl``) prove ZERO silent
  step loss: every ``(epoch, step)`` of the final curve is tiled by
  some generation's batch slices;
- every booked loss is finite;
- the surviving writer's runlog passes ``tools/train_report.py
  --strict`` against the committed reference curve
  (``tests/data/elastic_train_reference.json``).

Workers train a deterministic synthetic objective (loss = 1/(1+step))
through the REAL machinery under test: MembershipPlane leases +
generations, ElasticDriver step checks + resume, the rolling
rename-aside checkpoint chain (training/checkpoint.py), and the
training observatory (obs/train_watch.py) — only the model math is
stubbed, so the gate runs anywhere in seconds.

Kill modes (``--kill``):

- ``poll`` (default): the parent watches the victim's step ledger and
  SIGKILLs it once it has trained ``--kill-after-step`` steps — the
  OOM/preemption shape;
- ``failpoint``: arms ``NCNET_FAILPOINTS=membership.lease=kill:+N`` on
  the victim so it dies at exactly its (N+1)-th lease renewal —
  deterministic placement for the contract test;
- ``none``: no kill (bench_train --hosts uses this for clean scaling
  runs).

Prints ONE JSON line (the repo bench contract)::

    {"metric": "chaos_train", "value": 1.0, "ok": true, "hosts": 3,
     "killed": "host1", "generation": 2, "resumes": 1, "lost_steps": 4,
     "ledger_ok": true, "strict_ok": true, ...}

Exit 0 iff every check passed. Prose goes to stderr.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_REFERENCE = os.path.join(
    REPO, "tests", "data", "elastic_train_reference.json")


# ---------------------------------------------------------------------------
# worker: one "host" of the fleet
# ---------------------------------------------------------------------------

def run_worker(args) -> int:
    import numpy as np

    from ncnet_tpu import obs
    from ncnet_tpu.models.backbone import BackboneConfig
    from ncnet_tpu.models.ncnet import NCNetConfig
    from ncnet_tpu.obs.train_watch import TrainWatch
    from ncnet_tpu.parallel.membership import (
        MembershipPlane, StaleGenerationError)
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.training import elastic as elastic_mod
    from ncnet_tpu.training import save_checkpoint, load_latest_checkpoint

    root = args.membership_root
    host = args.host
    gang = [h for h in args.gang.split(",") if h]
    plane = MembershipPlane(root, host, lease_ttl_s=args.lease_ttl_s)
    plane.form(gang)
    driver = elastic_mod.ElasticDriver(
        plane, check_interval_s=args.check_interval_s, ledger_dir=root)
    driver.start()

    run_log = obs.init_run(
        "train", os.path.join(root, f"runlog-train-{host}.jsonl"),
        args=args, heartbeat_s=0)
    watch = TrainWatch(policy="halt", host=host, log_interval=1)
    ckpt_dir = os.path.join(root, "ckpt")

    # Tiny-but-real checkpoint payload: the chain, swap, and fallback
    # walk under test are byte-identical to a full run's.
    config = NCNetConfig(
        backbone=BackboneConfig(cnn="vgg"),
        ncons_kernel_sizes=(3,), ncons_channels=(1,))
    params = {"neigh_consensus": np.zeros(4, np.float32)}

    def save(epoch, step_in_epoch=None):
        extra = {"train_loss": [], "val_loss": []}
        if step_in_epoch is not None:
            extra["step_in_epoch"] = step_in_epoch
        save_checkpoint(
            ckpt_dir, params, config, epoch, extra=extra,
            tag="step" if step_in_epoch is not None else None)

    n_nonfinite = 0
    n_steps_trained = 0
    pairs = 0
    train_time_s = 0.0
    start_epoch, skip = 1, 0
    rc = 0
    try:
        while True:
            try:
                for epoch in range(start_epoch, args.epochs + 1):
                    watch.reset_epoch()
                    skip_now = skip if epoch == start_epoch else 0
                    gbs = elastic_mod.adjusted_global_batch(
                        args.batch, driver.n_hosts)
                    bslice = (driver.slice_for(gbs)
                              if driver.n_hosts > 1 else (0, gbs))
                    t_ep = time.monotonic()
                    losses = []
                    for i, _b in watch.steps(
                            iter(range(skip_now, args.steps)),
                            start=skip_now):
                        failpoints.fire("train.step", payload=i)
                        driver.step_check(epoch, i)
                        gstep = (epoch - 1) * args.steps + i
                        time.sleep(args.step_s)
                        loss = 1.0 / (1.0 + gstep)
                        watch.book(epoch=epoch, step=i, loss=loss,
                                   grad_norm=loss, update_ratio=1e-3)
                        if not np.isfinite(loss):
                            n_nonfinite += 1
                        losses.append(loss)
                        # The live generation's slice may differ from
                        # this epoch's opening one after a mid-epoch
                        # resume re-entered the loop.
                        driver.record_step(epoch, i, bslice)
                        n_steps_trained += 1
                        pairs += bslice[1] - bslice[0]
                        if (args.save_interval
                                and (i + 1) % args.save_interval == 0
                                and driver.is_writer
                                and driver.commit_barrier(epoch, i + 1)):
                            save(epoch, step_in_epoch=i + 1)
                            driver.note_commit(epoch, i + 1)
                    watch.drain()
                    dur = time.monotonic() - t_ep
                    train_time_s += dur
                    obs.event(
                        "epoch", epoch=epoch,
                        train_loss=float(np.mean(losses)) if losses
                        else 0.0,
                        val_loss=0.0, n_steps=len(losses), dur_s=dur,
                        pairs_per_s=(len(losses) * (bslice[1] - bslice[0])
                                     / max(dur, 1e-9)))
                    obs.get_run().flush_metrics(phase=f"epoch{epoch}")
                    if driver.is_writer and driver.commit_barrier(
                            epoch, args.steps):
                        save(epoch)
                        driver.note_commit(epoch + 1, 0)
                # An early finisher's expiring lease must not read as a
                # mid-run death to peers still training.
                driver.finish_barrier(args.epochs)
                break
            except elastic_mod.MembershipChange as chg:
                try:
                    _path, loaded = load_latest_checkpoint(ckpt_dir)
                    meta = loaded["meta"]
                    if "step_in_epoch" in meta:
                        r_e = int(meta["epoch"])
                        r_s = int(meta["step_in_epoch"])
                    else:
                        r_e, r_s = int(meta["epoch"]) + 1, 0
                except FileNotFoundError:
                    # Death before the first commit: replay from scratch.
                    r_e, r_s = 1, 0
                driver.resume(
                    chg.record, r_e, r_s,
                    chg.epoch if chg.epoch is not None else r_e,
                    chg.step if chg.step is not None else r_s,
                    steps_per_epoch=args.steps)
                print(f"{host}: resumed at generation {driver.generation} "
                      f"from epoch {r_e} step {r_s}", file=sys.stderr)
                start_epoch, skip = r_e, r_s
    except StaleGenerationError as exc:
        print(f"{host}: evicted: {exc}", file=sys.stderr)
        rc = 3
    finally:
        watch.close()
        result = {
            "host": host,
            "generation": driver.generation,
            "hosts": driver.hosts,
            "resumes": driver.resumes,
            "lost_steps": driver.lost_steps,
            "nonfinite": n_nonfinite,
            "steps_trained": n_steps_trained,
            "pairs": pairs,
            "train_time_s": train_time_s,
            "check_time_s": driver.check_time_s,
            "rc": rc,
        }
        with open(os.path.join(root, f"result-{host}.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(result, fh)
        driver.stop()
        run_log.close("ok" if rc == 0 else f"rc:{rc}")
    return rc


# ---------------------------------------------------------------------------
# parent: fleet boot, kill, audit
# ---------------------------------------------------------------------------

def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # A leaked pool address would send the CPU workers hunting for a
    # remote TPU fleet.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _read_ledger_lines(path: str):
    out = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def audit_ledgers(root: str, batch: int, epochs: int, steps: int) -> dict:
    """Zero-silent-step-loss audit over the per-host step ledgers.

    For every (epoch, step) of the final curve SOME generation's
    recorded slices must tile the full adjusted global batch of that
    generation — contiguous from row 0 with no gap and no missing
    tail. Steps before the kill tile under the old generation, the
    replayed tail under the new one; a step no generation covers is a
    silently lost step.
    """
    by_gen = {}      # gen -> {(epoch, step): set[(start, stop)]}
    gen_hosts = {}   # gen -> set[host]
    for path in glob.glob(os.path.join(root, "steps-*.jsonl")):
        for rec in _read_ledger_lines(path):
            gen = int(rec.get("gen", 0))
            key = (int(rec.get("epoch", 0)), int(rec.get("step", -1)))
            sl = rec.get("slice") or [0, batch]
            by_gen.setdefault(gen, {}).setdefault(key, set()).add(
                (int(sl[0]), int(sl[1])))
            gen_hosts.setdefault(gen, set()).add(rec.get("host"))

    def tiles(intervals, want: int) -> bool:
        pos = 0
        for a, b in sorted(intervals):
            if a > pos:
                return False
            pos = max(pos, b)
        return pos >= want

    missing = []
    for epoch in range(1, epochs + 1):
        for step in range(steps):
            key = (epoch, step)
            covered = False
            for gen, steps_map in by_gen.items():
                n = max(len(gen_hosts.get(gen, ())), 1)
                want = (batch // n) * n
                if key in steps_map and tiles(steps_map[key], want):
                    covered = True
                    break
            if not covered:
                missing.append(key)
    return {
        "ok": not missing,
        "missing_steps": missing[:20],
        "generations": sorted(by_gen),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--membership-root", default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--host", default="", help=argparse.SUPPRESS)
    ap.add_argument("--gang", default="", help=argparse.SUPPRESS)
    ap.add_argument("--hosts", type=int, default=3,
                    help="fleet size (one process per host)")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps", type=int, default=24,
                    help="steps per epoch")
    ap.add_argument("--batch", type=int, default=12,
                    help="global batch the hosts slice")
    ap.add_argument("--step-s", type=float, default=0.05,
                    help="synthetic device time per step")
    ap.add_argument("--save-interval", type=int, default=6,
                    help="steps between rolling checkpoints")
    ap.add_argument("--lease-ttl-s", type=float, default=0.75)
    ap.add_argument("--check-interval-s", type=float, default=0.1)
    ap.add_argument("--kill", choices=("poll", "failpoint", "none"),
                    default="poll")
    ap.add_argument("--kill-after-step", type=int, default=-1,
                    help="poll mode: SIGKILL the victim once its ledger "
                    "shows this epoch-1 step trained (default steps//3)")
    ap.add_argument("--kill-after-renewals", type=int, default=3,
                    help="failpoint mode: victim dies at its (N+1)-th "
                    "lease renewal")
    ap.add_argument("--resume-budget-steps", type=int, default=24,
                    help="max re-trained (lost) steps per survivor: the "
                    "save interval plus the detection window, with slack")
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--dir", default="",
                    help="membership/artifact root (default: a fresh "
                    "temp dir)")
    ap.add_argument("--reference", default=DEFAULT_REFERENCE,
                    help="train_report --strict reference curve")
    args = ap.parse_args(argv)

    if args.worker:
        return run_worker(args)

    import tempfile

    root = args.dir or tempfile.mkdtemp(prefix="chaos_train_")
    os.makedirs(root, exist_ok=True)
    hosts = [f"host{i}" for i in range(args.hosts)]
    gang = ",".join(hosts)
    kill = args.kill if args.hosts > 1 else "none"
    victim = hosts[1] if kill != "none" else None
    kill_after = (args.kill_after_step if args.kill_after_step >= 0
                  else max(args.steps // 3, 1))

    procs = {}
    for h in hosts:
        env = _worker_env()
        if kill == "failpoint" and h == victim:
            env["NCNET_FAILPOINTS"] = (
                f"membership.lease=kill:+{args.kill_after_renewals}")
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--membership-root", root, "--host", h, "--gang", gang,
               "--epochs", str(args.epochs), "--steps", str(args.steps),
               "--batch", str(args.batch), "--step-s", str(args.step_s),
               "--save-interval", str(args.save_interval),
               "--lease-ttl-s", str(args.lease_ttl_s),
               "--check-interval-s", str(args.check_interval_s)]
        procs[h] = subprocess.Popen(
            cmd, env=env, stdout=sys.stderr, stderr=sys.stderr)
    print(f"chaos_train: {args.hosts} hosts under {root}"
          + (f", will kill {victim} ({kill})" if victim else ""),
          file=sys.stderr)

    deadline = time.time() + args.timeout_s
    killed_at = None
    if kill == "poll":
        ledger = os.path.join(root, f"steps-{victim}.jsonl")
        while time.time() < deadline:
            lines = _read_ledger_lines(ledger)
            if any(l.get("epoch") == 1 and l.get("step", -1) >= kill_after
                   for l in lines):
                procs[victim].send_signal(signal.SIGKILL)
                killed_at = max(l.get("step", -1) for l in lines
                                if l.get("epoch") == 1)
                print(f"chaos_train: SIGKILL {victim} at epoch 1 step "
                      f"~{killed_at}", file=sys.stderr)
                break
            if procs[victim].poll() is not None:
                break  # died on its own (shouldn't)
            time.sleep(0.02)

    rcs = {}
    for h, p in procs.items():
        left = max(deadline - time.time(), 1.0)
        try:
            rcs[h] = p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            rcs[h] = "timeout"

    survivors = [h for h in hosts if h != victim]
    results = {}
    for h in survivors:
        try:
            with open(os.path.join(root, f"result-{h}.json"),
                      encoding="utf-8") as fh:
                results[h] = json.load(fh)
        except (OSError, json.JSONDecodeError):
            results[h] = None

    checks = {}
    checks["survivors_exited_clean"] = all(
        rcs.get(h) == 0 for h in survivors)
    checks["results_present"] = all(
        results.get(h) is not None for h in survivors)
    ok_results = {h: r for h, r in results.items() if r}

    try:
        with open(os.path.join(root, "generation.json"),
                  encoding="utf-8") as fh:
            final_gen = json.load(fh)
    except (OSError, json.JSONDecodeError):
        final_gen = {}
    if victim is not None:
        checks["victim_evicted"] = (
            victim not in final_gen.get("hosts", [victim]))
        checks["generation_bumped"] = final_gen.get("generation", 0) >= 2
        checks["survivors_resumed"] = all(
            r.get("resumes", 0) >= 1 for r in ok_results.values()
        ) and bool(ok_results)
        checks["resume_within_budget"] = all(
            r.get("lost_steps", 1 << 30) <= args.resume_budget_steps
            for r in ok_results.values()) and bool(ok_results)
    checks["zero_nonfinite_losses"] = all(
        r.get("nonfinite", 1) == 0 for r in ok_results.values()
    ) and bool(ok_results)

    ledger_audit = audit_ledgers(root, args.batch, args.epochs, args.steps)
    checks["ledger_no_silent_step_loss"] = ledger_audit["ok"]
    if not ledger_audit["ok"]:
        print(f"chaos_train: untiled steps: "
              f"{ledger_audit['missing_steps']}", file=sys.stderr)

    # The surviving writer's curve must pass the committed-reference
    # strict gate — recovery that wrecks the loss curve is not recovery.
    strict_report = {}
    if survivors and ok_results:
        writer = sorted(ok_results)[0]
        rp = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "train_report.py"),
             os.path.join(root, f"runlog-train-{writer}.jsonl"),
             "--strict", "--reference", args.reference],
            env=_worker_env(), capture_output=True, text=True,
            timeout=60)
        sys.stderr.write(rp.stderr)
        try:
            strict_report = json.loads(rp.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            strict_report = {"error": "unparseable train_report output"}
        checks["strict_curve"] = rp.returncode == 0
    else:
        checks["strict_curve"] = False

    ok = all(checks.values())
    total_lost = sum(r.get("lost_steps", 0) for r in ok_results.values())
    total_resumes = sum(r.get("resumes", 0) for r in ok_results.values())
    out = {
        "metric": "chaos_train",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "ok": ok,
        "hosts": args.hosts,
        "killed": victim,
        "kill_mode": kill,
        "generation": final_gen.get("generation"),
        "live_hosts": final_gen.get("hosts"),
        "resumes": total_resumes,
        "lost_steps": total_lost,
        "resume_budget_steps": args.resume_budget_steps,
        "ledger_ok": ledger_audit["ok"],
        "ledger_generations": ledger_audit["generations"],
        "strict_ok": checks.get("strict_curve"),
        "strict_final_loss": strict_report.get("final_loss"),
        "checks": checks,
        "exit_codes": rcs,
        "root": root,
    }
    print(json.dumps(out))
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'} {name}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
