"""On-TPU smoke test for the fused correlation+maxpool Pallas kernel.

Compiles `fused_correlation_maxpool_pallas` under the REAL Mosaic compiler
(the CPU test suite can only exercise interpret mode) and checks it against
the slab-scan XLA oracle at a small shape first (fast compile-failure
signal), then at the full InLoc shape (200x150 features, c=1024, k=2,
bf16 storage — the workload of the reference's eval_inloc.py:124-137).

Prints PASS/FAIL per shape; exit code 0 only if all pass.

Usage (TPU must be reachable):
    python tools/pallas_tpu_smoke.py [--dial_timeout 600]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.ops.pallas_kernels import (
        fused_correlation_maxpool_pallas,
        fused_correlation_maxpool_xla,
    )
    from ncnet_tpu.utils.profiling import (
        AlarmTimeout,
        dial_devices,
        run_with_alarm,
        setup_compile_cache,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        return 2
    dev = devices[0]
    log(f"backend up: {dev}")
    if dev.platform == "cpu":
        log("CPU backend: Mosaic not exercised, nothing to smoke-test here")
        return 2

    # (name, c, IA, JA, IB, JB) — small first so a Mosaic lowering failure
    # surfaces in seconds, then the full InLoc query x pano shape.
    cases = [
        ("small 40x30", 64, 40, 30, 40, 30),
        ("inloc 200x150", 1024, 200, 150, 200, 150),
    ]
    failures = 0
    for name, c, ia, ja, ib, jb in cases:
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        fa = jax.random.normal(k1, (1, c, ia, ja), jnp.float32)
        fb = jax.random.normal(k2, (1, c, ib, jb), jnp.float32)
        try:
            log(f"{name}: compiling Pallas kernel (Mosaic)...")
            run = jax.jit(
                lambda a, b: fused_correlation_maxpool_pallas(
                    a, b, k_size=2, corr_dtype=jnp.bfloat16
                )
            )
            pooled_p, deltas_p = jax.tree.map(np.asarray, run(fa, fb))
            log(f"{name}: Pallas compiled+ran; running XLA oracle...")
            oracle = jax.jit(
                lambda a, b: fused_correlation_maxpool_xla(
                    a, b, k_size=2, corr_dtype=jnp.bfloat16
                )
            )
            pooled_x, deltas_x = jax.tree.map(np.asarray, oracle(fa, fb))
        except Exception as exc:  # noqa: BLE001
            log(f"{name}: FAIL ({type(exc).__name__}: {exc})")
            failures += 1
            continue

        perr = float(
            np.max(np.abs(pooled_p.astype(np.float32) - pooled_x.astype(np.float32)))
        )
        # Argmax deltas: exact except where bf16 rounding creates ties
        # (first-wins order then differs between the two pooling orders).
        dmis = max(
            float(np.mean(dp != dx)) for dp, dx in zip(deltas_p, deltas_x)
        )
        ok = perr <= 0.05 and dmis <= 1e-3
        log(
            f"{name}: {'PASS' if ok else 'FAIL'} "
            f"pooled_max_abs_err={perr:.4g} delta_mismatch_frac={dmis:.2e}"
        )
        failures += 0 if ok else 1

        # Timing at the InLoc shape: Pallas vs the slab-scan oracle.
        if "inloc" in name and failures == 0:
            for label, fn in (("pallas", run), ("xla_slab", oracle)):
                fn(fa, fb)  # warm
                t0 = time.perf_counter()
                for _ in range(5):
                    out = fn(fa, fb)
                    jax.block_until_ready(out)
                    float(jnp.sum(out[0][0]))  # force through the tunnel
                log(f"{name}: {label} {(time.perf_counter() - t0) / 5 * 1e3:.1f} ms/call")

    # --- bidirectional extraction-statistics kernel (ops/extract_kernel) ---
    from ncnet_tpu.ops.extract_kernel import (
        bidir_extract_stats_pallas,
        bidir_extract_stats_xla,
        bidir_maxes_pallas,
    )

    # (name, M, N[, mutual]) — small first, then the InLoc post-pool matrix
    # (100x75 cells per side -> 7500x7500).
    ext_cases = [
        ("extract small 1200x1200", 1200, 1200, False),
        ("extract inloc 7500x7500", 7500, 7500, False),
        ("extract inloc fused-mutual", 7500, 7500, True),
    ]
    for name, m, n, fused_mutual in ext_cases:
        x = jax.random.normal(
            jax.random.PRNGKey(1), (m, n), jnp.float32
        ).astype(jnp.bfloat16)
        try:
            log(f"{name}: compiling (Mosaic)...")

            def pallas_fn(v, _fused=fused_mutual):
                maxes = bidir_maxes_pallas(v) if _fused else None
                return bidir_extract_stats_pallas(v, row_col_max=maxes)

            def xla_fn(v, _fused=fused_mutual):
                maxes = None
                if _fused:
                    (rm, _, _), (cm, _, _) = bidir_extract_stats_xla(
                        v, do_softmax=False
                    )
                    maxes = (rm, cm)
                return bidir_extract_stats_xla(v, row_col_max=maxes)

            run_e = jax.jit(pallas_fn)
            got = jax.tree.map(np.asarray, run_e(x))
            log(f"{name}: Pallas compiled+ran; running XLA oracle...")
            # Fence the oracle: XLA argmax over the 56M-element matrix is
            # the formulation class with a documented multi-minute
            # remote-compile pathology; one hang must not consume the
            # whole smoke phase (and its ALL PASS verdict).
            want = run_with_alarm(
                420, lambda: jax.tree.map(np.asarray, jax.jit(xla_fn)(x))
            )
        except AlarmTimeout:
            log(f"{name}: FAIL (XLA oracle timed out >420s; Pallas ran)")
            failures += 1
            continue
        except Exception as exc:  # noqa: BLE001
            log(f"{name}: FAIL ({type(exc).__name__}: {exc})")
            failures += 1
            continue
        worst = 0.0
        argmis = 0.0
        for (gm, ga, gs), (wm, wa, ws) in zip(got, want):
            worst = max(
                worst,
                float(np.max(np.abs(gm - wm))),
                float(np.max(np.abs(gs - ws) / np.maximum(np.abs(ws), 1e-6))),
            )
            argmis = max(argmis, float(np.mean(ga != wa)))
        ok = worst <= 1e-2 and argmis <= 1e-3
        log(
            f"{name}: {'PASS' if ok else 'FAIL'} "
            f"stat_err={worst:.4g} arg_mismatch_frac={argmis:.2e}"
        )
        failures += 0 if ok else 1
        if ok and m == 7500:
            run_e(x)  # warm
            t0 = time.perf_counter()
            for _ in range(5):
                out = run_e(x)
                jax.block_until_ready(out)
                float(jnp.sum(out[0][0]))
            log(f"{name}: pallas {(time.perf_counter() - t0) / 5 * 1e3:.1f} ms/call")

    # (A consensus layer-1 Pallas kernel was smoke-tested here through
    # rounds 3-5; deleted 2026-08-02 after its third distinct Mosaic
    # lowering rejection on hardware — see ops/conv4d.py.)

    log(f"{'ALL PASS' if failures == 0 else f'{failures} FAILURES'}")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
