"""Render an InLoc driver .mat matches file as side-by-side match images.

Parity: lib_matlab/show_matches2_horizontal.m + generate_ncnet_plot.m of
the reference — the Matlab-side visualization of the dense matches the
pipeline writes per query. Here it is a framework CLI over the same
per-query `.mat` contract (`evals.inloc.write_matches_mat`:
matches [1, n_panos, N, 5] with rows (xA, yA, xB, yB, score) in [0, 1]
'positive' coordinates, query_fn, pano_fn): one PNG per pano, match
lines colored by score (viridis), top-N by score.

Usage:
    python tools/show_matches.py matches/query_1.mat \
        --query_root datasets/inloc/query/iphone7 \
        --pano_root datasets/inloc/db_scans \
        --out_dir viz --top 50 [--pano 0]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_image(path):
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"))


def render_matches_mat(mat_path, query_root, pano_root, out_dir, top=50,
                       pano=None, min_score=0.0):
    """Render PNGs for one per-query .mat; returns the written paths."""
    from scipy.io import loadmat

    from ncnet_tpu.utils.plot import plot_matches_horizontal

    m = loadmat(mat_path)
    matches = np.asarray(m["matches"])  # [1, n_panos, N, 5]
    query_fn = str(np.ravel(m["query_fn"])[0])
    pano_fns = [str(np.ravel(p)[0]) for p in np.ravel(m["pano_fn"])]

    img_a = load_image(os.path.join(query_root, query_fn))
    ha, wa = img_a.shape[:2]

    os.makedirs(out_dir, exist_ok=True)
    out_paths = []
    panos = range(matches.shape[1]) if pano is None else [pano]
    for p in panos:
        rows = matches[0, p]
        keep = rows[:, 4] > min_score
        rows = rows[keep][:top]
        if not len(rows):
            continue
        img_b = load_image(os.path.join(pano_root, pano_fns[p]))
        hb, wb = img_b.shape[:2]
        pa = np.stack([rows[:, 0] * wa, rows[:, 1] * ha], axis=1)
        pb = np.stack([rows[:, 2] * wb, rows[:, 3] * hb], axis=1)
        stem = os.path.splitext(os.path.basename(mat_path))[0]
        out = os.path.join(out_dir, f"{stem}_pano{p:02d}.png")
        plot_matches_horizontal(
            img_a, img_b, pa, pb, out, scores=rows[:, 4]
        )
        out_paths.append(out)
    return out_paths


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("mat", help="per-query .mat from the InLoc driver")
    ap.add_argument("--query_root", required=True)
    ap.add_argument("--pano_root", required=True)
    ap.add_argument("--out_dir", default="viz")
    ap.add_argument("--top", type=int, default=50,
                    help="draw at most this many highest-score matches")
    ap.add_argument("--pano", type=int, default=None,
                    help="render only this pano index (default: all)")
    ap.add_argument("--min_score", type=float, default=0.0)
    args = ap.parse_args(argv)

    outs = render_matches_mat(
        args.mat, args.query_root, args.pano_root, args.out_dir,
        top=args.top, pano=args.pano, min_score=args.min_score,
    )
    for o in outs:
        print(o)
    if not outs:
        print("no matches above --min_score; nothing rendered",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
