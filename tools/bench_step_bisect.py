"""Bisect the InLoc per-pano device step: true IN-STEP stage costs.

Stage-level chained benches (bench_consensus / bench_extract) and the
per-call staged profile (profile_inloc) disagree by up to 3x about the
consensus stage, and four stage-level optimizations moved none of the
headline — so the only trustworthy attribution is differential: time the
REAL step (the exact program bench.py scans over panos) with one stage
knocked out at a time, all variants chained inside one jit. The
difference between adjacent variants is that stage's true in-step cost,
with all cross-stage fusion effects included.

CAVEAT (round 3): differential attribution is DCE-skewed. Knocking out
a stage lets XLA dead-code-eliminate upstream work feeding only that
stage — the round-2 bisect charged ~68 ms to corr+pool that the device
trace shows was mostly backbone convs disappearing with it (the kernel
itself is ~10 ms in-step; see docs/NEXT.md round-3 trace attribution).
Treat adjacent-variant deltas as UPPER bounds on a stage; use
tools/trace_step.py + tools/trace_optable.py as ground truth.

Variants (each includes everything above it):
  feats-only      pano backbone + feature norm
  +corr+pool      fused correlation + maxpool (packed deltas)
  +mutual1        first soft mutual-NN filter
  +consensus      symmetric Conv4d stack
  +mutual2        second filter (full match_pipeline)
  +extract (full) both-direction extraction + sort + recenter = the step

Usage:
    python tools/bench_step_bisect.py [--reps 3] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    p.add_argument("--image", type=int, default=3200)
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        AlarmTimeout,
        chain_reps,
        dial_devices,
        run_with_alarm,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.evals import inloc_device_matches
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import (
        extract_features,
        match_pipeline,
        ncnet_forward_from_features,
    )
    from ncnet_tpu.ops.conv4d import neigh_consensus_apply
    from ncnet_tpu.ops.mutual import mutual_matching

    config = NCNetConfig(
        backbone=BackboneConfig(compute_dtype="bfloat16"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
        use_fused_corr_pool=True,
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)
    # Same bucketing as bench.py's headline (NCNET_INLOC_FEAT_UNIT, auto
    # -> 16): the consensus stage is ~34% shape-sensitive between the
    # bucketed and reference dims, so the bisect must attribute stages at
    # the SAME shape the headline runs.
    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape, resolve_feat_units

    units = resolve_feat_units(
        int(os.environ.get("NCNET_INLOC_FEAT_UNIT", "-1")), args.image, 2
    )
    h, w = inloc_resize_shape(
        args.image, args.image * 3 // 4, args.image, 2,
        h_unit=units[0], w_unit=units[1],
    )
    log(f"image {h}x{w} (nominal {args.image}, units {units}), "
        f"reps={args.reps}")
    key = jax.random.PRNGKey(1)
    src = jax.random.normal(key, (1, 3, h, w), jnp.float32)
    feat_a = jax.jit(lambda p, s: extract_features(config, p, s))(params, src)
    jax.block_until_ready(feat_a)

    from ncnet_tpu.ops.pallas_kernels import fused_correlation_maxpool

    def probe(*leaves):
        return sum(jnp.sum(v.astype(jnp.float32)) for v in leaves)

    def feats_only(tgt):
        return probe(extract_features(config, params, tgt))

    def corr_pool(tgt):
        fb = extract_features(config, params, tgt)
        pooled, deltas = fused_correlation_maxpool(
            feat_a, fb, 2, corr_dtype=config.corr_dtype, decode_deltas=False
        )
        return probe(pooled, deltas)

    def plus_mutual1(tgt):
        fb = extract_features(config, params, tgt)
        pooled, deltas = fused_correlation_maxpool(
            feat_a, fb, 2, corr_dtype=config.corr_dtype, decode_deltas=False
        )
        return probe(mutual_matching(pooled), deltas)

    def plus_consensus(tgt):
        fb = extract_features(config, params, tgt)
        pooled, deltas = fused_correlation_maxpool(
            feat_a, fb, 2, corr_dtype=config.corr_dtype, decode_deltas=False
        )
        c = neigh_consensus_apply(
            params["neigh_consensus"], mutual_matching(pooled), symmetric=True
        )
        return probe(c, deltas)

    def plus_mutual2(tgt):
        fb = extract_features(config, params, tgt)
        pooled, deltas = fused_correlation_maxpool(
            feat_a, fb, 2, corr_dtype=config.corr_dtype, decode_deltas=False
        )
        return probe(match_pipeline(config, params, pooled), deltas)

    def full_step(tgt):
        fb = extract_features(config, params, tgt)
        corr, deltas = ncnet_forward_from_features(config, params, feat_a, fb)
        return probe(*inloc_device_matches(corr, delta4d=deltas, k_size=2))

    variants = [
        ("feats-only", feats_only),
        ("+corr+pool", corr_pool),
        ("+mutual1", plus_mutual1),
        ("+consensus", plus_consensus),
        ("+mutual2", plus_mutual2),
        ("+extract (full step)", full_step),
    ]
    prev = None  # (label, ms) of the last SUCCESSFUL variant
    for label, fn in variants:
        try:
            first, dt, _ = run_with_alarm(
                420, timed_steady, chain_reps(fn, args.reps),
                jax.random.normal(key, (1, 3, h, w), jnp.float32),
                iters=args.iters,
            )
            ms = dt * 1000 / args.reps
            delta = (
                "" if prev is None
                else f"  (+{ms - prev[1]:6.1f}ms vs {prev[0]})"
            )
            log(f"{label:22s} first={first:6.2f}s -> {ms:7.1f}ms/pano{delta}")
            prev = (label, ms)
        except AlarmTimeout:
            log(f"{label:22s} TIMED OUT (>420s compile/run)")
            prev = None  # a delta against a skipped stage would mislabel
        except Exception as exc:  # noqa: BLE001
            log(f"{label:22s} FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")
            prev = None


if __name__ == "__main__":
    main()
