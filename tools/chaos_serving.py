"""Chaos harness for the online matching service: load + timed faults.

Spins up an in-process :class:`MatchServer` (failpoints are
process-local, so the faults must be injected from inside), drives it
with the same open-loop arrival schedule as tools/bench_serving.py,
and arms/disarms failpoint windows on a wall-clock schedule::

    python tools/chaos_serving.py --synthetic 96x128 --rate 6 \
        --duration_s 8 --breaker_threshold 3 --breaker_reset_s 1.0 \
        --fault "engine.device=error:1.0@2.0-4.0"

``--fault "site=mode[:args]@start-end"`` (repeatable) arms the term at
``start`` seconds into the run and disarms it at ``end``;
``--failpoints SPEC`` arms a static spec for the whole run. A healthz
poller records every breaker state change it observes.

With ``--replicas N`` the harness serves an in-process replica FLEET
(serving/fleet.py) instead of a single engine, and the fault verb
``kill_replica[:idx]@start-end`` stops that replica for the window
(revived at ``end``): its queued riders must re-route to the surviving
replicas within one breaker window — the acceptance check is the same
``dropped == 0`` exit gate, plus the ``redispatched`` count in the
output line. ``kill_replica`` requires ``--replicas >= 2`` (someone
has to be left to re-route to).

Prints ONE JSON line (the repo's bench stdout contract,
tests/test_bench_contract.py)::

    {"metric": "chaos_serving_survival", "value": <ok+rejected+poison
     fraction of sent>, "unit": "frac", "sent": ..., "ok": ...,
     "rejected": ..., "poison": ..., "errors": ..., "dropped": ...,
     "breaker_transitions": [...], "faults": {...}, "duration_s": ...}

``dropped`` is the no-silent-drops check: every scheduled request must
come back as ok / rejected / poison / error — anything unaccounted for
is a hung or vanished request, and the exit code is nonzero.
Stage notes go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from bench_serving import note, percentile, synth_jpegs  # noqa: E402


def parse_fault_window(spec):
    """``site=mode[:args]@start-end`` -> (term, site, start_s, end_s)."""
    term, sep, window = spec.rpartition("@")
    if not sep:
        raise ValueError(f"bad --fault {spec!r} (want term@start-end)")
    start_s, _, end_s = window.partition("-")
    site = term.partition("=")[0].strip()
    return term.strip(), site, float(start_s), float(end_s)


def main(argv=None, model=None):
    parser = argparse.ArgumentParser(
        description="chaos harness: in-process serving under load + faults"
    )
    parser.add_argument("--rate", type=float, default=6.0,
                        help="open-loop arrival rate, requests/s")
    parser.add_argument("--duration_s", type=float, default=8.0)
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--synthetic", type=str, default="96x128",
                        help="HxW: random images, sent inline b64")
    parser.add_argument("--fault", action="append", default=[],
                        help="timed window: site=mode[:args]@start-end "
                        "seconds into the run (repeatable)")
    parser.add_argument("--failpoints", type=str, default="",
                        help="static spec armed for the whole run "
                        "(NCNET_FAILPOINTS grammar)")
    parser.add_argument("--image_size", type=int, default=64)
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--max_delay_ms", type=float, default=50.0)
    parser.add_argument("--breaker_threshold", type=int, default=3)
    parser.add_argument("--breaker_reset_s", type=float, default=1.0)
    parser.add_argument("--no_isolate_poison", action="store_true")
    parser.add_argument("--replicas", type=int, default=0,
                        help="serve an in-process N-replica fleet "
                             "(enables the kill_replica fault verb; "
                             "0 = single engine)")
    parser.add_argument("--client_retries", type=int, default=2)
    parser.add_argument("--health_poll_s", type=float, default=0.1)
    parser.add_argument("--run_log", type=str, default="",
                        help="structured JSONL run log path (empty disables)")
    args = parser.parse_args(argv)
    windows = [parse_fault_window(s) for s in args.fault]
    if any(site.startswith("kill_replica") for _, site, _, _ in windows) \
            and args.replicas < 2:
        parser.error("kill_replica faults need --replicas >= 2 "
                     "(survivors to re-route the riders to)")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu import obs
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        PoisonRequestError,
        ServingError,
    )
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    run_log = None
    if args.run_log:
        run_log = obs.init_run("chaos_serving", args.run_log, args=args)

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    warm_batches = sorted({1, max(1, args.max_batch // 2),
                           args.max_batch})
    fleet = None
    if args.replicas > 0:
        from ncnet_tpu.serving.fleet import MatchFleet

        fleet = MatchFleet.build(
            config, params,
            n_replicas=args.replicas,
            base_id="chaos",
            cache_mb=0,
            engine_kwargs=dict(k_size=2, image_size=args.image_size),
            replica_kwargs=dict(
                max_batch=args.max_batch,
                max_delay_s=args.max_delay_ms / 1e3,
                default_timeout_s=max(args.duration_s * 4, 60.0),
                breaker_threshold=args.breaker_threshold,
                breaker_reset_s=args.breaker_reset_s,
                isolate_poison=not args.no_isolate_poison,
            ),
        )
        # Warm the exact buckets the load hits: the run must measure
        # the reliability machinery, not first-request XLA compiles
        # racing the fault windows.
        fleet.warmup([(h, w, h, w)], batch_sizes=warm_batches)
    else:
        engine = MatchEngine(config, params, k_size=2,
                             image_size=args.image_size, cache_mb=0)
        engine.warmup([(h, w, h, w)], batch_sizes=warm_batches)
    if args.failpoints:
        failpoints.configure(args.failpoints)
        note(f"static failpoints: {sorted(failpoints.active())}")
    redispatched0 = obs.counter("serving.redispatched").value
    server = MatchServer(
        None if fleet is not None else engine, port=0,
        max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3,
        default_timeout_s=max(args.duration_s * 4, 60.0),
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        isolate_poison=not args.no_isolate_poison,
        run_log=run_log,
        fleet=fleet,
    ).start()
    note(f"serving on {server.url}"
         + (f" ({args.replicas} replicas)" if fleet is not None else "")
         + f"; fault windows: {[(t, a, b) for t, _, a, b in windows]}")

    q_bytes, p_bytes = synth_jpegs(args.synthetic)
    kwargs = {"query_bytes": q_bytes, "pano_bytes": p_bytes,
              "max_matches": 8}
    client = MatchClient(server.url, timeout_s=max(args.duration_s * 4, 60.0),
                         retries=args.client_retries,
                         retry_deadline_s=args.duration_s)

    stop = threading.Event()
    t0 = time.monotonic()

    fault_log = {}

    def fault_scheduler():
        """Arm/disarm each window at its wall-clock offsets."""
        events = sorted(
            [(start, "arm", term, site) for term, site, start, _ in windows]
            + [(end, "disarm", term, site) for term, site, _, end in windows]
        )
        for at, action, term, site in events:
            delay = t0 + at - time.monotonic()
            if delay > 0 and stop.wait(delay):
                return
            if site.startswith("kill_replica"):
                # Fleet verb, not a failpoint: kill_replica[:idx]
                # stops that replica (default: the last one) for the
                # window; revive at disarm.
                idx = int(site.partition(":")[2] or -1)
                if action == "arm":
                    r = fleet.kill(idx)
                    note(f"t+{at:.1f}s killed {r.replica_id}")
                else:
                    r = fleet.revive(idx)
                    note(f"t+{at:.1f}s revived {r.replica_id}")
            elif action == "arm":
                fp = failpoints.parse_spec(term)[site]
                failpoints.registry().set(
                    site, fp.mode, prob=fp.prob, delay_s=fp.delay_s,
                    max_fires=fp.max_fires,
                )
                note(f"t+{at:.1f}s armed {term}")
            else:
                failpoints.clear(site)
                note(f"t+{at:.1f}s cleared {site}")
            fault_log.setdefault(site, []).append(
                {"t_s": at, "action": action})

    transitions = []

    def health_poller():
        """Record every /healthz status + breaker state change seen."""
        probe = MatchClient(server.url, timeout_s=5.0, retries=0)
        last = None
        while not stop.is_set():
            try:
                hz = probe.healthz()
            except (ServingError, OSError):
                stop.wait(args.health_poll_s)
                continue
            if "fleet" in hz:
                detail = (f"healthy={hz['fleet']['healthy']}"
                          f"/{hz['fleet']['size']}")
            else:
                detail = hz["breaker"]["state"]
            cur = (hz["status"], detail)
            if cur != last:
                transitions.append({
                    "t_s": round(time.monotonic() - t0, 3),
                    "status": cur[0], "breaker": cur[1],
                })
                last = cur
            stop.wait(args.health_poll_s)

    n_requests = max(1, int(args.rate * args.duration_s))
    lock = threading.Lock()
    lat_ms = []
    counts = {"sent": 0, "ok": 0, "rejected": 0, "poison": 0, "errors": 0}
    sched = {"next": 0}

    def worker():
        while True:
            with lock:
                i = sched["next"]
                if i >= n_requests:
                    return
                sched["next"] = i + 1
            due = t0 + i / args.rate
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                client.match(**kwargs)
            except OverCapacityError:
                with lock:
                    counts["sent"] += 1
                    counts["rejected"] += 1
                continue
            except PoisonRequestError:
                with lock:
                    counts["sent"] += 1
                    counts["poison"] += 1
                continue
            except (ServingError, OSError) as exc:
                with lock:
                    counts["sent"] += 1
                    counts["errors"] += 1
                note(f"error on req {i}: {exc}")
                continue
            dt_ms = (time.monotonic() - t_req) * 1e3
            with lock:
                counts["sent"] += 1
                counts["ok"] += 1
                lat_ms.append(dt_ms)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(min(args.threads, n_requests))]
    aux = [threading.Thread(target=fault_scheduler, daemon=True),
           threading.Thread(target=health_poller, daemon=True)]
    for t in aux + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in aux:
        t.join(timeout=5)
    elapsed = time.monotonic() - t0
    failpoints.clear()
    server.stop()
    if run_log is not None:
        run_log.close("ok")

    # Survival: every request is accounted for AND got a structured
    # outcome the client can act on (success, retryable 503, or a
    # proven-poison 422). errors (500s, transport) and silent drops are
    # the chaos failures this tool exists to surface.
    accounted = sum(counts[k] for k in ("ok", "rejected", "poison", "errors"))
    dropped = n_requests - accounted
    survived = counts["ok"] + counts["rejected"] + counts["poison"]
    lat_ms.sort()
    rec = {
        "metric": "chaos_serving_survival",
        "value": round(survived / n_requests, 4),
        "unit": "frac",
        "sent": counts["sent"],
        "ok": counts["ok"],
        "rejected": counts["rejected"],
        "poison": counts["poison"],
        "errors": counts["errors"],
        "dropped": dropped,
        "replicas": args.replicas,
        "redispatched": (obs.counter("serving.redispatched").value
                         - redispatched0),
        "latency_ms": {
            "p50": round(percentile(lat_ms, 50), 3) if lat_ms else None,
            "p99": round(percentile(lat_ms, 99), 3) if lat_ms else None,
        },
        "breaker_transitions": transitions,
        "faults": fault_log,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    return 0 if dropped == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
