"""Chaos harness for the online matching service: load + timed faults.

Spins up an in-process :class:`MatchServer` (failpoints are
process-local, so the faults must be injected from inside), drives it
with the same open-loop arrival schedule as tools/bench_serving.py,
and arms/disarms failpoint windows on a wall-clock schedule::

    python tools/chaos_serving.py --synthetic 96x128 --rate 6 \
        --duration_s 8 --breaker_threshold 3 --breaker_reset_s 1.0 \
        --fault "engine.device=error:1.0@2.0-4.0"

``--fault "site=mode[:args]@start-end"`` (repeatable) arms the term at
``start`` seconds into the run and disarms it at ``end``;
``--failpoints SPEC`` arms a static spec for the whole run. A healthz
poller records every breaker state change it observes.

With ``--replicas N`` the harness serves an in-process replica FLEET
(serving/fleet.py) instead of a single engine, and the fault verb
``kill_replica[:idx]@start-end`` stops that replica for the window
(revived at ``end``): its queued riders must re-route to the surviving
replicas within one breaker window — the acceptance check is the same
``dropped == 0`` exit gate, plus the ``redispatched`` count in the
output line. ``kill_replica`` requires ``--replicas >= 2`` (someone
has to be left to re-route to).

Prints ONE JSON line (the repo's bench stdout contract,
tests/test_bench_contract.py)::

    {"metric": "chaos_serving_survival", "value": <ok+rejected+poison
     fraction of sent>, "unit": "frac", "sent": ..., "ok": ...,
     "rejected": ..., "poison": ..., "errors": ..., "dropped": ...,
     "breaker_transitions": [...], "faults": {...}, "duration_s": ...}

``dropped`` is the no-silent-drops check: every scheduled request must
come back as ok / rejected / poison / error — anything unaccounted for
is a hung or vanished request, and the exit code is nonzero.
Stage notes go to stderr.

``--tenant_flood`` runs the multi-tenant QoS contract instead
(docs/RELIABILITY.md, degradation before refusal): three tenants —
``victim`` (interactive), ``lowpri`` (batch), ``flood`` (best_effort,
bursting at ``--flood_x`` times the base rate) — against a server with
a declared quality ladder and a deliberately slowed device
(``engine.device`` delay failpoint pins a capacity floor). The verb
SELF-CALIBRATES: after warmup it times one batch through the armed
delay failpoint and derives the base (victim/lowpri) rate as a
quarter of the measured capacity, and the rung step-down interval as
the time the device needs to drain two tenants' queue slots. Absolute
rates make the gate flaky — a load that is a gentle nudge on a TPU is
an unwinnable 10x overload on a laptop CPU, and an unwinnable
overload ends with the controller correctly shedding the victim.
``--qos_base_rate`` overrides the calibration. The gate FAILS
(nonzero exit) if:

* any ``victim`` request gets anything but a 200 (availability is the
  thing being protected);
* the QoS controller records no rung transition (the ladder never
  engaged — the scenario proved nothing);
* low-priority traffic never ran degraded (the ladder was skipped);
* any ``over_capacity`` 503 was served while a coarser quality rung
  was still untried (``qos_rung`` < the ladder length — refusal
  before degradation, the contract violation this verb exists to
  catch). Tenant-scoped 429s (``tenant_budget`` / ``tenant_slots``)
  are the flood throttling at its OWN limits and are exempt, as are
  breaker/replica-death 503s (device failure, not load shedding).

Prints ONE JSON line: ``{"metric": "chaos_tenant_flood", "value":
<victim availability frac>, ...}`` with per-tenant outcome counts,
rungs visited, transition counts, and the violation list.

``--session_stream`` runs the streaming-session chaos contract
(docs/RELIABILITY.md, re-seed-not-die): ``--sessions`` concurrent
video sessions stream closed-loop frames against an in-process
replica fleet while ``kill_replica`` fault windows take replicas down
mid-stream. The seed held by a killed replica is useless to the
survivors, so the contract is that the session layer RE-SEEDS — the
next frame pays one full coarse pass on a healthy replica and the
stream continues. The gate FAILS (nonzero exit) if:

* any session DIES (an exception escapes the stream — a kill must
  never end a session);
* any frame is silently dropped (sent but unaccounted);
* any frame gets a non-retryable error (the re-seed path must answer
  200, not 5xx);
* a kill window was armed but no frame ever reported ``reseeded``
  (the scenario proved nothing).

Prints ONE JSON line: ``{"metric": "chaos_session_stream", "value":
<delivered frac>, ...}`` with frame outcome counts, per-session close
stats, re-seed counts, and the violation list.

``--localize_fanout`` runs the localize fan-out chaos contract
(docs/SERVING.md, "Localization as a service"): ``--threads`` drivers
stream ``/v1/localize`` queries (``--panos``-wide shortlists) against
an in-process replica fleet while a ``kill_replica`` window (default:
the middle of the run) takes a replica down mid-fan-out. The victim's
pano legs must REDISPATCH to survivors — the query keeps answering
200 with every pano accounted for. The gate FAILS (nonzero exit) if:

* any query gets a non-200 (a kill mid-fan-out must not fail the
  query);
* any response silently drops a pano (rows missing vs the shortlist,
  or ``n_ok + n_failed`` disagreeing with the row count);
* any pano leg FAILS (the victim's share must re-route, not error);
* no leg was ever redispatched (the window missed all in-flight
  fan-outs — the scenario proved nothing);
* redispatched legs never appear as ``redispatch`` spans joined into
  a localize query's trace (the per-query record of where legs ran).

Prints ONE JSON line: ``{"metric": "chaos_localize_fanout", "value":
<query 200 frac>, ...}`` with query/leg outcome counts, redispatch
totals (counter + joined trace spans), and the violation list.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import threading
import time

from bench_serving import note, percentile, synth_jpegs  # noqa: E402


def parse_fault_window(spec):
    """``site=mode[:args]@start-end`` -> (term, site, start_s, end_s)."""
    term, sep, window = spec.rpartition("@")
    if not sep:
        raise ValueError(f"bad --fault {spec!r} (want term@start-end)")
    start_s, _, end_s = window.partition("-")
    site = term.partition("=")[0].strip()
    return term.strip(), site, float(start_s), float(end_s)


def run_tenant_flood(args, model=None):
    """The multi-tenant QoS chaos contract (module docstring)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu import obs
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        PoisonRequestError,
        ServingError,
    )
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.qos import (
        QosController,
        TenantPolicy,
        TenantTable,
        parse_ladder,
    )
    from ncnet_tpu.serving.server import MatchServer

    run_log = None
    if args.run_log:
        run_log = obs.init_run("chaos_serving", args.run_log, args=args)
    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    ladder = parse_ladder(args.qos_ladder)
    if not ladder:
        raise SystemExit("--tenant_flood needs a non-empty --qos_ladder")
    engine = MatchEngine(config, params, k_size=2,
                         image_size=args.image_size, cache_mb=0)
    warm_batches = sorted({1, max(1, args.max_batch // 2), args.max_batch})
    # Warm every ladder rung too: the contract measures the QoS
    # machinery, not cold XLA compiles racing the flood.
    engine.warmup([(h, w, h, w)], batch_sizes=warm_batches,
                  modes=("oneshot", "c2f"),
                  c2f_ops=[r.knobs() for r in ladder])
    # Pin a device-capacity floor: a fixed per-batch delay keeps "the
    # flood outruns the device" true even on fast hosts.
    failpoints.configure(
        f"engine.device=delay:{args.device_delay_ms:g}ms")
    q_bytes, p_bytes = synth_jpegs(args.synthetic)
    # Calibrate (docstring): time a warmed batch THROUGH the armed
    # delay failpoint and size the offered load off what this host can
    # actually serve, so the overload is winnable by shedding the
    # flood — never so deep that protecting the victim is impossible.
    cal_req = {
        "query_b64": base64.b64encode(q_bytes).decode("ascii"),
        "pano_b64": base64.b64encode(p_bytes).decode("ascii"),
        "max_matches": 8,
    }
    cal = [engine.prepare(dict(cal_req)) for _ in range(args.max_batch)]
    t_cal = time.monotonic()
    for _ in range(2):
        engine.run_batch(cal[0].bucket_key, cal)
    t_batch = max((time.monotonic() - t_cal) / 2.0, 1e-3)
    capacity = args.max_batch / t_batch
    base_rate = args.qos_base_rate or capacity / 4.0
    slot_cap = max(1, int(args.max_queue * args.tenant_queue_frac))
    # One tenant's already-admitted queue slots must drain before the
    # controller may take another step, or backlog the shed can't
    # cancel ratchets the rung straight past the relief it just
    # engaged and into shedding higher priorities.
    step_down_s = max(args.qos_step_down_s, 2.0 * slot_cap / capacity)
    qos = QosController(
        ladder,
        high_water_frac=args.qos_high_water,
        step_down_interval_s=step_down_s,
        step_up_hold_s=args.qos_step_up_hold_s,
    )
    tenants = TenantTable([
        TenantPolicy("victim", "interactive"),
        TenantPolicy("lowpri", "batch"),
        TenantPolicy("flood", "best_effort", rate=args.flood_budget_rps),
    ])
    transitions0 = obs.counter("serving.qos.transitions").value
    server = MatchServer(
        engine, port=0,
        max_batch=args.max_batch,
        max_queue=args.max_queue,
        max_delay_s=args.max_delay_ms / 1e3,
        default_timeout_s=max(args.duration_s * 4, 60.0),
        isolate_poison=not args.no_isolate_poison,
        run_log=run_log,
        qos=qos,
        tenants=tenants,
        tenant_queue_frac=args.tenant_queue_frac,
    ).start()
    note(f"serving on {server.url}; ladder={args.qos_ladder!r} "
         f"flood={args.flood_x:g}x device_delay={args.device_delay_ms:g}ms "
         f"capacity={capacity:.2f}rps base_rate={base_rate:.2f}rps "
         f"step_down={step_down_s:.2f}s")

    kwargs = {"query_bytes": q_bytes, "pano_bytes": p_bytes,
              "max_matches": 8}
    n_quality = len(ladder)
    t0 = time.monotonic()
    lock = threading.Lock()
    stats = {
        name: {"sent": 0, "ok": 0, "degraded": 0, "shed": 0,
               "over_capacity": 0, "tenant_budget": 0, "tenant_slots": 0,
               "breaker": 0, "errors": 0, "rungs": set(), "lat_ms": []}
        for name in ("victim", "lowpri", "flood")
    }
    violations = []

    def account(name, status, payload):
        """Classify one response under the gate's rules (caller holds
        ``lock``)."""
        st = stats[name]
        st["sent"] += 1
        if status == 200:
            st["ok"] += 1
            qv = (payload or {}).get("qos") or {}
            st["rungs"].add(int(qv.get("rung", 0)))
            if qv.get("degraded"):
                st["degraded"] += 1
            return
        kind = (payload or {}).get("kind") if isinstance(payload, dict) \
            else None
        if kind == "shed":
            st["shed"] += 1
        elif kind == "over_capacity":
            st["over_capacity"] += 1
            rung = (payload or {}).get("qos_rung", 0)
            if rung < n_quality:
                violations.append(
                    f"over_capacity 503 to {name} at rung {rung} "
                    f"with {n_quality - rung} coarser rung(s) untried")
        elif kind in ("tenant_budget", "tenant_slots"):
            st[kind] += 1
        elif kind in ("breaker_open", "replica_dead"):
            st["breaker"] += 1
        else:
            st["errors"] += 1
        if name == "victim":
            violations.append(
                f"victim got {status} kind={kind} (availability)")

    def drive(name, rate, n_requests, retries=0):
        client = MatchClient(
            server.url, timeout_s=max(args.duration_s * 4, 60.0),
            retries=retries)
        sched = {"next": 0}

        def worker():
            while True:
                with lock:
                    i = sched["next"]
                    if i >= n_requests:
                        return
                    sched["next"] = i + 1
                due = t0 + i / rate
                delay = due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                t_req = time.monotonic()
                try:
                    payload = client.match(tenant=name, **kwargs)
                    status = 200
                except (OverCapacityError, PoisonRequestError,
                        ServingError) as exc:
                    payload, status = exc.payload, exc.status
                except OSError as exc:
                    with lock:
                        stats[name]["sent"] += 1
                        stats[name]["errors"] += 1
                        violations.append(f"{name} transport error: {exc}")
                    continue
                with lock:
                    account(name, status, payload)
                    if status == 200:
                        stats[name]["lat_ms"].append(
                            (time.monotonic() - t_req) * 1e3)

        n_threads = max(4, min(args.threads, n_requests))
        return [threading.Thread(target=worker, daemon=True)
                for _ in range(n_threads)], n_requests

    plans = [
        drive("victim", base_rate,
              max(1, int(base_rate * args.duration_s))),
        drive("lowpri", base_rate,
              max(1, int(base_rate * args.duration_s))),
        drive("flood", base_rate * args.flood_x,
              max(1, int(base_rate * args.flood_x * args.duration_s))),
    ]
    threads = [t for ts, _ in plans for t in ts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    failpoints.clear()
    qos_snap = qos.snapshot()
    transitions = (obs.counter("serving.qos.transitions").value
                   - transitions0)
    server.stop()
    if run_log is not None:
        run_log.close("ok")

    scheduled = sum(n for _, n in plans)
    accounted = sum(st["sent"] for st in stats.values())
    dropped = scheduled - accounted
    if dropped:
        violations.append(f"{dropped} request(s) unaccounted for")
    if transitions <= 0:
        violations.append("no qos rung transitions recorded")
    if stats["lowpri"]["degraded"] + stats["flood"]["degraded"] <= 0:
        violations.append("low-priority traffic never ran degraded")
    victim = stats["victim"]
    value = victim["ok"] / max(victim["sent"], 1)
    for st in stats.values():
        st["rungs"] = sorted(st["rungs"])
        lat = sorted(st.pop("lat_ms"))
        st["p99_ms"] = round(percentile(lat, 99), 3) if lat else None
    rec = {
        "metric": "chaos_tenant_flood",
        "value": round(value, 4),
        "unit": "frac",
        "flood_x": args.flood_x,
        "capacity_rps": round(capacity, 3),
        "base_rate_rps": round(base_rate, 3),
        "step_down_s": round(step_down_s, 3),
        "quality_rungs": n_quality,
        "transitions": transitions,
        "shed_total": qos_snap["shed_total"],
        "final_rung": qos_snap["rung"],
        "tenants": stats,
        "dropped": dropped,
        "violations": violations,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    if violations:
        note("VIOLATIONS: " + "; ".join(violations))
    return 0 if not violations else 1


def run_session_stream(args, model=None):
    """The streaming-session chaos contract (module docstring)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu import obs
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        PoisonRequestError,
        ServingError,
    )
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    windows = [parse_fault_window(s) for s in args.fault]
    for _, site, _, _ in windows:
        if not site.startswith("kill_replica"):
            raise SystemExit("--session_stream only takes kill_replica "
                             f"fault windows (got {site!r})")
    if args.replicas < 2:
        raise SystemExit("--session_stream needs --replicas >= 2 "
                         "(a survivor to re-seed on)")
    run_log = None
    if args.run_log:
        run_log = obs.init_run("chaos_serving", args.run_log, args=args)
    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    fleet = MatchFleet.build(
        config, params,
        n_replicas=args.replicas,
        base_id="chaos",
        cache_mb=0,
        engine_kwargs=dict(k_size=2, image_size=args.image_size,
                           c2f_topk=4),
        replica_kwargs=dict(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            default_timeout_s=max(args.duration_s * 4, 60.0),
            breaker_threshold=args.breaker_threshold,
            breaker_reset_s=args.breaker_reset_s,
            isolate_poison=not args.no_isolate_poison,
        ),
    )
    # Warm the WHOLE session program family on every replica before the
    # measured clock starts: the open frame (full coarse from the ref
    # image), the cached-ref full coarse (what a frame runs right after
    # a re-seed), and the seeded refinement program. Leaving any of
    # these to compile cold mid-run eats the duration in compile time
    # and the kill windows never intersect live seeded traffic — the
    # re-seed gate then fails on timing, not on correctness.
    warm_batches = sorted({1, args.max_batch})
    fleet.warmup([(h, w, h, w)], batch_sizes=warm_batches,
                 modes=("oneshot", "c2f"))
    sess_batches = sorted({1, min(args.max_batch, args.sessions)})
    warm_imgs = synth_jpegs(args.synthetic, seed=11, n=2)
    warm_ref = base64.b64encode(warm_imgs[0]).decode()
    warm_q = base64.b64encode(warm_imgs[1]).decode()
    t_warm = time.monotonic()
    for r in fleet.replicas:
        eng = r.engine
        for n in sess_batches:
            p1 = [eng.prepare_session_frame({"query_b64": warm_q},
                                            ref_b64=warm_ref)
                  for _ in range(n)]
            out = eng.run_batch(p1[0].bucket_key, p1)
            rider = out[0]["session"]
            p2 = [eng.prepare_session_frame({"query_b64": warm_q},
                                            ref_feats=rider["ref_feats"])
                  for _ in range(n)]
            eng.run_batch(p2[0].bucket_key, p2)
            p3 = [eng.prepare_session_frame(
                      {"query_b64": warm_q}, ref_feats=rider["ref_feats"],
                      seed=rider["gates"], seed_bucket=p2[0].bucket_key)
                  for _ in range(n)]
            eng.run_batch(p3[0].bucket_key, p3)
    note(f"session warmup: {len(fleet.replicas)} replica(s) x "
         f"batch {sess_batches} in {time.monotonic() - t_warm:.1f}s")
    server = MatchServer(
        None, port=0,
        max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3,
        default_timeout_s=max(args.duration_s * 4, 60.0),
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        isolate_poison=not args.no_isolate_poison,
        run_log=run_log,
        fleet=fleet,
    ).start()
    note(f"serving on {server.url} ({args.replicas} replicas); "
         f"{args.sessions} session(s); fault windows: "
         f"{[(t, a, b) for t, _, a, b in windows]}")

    imgs = synth_jpegs(args.synthetic, seed=7, n=6)
    ref, frame_pool = imgs[0], imgs[1:]
    t0 = time.monotonic()
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"sent": 0, "ok": 0, "rejected": 0, "errors": 0,
             "seeded": 0, "reseeded": 0}
    deaths = []
    close_stats = []

    def stream(sess_idx):
        client = MatchClient(
            server.url, timeout_s=max(args.duration_s * 4, 60.0),
            retries=args.client_retries,
            retry_deadline_s=args.duration_s)
        i = sess_idx  # offset so sessions don't send identical frames
        try:
            with client.session(ref_bytes=ref) as s:
                while time.monotonic() - t0 < args.duration_s:
                    fb = frame_pool[i % len(frame_pool)]
                    i += 1
                    with lock:
                        stats["sent"] += 1
                    try:
                        resp = s.frame(query_bytes=fb)
                    except OverCapacityError:
                        with lock:
                            stats["rejected"] += 1
                        continue
                    except (PoisonRequestError, ServingError,
                            OSError) as exc:
                        with lock:
                            stats["errors"] += 1
                        note(f"session {sess_idx} frame error: {exc}")
                        continue
                    info = resp.get("session") or {}
                    with lock:
                        stats["ok"] += 1
                        if info.get("seeded"):
                            stats["seeded"] += 1
                        if info.get("reseeded"):
                            stats["reseeded"] += 1
                cs = s.close()
                if cs is not None:
                    with lock:
                        close_stats.append(cs)
        except Exception as exc:  # noqa: BLE001 — any escape IS the gate
            with lock:
                deaths.append(f"session {sess_idx}: {exc!r}")

    fault_log = {}

    def fault_scheduler():
        events = sorted(
            [(s0, "arm", site) for _, site, s0, _ in windows]
            + [(e0, "disarm", site) for _, site, _, e0 in windows]
        )
        for at, action, site in events:
            delay = t0 + at - time.monotonic()
            if delay > 0 and stop.wait(delay):
                return
            idx = int(site.partition(":")[2] or -1)
            if action == "arm":
                r = fleet.kill(idx)
                note(f"t+{at:.1f}s killed {r.replica_id}")
            else:
                r = fleet.revive(idx)
                note(f"t+{at:.1f}s revived {r.replica_id}")
            fault_log.setdefault(site, []).append(
                {"t_s": at, "action": action})

    threads = [threading.Thread(target=stream, args=(k,), daemon=True)
               for k in range(args.sessions)]
    aux = threading.Thread(target=fault_scheduler, daemon=True)
    aux.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    aux.join(timeout=5)
    elapsed = time.monotonic() - t0
    server.stop()
    if run_log is not None:
        run_log.close("ok")

    violations = list(deaths)
    dropped = stats["sent"] - (stats["ok"] + stats["rejected"]
                               + stats["errors"])
    if dropped:
        violations.append(f"{dropped} frame(s) unaccounted for")
    if stats["errors"]:
        violations.append(
            f"{stats['errors']} non-retryable frame error(s) "
            "(re-seed must answer 200)")
    if windows and stats["reseeded"] < 1:
        violations.append("kill window armed but no frame reseeded")
    reseeds = sum(cs.get("reseeds", 0) for cs in close_stats)
    rec = {
        "metric": "chaos_session_stream",
        "value": round(stats["ok"] / max(stats["sent"], 1), 4),
        "unit": "frac",
        "sessions": args.sessions,
        "replicas": args.replicas,
        "frames": stats,
        "dropped": dropped,
        "session_deaths": deaths,
        "reseeds": reseeds,
        "session_close": close_stats,
        "faults": fault_log,
        "violations": violations,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    if violations:
        note("VIOLATIONS: " + "; ".join(violations))
    return 0 if not violations else 1


def run_localize_fanout(args, model=None):
    """The localize fan-out chaos contract (module docstring)."""
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu import obs
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        ServingError,
    )
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    windows = [parse_fault_window(s) for s in args.fault]
    for _, site, _, _ in windows:
        if not site.startswith("kill_replica"):
            raise SystemExit("--localize_fanout only takes kill_replica "
                             f"fault windows (got {site!r})")
    if args.replicas < 2:
        raise SystemExit("--localize_fanout needs --replicas >= 2 "
                         "(a survivor for the victim's legs)")
    if not windows:
        # The verb exists to kill a replica mid-fan-out; default one
        # window across the middle of the run.
        windows = [("kill_replica:-1", "kill_replica:-1",
                    args.duration_s * 0.3, args.duration_s * 0.7)]
    # The trace-join gate needs a runlog to scan; make a private one if
    # the caller didn't ask for a copy.
    log_path = args.run_log or os.path.join(
        tempfile.mkdtemp(prefix="chaos_localize_"), "run.jsonl")
    run_log = obs.init_run("chaos_serving", log_path, args=args)
    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    fleet = MatchFleet.build(
        config, params,
        n_replicas=args.replicas,
        base_id="chaos",
        cache_mb=0,
        engine_kwargs=dict(k_size=2, image_size=args.image_size),
        replica_kwargs=dict(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            default_timeout_s=max(args.duration_s * 4, 60.0),
            breaker_threshold=args.breaker_threshold,
            breaker_reset_s=args.breaker_reset_s,
            isolate_poison=not args.no_isolate_poison,
        ),
    )
    fleet.warmup([(h, w, h, w)],
                 batch_sizes=sorted({1, args.max_batch}))
    server = MatchServer(
        None, port=0,
        max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3,
        default_timeout_s=max(args.duration_s * 4, 60.0),
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        isolate_poison=not args.no_isolate_poison,
        run_log=run_log,
        fleet=fleet,
    ).start()
    note(f"serving on {server.url} ({args.replicas} replicas); "
         f"shortlist width {args.panos}; fault windows: "
         f"{[(t, a, b) for t, _, a, b in windows]}")

    imgs = synth_jpegs(args.synthetic, seed=23, n=args.panos + 4)
    shortlist, query_pool = imgs[:args.panos], imgs[args.panos:]
    t0 = time.monotonic()
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"sent": 0, "ok": 0, "rejected": 0, "errors": 0,
             "legs": 0, "legs_ok": 0, "legs_failed": 0,
             "silent_drops": 0, "redispatched": 0}
    trace_ids = set()
    deaths = []

    def drive(k):
        client = MatchClient(
            server.url, timeout_s=max(args.duration_s * 4, 60.0),
            retries=args.client_retries,
            retry_deadline_s=args.duration_s)
        i = k
        try:
            while time.monotonic() - t0 < args.duration_s:
                qb = query_pool[i % len(query_pool)]
                i += 1
                with lock:
                    stats["sent"] += 1
                try:
                    resp = client.localize(query_bytes=qb,
                                           panos=list(shortlist))
                except OverCapacityError:
                    with lock:
                        stats["rejected"] += 1
                    continue
                except (ServingError, OSError) as exc:
                    with lock:
                        stats["errors"] += 1
                    note(f"driver {k} query error: {exc}")
                    continue
                # No silent drops: every shortlist pano must come back
                # as a per-pano row, ok or structured-failed.
                rows = resp.get("panos", [])
                n_ok = sum(1 for r in rows if r.get("ok"))
                with lock:
                    stats["ok"] += 1
                    stats["legs"] += len(shortlist)
                    stats["legs_ok"] += n_ok
                    stats["legs_failed"] += len(rows) - n_ok
                    if (len(rows) != len(shortlist)
                            or resp.get("n_ok", -1)
                            + resp.get("n_failed", -1) != len(rows)):
                        stats["silent_drops"] += 1
                    stats["redispatched"] += int(
                        resp.get("redispatched", 0))
                    if resp.get("trace_id"):
                        trace_ids.add(resp["trace_id"])
        except Exception as exc:  # noqa: BLE001 — any escape IS the gate
            with lock:
                deaths.append(f"driver {k}: {exc!r}")

    fault_log = {}

    def fault_scheduler():
        events = sorted(
            [(s0, "arm", site) for _, site, s0, _ in windows]
            + [(e0, "disarm", site) for _, site, _, e0 in windows]
        )
        for at, action, site in events:
            delay = t0 + at - time.monotonic()
            if delay > 0 and stop.wait(delay):
                return
            idx = int(site.partition(":")[2] or -1)
            if action == "arm":
                r = fleet.kill(idx)
                note(f"t+{at:.1f}s killed {r.replica_id}")
            else:
                r = fleet.revive(idx)
                note(f"t+{at:.1f}s revived {r.replica_id}")
            fault_log.setdefault(site, []).append(
                {"t_s": at, "action": action})

    threads = [threading.Thread(target=drive, args=(k,), daemon=True)
               for k in range(args.threads)]
    aux = threading.Thread(target=fault_scheduler, daemon=True)
    aux.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    aux.join(timeout=5)
    elapsed = time.monotonic() - t0
    server.stop()
    run_log.close("ok")

    # Joined-trace check: the dispatcher books a ``redispatch`` span
    # for every bounced leg, parented into the request's trace via the
    # context captured at submit — so a redispatched leg MUST show up
    # in the runlog under one of the localize queries' trace ids.
    joined_redispatch = 0
    with open(log_path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (rec.get("event") == "redispatch"
                    or (rec.get("kind") == "span"
                        and rec.get("event") == "redispatch")):
                if rec.get("trace_id") in trace_ids:
                    joined_redispatch += 1

    violations = list(deaths)
    dropped = stats["sent"] - (stats["ok"] + stats["rejected"]
                               + stats["errors"])
    if dropped:
        violations.append(f"{dropped} quer(ies) unaccounted for")
    if stats["errors"]:
        violations.append(f"{stats['errors']} non-200 quer(ies) "
                          "(a kill mid-fan-out must still answer 200)")
    if stats["silent_drops"]:
        violations.append(f"{stats['silent_drops']} response(s) with "
                          "silently dropped panos")
    if stats["legs_failed"]:
        violations.append(f"{stats['legs_failed']} pano leg(s) failed "
                          "(the victim's share must redispatch, "
                          "not fail)")
    if windows and not stats["redispatched"]:
        violations.append("kill window armed but no leg was ever "
                          "redispatched (scenario proved nothing)")
    if stats["redispatched"] and not joined_redispatch:
        violations.append("redispatched legs never appeared in a "
                          "localize query's joined trace")
    rec = {
        "metric": "chaos_localize_fanout",
        "value": round(stats["ok"] / max(stats["sent"], 1), 4),
        "unit": "frac",
        "replicas": args.replicas,
        "fanout_width": args.panos,
        "queries": {k: stats[k] for k in
                    ("sent", "ok", "rejected", "errors")},
        "legs": {k: stats[k] for k in
                 ("legs", "legs_ok", "legs_failed")},
        "dropped": dropped,
        "silent_drops": stats["silent_drops"],
        "redispatched": stats["redispatched"],
        "joined_redispatch_spans": joined_redispatch,
        "faults": fault_log,
        "violations": violations,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    if violations:
        note("VIOLATIONS: " + "; ".join(violations))
    return 0 if not violations else 1


def main(argv=None, model=None):
    parser = argparse.ArgumentParser(
        description="chaos harness: in-process serving under load + faults"
    )
    parser.add_argument("--rate", type=float, default=6.0,
                        help="open-loop arrival rate, requests/s")
    parser.add_argument("--duration_s", type=float, default=8.0)
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--synthetic", type=str, default="96x128",
                        help="HxW: random images, sent inline b64")
    parser.add_argument("--fault", action="append", default=[],
                        help="timed window: site=mode[:args]@start-end "
                        "seconds into the run (repeatable)")
    parser.add_argument("--failpoints", type=str, default="",
                        help="static spec armed for the whole run "
                        "(NCNET_FAILPOINTS grammar)")
    parser.add_argument("--image_size", type=int, default=64)
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--max_delay_ms", type=float, default=50.0)
    parser.add_argument("--breaker_threshold", type=int, default=3)
    parser.add_argument("--breaker_reset_s", type=float, default=1.0)
    parser.add_argument("--no_isolate_poison", action="store_true")
    parser.add_argument("--replicas", type=int, default=0,
                        help="serve an in-process N-replica fleet "
                             "(enables the kill_replica fault verb; "
                             "0 = single engine)")
    parser.add_argument("--client_retries", type=int, default=2)
    parser.add_argument("--health_poll_s", type=float, default=0.1)
    parser.add_argument("--run_log", type=str, default="",
                        help="structured JSONL run log path (empty disables)")
    parser.add_argument("--tenant_flood", action="store_true",
                        help="run the multi-tenant QoS contract instead "
                        "of fault windows (module docstring): victim/"
                        "lowpri/flood tenants, quality ladder, "
                        "degradation-before-refusal gate")
    parser.add_argument("--flood_x", type=float, default=10.0,
                        help="flood tenant bursts at this multiple of "
                        "the base (victim/lowpri) rate")
    parser.add_argument("--qos_base_rate", type=float, default=0.0,
                        help="victim/lowpri arrival rate for "
                        "--tenant_flood, requests/s (0 = auto: a "
                        "quarter of the measured post-warmup device "
                        "capacity, so the overload is winnable on any "
                        "host)")
    parser.add_argument("--qos_ladder", type=str,
                        default="c2f:factor=2,topk=16;c2f:factor=4,topk=8",
                        help="quality ladder under test (serving/qos.py "
                        "grammar)")
    parser.add_argument("--device_delay_ms", type=float, default=250.0,
                        help="engine.device delay failpoint pinning a "
                        "capacity floor for --tenant_flood (measured "
                        "calibration includes it)")
    parser.add_argument("--max_queue", type=int, default=16)
    parser.add_argument("--tenant_queue_frac", type=float, default=0.25,
                        help="per-tenant queue-slot share for "
                        "--tenant_flood")
    parser.add_argument("--flood_budget_rps", type=float, default=0.0,
                        help="flood tenant's token-bucket admission "
                        "budget (0 = unlimited; throttled requests are "
                        "429 tenant_budget, exempt from the gate)")
    parser.add_argument("--qos_high_water", type=float, default=0.3,
                        help="queue fraction that counts as overload "
                        "(above one tenant's slot share, so a single "
                        "capped tenant can't pin the signal hot alone)")
    parser.add_argument("--qos_step_down_s", type=float, default=0.05,
                        help="FLOOR for the rung step-down interval; "
                        "--tenant_flood auto-raises it to the time the "
                        "device needs to drain two tenants' queue slots")
    parser.add_argument("--qos_step_up_hold_s", type=float, default=1.0)
    parser.add_argument("--session_stream", action="store_true",
                        help="run the streaming-session chaos contract "
                        "instead of open-loop match load (module "
                        "docstring): concurrent sessions must survive "
                        "kill_replica windows by re-seeding")
    parser.add_argument("--sessions", type=int, default=2,
                        help="concurrent streaming sessions for "
                        "--session_stream")
    parser.add_argument("--localize_fanout", action="store_true",
                        help="run the localize fan-out chaos contract "
                        "instead of open-loop match load (module "
                        "docstring): kill a replica mid-fan-out; every "
                        "pano must come back (redispatched, visible in "
                        "the joined trace) and the query must still 200")
    parser.add_argument("--panos", type=int, default=6,
                        help="shortlist width per localize query for "
                        "--localize_fanout")
    args = parser.parse_args(argv)
    if args.tenant_flood:
        return run_tenant_flood(args, model)
    if args.session_stream:
        return run_session_stream(args, model)
    if args.localize_fanout:
        return run_localize_fanout(args, model)
    windows = [parse_fault_window(s) for s in args.fault]
    if any(site.startswith("kill_replica") for _, site, _, _ in windows) \
            and args.replicas < 2:
        parser.error("kill_replica faults need --replicas >= 2 "
                     "(survivors to re-route the riders to)")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ncnet_tpu import obs
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.client import (
        MatchClient,
        OverCapacityError,
        PoisonRequestError,
        ServingError,
    )
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    run_log = None
    if args.run_log:
        run_log = obs.init_run("chaos_serving", args.run_log, args=args)

    if model is None:
        from ncnet_tpu.cli.common import build_model

        note("building tiny model (pass model= to reuse one in-process)")
        model = build_model(
            ncons_kernel_sizes=(3, 3),
            ncons_channels=(16, 1),
            relocalization_k_size=2,
            half_precision=True,
            backbone_bf16=True,
        )
    config, params = model
    h, w = (int(v) for v in args.synthetic.split("x"))
    warm_batches = sorted({1, max(1, args.max_batch // 2),
                           args.max_batch})
    fleet = None
    if args.replicas > 0:
        from ncnet_tpu.serving.fleet import MatchFleet

        fleet = MatchFleet.build(
            config, params,
            n_replicas=args.replicas,
            base_id="chaos",
            cache_mb=0,
            engine_kwargs=dict(k_size=2, image_size=args.image_size),
            replica_kwargs=dict(
                max_batch=args.max_batch,
                max_delay_s=args.max_delay_ms / 1e3,
                default_timeout_s=max(args.duration_s * 4, 60.0),
                breaker_threshold=args.breaker_threshold,
                breaker_reset_s=args.breaker_reset_s,
                isolate_poison=not args.no_isolate_poison,
            ),
        )
        # Warm the exact buckets the load hits: the run must measure
        # the reliability machinery, not first-request XLA compiles
        # racing the fault windows.
        fleet.warmup([(h, w, h, w)], batch_sizes=warm_batches)
    else:
        engine = MatchEngine(config, params, k_size=2,
                             image_size=args.image_size, cache_mb=0)
        engine.warmup([(h, w, h, w)], batch_sizes=warm_batches)
    if args.failpoints:
        failpoints.configure(args.failpoints)
        note(f"static failpoints: {sorted(failpoints.active())}")
    redispatched0 = obs.counter("serving.redispatched").value
    server = MatchServer(
        None if fleet is not None else engine, port=0,
        max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3,
        default_timeout_s=max(args.duration_s * 4, 60.0),
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        isolate_poison=not args.no_isolate_poison,
        run_log=run_log,
        fleet=fleet,
    ).start()
    note(f"serving on {server.url}"
         + (f" ({args.replicas} replicas)" if fleet is not None else "")
         + f"; fault windows: {[(t, a, b) for t, _, a, b in windows]}")

    q_bytes, p_bytes = synth_jpegs(args.synthetic)
    kwargs = {"query_bytes": q_bytes, "pano_bytes": p_bytes,
              "max_matches": 8}
    client = MatchClient(server.url, timeout_s=max(args.duration_s * 4, 60.0),
                         retries=args.client_retries,
                         retry_deadline_s=args.duration_s)

    stop = threading.Event()
    t0 = time.monotonic()

    fault_log = {}

    def fault_scheduler():
        """Arm/disarm each window at its wall-clock offsets."""
        events = sorted(
            [(start, "arm", term, site) for term, site, start, _ in windows]
            + [(end, "disarm", term, site) for term, site, _, end in windows]
        )
        for at, action, term, site in events:
            delay = t0 + at - time.monotonic()
            if delay > 0 and stop.wait(delay):
                return
            if site.startswith("kill_replica"):
                # Fleet verb, not a failpoint: kill_replica[:idx]
                # stops that replica (default: the last one) for the
                # window; revive at disarm.
                idx = int(site.partition(":")[2] or -1)
                if action == "arm":
                    r = fleet.kill(idx)
                    note(f"t+{at:.1f}s killed {r.replica_id}")
                else:
                    r = fleet.revive(idx)
                    note(f"t+{at:.1f}s revived {r.replica_id}")
            elif action == "arm":
                fp = failpoints.parse_spec(term)[site]
                failpoints.registry().set(
                    site, fp.mode, prob=fp.prob, delay_s=fp.delay_s,
                    max_fires=fp.max_fires,
                )
                note(f"t+{at:.1f}s armed {term}")
            else:
                failpoints.clear(site)
                note(f"t+{at:.1f}s cleared {site}")
            fault_log.setdefault(site, []).append(
                {"t_s": at, "action": action})

    transitions = []

    def health_poller():
        """Record every /healthz status + breaker state change seen."""
        probe = MatchClient(server.url, timeout_s=5.0, retries=0)
        last = None
        while not stop.is_set():
            try:
                hz = probe.healthz()
            except (ServingError, OSError):
                stop.wait(args.health_poll_s)
                continue
            if "fleet" in hz:
                detail = (f"healthy={hz['fleet']['healthy']}"
                          f"/{hz['fleet']['size']}")
            else:
                detail = hz["breaker"]["state"]
            cur = (hz["status"], detail)
            if cur != last:
                transitions.append({
                    "t_s": round(time.monotonic() - t0, 3),
                    "status": cur[0], "breaker": cur[1],
                })
                last = cur
            stop.wait(args.health_poll_s)

    n_requests = max(1, int(args.rate * args.duration_s))
    lock = threading.Lock()
    lat_ms = []
    counts = {"sent": 0, "ok": 0, "rejected": 0, "poison": 0, "errors": 0}
    sched = {"next": 0}

    def worker():
        while True:
            with lock:
                i = sched["next"]
                if i >= n_requests:
                    return
                sched["next"] = i + 1
            due = t0 + i / args.rate
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                client.match(**kwargs)
            except OverCapacityError:
                with lock:
                    counts["sent"] += 1
                    counts["rejected"] += 1
                continue
            except PoisonRequestError:
                with lock:
                    counts["sent"] += 1
                    counts["poison"] += 1
                continue
            except (ServingError, OSError) as exc:
                with lock:
                    counts["sent"] += 1
                    counts["errors"] += 1
                note(f"error on req {i}: {exc}")
                continue
            dt_ms = (time.monotonic() - t_req) * 1e3
            with lock:
                counts["sent"] += 1
                counts["ok"] += 1
                lat_ms.append(dt_ms)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(min(args.threads, n_requests))]
    aux = [threading.Thread(target=fault_scheduler, daemon=True),
           threading.Thread(target=health_poller, daemon=True)]
    for t in aux + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in aux:
        t.join(timeout=5)
    elapsed = time.monotonic() - t0
    failpoints.clear()
    server.stop()
    if run_log is not None:
        run_log.close("ok")

    # Survival: every request is accounted for AND got a structured
    # outcome the client can act on (success, retryable 503, or a
    # proven-poison 422). errors (500s, transport) and silent drops are
    # the chaos failures this tool exists to surface.
    accounted = sum(counts[k] for k in ("ok", "rejected", "poison", "errors"))
    dropped = n_requests - accounted
    survived = counts["ok"] + counts["rejected"] + counts["poison"]
    lat_ms.sort()
    rec = {
        "metric": "chaos_serving_survival",
        "value": round(survived / n_requests, 4),
        "unit": "frac",
        "sent": counts["sent"],
        "ok": counts["ok"],
        "rejected": counts["rejected"],
        "poison": counts["poison"],
        "errors": counts["errors"],
        "dropped": dropped,
        "replicas": args.replicas,
        "redispatched": (obs.counter("serving.redispatched").value
                         - redispatched0),
        "latency_ms": {
            "p50": round(percentile(lat_ms, 50), 3) if lat_ms else None,
            "p99": round(percentile(lat_ms, 99), 3) if lat_ms else None,
        },
        "breaker_transitions": transitions,
        "faults": fault_log,
        "duration_s": round(elapsed, 3),
    }
    print(json.dumps(rec), flush=True)
    return 0 if dropped == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
