"""One-dial TPU experiment session: every queued experiment in ONE process.

The axon tunnel is single-session and wedges for 10-25 min when a client
disconnects uncleanly — including the lease linger after a *clean* exit
(observed 2026-07-31 01:03: a bench exited rc=0 and the very next process's
dial hung for its full watchdog). Running each tool as its own process costs
one dial per tool and one wedge risk per handoff; this driver dials once and
then calls each tool's main() in-process — jax caches the initialized
backend, so the tools' own dial_devices() calls return instantly.

Phases run in value order and are individually fenced: a failure in one
records the traceback and moves on, so a mid-session tunnel death still
leaves the highest-value numbers on disk.

Usage:
    python tools/tpu_session.py [--dial_timeout 600] [--skip phase,phase]
Phases: corr_pool, consensus, extract, backbone, profile, conv4d, train,
bench.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[session {time.time() - _T0:7.1f}s] {msg}", flush=True)


def _load(name):
    path = os.path.join(os.path.dirname(__file__), name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=600.0)
    p.add_argument("--skip", type=str, default="",
                   help="comma-separated phase names to skip")
    p.add_argument("--iters", type=int, default=3)
    args = p.parse_args(argv)
    skip = set(filter(None, args.skip.split(",")))

    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    setup_compile_cache()
    log(f"dialing (watchdog {args.dial_timeout:.0f}s)...")
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("dial timed out; aborting session")
        return 2
    log(f"devices: {devices}")

    # Tools re-dial internally; the backend is already up, so give them a
    # short watchdog — if the tunnel died between phases we want to move on,
    # not burn 10 minutes per remaining phase.
    phases = [
        ("corr_pool", "bench_corr_pool",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("consensus", "bench_consensus",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("extract", "bench_extract",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("backbone", "bench_backbone",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("profile", "profile_inloc",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("conv4d", "bench_conv4d",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("train", "bench_train", ["--dial_timeout", "120", "--iters", "4"]),
    ]
    for label, modname, phase_argv in phases:
        if label in skip:
            log(f"=== {label}: SKIPPED ===")
            continue
        log(f"=== {label} ===")
        try:
            _load(modname).main(phase_argv)
        except SystemExit as exc:  # tools os._exit on dial fail only
            log(f"{label} exited: {exc}")
        except Exception:  # noqa: BLE001
            log(f"{label} FAILED:\n{traceback.format_exc()}")

    if "bench" not in skip:
        os.environ["NCNET_BENCH_DIAL_TIMEOUT"] = "120"
        # The baseline run must not inherit a mix left over from a prior
        # manual experiment — the A/B below would then compare a config
        # with itself.
        os.environ.pop("NCNET_CONSENSUS_STRATEGIES", None)
        log("=== bench (headline JSON on stdout) ===")
        try:
            _load("../bench").main()
        except Exception:  # noqa: BLE001
            log(f"bench FAILED:\n{traceback.format_exc()}")
        # Candidate-mix re-run: the CPU A/B's best consensus strategy mix,
        # via the trace-time env knob — if this line beats the default's,
        # flip the 'auto' heuristic in ops/conv4d.py.
        log("=== bench with NCNET_CONSENSUS_STRATEGIES="
            "conv2d_stacked,conv2d_outstacked ===")
        try:
            os.environ["NCNET_CONSENSUS_STRATEGIES"] = (
                "conv2d_stacked,conv2d_outstacked"
            )
            _load("../bench").main()
        except Exception:  # noqa: BLE001
            log(f"bench(mix) FAILED:\n{traceback.format_exc()}")
        finally:
            os.environ.pop("NCNET_CONSENSUS_STRATEGIES", None)
    log("session DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
