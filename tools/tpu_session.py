"""One-dial TPU experiment session: every queued experiment in ONE process.

The axon tunnel is single-session and wedges for 10-25 min when a client
disconnects uncleanly — including the lease linger after a *clean* exit
(observed 2026-07-31 01:03: a bench exited rc=0 and the very next process's
dial hung for its full watchdog). Running each tool as its own process costs
one dial per tool and one wedge risk per handoff; this driver dials once and
then calls each tool's main() in-process — jax caches the initialized
backend, so the tools' own dial_devices() calls return instantly.

Phases run in value order and are individually fenced: a failure in one
records the traceback and moves on, so a mid-session tunnel death still
leaves the highest-value numbers on disk.

Usage:
    python tools/tpu_session.py [--dial_timeout 600] [--skip phase,phase]
Phases (in run order): bench (the headline A/B matrix, always first),
smoke, trace, train, train_accum, bisect, backbone, profile, conv4d,
extract, train_e2e, consensus, corr_pool.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[session {time.time() - _T0:7.1f}s] {msg}", flush=True)


def _load(name):
    path = os.path.join(os.path.dirname(__file__), name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=600.0)
    p.add_argument("--skip", type=str, default="",
                   help="comma-separated phase names to skip")
    p.add_argument("--iters", type=int, default=3)
    args = p.parse_args(argv)
    skip = set(filter(None, args.skip.split(",")))

    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    setup_compile_cache()
    log(f"dialing (watchdog {args.dial_timeout:.0f}s)...")
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("dial timed out; aborting session")
        return 2
    log(f"devices: {devices}")

    # Tools re-dial internally; the backend is already up, so give them a
    # short watchdog — if the tunnel died between phases we want to move on,
    # not burn 10 minutes per remaining phase.
    # Ordered by information value, with the two observed wedge classes
    # LAST (reordered 2026-08-01 12:06): a fresh-shape reps-wrapped
    # compile can hang the remote-compile helper through every fence
    # (corr_pool at 08:35, consensus at 11:37 — both wedged their
    # session at its FIRST standalone-stage compile and cost a hard
    # exit + a 10-25 min tunnel wedge). The matrix + trace + train
    # phases carry the round's open decisions; the standalone stage
    # benches are refinement.
    phases = [
        # Correctness: kernels vs oracles under real Mosaic (PASSED twice
        # this round already — skip via loop args when windows are short).
        ("smoke", "pallas_tpu_smoke", ["--dial_timeout", "120"]),
        # Op-level truth: device trace of the headline step, parsed
        # in-process (top ops by self time into this log).
        ("trace", "trace_step", ["--dial_timeout", "120"]),
        ("train", "bench_train",
         ["--dial_timeout", "120", "--iters", "4",
          "--policies", "full,dots,none"]),
        # Round-4: gradient accumulation (4 micro-batches of 4) — the AD
        # memory drops ~4x, so the cheaper remat policies may fit where
        # they OOM'd at batch 16; sweep the two fastest CPU-pre-read
        # policies under accumulation.
        ("train_accum", "bench_train",
         ["--dial_timeout", "120", "--iters", "4", "--accum", "4",
          "--policies", "dots,none"]),
        # Differential truth: the real step with stages knocked out one at
        # a time — the only attribution that includes in-step fusion.
        ("bisect", "bench_step_bisect",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("backbone", "bench_backbone",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("profile", "profile_inloc",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("conv4d", "bench_conv4d",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("extract", "bench_extract",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        # VERDICT r4 #5b: the full train -> checkpoint -> eval -> export
        # round trip ON HARDWARE (small corpus; proves the pipeline, not
        # the model). One JSON line lands in this log. Runs LATE: its
        # 96 px vgg programs are entirely fresh shapes, and a fresh-shape
        # first compile is the documented wedge class — after this point
        # only the two refinement stage benches are at risk.
        ("train_e2e", "train_eval_pipeline",
         ["--out", "/tmp/train_e2e_tpu", "--epochs", "2"]),
        # The two wedge-prone standalone stage benches, dead last: if one
        # hangs, only refinement numbers are lost.
        ("consensus", "bench_consensus",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
        ("corr_pool", "bench_corr_pool",
         ["--dial_timeout", "120", "--iters", str(args.iters)]),
    ]
    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    # Hard backstop for hangs SIGALRM cannot reach: a remote-compile wait
    # stuck in native code defers signal delivery indefinitely (observed
    # 2026-07-31 06:15-06:40: a case hung 25+ min THROUGH both its 420 s
    # case fence and the 1500 s phase fence). The obs Watchdog hard-exits
    # the session 180 s past any phase deadline; the probe loop treats the
    # nonzero rc as a failed session and redials.
    from ncnet_tpu.obs import Watchdog

    watchdog = Watchdog(label="tpu_session", log=log).start()

    # Bench matrix runs BEFORE the per-stage phases (flipped 2026-08-01):
    # tunnel windows have measured ~30 min (08:31-09:03 this round), the
    # matrix carries the round's open knob verdicts in headline units
    # (bb5/bb10, conv1fold, and l1-pallas were decided this way before
    # their lines retired), and its baseline run compiles the
    # exact program the driver's round-end bench.py must find warm in the
    # disk cache. The phases refine attribution afterwards if the window
    # holds.
    if "bench" not in skip:
        os.environ["NCNET_BENCH_DIAL_TIMEOUT"] = "120"
        # In-process bench must fail loudly, not fall back: standalone
        # bench.py re-execs itself as a CPU smoke when the dial fails,
        # which inside this session would silently replace the whole
        # process (phases never run, rc=0, the loop logs success).
        os.environ["NCNET_BENCH_NO_REEXEC"] = "1"
        # Headline A/B matrix via trace-time env knobs. The baseline run
        # must not inherit knobs left over from a prior manual experiment
        # — each run sets exactly its own dict and pops it afterwards.
        # Winners get promoted to code defaults:
        #   mix          -> the 'auto' heuristic in ops/conv4d.py
        #   fused-mutual -> the step composition in bench.py /
        #                   cli/eval_inloc.py
        #   full-fusion  -> additionally NCNET_FUSE_CORR_MAXES default in
        #                   models/ncnet.py
        # Trimmed to the undecided combos: feat2 (3.43 x3 sessions),
        # fused-mutual/full-fusion (+0.2% x3), fold2 (-10%) are measured
        # and recorded in docs/NEXT.md; re-running them burns flaky
        # remote-compile budget (the 08:03 session lost two bench lines
        # to >25 min compiles).
        # Ordered by information value: baseline (with kept trace)
        # first, then the cache-hit and bb1 references.
        # Matrix updated 2026-08-01 after session_1128 decided the round-3
        # knobs (bb5 PROMOTED to code default 9.69 vs 6.09; bb10 8.14 and
        # bb5+conv1fold 9.24 LOSE — dropped from the matrix, knobs kept
        # in code; numbers in docs/NEXT.md).
        # (label, env, fence_s). Default fence matches the phases; 1500 s
        # covers the documented >20 min XLA-extraction-tier compile hang
        # class without starving the rest of the queue.
        bench_runs = [
            # 'default' now means bb5 (the promoted code default). Keep
            # this run's trace: the scan-batched block's 'other' stage
            # (77-99 ms/pair in session_1128, now the #1 cost) exists
            # only in the bench block's own capture — read it with
            # tools/trace_optable.py docs/tpu_r05/bench_trace.
            ("default (bb5)",
             {"NCNET_BENCH_KEEP_TRACE": "docs/tpu_r05/bench_trace"}, 1500),
            # Cache-hit steady state of the cross-query pano feature
            # cache (default ON in cli/eval_inloc.py); its block also
            # compiles fastest (no pano backbone).
            ("default+featcache-hit", {"NCNET_BENCH_HIT_PATH": "1"}, 1500),
            # Pre-promotion reference so a bb5 regression vs bb1 stays
            # detectable session-over-session.
            ("bb1 reference", {"NCNET_PANO_BACKBONE_BATCH": "1"}, 1500),
            # (The default+l1-pallas line died 2026-08-02: third distinct
            # Mosaic lowering rejection, kernel deleted — ops/conv4d.py.)
        ]
        # Snapshot inherited knob overrides: the matrix must strip them so
        # each run measures exactly its own dict, but the phases that now
        # run AFTER the matrix must still see the operator's env (an
        # inherited override silently cleared here would make every phase
        # measure plain defaults while its log reads as the override's).
        _matrix_knobs = (
            "NCNET_CONSENSUS_STRATEGIES", "NCNET_FUSE_MUTUAL_EXTRACT",
            "NCNET_FUSE_CORR_MAXES", "NCNET_CONSENSUS_KL_FOLD",
            "NCNET_INLOC_FEAT_UNIT", "NCNET_BACKBONE_NHWC",
            "NCNET_CONSENSUS_CL",
            "NCNET_PANO_BACKBONE_BATCH", "NCNET_BACKBONE_CONV1_FOLD",
            "NCNET_BENCH_HIT_PATH", "NCNET_BENCH_KEEP_TRACE",
        )
        _inherited = {k: os.environ[k] for k in _matrix_knobs
                      if k in os.environ}
        for run_label, env, fence in bench_runs:
            for k in _matrix_knobs:
                os.environ.pop(k, None)
            os.environ.update(env)
            log(f"=== bench[{run_label}] env={env} (JSON on stdout) ===")
            watchdog.arm(fence + 180)
            try:
                # Default fence matches the phases: bench.py's fallback
                # ladder can reach the XLA extraction tier whose
                # InLoc-shape compile is the documented >20 min
                # remote-compile hang. Individual runs may carry a
                # tighter fence (3rd tuple element).
                run_with_alarm(fence, _load("../bench").main)
            except AlarmTimeout as exc:
                log(f"bench[{run_label}] TIMED OUT: {exc}")
            except Exception:  # noqa: BLE001
                log(f"bench[{run_label}] FAILED:\n{traceback.format_exc()}")
            finally:
                watchdog.disarm()
                for k in env:
                    os.environ.pop(k, None)
        os.environ.update(_inherited)

    for label, modname, phase_argv in phases:
        if label in skip:
            log(f"=== {label}: SKIPPED ===")
            continue
        log(f"=== {label} ===")
        watchdog.arm(1500 + 180)
        try:
            # 25 min per phase: one pathological compile must not starve
            # the rest of the queue (observed 2026-07-31, see
            # run_with_alarm). Individual tools add tighter per-candidate
            # fences where hangs were actually seen.
            run_with_alarm(1500, _load(modname).main, phase_argv)
        except AlarmTimeout as exc:
            log(f"{label} TIMED OUT: {exc}")
        except SystemExit as exc:  # tools os._exit on dial fail only
            log(f"{label} exited: {exc}")
        except Exception:  # noqa: BLE001
            log(f"{label} FAILED:\n{traceback.format_exc()}")
        finally:
            watchdog.disarm()

    log("session DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
