"""A/B the fused correlation+maxpool formulations on the live backend.

Times each candidate at the InLoc feature shape (200x150, k=2, bf16
storage) with R repetitions chained inside ONE jit via lax.scan — a
tunneled backend costs ~40 ms per host round trip, so per-call timing
has an ~85 ms floor that would swamp a sub-100 ms kernel. Each scan
iteration perturbs the input with the previous iteration's probe scalar
(x * (1 + eps*0) pattern) so XLA cannot hoist the loop body.

Candidates:
  * pallas   — ops.pallas_kernels.fused_correlation_maxpool_pallas
  * xla      — the slab-scan fallback (same never-materialize property)
  * unfused  — plain einsum correlation + ops.pool4d.maxpool4d; the
               pre-pool tensor (1.8 GB bf16 at InLoc shapes) DOES
               materialize — affordable since the consensus stage's
               round-2 memory plan freed the HBM headroom.

Usage:
    python tools/bench_corr_pool.py [--scale 1.0] [--reps 4] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=4,
                   help="kernel applications chained inside one jit")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.ops.correlation import feature_correlation
    from ncnet_tpu.ops.pool4d import maxpool4d
    from ncnet_tpu.ops.pallas_kernels import (
        fused_correlation_maxpool_pallas,
        fused_correlation_maxpool_xla,
    )

    fh = int(200 * args.scale)
    fw = int(150 * args.scale)
    c = 1024
    log(f"features {fh}x{fw} c={c} k=2 bf16 storage, reps={args.reps}")

    fa = jax.random.normal(jax.random.PRNGKey(0), (1, c, fh, fw), jnp.float32)
    fb = jax.random.normal(jax.random.PRNGKey(1), (1, c, fh, fw), jnp.float32)

    def unfused(a, b):
        corr = feature_correlation(a, b, compute_dtype=jnp.bfloat16).astype(
            jnp.bfloat16
        )
        return maxpool4d(corr, 2)

    # Decision-value order: the production default (bigdot_ab) and the
    # XLA reference land first so a mid-phase death (2026-08-01: the
    # then-first candidate's cold reps-compile hung >20 min through every
    # fence) still records the pair the kernel-vs-XLA default decision
    # needs. t768 last: its compile vmem-OOMs (session 0646).
    candidates = {
        "pallas_bigdot_ab": lambda a, b: fused_correlation_maxpool_pallas(
            a, b, k_size=2, corr_dtype=jnp.bfloat16, kernel_impl="bigdot",
            grid_order="ab",
        ),
        "xla_slab": lambda a, b: fused_correlation_maxpool_xla(
            a, b, k_size=2, corr_dtype=jnp.bfloat16
        ),
        # grid_order pinned on EVERY candidate: an inherited env override
        # would otherwise make lines incomparable across runs.
        "pallas_dots": lambda a, b: fused_correlation_maxpool_pallas(
            a, b, k_size=2, corr_dtype=jnp.bfloat16, kernel_impl="dots",
            grid_order="ba",
        ),
        "pallas_bigdot_ba": lambda a, b: fused_correlation_maxpool_pallas(
            a, b, k_size=2, corr_dtype=jnp.bfloat16, kernel_impl="bigdot",
            grid_order="ba",
        ),
        "unfused": unfused,
        "pallas_bigdot_t768": lambda a, b: fused_correlation_maxpool_pallas(
            a, b, k_size=2, corr_dtype=jnp.bfloat16, kernel_impl="bigdot",
            tile_b_cells=768, grid_order="ba",
        ),
    }

    for name, fn in candidates.items():
        try:
            first, dt, _ = timed_steady(
                chain_reps(fn, args.reps), fa, fb, iters=args.iters
            )
            log(f"{name:10s} first={first:6.2f}s total={dt * 1000:8.1f}ms "
                f"-> {dt * 1000 / args.reps:7.1f}ms/app (incl ~one RTT/iter)")
        except Exception as exc:  # noqa: BLE001
            log(f"{name:10s} FAILED: {type(exc).__name__}: {exc}")


if __name__ == "__main__":
    main()
