"""Staged profiler for the InLoc dense-matching pipeline.

Times each stage of the headline workload (SURVEY.md §3.3) separately —
backbone, fused correlation+pool, consensus, match extraction — so a
regression or a wedged backend is attributable to a stage instead of one
opaque end-to-end number. Timestamps print immediately (never pipe this
through a buffering grep on a long TPU run).

Usage:
    python tools/profile_inloc.py                 # full InLoc shapes
    python tools/profile_inloc.py --scale 0.5     # half-size features
    JAX_PLATFORMS=cpu python tools/profile_inloc.py --scale 0.2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0,
                   help="scale on the InLoc image size (1.0 = 3200x2400)")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=900.0)
    p.add_argument("--conv4d_strategy", type=str, default="",
                   choices=("", "conv2d", "conv3d", "conv2d_stacked",
                            "convnd", "auto"),
                   help="A/B the Conv4d formulation (sets "
                   "NCNET_CONV4D_STRATEGY before ncnet_tpu import)")
    args = p.parse_args(argv)

    if args.conv4d_strategy:
        os.environ["NCNET_CONV4D_STRATEGY"] = args.conv4d_strategy

    import jax

    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.backbone import backbone_apply
    from ncnet_tpu.ops import (
        corr_to_matches,
        mutual_matching,
        neigh_consensus_apply,
        neigh_consensus_init,
    )
    from ncnet_tpu.ops.pallas_kernels import fused_correlation_maxpool

    # InLoc config: long side 3200 -> stride-16 features 200x150, k=2.
    h = int(3200 * args.scale) // 32 * 32
    w = int(2400 * args.scale) // 32 * 32
    fh, fw = h // 16, w // 16
    log(f"image {h}x{w} -> features {fh}x{fw}")

    config = NCNetConfig(
        backbone=BackboneConfig(compute_dtype="bfloat16"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)
    log("params built")

    from ncnet_tpu.utils.profiling import timed_steady

    def timed(name, fn, *xs):
        t_first, dt, out = timed_steady(fn, *xs, iters=args.iters)
        log(f"{name}: compile+first={t_first:.2f}s steady={dt * 1000:.1f}ms")
        return out

    bb = jax.jit(lambda p, x: backbone_apply(config.backbone, p, x))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 3, h, w), jnp.float32)
    feat = timed(f"backbone {h}x{w}", bb, params["backbone"], x)
    log(f"  features: {feat.shape} {feat.dtype}")

    fused = jax.jit(
        lambda a, b: fused_correlation_maxpool(
            a, b, k_size=2, corr_dtype=config.corr_dtype
        )
    )
    fa = jax.random.normal(jax.random.PRNGKey(2), (1, 1024, fh, fw), jnp.float32)
    fb = jax.random.normal(jax.random.PRNGKey(3), (1, 1024, fh, fw), jnp.float32)
    pooled, deltas = timed(f"fused corr+pool {fh}x{fw}", fused, fa, fb)
    log(f"  pooled: {pooled.shape} {pooled.dtype}")

    nc = params["neigh_consensus"]

    def consensus(p, corr):
        corr = mutual_matching(corr)
        corr = neigh_consensus_apply(p, corr, symmetric=True)
        return mutual_matching(corr)

    corr4d = timed(
        "mutual+consensus+mutual", jax.jit(consensus), nc,
        pooled.astype(jnp.float32),
    )

    def extract(corr, d):
        m1 = corr_to_matches(
            corr, delta4d=d, k_size=2, do_softmax=True, scale="positive"
        )
        m2 = corr_to_matches(
            corr, delta4d=d, k_size=2, do_softmax=True, scale="positive",
            invert_matching_direction=True,
        )
        return m1, m2

    timed("corr_to_matches both dirs", jax.jit(extract), corr4d, deltas)
    log("ALL DONE")


if __name__ == "__main__":
    main()
