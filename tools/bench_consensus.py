"""A/B the consensus-stage memory plans and Conv4d strategies on device.

Times mutual->symmetric-consensus->mutual at the InLoc post-pool shape
([1,1,100,75,100,75] bf16, 3^4 kernels, 1->16->1 channels) across
chunk_i values and per-layer Conv4d strategy mixes, with R applications
chained inside one jit (lax.scan) so the ~40 ms tunnel round trip does
not floor the measurement (see tools/bench_corr_pool.py). The
NCNET_CONV4D_STRATEGY env var is cleared for the whole run so the
'auto'-labeled cases really measure layer-wise auto.

Usage:
    python tools/bench_consensus.py [--scale 1.0] [--reps 4] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.ops.conv4d import neigh_consensus_apply, neigh_consensus_init
    from ncnet_tpu.ops.mutual import mutual_matching

    ii = max(int(100 * args.scale) // 4 * 4, 8)
    jj = max(int(75 * args.scale) // 4 * 4, 8)
    log(f"consensus stage at [1,1,{ii},{jj},{ii},{jj}] bf16, reps={args.reps}")

    params = neigh_consensus_init(jax.random.PRNGKey(0), (3, 3), (16, 1))
    corr = jax.random.normal(
        jax.random.PRNGKey(1), (1, 1, ii, jj, ii, jj), jnp.float32
    ).astype(jnp.bfloat16)

    # Isolation: the per-backend env override must not leak into the
    # 'auto'-labeled cases (conv4d_prepadded falls back to os.environ when
    # a layer's strategy is None).
    os.environ.pop("NCNET_CONV4D_STRATEGY", None)

    # (label, chunk_i, per-layer strategies or None for layer-wise 'auto')
    cases = [
        ("chunk3-auto   (round-2 default)", 3, None),
        ("chunk7-auto", 7, None),
        ("chunk13-auto", 13, None),
        ("chunk25-auto", 25, None),
        ("chunk13-conv3d", 13, ("conv3d", "conv3d")),
        ("oneshot-conv3d", 0, ("conv3d", "conv3d")),
        # conv2d OOMs the one-shot layer 2 at full scale; does the
        # stacked-l1 + conv3d-l2 mix fit and win?
        ("oneshot-stacked+conv3d", 0, ("conv2d_stacked", "conv3d")),
        # Output-stacked layer 2: single input read + MXU N=9 (vs 1) —
        # the traffic/shape argument says this should be the l2 winner.
        ("oneshot-stacked+outstacked", 0,
         ("conv2d_stacked", "conv2d_outstacked")),
        ("chunk13-stacked+outstacked", 13,
         ("conv2d_stacked", "conv2d_outstacked")),
    ]
    # Best-chunk case re-run with the transposed-major mutual_matching:
    # its per-B max reduces over the major axes, the same axis class that
    # cost extraction ~100x pre-rewrite.
    cases.append(("chunk13-auto+mutualT", 13, None, True))

    for case in cases:
        label, chunk_i, strats = case[0], case[1], case[2]
        mutual_t = case[3] if len(case) > 3 else False

        def stage(c, chunk_i=chunk_i, strats=strats, mutual_t=mutual_t):
            c = mutual_matching(c, transpose_major=mutual_t)
            c = neigh_consensus_apply(
                params, c, symmetric=True, chunk_i=chunk_i, strategies=strats
            )
            return mutual_matching(c, transpose_major=mutual_t)

        try:
            first, dt, _ = timed_steady(
                chain_reps(stage, args.reps), corr, iters=args.iters
            )
            log(f"{label:32s} first={first:6.2f}s "
                f"-> {dt * 1000 / args.reps:7.1f}ms/app (+~RTT/iter amortized)")
        except Exception as exc:  # noqa: BLE001
            log(f"{label:32s} FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")


if __name__ == "__main__":
    main()
