"""A/B the consensus-stage memory plans and Conv4d strategies on device.

Times mutual->symmetric-consensus->mutual at the InLoc post-pool shape
([1,1,100,75,100,75] bf16, 3^4 kernels, 1->16->1 channels) across
chunk_i values and per-layer Conv4d strategy mixes, with R applications
chained inside one jit (lax.scan) so the ~40 ms tunnel round trip does
not floor the measurement (see tools/bench_corr_pool.py). The
NCNET_CONV4D_STRATEGY env var is cleared for the whole run so the
'auto'-labeled cases really measure layer-wise auto.

The plan cases come from ncnet_tpu.ops.autotune.enumerate_plans — the
single legal-candidate home — so the algebraic arms (cp:rank=R, fft;
ops/cp4d.py) appear here automatically. For those approximate arms the
tool also measures output agreement vs the dense reference stack, and
the whole run ends with ONE JSON line on stdout (per-arm ms + agreement
delta; prose stays on stderr) so a session script can record the A/B
the same way it records bench.py.

Usage:
    python tools/bench_consensus.py [--scale 1.0] [--reps 4] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    # Prose to stderr: stdout is the ONE-JSON-line machine contract.
    print(f"[{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--dial_timeout", type=float, default=600.0)
    p.add_argument("--max_plans", type=int, default=0,
                   help="cap the enumerated plan cases (0 = all); the "
                        "diagnostic cases always run")
    args = p.parse_args(argv)

    import jax

    from ncnet_tpu.utils.profiling import (
        chain_reps,
        dial_devices,
        setup_compile_cache,
        timed_steady,
    )

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.ops.conv4d import neigh_consensus_apply, neigh_consensus_init
    from ncnet_tpu.ops.mutual import mutual_matching

    # EXACT pipeline shape — no rounding: the earlier //4*4 alignment
    # measured 100x72 for a stage whose real input is 100x75, and vector
    # padding effects (75 -> 80 sublanes / 128 lanes) are part of what
    # this tool exists to observe.
    ii = max(int(100 * args.scale), 8)
    jj = max(int(75 * args.scale), 8)
    log(f"consensus stage at [1,1,{ii},{jj},{ii},{jj}] bf16, reps={args.reps}")

    params = neigh_consensus_init(jax.random.PRNGKey(0), (3, 3), (16, 1))
    corr = jax.random.normal(
        jax.random.PRNGKey(1), (1, 1, ii, jj, ii, jj), jnp.float32
    ).astype(jnp.bfloat16)

    # Isolation: the per-backend env override must not leak into the
    # 'auto'-labeled cases (conv4d_prepadded falls back to os.environ when
    # a layer's strategy is None).
    os.environ.pop("NCNET_CONV4D_STRATEGY", None)

    # Post-2026-07-31 sweep: the chunk scan and conv3d rows are decided
    # (one-shot stacked+outstacked won at 122-132 ms and is now the code
    # default); the cases below keep the champion + chunked sanity as
    # regression anchors and add the DIAGNOSTIC splits that decide whether
    # a fused consensus Pallas kernel is worth building — where the stage
    # time goes (mutual reductions vs per-layer convs vs the symmetric
    # double-evaluation).
    maxes = (
        jnp.max(corr.astype(jnp.float32), axis=(4, 5)).reshape(-1),
        jnp.max(corr.astype(jnp.float32), axis=(2, 3)).reshape(-1),
    )

    def full_stage(c):  # what the pipeline default runs
        c = mutual_matching(c)
        c = neigh_consensus_apply(params, c, symmetric=True, chunk_i=0)
        return mutual_matching(c)

    def chunked_stage(c):
        c = mutual_matching(c)
        c = neigh_consensus_apply(params, c, symmetric=True, chunk_i=25)
        return mutual_matching(c)

    def c2f_stage(c):
        # The coarse-to-fine replacement for the full stage at this
        # shape (ops/c2f.py, docs/PERF.md): coarse consensus at factor 2
        # + two top-K window-stack refinements (per-B and per-A). Inputs
        # are carved from `c` inside the jit so the case slots into the
        # shared chain_reps/timed_steady loop unchanged.
        from ncnet_tpu.ops.c2f import refine_consensus

        s, topk = 4, 8
        ii2, jj2 = ii // 2, jj // 2
        wbh, wbw = min(3 * s, ii), min(3 * s, jj)
        coarse = mutual_matching(c[:, :, :ii2, :jj2, :ii2, :jj2])
        coarse = neigh_consensus_apply(
            params, coarse, symmetric=True, chunk_i=0)
        acc = jnp.sum(mutual_matching(coarse).astype(jnp.float32))
        for off in (0, 1):
            wins = jnp.stack(
                [c[0, 0, (k + off) % s:(k + off) % s + s, :s, :wbh, :wbw]
                 for k in range(topk)]
            )[:, None].astype(jnp.float32)
            acc = acc + jnp.sum(
                refine_consensus(params, wins, corr_dtype=jnp.bfloat16))
        return acc

    def convs_only(c):
        return neigh_consensus_apply(params, c, symmetric=True, chunk_i=0)

    def convs_nonsym(c):
        return neigh_consensus_apply(params, c, symmetric=False, chunk_i=0)

    def l1_only(c):
        return neigh_consensus_apply(
            params[:1], c, symmetric=False, chunk_i=0,
            strategies=("conv2d_stacked",),
        )

    def mutuals_only(c):
        return mutual_matching(mutual_matching(c))

    def mutual_elementwise(c):
        # The emit_maxes downstream: filter with precomputed maxes — no
        # reduction passes.
        return mutual_matching(c, maxes=maxes)

    def convs_plan(c):
        # Knob-driven variant: every plan axis (strategies, fusion,
        # fold, chunk) comes from the case env, none pinned by args.
        return neigh_consensus_apply(params, c, symmetric=True)

    cases = [
        ("oneshot-auto (default, full stage)", full_stage, {}),
        ("chunk25-auto (chunked sanity)", chunked_stage, {}),
        ("c2f stage (coarse f2 + topk windows)", c2f_stage, {}),
        ("convs-only symmetric", convs_only, {}),
        ("convs-only non-symmetric", convs_nonsym, {}),
        ("l1-only stacked (1->16)", l1_only, {}),
        # l2-only RETIRED: its 16-channel-input one-shot compile hung the
        # remote-compile helper through two sessions (0522, 0610), evading
        # even the SIGALRM fence (the hang sits in native code). Its cost
        # is derivable: l2 = (convs-only non-symmetric) - (l1-only).
        ("mutual x2 (reductions)", mutuals_only, {}),
        ("mutual elementwise (maxes given)", mutual_elementwise, {}),
    ]

    # Plan cases come from the autotuner's enumeration (the single home
    # shared with tools/autotune_consensus.py and bench_strategies_ab):
    # per-layer strategy mixes x branch-fused/unfused x KL-fold. Each
    # runs with the strategy cache disabled so a tuned plan can't fill
    # the knobs a candidate leaves open and mislabel the line.
    from ncnet_tpu.ops import autotune

    plans = autotune.enumerate_plans(params, symmetric=True)
    if args.max_plans and len(plans) > args.max_plans:
        log(f"capping {len(plans)} enumerated plans to {args.max_plans}")
        plans = plans[: args.max_plans]
    plan_by_label = {}
    for plan in plans:
        label = f"plan {autotune.plan_label(plan)}"
        plan_by_label[label] = plan
        cases.append((
            label, convs_plan,
            dict(autotune.plan_env(plan), NCNET_STRATEGY_CACHE=""),
        ))

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    # Snapshot the shared process env: this tool runs in-process under
    # tpu_session, and stripping the operator's own overrides would make
    # every LATER phase silently measure the defaults.
    _knobs = autotune.PLAN_ENV_KEYS + ("NCNET_STRATEGY_CACHE",)
    _saved = {k: os.environ.get(k) for k in _knobs}

    records = []
    for label, stage, env in cases:
        for k in _knobs:
            os.environ.pop(k, None)
        os.environ.update(env)
        rec = {"label": label, "ms": None, "first_s": None,
               "status": "ok"}
        plan = plan_by_label.get(label)
        if plan is not None:
            rec["plan_kind"] = plan["kind"]
            if plan["kind"] == "cp":
                rec["cp_rank"] = plan["cp_rank"]
        try:
            # Per-case fence: a single pathological remote compile must
            # cost one case, not the phase (2026-07-31: the l2-only case
            # sat >20 min in the compile helper).
            first, dt, _ = run_with_alarm(
                420,
                timed_steady,
                chain_reps(stage, args.reps),
                corr,
                iters=args.iters,
            )
            rec["ms"] = dt * 1000 / args.reps
            rec["first_s"] = first
            log(f"{label:34s} first={first:6.2f}s "
                f"-> {dt * 1000 / args.reps:7.1f}ms/app (+~RTT/iter amortized)")
        except AlarmTimeout:
            rec["status"] = "timeout"
            log(f"{label:34s} TIMED OUT (>420s compile/run)")
        except Exception as exc:  # noqa: BLE001
            rec["status"] = f"failed: {type(exc).__name__}"
            log(f"{label:34s} FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")
        records.append(rec)

    # Agreement-vs-dense for the approximate algebraic arms (cp/fft):
    # one eager apply per arm against the dense reference stack, so a
    # "plan cp:rank=4 wins" line can never hide the quality price. Runs
    # with the knob env still stripped (explicit args win per knob).
    from ncnet_tpu.ops import cp4d

    approx = [r for r in records
              if r.get("plan_kind") in ("cp", "fft") and r["ms"]]
    if approx:
        try:
            dense_ref = run_with_alarm(
                420, lambda: neigh_consensus_apply(
                    params, corr, symmetric=True))
            for rec in approx:
                out = run_with_alarm(
                    420, lambda r=rec: neigh_consensus_apply(
                        params, corr, symmetric=True,
                        kind=r["plan_kind"], cp_rank=r.get("cp_rank")))
                rec["agreement_vs_dense"] = round(
                    cp4d.output_agreement(dense_ref, out), 4)
                log(f"{rec['label']:34s} agreement vs dense = "
                    f"{rec['agreement_vs_dense']:.4f}")
        except Exception as exc:  # noqa: BLE001
            log(f"agreement pass FAILED: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:120]}")
    for k, v in _saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    # The one-JSON-line contract (bench_serving.py posture): headline =
    # fastest timed plan case, with the plan kind / rank / measured
    # agreement tools/bench_trend.py passes through, the dense anchor
    # for the delta, and the full per-case table.
    import json

    timed = [r for r in records if r["ms"] is not None]
    plan_cases = [r for r in timed if r["label"] in plan_by_label]
    dense_cases = [r for r in plan_cases
                   if r.get("plan_kind", "dense") == "dense"]
    dense_ms = min((r["ms"] for r in dense_cases), default=None)
    best = min(plan_cases or timed, key=lambda r: r["ms"], default=None)
    headline = {
        "metric": "consensus_bench_best_ms",
        "unit": "ms",
        "value": None if best is None else round(best["ms"], 3),
        "best_label": None if best is None else best["label"],
        "consensus_plan_kind": (None if best is None
                                else best.get("plan_kind", "dense")),
        "cp_rank": None if best is None else best.get("cp_rank", 0),
        "cp_agreement": (None if best is None
                         else best.get("agreement_vs_dense")),
        "dense_ms": None if dense_ms is None else round(dense_ms, 3),
        "vs_dense": (None if (best is None or not dense_ms)
                     else round(best["ms"] / dense_ms, 3)),
        "shape": [1, 1, ii, jj, ii, jj],
        "reps": args.reps,
        "iters": args.iters,
        "cases": [{k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in r.items()} for r in records],
    }
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
