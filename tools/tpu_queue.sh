#!/bin/bash
# Strictly-serial TPU experiment queue (round 2).
#
# The axon tunnel is single-session: TWO concurrent JAX clients wedge it
# for ~10-25 min of lease expiry (observed 2026-07-30 when a smoke test
# and a bench dialed together). This queue is the only sanctioned way to
# run TPU jobs: one process at a time, dial-probe before each batch,
# retry with sleeps while the tunnel recovers.
cd /root/repo || exit 1
OUT=docs/tpu_r02
mkdir -p "$OUT"
for n in $(seq 1 60); do
  echo "=== queue attempt $n $(date -u +%FT%TZ) ===" | tee -a "$OUT/queue.log"
  if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "=== tunnel up; running serial queue ===" | tee -a "$OUT/queue.log"
    # Every job under `timeout`: a tunnel wedge AFTER a successful dial
    # otherwise hangs the job in a device fetch forever and starves the
    # rest of the queue (the dial watchdog only bounds the dial).
    timeout 1800 python tools/bench_corr_pool.py --dial_timeout 300 \
      > "$OUT/bench_corr_pool.txt" 2>&1
    echo "--- corr_pool rc=$? ---" >> "$OUT/queue.log"
    timeout 1800 python tools/bench_consensus.py --dial_timeout 300 \
      > "$OUT/bench_consensus.txt" 2>&1
    echo "--- consensus rc=$? ---" >> "$OUT/queue.log"
    timeout 1800 python tools/pallas_tpu_smoke.py --dial_timeout 300 \
      > "$OUT/pallas_smoke.txt" 2>&1
    echo "--- smoke rc=$? ---" >> "$OUT/queue.log"
    NCNET_BENCH_DIAL_TIMEOUT=300 timeout 1800 python bench.py \
      > "$OUT/bench_last.json" 2>> "$OUT/queue.log"
    echo "--- bench rc=$? ---" >> "$OUT/queue.log"
    echo "=== queue DONE $(date -u +%FT%TZ) ===" | tee -a "$OUT/queue.log"
    exit 0
  fi
  echo "tunnel down; sleeping 240s" >> "$OUT/queue.log"
  sleep 240
done
exit 3
