"""Render and diff structured run logs (docs/OBSERVABILITY.md).

Summary mode — one run's ``runlog-*.jsonl`` as a human-readable report:
run metadata (component, git rev, host, status), duration, event counts,
span time rollup, heartbeat/stall record, and the final metrics
snapshot::

    python tools/obs_report.py out/runlog-eval_inloc-20260805-1.jsonl

Diff mode — two runs' final metrics side by side, relative deltas
computed for every numeric metric present in either run, rows past
``--threshold`` flagged (the regression gate for A/Bing two eval or
bench runs)::

    python tools/obs_report.py --diff a.jsonl b.jsonl --threshold 0.05

``--strict`` makes flagged rows a nonzero exit, so the diff can gate a
session script the way tier-1 tests gate a commit.

Join mode — N runlogs (client + replicas) rendered as ONE
cross-process span tree, spans joined by ``trace_id``/``parent_id``
across files (the ``X-NCNet-Trace`` propagation makes ids global —
docs/OBSERVABILITY.md, "Cross-process tracing")::

    python tools/obs_report.py --join client.jsonl replica0.jsonl

A span whose parent lives in ANOTHER process's runlog (its record
carries ``remote_parent: true``) renders as a ``<remote xxxxxxxx>``
root showing the wire parent id — not as ``<orphaned>``, which stays
reserved for genuinely lost parents (crash-truncated logs).

Truncated final lines (a run killed mid-write) are tolerated: every
complete line still parses, which is the crash-safety point of the
line-flushed JSONL format. Rotated logs (obs/events.py,
``NCNET_RUNLOG_MAX_MB``) are read as their whole segment set — pass
the base path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _segments(path: str) -> List[str]:
    """The (possibly rotated) log's segment set, oldest first — the
    canonical lister lives in ncnet_tpu.obs.events.runlog_segments."""
    try:
        from ncnet_tpu.obs.events import runlog_segments
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from ncnet_tpu.obs.events import runlog_segments
    return runlog_segments(path)


def load_run(path: str) -> List[dict]:
    """All complete JSON records of one run log, in file order —
    including any rotated-out segments."""
    records = []
    for seg in _segments(path):
        if not os.path.exists(seg):
            continue
        with open(seg, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    # A SIGKILL mid-write loses at most the final line;
                    # the rest of the run stays reportable.
                    continue
    return records


def _last_metrics(records: List[dict]) -> Optional[dict]:
    snaps = [r for r in records if r.get("event") == "metrics"]
    return snaps[-1]["snapshot"] if snaps else None


def _series_parts(key: str) -> tuple:
    """Split a (possibly labeled) series key into (base, labels):
    ``serving.requests{replica="r0"}`` -> ``("serving.requests",
    '{replica="r0"}')``. Unlabeled keys get an empty labels part, so a
    (base, labels) sort groups a family's children together with the
    unlabeled parent first."""
    base, brace, rest = key.partition("{")
    return base, brace + rest


def _suffixed(key: str, suffix: str) -> str:
    """Append a derived-stat suffix to the series BASE name, keeping the
    label block terminal: ``h{replica="r0"}`` + ``.mean`` ->
    ``h.mean{replica="r0"}``."""
    base, labels = _series_parts(key)
    return base + suffix + labels


def final_metrics(records: List[dict]) -> Dict[str, float]:
    """Flatten the run's last metrics snapshot to {series: value}.

    Counters and gauges map directly; histograms contribute their mean
    as ``<name>.mean`` plus ``<name>.count`` (the two numbers a
    regression diff can act on). Labeled series (obs/metrics.py labels)
    keep their full ``name{k="v"}`` key, one row per child.
    """
    snap = _last_metrics(records)
    if snap is None:
        return {}
    out: Dict[str, float] = {}
    for name, v in snap.get("counters", {}).items():
        out[name] = float(v)
    for name, v in snap.get("gauges", {}).items():
        out[name] = float(v)
    for name, h in snap.get("histograms", {}).items():
        if h.get("count"):
            out[_suffixed(name, ".mean")] = float(h["mean"])
            out[_suffixed(name, ".count")] = float(h["count"])
    return out


def span_rollup(records: List[dict]) -> Dict[str, dict]:
    """{span name: {count, total_s, mean_s, max_s}} over the run."""
    out: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "span" or "dur_s" not in r:
            continue
        agg = out.setdefault(
            r["event"], {"count": 0, "total_s": 0.0, "max_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += r["dur_s"]
        agg["max_s"] = max(agg["max_s"], r["dur_s"])
    for agg in out.values():
        agg["mean_s"] = agg["total_s"] / agg["count"]
    return out


def _spans(records: List[dict]) -> List[dict]:
    return [r for r in records
            if r.get("kind") == "span" and "dur_s" in r]


def span_tree(records: List[dict]) -> Dict[tuple, dict]:
    """Aggregate traced spans (schema v2 trace_id/span_id/parent_id)
    by their NAME PATH from root: {("request", "queue_wait"): {count,
    total_s, max_s, mean_s}, ...}.

    A span whose parent_id doesn't resolve (its parent record was lost
    to a crash mid-write, or the log was truncated) is grouped under a
    synthetic ``<orphaned>`` root rather than silently posing as a
    top-level span — a truncated runlog then reads as truncated
    instead of as a differently-shaped request. One exception: a span
    carrying ``remote_parent`` (serving/server.py continued a trace
    from the ``X-NCNet-Trace`` header) has its parent in the CALLER'S
    runlog by design, so it roots under ``<remote xxxxxxxx>`` showing
    the wire parent id — join the caller's log (``--join``) to resolve
    it into one tree. Spans with a null parent_id are genuine roots
    and stay unmarked; cycles (defensive: the walk's ``seen`` guard)
    are not marked either.
    """
    spans = [r for r in _spans(records) if r.get("span_id")]
    by_id = {r["span_id"]: r for r in spans}
    out: Dict[tuple, dict] = {}
    for r in spans:
        path, node, seen = [], r, set()
        while node is not None and node["span_id"] not in seen:
            seen.add(node["span_id"])
            path.append(node["event"])
            parent_id = node.get("parent_id")
            last = node
            node = by_id.get(parent_id)
            if node is None and parent_id is not None:
                if last.get("remote_parent"):
                    path.append(f"<remote {parent_id[:8]}>")
                else:
                    path.append("<orphaned>")
        key = tuple(reversed(path))
        agg = out.setdefault(key, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += r["dur_s"]
        agg["max_s"] = max(agg["max_s"], r["dur_s"])
    for agg in out.values():
        agg["mean_s"] = agg["total_s"] / agg["count"]
    return out


def slowest_spans(records: List[dict], n: int = 10) -> List[dict]:
    """The n individually-slowest span records, longest first."""
    return sorted(_spans(records), key=lambda r: -r["dur_s"])[:n]


def shadow_rollup(records: List[dict]) -> dict:
    """Per-rung aggregates over ``shadow_compare`` events
    (serving/shadow.py): {"rungs": {rung: {count, mean, min, bitwise,
    seeded}}, "errors": n} — the run-log view of the quality-cost
    table /healthz serves live."""
    rungs: Dict[int, dict] = {}
    errors = 0
    for r in records:
        if r.get("event") != "shadow_compare":
            continue
        if "error" in r:
            errors += 1
            continue
        agg = rungs.setdefault(r.get("rung", 0), {
            "count": 0, "sum": 0.0, "min": None,
            "bitwise": 0, "seeded": 0})
        a = float(r.get("agreement", 0.0))
        agg["count"] += 1
        agg["sum"] += a
        agg["min"] = a if agg["min"] is None else min(agg["min"], a)
        if r.get("bitwise"):
            agg["bitwise"] += 1
        if r.get("seeded"):
            agg["seeded"] += 1
    for agg in rungs.values():
        agg["mean"] = agg["sum"] / agg["count"]
    return {"rungs": rungs, "errors": errors}


def summarize(path: str, records: List[dict], out=None) -> None:
    w = (out or sys.stdout).write
    if not records:
        w(f"{path}: empty run log\n")
        return
    start = next((r for r in records if r.get("event") == "run_start"), {})
    end = next((r for r in reversed(records)
                if r.get("event") == "run_end"), None)
    w(f"run {start.get('run_id', records[0].get('run_id'))}\n")
    w(f"  component : {start.get('component')}\n")
    w(f"  file      : {path}\n")
    w(f"  git_rev   : {start.get('git_rev')}\n")
    w(f"  host/pid  : {start.get('hostname')}/{start.get('pid')}"
      f" (platform {start.get('jax_platforms')})\n")
    if end is not None:
        w(f"  status    : {end.get('status')}"
          f" after {end.get('dur_s', 0):.1f}s\n")
    else:
        w("  status    : NO run_end (crashed or still running)\n")

    counts: Dict[str, int] = {}
    for r in records:
        counts[r.get("event", "?")] = counts.get(r.get("event", "?"), 0) + 1
    w("  events    : " + ", ".join(
        f"{k}={v}" for k, v in sorted(counts.items())) + "\n")

    beats = [r for r in records if r.get("event") == "heartbeat"]
    stalls = [r for r in records if r.get("event") == "stall"]
    if beats:
        max_idle = max(r.get("idle_s", 0.0) for r in beats)
        w(f"  heartbeat : {len(beats)} beats, max idle {max_idle:.1f}s, "
          f"{len(stalls)} stall(s)\n")
    for r in stalls:
        w(f"    stall after {r.get('idle_s', 0):.1f}s idle "
          f"(threshold {r.get('stall_after_s', 0):.1f}s)\n")

    drift_events = [r for r in records
                    if r.get("event") == "quality_drift"]
    if drift_events:
        w("  quality drift episodes:\n")
        for r in drift_events:
            w(f"    {r.get('endpoint', '?'):<16} {r.get('state', '?'):<6}"
              f" psi {r.get('psi', 0.0):.3f}"
              f" (threshold {r.get('threshold', 0.0):g},"
              f" window {r.get('window', '?')})\n")
    shadow = shadow_rollup(records)
    if shadow["rungs"] or shadow["errors"]:
        w("  shadow comparisons (agreement@τ vs full quality):\n")
        for rung, agg in sorted(shadow["rungs"].items()):
            w(f"    rung {rung:<3} x{agg['count']:<5}"
              f" mean agree {agg['mean']:.4f}"
              f"  min {agg['min']:.4f}"
              f"  bitwise {agg['bitwise']}/{agg['count']}"
              f"  seeded {agg['seeded']}\n")
        if shadow["errors"]:
            w(f"    {shadow['errors']} comparison error(s)\n")

    spans = span_rollup(records)
    if spans:
        w("  spans:\n")
        for name, agg in sorted(spans.items(),
                                key=lambda kv: -kv[1]["total_s"]):
            w(f"    {name:<28} x{agg['count']:<5} total "
              f"{agg['total_s']:8.2f}s  mean {agg['mean_s']:.3f}s  "
              f"max {agg['max_s']:.3f}s\n")

    tree = span_tree(records)
    if tree:
        w("  span tree (traced):\n")
        # Lexicographic path order keeps children under their parent;
        # indentation = depth.
        for path, agg in sorted(tree.items()):
            indent = "  " * (len(path) - 1)
            label = indent + path[-1]
            w(f"    {label:<28} x{agg['count']:<5} total "
              f"{agg['total_s']:8.2f}s  mean {agg['mean_s']:.3f}s  "
              f"max {agg['max_s']:.3f}s\n")
        slow = slowest_spans(records, n=10)
        w("  slowest spans:\n")
        for r in slow:
            tid = (r.get("trace_id") or "-")[:8]
            w(f"    {r['event']:<28} {r['dur_s']:9.3f}s  trace {tid}\n")

    metrics = final_metrics(records)
    if metrics:
        w("  final metrics:\n")
        for name, v in sorted(metrics.items(),
                              key=lambda kv: _series_parts(kv[0])):
            w(f"    {name:<40} {v:g}\n")


def diff_metrics(
    a: Dict[str, float], b: Dict[str, float], threshold: float,
) -> List[dict]:
    """Rows {name, a, b, delta, rel, flagged} over the union of metrics.

    rel is (b - a) / |a| (None when a == 0 or the metric is one-sided);
    flagged when |rel| >= threshold — direction-agnostic, because the
    reader knows which direction is a regression for each metric and
    the threshold's job is only to separate noise from signal.
    """
    rows = []
    # Sort by (base, labels) so a labeled family's children sit together
    # under the unlabeled parent, in stable label order.
    for name in sorted(set(a) | set(b), key=_series_parts):
        va, vb = a.get(name), b.get(name)
        delta = rel = None
        if va is not None and vb is not None:
            delta = vb - va
            if va != 0:
                rel = delta / abs(va)
        flagged = rel is not None and abs(rel) >= threshold and delta != 0
        rows.append({"name": name, "a": va, "b": vb,
                     "delta": delta, "rel": rel, "flagged": flagged})
    return rows


def render_diff(rows: List[dict], path_a: str, path_b: str,
                out=None) -> int:
    w = (out or sys.stdout).write
    w(f"A: {path_a}\nB: {path_b}\n")
    w(f"{'metric':<40} {'A':>12} {'B':>12} {'delta':>12} {'rel':>8}\n")
    n_flagged = 0
    for r in rows:
        fa = f"{r['a']:g}" if r["a"] is not None else "-"
        fb = f"{r['b']:g}" if r["b"] is not None else "-"
        fd = f"{r['delta']:+g}" if r["delta"] is not None else "-"
        fr = f"{r['rel']:+.1%}" if r["rel"] is not None else "-"
        mark = "  <-- FLAGGED" if r["flagged"] else ""
        if r["flagged"]:
            n_flagged += 1
        w(f"{r['name']:<40} {fa:>12} {fb:>12} {fd:>12} {fr:>8}{mark}\n")
    w(f"{n_flagged} metric(s) past threshold\n")
    return n_flagged


def render_join(paths: List[str], record_sets: List[List[dict]],
                out=None) -> None:
    """One cross-process span tree over N runlogs, joined by span ids.

    Wire propagation (X-NCNet-Trace) makes trace/span ids global, so
    concatenating the record sets lets ``span_tree`` resolve a server
    span's ``remote_parent`` edge into the client's own span — the
    joined tree shows a /v1/match request as client.request →
    client.attempt → request → admit/... in ONE rooted tree. Durations
    are wall-clock per process (no skew correction here — that's
    tools/trace_export.py's job, which emits aligned timelines).
    """
    w = (out or sys.stdout).write
    merged: List[dict] = []
    w(f"joined trace view over {len(paths)} log(s):\n")
    for path, records in zip(paths, record_sets):
        start = next((r for r in records
                      if r.get("event") == "run_start"), {})
        comp = start.get("component", "?")
        w(f"  {path}  component={comp}"
          f"  pid={start.get('pid')}  spans="
          f"{sum(1 for r in _spans(records) if r.get('span_id'))}\n")
        merged.extend(records)
    traces = {r["trace_id"] for r in _spans(merged) if r.get("trace_id")}
    w(f"  joined traces: {len(traces)}\n")
    tree = span_tree(merged)
    if not tree:
        w("  no traced spans\n")
        return
    w("  cross-process span tree:\n")
    for path_key, agg in sorted(tree.items()):
        indent = "  " * (len(path_key) - 1)
        label = indent + path_key[-1]
        w(f"    {label:<36} x{agg['count']:<5} total "
          f"{agg['total_s']:8.2f}s  mean {agg['mean_s']:.3f}s  "
          f"max {agg['max_s']:.3f}s\n")
    unresolved = [p for p in tree if any(
        part.startswith("<remote ") or part == "<orphaned>"
        for part in p)]
    if unresolved:
        w(f"  {len(unresolved)} path(s) still unresolved — a parent's "
          f"runlog is missing from the join\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logs", nargs="+", help="run-log JSONL file(s)")
    ap.add_argument("--join", action="store_true",
                    help="merge all logs and render one cross-process "
                         "span tree (spans joined by trace/span ids)")
    ap.add_argument("--diff", action="store_true",
                    help="diff the final metrics of exactly two runs")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative delta at/above which a diff row is "
                         "flagged (default 0.05)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when the diff flags any metric")
    args = ap.parse_args(argv)

    if args.join:
        if args.diff:
            ap.error("--join and --diff are mutually exclusive")
        render_join(args.logs, [load_run(p) for p in args.logs])
        return 0

    if args.diff:
        if len(args.logs) != 2:
            ap.error("--diff takes exactly two run logs")
        a, b = (final_metrics(load_run(p)) for p in args.logs)
        n_flagged = render_diff(
            diff_metrics(a, b, args.threshold), args.logs[0], args.logs[1])
        return 1 if (args.strict and n_flagged) else 0

    for path in args.logs:
        summarize(path, load_run(path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
