"""Learning-signal experiment: does weak-supervision training lift PCK?

Builds a fully synthetic PF-Pascal-layout dataset (random smooth textures;
pairs are known warps, so ground-truth keypoint correspondences are exact),
measures keypoint-transfer PCK with the UNTRAINED model, trains with
`cli.train` (the weak loss of reference train.py:110-156), and measures
again. Report-only (exit 0 either way) — see the finding below.

FINDING (2026-07-30, CPU, no pretrained weights available offline): with a
RANDOMLY-INITIALIZED backbone the weak loss decreases (pos-vs-rolled-neg
discrimination improves: -1e-6 -> -2e-4 over 300 steps) while PCK drops
(e.g. 9.4% -> 0% on translation-only pairs; per-keypoint transfer errors
grow 2-3x). The loss can be satisfied by a texture-identity shortcut —
sharpening SOME peak for same-texture pairs — which only aligns with
geometrically correct peaks when the backbone features are themselves
meaningful (ImageNet-pretrained, as the reference assumes:
lib/model.py:25-44 downloads torchvision weights). The loss/gradient math
itself is golden-tested against the reference formulation
(tests/test_model.py::test_weak_loss_feature_roll_equals_image_roll), so
re-run this experiment for a positive signal once pretrained weights are
fetchable (docs/NEXT.md).

SEED TABLE (2026-08-02, --corpus parts --epochs 50 --pretrain_steps 300,
delta_pct = trained - untrained PCK): s0 +15.63, s1 -2.08, s2 +9.38,
s3 -1.04, s4 0.00, s5 -2.09 (mean +3.3). Bimodal: two of six seeds
learn genuine correspondence (9-17% PCK from ~1%), the rest sit at the
±2-keypoint noise floor; the paired random-backbone arms (-1.04 both
seeds run) still collapse. So the weak loss demonstrably CAN improve a
model whose features are meaningful — the round-2..4 "fixed point"
was a random-features property — while seed-robustness on this tiny
synthetic corpus is limited; the definitive check (ImageNet weights +
real PF-Pascal) remains egress-gated.

Runs on CPU in a few minutes:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python tools/sanity_train_improves_pck.py --out /tmp/sanity_pck
"""

import argparse
import csv
import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _texture(rng, size, cells=12):
    t = rng.random((cells, cells, 3))
    t = np.kron(t, np.ones((size // cells, size // cells, 1)))
    t = (t[:size, :size] * 255).astype("uint8")
    # kron comes up short when cells doesn't divide size; every caller
    # (dataset writer, pretrain batcher) needs exactly size x size.
    ph, pw = size - t.shape[0], size - t.shape[1]
    if ph or pw:
        t = np.pad(t, ((0, ph), (0, pw), (0, 0)), mode="edge")
    return t


def _affine(rng, size, max_rot=0.0, max_scale=0.0, max_shift=0.15):
    """Random affine M mapping TARGET pixel coords -> SOURCE pixel coords.

    Defaults are TRANSLATION-only: without downloadable ImageNet weights
    the backbone is randomly initialized, and random conv features are
    translation-equivariant but have no rotation/scale invariance — rotated
    pairs would be noise-level matchable regardless of the consensus stack,
    telling us nothing about the training signal."""
    a = rng.uniform(-max_rot, max_rot)
    s = 1.0 + rng.uniform(-max_scale, max_scale)
    c, r = np.cos(a) * s, np.sin(a) * s
    t = rng.uniform(-max_shift, max_shift, 2) * size
    center = size / 2.0
    M = np.array([[c, -r, 0.0], [r, c, 0.0]])
    M[:, 2] = center - M[:, :2] @ [center, center] + t
    return M


def _warp(img, M):
    from scipy.ndimage import map_coordinates

    h, w = img.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    src = np.einsum("ij,jhw->ihw", M, np.stack(
        [xs, ys, np.ones_like(xs)]).astype(np.float64))
    out = np.stack(
        [
            map_coordinates(img[..., ch].astype(np.float64), [src[1], src[0]],
                            order=1, mode="reflect")
            for ch in range(img.shape[2])
        ],
        axis=-1,
    )
    return out.astype("uint8")


def build_parts_dataset(root, rng, size=96, n_train=24, n_val=4,
                        n_test=8, n_kp=6, n_categories=4):
    """INTER-INSTANCE pairs: n_categories part-layout categories, each
    pair = two independently-drawn instances of ONE category (own affine
    placement, own appearance jitter, own background). Matching requires
    part-identity features, not pixel identity — the regime PF-Pascal's
    intra-class pairs live in.

    Multiple categories are ESSENTIAL for the weak loss: it forms
    negatives by rolling within the batch (training/loss.py), and with a
    single category a rolled "negative" is indistinguishable from a
    positive — the loss then correctly suppresses all scores and the
    model collapses (measured 2026-08-02: pretrained 14.58% -> 0.00%
    after 50 epochs on a 1-category corpus). Categories are written
    round-robin, but cli/train.py shuffles each epoch, so a roll-by-1
    negative is merely cross-category with HIGH PROBABILITY
    (~1 - (n_per_cat-1)/(N-1)); occasional same-category "negatives"
    remain — which IS the PF-Pascal regime (the reference train.py:88
    also shuffles, and its 20-class batches collide the same way)."""
    os.makedirs(os.path.join(root, "images"), exist_ok=True)
    os.makedirs(os.path.join(root, "image_pairs"), exist_ok=True)
    from PIL import Image

    # Per-category definition, fixed for the corpus: canonical part
    # positions + identity colors (part k of category c is findable
    # across that category's instances, and looks unlike category c').
    layouts = [rng.uniform(0.30, 0.70, (n_kp, 2)) * size
               for _ in range(n_categories)]
    colors = [rng.uniform(80, 255, (n_kp, 3)) for _ in range(n_categories)]
    radius = size * 0.055

    def instance(cat):
        M = _affine(rng, size)
        centers = layouts[cat] @ M[:, :2].T + M[:, 2]
        img = _texture(rng, size, cells=int(rng.integers(6, 12))) * 0.25
        ys, xs = np.meshgrid(np.arange(size), np.arange(size),
                             indexing="ij")
        for k in range(n_kp):
            col = np.clip(colors[cat][k] + rng.normal(0, 18, 3), 0, 255)
            r_k = radius * float(rng.uniform(0.85, 1.15))
            d2 = (xs - centers[k, 0]) ** 2 + (ys - centers[k, 1]) ** 2
            w = np.exp(-d2 / (2.0 * r_k * r_k))[..., None]
            img = img * (1 - w) + col * w
        return img.astype("uint8"), centers

    def make_pair(i, cat):
        src, kp_src = instance(cat)
        tgt, kp_tgt = instance(cat)
        sn, tn = f"images/s{i}.png", f"images/t{i}.png"
        Image.fromarray(src).save(os.path.join(root, sn))
        Image.fromarray(tgt).save(os.path.join(root, tn))
        return sn, tn, kp_src, kp_tgt

    for split, n in (("train_pairs", n_train), ("val_pairs", n_val)):
        with open(os.path.join(root, "image_pairs", f"{split}.csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["source_image", "target_image", "class", "flip"])
            for i in range(n):
                cat = i % n_categories  # round-robin: see docstring
                sn, tn, _, _ = make_pair(f"{split}_{i}", cat)
                w.writerow([sn, tn, cat + 1, 0])

    with open(os.path.join(root, "image_pairs", "test_pairs.csv"), "w",
              newline="") as f:
        w = csv.writer(f)
        w.writerow(["source_image", "target_image", "class",
                    "XA", "YA", "XB", "YB"])
        for i in range(n_test):
            cat = i % n_categories
            sn, tn, kp_src, kp_tgt = make_pair(f"test_{i}", cat)
            w.writerow([
                sn, tn, cat + 1,
                ";".join(f"{v:.2f}" for v in kp_src[:, 0]),
                ";".join(f"{v:.2f}" for v in kp_src[:, 1]),
                ";".join(f"{v:.2f}" for v in kp_tgt[:, 0]),
                ";".join(f"{v:.2f}" for v in kp_tgt[:, 1]),
            ])


def build_dataset(root, rng, size=96, n_train=24, n_val=4, n_test=8, n_kp=8):
    os.makedirs(os.path.join(root, "images"), exist_ok=True)
    os.makedirs(os.path.join(root, "image_pairs"), exist_ok=True)
    from PIL import Image

    def make_pair(i):
        src = _texture(rng, size, cells=int(rng.integers(8, 16)))
        M = _affine(rng, size)
        tgt = _warp(src, M)
        sn, tn = f"images/s{i}.png", f"images/t{i}.png"
        Image.fromarray(src).save(os.path.join(root, sn))
        Image.fromarray(tgt).save(os.path.join(root, tn))
        return sn, tn, M

    for split, n in (("train_pairs", n_train), ("val_pairs", n_val)):
        with open(os.path.join(root, "image_pairs", f"{split}.csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["source_image", "target_image", "class", "flip"])
            for i in range(n):
                sn, tn, _ = make_pair(f"{split}_{i}")
                w.writerow([sn, tn, 1, 0])

    with open(os.path.join(root, "image_pairs", "test_pairs.csv"), "w",
              newline="") as f:
        w = csv.writer(f)
        w.writerow(["source_image", "target_image", "class",
                    "XA", "YA", "XB", "YB"])
        for i in range(n_test):
            sn, tn, M = make_pair(f"test_{i}")
            # Target keypoints on an interior grid; source = M @ target.
            m = size * 0.25
            kp = rng.uniform(m, size - m, (n_kp, 2))
            src_kp = kp @ M[:, :2].T + M[:, 2]
            w.writerow([
                sn, tn, 1,
                ";".join(f"{v:.2f}" for v in src_kp[:, 0]),
                ";".join(f"{v:.2f}" for v in src_kp[:, 1]),
                ";".join(f"{v:.2f}" for v in kp[:, 0]),
                ";".join(f"{v:.2f}" for v in kp[:, 1]),
            ])


def pretrain_backbone(config, params, steps, rng, size, batch=4,
                      lr=1e-3, tau=0.1, log_every=25):
    """Self-supervised correspondence pretraining of the backbone
    (VERDICT r3 item 7c: the best non-random features available offline).

    InfoNCE over known-warp pairs: for each target feature cell, the
    positive is the SOURCE feature bilinearly sampled at the cell's
    ground-truth (affine-mapped) location, negatives are every other
    cell's sample. This directly optimizes what the PCK hypothesis needs
    — spatially localized, discriminative features — using only the
    synthetic texture generator (no ImageNet, no egress). The weak-loss
    training afterwards keeps the backbone FROZEN (the reference's
    default), so any PCK delta is attributable to the consensus training
    signal operating on meaningful vs random features.

    Returns (backbone_params, final_contrastive_accuracy).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ncnet_tpu.data.normalization import normalize_image
    from ncnet_tpu.geometry.grid import grid_sample
    from ncnet_tpu.models.backbone import backbone_apply
    from ncnet_tpu.ops.correlation import feature_l2norm

    # Feature stride from one probe forward.
    probe = jnp.zeros((1, 3, size, size), jnp.float32)
    fh, fw = jax.eval_shape(
        lambda p, x: backbone_apply(config.backbone, p, x),
        params["backbone"], probe,
    ).shape[2:]
    stride = size // fh

    def gen_batch():
        srcs, tgts, mats = [], [], []
        for _ in range(batch):
            img = _texture(rng, size, cells=int(rng.integers(8, 16)))
            M = _affine(rng, size)
            tgts.append(normalize_image(
                np.moveaxis(_warp(img, M), -1, 0).astype(np.float32) / 255.0
            ))
            srcs.append(normalize_image(
                np.moveaxis(img, -1, 0).astype(np.float32) / 255.0
            ))
            mats.append(M.astype(np.float32))
        return (np.stack(srcs), np.stack(tgts), np.stack(mats))

    # Target cell centers in pixel coords (all fh*fw cells).
    ii, jj = np.meshgrid(np.arange(fh), np.arange(fw), indexing="ij")
    centers = np.stack(
        [jj.ravel() * stride + (stride - 1) / 2.0,
         ii.ravel() * stride + (stride - 1) / 2.0], axis=-1
    ).astype(np.float32)  # [P, 2] as (x, y)
    n_pts = centers.shape[0]

    def loss_fn(bb_params, src, tgt, M):
        fa = feature_l2norm(backbone_apply(config.backbone, bb_params, src))
        fb = feature_l2norm(backbone_apply(config.backbone, bb_params, tgt))
        b, c = fa.shape[0], fa.shape[1]
        # Ground-truth source pixel of each target cell center, per pair.
        pts = jnp.asarray(centers)  # [P, 2]
        src_px = (
            jnp.einsum("bij,pj->bpi", M[:, :, :2], pts) + M[:, :, 2][:, None, :]
        )  # [B, P, 2] (x, y)
        # Pixel -> feature coords -> corner-aligned normalized grid.
        fxy = (src_px - (stride - 1) / 2.0) / stride
        gx = 2.0 * fxy[..., 0] / (fw - 1) - 1.0
        gy = 2.0 * fxy[..., 1] / (fh - 1) - 1.0
        grid = jnp.stack([gx, gy], axis=-1)[:, :, None, :]  # [B, P, 1, 2]
        fa_s = grid_sample(fa, grid)[..., 0]  # [B, C, P]
        fa_s = jnp.moveaxis(fa_s, 1, 2)  # [B, P, C]
        fb_flat = fb.reshape(b, c, n_pts).transpose(0, 2, 1)  # [B, P, C]
        logits = jnp.einsum("bpc,bqc->bpq", fb_flat, fa_s) / tau
        labels = jnp.arange(n_pts)
        # Only cells whose GT source lies inside the feature grid.
        valid = (
            (fxy[..., 0] >= 0) & (fxy[..., 0] <= fw - 1)
            & (fxy[..., 1] >= 0) & (fxy[..., 1] <= fh - 1)
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.broadcast_to(labels, (b, n_pts))
        )
        loss = jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1)
        acc = jnp.sum(
            (jnp.argmax(logits, axis=-1) == labels) * valid
        ) / jnp.maximum(jnp.sum(valid), 1)
        return loss, acc

    tx = optax.adam(lr)
    bb_params = params["backbone"]
    opt_state = tx.init(bb_params)

    @jax.jit
    def step(bb_params, opt_state, src, tgt, M):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            bb_params, src, tgt, M
        )
        updates, opt_state = tx.update(grads, opt_state, bb_params)
        return optax.apply_updates(bb_params, updates), opt_state, loss, acc

    acc = 0.0
    for i in range(steps):
        src, tgt, M = gen_batch()
        bb_params, opt_state, loss, acc = step(
            bb_params, opt_state, jnp.asarray(src), jnp.asarray(tgt),
            jnp.asarray(M)
        )
        if i % log_every == 0 or i == steps - 1:
            print(f"pretrain step {i}: nce loss {float(loss):.4f} "
                  f"acc {float(acc) * 100:.1f}%", flush=True)
    return jax.tree.map(np.asarray, bb_params), float(acc)


def run_pck(root, ckpt, image_size):
    import contextlib
    import io

    from ncnet_tpu.cli import eval_pf_pascal

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        eval_pf_pascal.main([
            "--checkpoint", ckpt,
            "--eval_dataset_path", root,
            "--image_size", str(image_size),
            "--batch_size", "4",
            "--pck_procedure", "pf",
        ])
    out = buf.getvalue()
    m = re.search(r"PCK[^0-9]*([0-9.]+)%", out)
    assert m, out
    return float(m.group(1))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/sanity_pck")
    p.add_argument("--size", type=int, default=96)
    p.add_argument("--image_size", type=int, default=96)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    # VERDICT r3 item 7c: N>0 pretrains the backbone with self-supervised
    # correspondence InfoNCE before the weak-loss training, testing the
    # "meaningful features flip the PCK direction" prediction offline.
    p.add_argument("--pretrain_steps", type=int, default=0)
    # 'warp' = same-image affine pairs (the item-7c fixed-point corpus);
    # 'parts' = inter-instance pairs of one part-layout category —
    # appearance differs, geometry correlates, the PF-Pascal regime.
    p.add_argument("--corpus", choices=("warp", "parts"), default="warp")
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    root = args.out
    if args.corpus == "parts":
        # 16 test pairs x 6 kp = 96 keypoints: ~1% PCK resolution (the
        # 48-step warp-corpus table was noise-limited at 64 kp).
        build_parts_dataset(root, rng, size=args.size, n_test=16)
    else:
        build_dataset(root, rng, size=args.size)
    print(f"synthetic {args.corpus}-pair dataset under {root}")

    import jax

    from ncnet_tpu.cli import train as train_cli
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.training.checkpoint import save_checkpoint

    # Untrained reference point: the same architecture at init.
    config = NCNetConfig(
        backbone=BackboneConfig(cnn="vgg"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
    )
    params = jax.tree.map(
        np.asarray, ncnet_init(jax.random.PRNGKey(args.seed), config)
    )
    nce_acc = None
    if args.pretrain_steps > 0:
        print(f"pretraining backbone ({args.pretrain_steps} InfoNCE steps)")
        bb, nce_acc = pretrain_backbone(
            config, params, args.pretrain_steps, rng, args.size
        )
        params = dict(params, backbone=bb)
    init_ckpt = save_checkpoint(os.path.join(root, "init"), params, config, 0)
    pck_before = run_pck(root, init_ckpt, args.image_size)
    print(f"PCK untrained: {pck_before:.2f}%")

    train_cli.main([
        "--dataset_image_path", root,
        "--dataset_csv_path", os.path.join(root, "image_pairs"),
        "--num_epochs", str(args.epochs),
        "--batch_size", "4",
        "--image_size", str(args.image_size),
        "--backbone", "vgg",
        "--ncons_kernel_sizes", "3", "3",
        "--ncons_channels", "16", "1",
        "--checkpoint", init_ckpt,
        "--result_model_dir", os.path.join(root, "models"),
        "--num_workers", "2",
        "--seed", str(args.seed),
        "--log_interval", "10",
    ])
    # Newest run dir: re-runs into the same --out leave older runs behind.
    runs = os.path.join(root, "models")
    run = max(os.listdir(runs), key=lambda d: os.path.getmtime(os.path.join(runs, d)))
    best = os.path.join(runs, run, "best")
    pck_after = run_pck(root, best, args.image_size)
    print(f"PCK trained:   {pck_after:.2f}%")
    print(json.dumps({
        "pck_untrained_pct": pck_before,
        "pck_trained_pct": pck_after,
        "delta_pct": round(pck_after - pck_before, 2),
        "pretrain_steps": args.pretrain_steps,
        "pretrain_nce_acc_pct": (
            round(nce_acc * 100, 1) if nce_acc is not None else None
        ),
        "note": (
            "pretrained features: the hypothesis predicts a positive delta"
            if args.pretrain_steps > 0 else
            "random backbone: see module docstring before reading "
            "a negative delta as a training-stack bug"
        ),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
