"""Learning-signal experiment: does weak-supervision training lift PCK?

Builds a fully synthetic PF-Pascal-layout dataset (random smooth textures;
pairs are known warps, so ground-truth keypoint correspondences are exact),
measures keypoint-transfer PCK with the UNTRAINED model, trains with
`cli.train` (the weak loss of reference train.py:110-156), and measures
again. Report-only (exit 0 either way) — see the finding below.

FINDING (2026-07-30, CPU, no pretrained weights available offline): with a
RANDOMLY-INITIALIZED backbone the weak loss decreases (pos-vs-rolled-neg
discrimination improves: -1e-6 -> -2e-4 over 300 steps) while PCK drops
(e.g. 9.4% -> 0% on translation-only pairs; per-keypoint transfer errors
grow 2-3x). The loss can be satisfied by a texture-identity shortcut —
sharpening SOME peak for same-texture pairs — which only aligns with
geometrically correct peaks when the backbone features are themselves
meaningful (ImageNet-pretrained, as the reference assumes:
lib/model.py:25-44 downloads torchvision weights). The loss/gradient math
itself is golden-tested against the reference formulation
(tests/test_model.py::test_weak_loss_feature_roll_equals_image_roll), so
re-run this experiment for a positive signal once pretrained weights are
fetchable (docs/NEXT.md).

Runs on CPU in a few minutes:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python tools/sanity_train_improves_pck.py --out /tmp/sanity_pck
"""

import argparse
import csv
import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _texture(rng, size, cells=12):
    t = rng.random((cells, cells, 3))
    t = np.kron(t, np.ones((size // cells, size // cells, 1)))
    return (t[:size, :size] * 255).astype("uint8")


def _affine(rng, size, max_rot=0.0, max_scale=0.0, max_shift=0.15):
    """Random affine M mapping TARGET pixel coords -> SOURCE pixel coords.

    Defaults are TRANSLATION-only: without downloadable ImageNet weights
    the backbone is randomly initialized, and random conv features are
    translation-equivariant but have no rotation/scale invariance — rotated
    pairs would be noise-level matchable regardless of the consensus stack,
    telling us nothing about the training signal."""
    a = rng.uniform(-max_rot, max_rot)
    s = 1.0 + rng.uniform(-max_scale, max_scale)
    c, r = np.cos(a) * s, np.sin(a) * s
    t = rng.uniform(-max_shift, max_shift, 2) * size
    center = size / 2.0
    M = np.array([[c, -r, 0.0], [r, c, 0.0]])
    M[:, 2] = center - M[:, :2] @ [center, center] + t
    return M


def _warp(img, M):
    from scipy.ndimage import map_coordinates

    h, w = img.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    src = np.einsum("ij,jhw->ihw", M, np.stack(
        [xs, ys, np.ones_like(xs)]).astype(np.float64))
    out = np.stack(
        [
            map_coordinates(img[..., ch].astype(np.float64), [src[1], src[0]],
                            order=1, mode="reflect")
            for ch in range(img.shape[2])
        ],
        axis=-1,
    )
    return out.astype("uint8")


def build_dataset(root, rng, size=96, n_train=24, n_val=4, n_test=8, n_kp=8):
    os.makedirs(os.path.join(root, "images"), exist_ok=True)
    os.makedirs(os.path.join(root, "image_pairs"), exist_ok=True)
    from PIL import Image

    def make_pair(i):
        src = _texture(rng, size, cells=int(rng.integers(8, 16)))
        M = _affine(rng, size)
        tgt = _warp(src, M)
        sn, tn = f"images/s{i}.png", f"images/t{i}.png"
        Image.fromarray(src).save(os.path.join(root, sn))
        Image.fromarray(tgt).save(os.path.join(root, tn))
        return sn, tn, M

    for split, n in (("train_pairs", n_train), ("val_pairs", n_val)):
        with open(os.path.join(root, "image_pairs", f"{split}.csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["source_image", "target_image", "class", "flip"])
            for i in range(n):
                sn, tn, _ = make_pair(f"{split}_{i}")
                w.writerow([sn, tn, 1, 0])

    with open(os.path.join(root, "image_pairs", "test_pairs.csv"), "w",
              newline="") as f:
        w = csv.writer(f)
        w.writerow(["source_image", "target_image", "class",
                    "XA", "YA", "XB", "YB"])
        for i in range(n_test):
            sn, tn, M = make_pair(f"test_{i}")
            # Target keypoints on an interior grid; source = M @ target.
            m = size * 0.25
            kp = rng.uniform(m, size - m, (n_kp, 2))
            src_kp = kp @ M[:, :2].T + M[:, 2]
            w.writerow([
                sn, tn, 1,
                ";".join(f"{v:.2f}" for v in src_kp[:, 0]),
                ";".join(f"{v:.2f}" for v in src_kp[:, 1]),
                ";".join(f"{v:.2f}" for v in kp[:, 0]),
                ";".join(f"{v:.2f}" for v in kp[:, 1]),
            ])


def run_pck(root, ckpt, image_size):
    import contextlib
    import io

    from ncnet_tpu.cli import eval_pf_pascal

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        eval_pf_pascal.main([
            "--checkpoint", ckpt,
            "--eval_dataset_path", root,
            "--image_size", str(image_size),
            "--batch_size", "4",
            "--pck_procedure", "pf",
        ])
    out = buf.getvalue()
    m = re.search(r"PCK[^0-9]*([0-9.]+)%", out)
    assert m, out
    return float(m.group(1))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/sanity_pck")
    p.add_argument("--size", type=int, default=96)
    p.add_argument("--image_size", type=int, default=96)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    root = args.out
    build_dataset(root, rng, size=args.size)
    print(f"synthetic affine-pair dataset under {root}")

    import jax

    from ncnet_tpu.cli import train as train_cli
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.training.checkpoint import save_checkpoint

    # Untrained reference point: the same architecture at init.
    config = NCNetConfig(
        backbone=BackboneConfig(cnn="vgg"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
    )
    params = jax.tree.map(
        np.asarray, ncnet_init(jax.random.PRNGKey(args.seed), config)
    )
    init_ckpt = save_checkpoint(os.path.join(root, "init"), params, config, 0)
    pck_before = run_pck(root, init_ckpt, args.image_size)
    print(f"PCK untrained: {pck_before:.2f}%")

    train_cli.main([
        "--dataset_image_path", root,
        "--dataset_csv_path", os.path.join(root, "image_pairs"),
        "--num_epochs", str(args.epochs),
        "--batch_size", "4",
        "--image_size", str(args.image_size),
        "--backbone", "vgg",
        "--ncons_kernel_sizes", "3", "3",
        "--ncons_channels", "16", "1",
        "--checkpoint", init_ckpt,
        "--result_model_dir", os.path.join(root, "models"),
        "--num_workers", "2",
        "--seed", str(args.seed),
        "--log_interval", "10",
    ])
    # Newest run dir: re-runs into the same --out leave older runs behind.
    runs = os.path.join(root, "models")
    run = max(os.listdir(runs), key=lambda d: os.path.getmtime(os.path.join(runs, d)))
    best = os.path.join(runs, run, "best")
    pck_after = run_pck(root, best, args.image_size)
    print(f"PCK trained:   {pck_after:.2f}%")
    print(json.dumps({
        "pck_untrained_pct": pck_before,
        "pck_trained_pct": pck_after,
        "delta_pct": round(pck_after - pck_before, 2),
        "note": "random backbone: see module docstring before reading "
                "a negative delta as a training-stack bug",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
