#!/bin/bash
# Background TPU probe + experiment queue (round 2).
#
# The axon tunnel is single-session and can be down for hours (docs/NEXT.md,
# round 1): keep EXACTLY ONE dialer alive, retry with sleeps, and the moment
# a dial succeeds run the whole hardware queue while the tunnel lasts.
# Breaks only on a non-cpu_smoke bench metric (or attempt cap).
cd /root/repo || exit 1
OUT=docs/tpu_r03
mkdir -p "$OUT"
for n in $(seq 1 90); do
  echo "=== attempt $n $(date -u +%FT%TZ) ===" >> "$OUT/probe.log"
  NCNET_BENCH_DIAL_TIMEOUT=600 NCNET_BENCH_SMOKE_SIZE=64 \
    python bench.py > "$OUT/bench_last.json" 2>> "$OUT/probe.log"
  if grep -q '"inloc_dense_match_pairs_per_s_per_chip"' "$OUT/bench_last.json"; then
    cp "$OUT/bench_last.json" "$OUT/bench_tpu.json"
    echo "=== TPU UP at attempt $n — running queue ===" >> "$OUT/probe.log"
    python tools/pallas_tpu_smoke.py --dial_timeout 600 \
      > "$OUT/pallas_smoke.txt" 2>&1
    python tools/profile_inloc.py --dial_timeout 600 \
      > "$OUT/profile_inloc.txt" 2>&1
    python tools/bench_conv4d.py --dial_timeout 600 --iters 3 \
      > "$OUT/bench_conv4d.txt" 2>&1
    python tools/bench_train.py > "$OUT/bench_train.txt" 2>&1
    echo "=== queue DONE $(date -u +%FT%TZ) ===" >> "$OUT/probe.log"
    exit 0
  fi
  sleep 240
done
echo "=== gave up after 90 attempts ===" >> "$OUT/probe.log"
exit 3
