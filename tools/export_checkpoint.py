"""Delegate: the implementation lives in ncnet_tpu.cli.export_checkpoint
(installable as the `ncnet-export-checkpoint` console script); this
path is kept so `python tools/export_checkpoint.py` keeps working from a checkout."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ncnet_tpu.cli.export_checkpoint import main

if __name__ == "__main__":
    sys.exit(main())
