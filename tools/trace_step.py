"""Capture a device trace of the headline block and print the op table.

The step bisect gives true per-STAGE costs, but two of them resist
stage-level explanation (in-step corr+pool costs 2.5x its standalone
chained time; consensus 115 ms vs a ~26 ms traffic roofline). A device
trace answers at the op level. This tool runs the exact bench.py block
under jax.profiler.trace and parses the xplane with
tensorboard_plugin_profile (installed in this image), printing the
top ops by self time into the session log — no TensorBoard needed.

Usage:
    python tools/trace_step.py [--dial_timeout 600] [--image 3200]
Trace artifacts land in docs/tpu_r02/trace/ for later inspection.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_T0 = time.time()


def log(msg):
    print(f"[{time.time() - _T0:7.1f}s] {msg}", flush=True)


def _print_op_table(logdir):
    """Parse the captured xplane and print top ops by self time.

    Runs in THIS process only when invoked with --parse_only (a fresh
    process where no protobuf has been imported yet): the plugin's
    generated protos need PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python,
    which must be set before the first google.protobuf import.
    """
    xplanes = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.xplane.pb")
    )
    if not xplanes:
        log("no xplane captured")
        return
    # The logdir accumulates one timestamped dir per run — parse the
    # NEWEST capture, not directory order.
    xplanes = [max(xplanes, key=os.path.getmtime)]
    # Parse the XSpace proto directly (the tensorboard plugin's converter
    # needs a TF pywrap symbol this build lacks): aggregate event
    # durations by op name over the device plane's lines.
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    with open(xplanes[0], "rb") as f:
        space.ParseFromString(f.read())
    # Prefer the accelerator plane; '/host:CPU' is the CPU-smoke fallback.
    planes = sorted(
        space.planes,
        key=lambda p: (("TPU" not in p.name) and ("device" not in p.name.lower()),
                       p.name != "/host:CPU"),
    )
    for plane in planes:
        if plane.name in ("/host:metadata", "Task Environment"):
            continue
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        # Hierarchical lines (modules > ops > ...) overlap in time —
        # summing across all of them double-counts and lets a whole-module
        # event top the table. Aggregate ONE line: the op-granularity one
        # ('XLA Ops' on TPU planes), falling back to the busiest line.
        lines = list(plane.lines)
        if not lines:
            continue
        op_lines = [l for l in lines if "op" in l.name.lower()]
        line = (op_lines or sorted(lines, key=lambda l: -len(l.events)))[0]
        totals = {}
        for ev in line.events:
            name = meta.get(ev.metadata_id, str(ev.metadata_id))
            totals[name] = totals.get(name, 0) + ev.duration_ps
        if not totals:
            continue
        top = sorted(totals.items(), key=lambda kv: -kv[1])[:30]
        total_us = sum(totals.values()) / 1e6
        log(f"plane {plane.name}, line '{line.name}': {len(totals)} "
            f"distinct events, {total_us:.0f} us total (2 traced steps)")
        for name, ps in top:
            log(f"  {ps / 1e6:>10.0f} us  {name[:100]}")
        return
    log(f"no device plane found in {xplanes[0]} "
        f"(planes: {[p.name for p in space.planes][:8]})")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dial_timeout", type=float, default=600.0)
    p.add_argument("--image", type=int, default=3200)
    p.add_argument("--iters", type=int, default=3)  # accepted for session API
    p.add_argument("--logdir", type=str, default="docs/tpu_r05/trace")
    p.add_argument("--parse_only", action="store_true")
    args = p.parse_args(argv)

    if args.parse_only:
        # Must precede the first google.protobuf import (fresh process).
        os.environ.setdefault(
            "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python"
        )
        _print_op_table(args.logdir)
        return

    import jax

    from ncnet_tpu.utils.profiling import dial_devices, setup_compile_cache

    setup_compile_cache()
    devices = dial_devices(args.dial_timeout)
    if devices is None:
        log("backend dial timed out; aborting")
        os._exit(2)
    log(f"devices: {devices}")

    import jax.numpy as jnp

    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape, resolve_feat_units
    from ncnet_tpu.evals import inloc_device_matches
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import (
        extract_features,
        ncnet_forward_from_features,
    )

    config = NCNetConfig(
        backbone=BackboneConfig(compute_dtype="bfloat16"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
        use_fused_corr_pool=True,
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)
    units = resolve_feat_units(
        int(os.environ.get("NCNET_INLOC_FEAT_UNIT", "-1")), args.image, 2
    )
    h, w = inloc_resize_shape(
        args.image, args.image * 3 // 4, args.image, 2,
        h_unit=units[0], w_unit=units[1],
    )
    log(f"image {h}x{w}")
    key = jax.random.PRNGKey(1)
    src = jax.random.normal(key, (1, 3, h, w), jnp.float32)
    tgt = jax.random.normal(jax.random.PRNGKey(2), (1, 3, h, w), jnp.float32)

    @jax.jit
    def step(params, src, tgt):
        feat_a = extract_features(config, params, src)
        feat_b = extract_features(config, params, tgt)
        corr, delta = ncnet_forward_from_features(config, params, feat_a, feat_b)
        m = inloc_device_matches(corr, delta4d=delta, k_size=2)
        return sum(jnp.sum(v.astype(jnp.float32)) for v in m)

    log("compile+warm...")
    float(step(params, src, tgt))
    log("tracing 2 steps...")
    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        for _ in range(2):
            float(step(params, src, tgt))
    log("parsing (subprocess: the proto impl env must precede any "
        "protobuf import, and jax already imported one here)...")
    import subprocess

    env = dict(
        os.environ,
        PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION="python",
        JAX_PLATFORMS="cpu",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # parse must not dial the tunnel
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--parse_only",
         "--logdir", args.logdir],
        env=env, capture_output=True, text=True, timeout=600,
    )
    print(out.stdout, flush=True)
    if out.returncode:
        log(f"parse subprocess rc={out.returncode}: {out.stderr[-800:]}")


if __name__ == "__main__":
    main()
