"""Delegate: the implementation lives in ncnet_tpu.cli.convert_checkpoint
(installable as the `ncnet-convert-checkpoint` console script); this
path is kept so `python tools/convert_checkpoint.py` keeps working from a checkout."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ncnet_tpu.cli.convert_checkpoint import main

if __name__ == "__main__":
    sys.exit(main())
