"""Honest steady-state throughput of the cross-query pano feature cache.

VERDICT r4 weak #5: the bench's `featcache-hit` mode measures the
ALL-HITS bound (12.39 pairs/s/chip on v5e, bf16 entries); the honest
steady state depends on the real pano hit-rate over the InLoc eval's
356-query x top-10 shortlist (`densePE_top100_shortlist_cvpr18.mat`,
reference eval_inloc.py:34-35,103-104), which this sandbox cannot
download. This tool measures the hit-rate on a POSE-GROUNDED replay of
that shortlist structure instead:

- Query stream: the 329 GT-registered InLoc queries from the reference's
  committed `lib_matlab/DUC_refposes_all.mat` (DUC1 198 + DUC2 131), in
  list order (capture order — the locality the LRU actually sees). Each
  entry carries the query's camera pose P and the scan it registered to.
- Database model: InLoc's retrieval database is perspective cutouts,
  12 yaw x 3 pitch = 36 per scan (InLoc dataset convention). Scan
  positions are approximated by the centroid of the camera centers of
  the queries registered to each scan.
- Retrieval surrogate: per query, cutouts score by scan distance plus
  yaw mismatch against the query's viewing direction, top-10 kept —
  a NetVLAD-shaped stand-in with the right spatial locality.
- Cache: the REAL `PanoFeatureCache` (byte-bounded LRU), default budget
  (eval_inloc `--pano_feature_cache_mb` 4096), real per-entry bytes for
  the production feature shape (1024 x 192 x 144 bf16 at the 3072x2304
  resize bucket = 56.6 MB/pano). Entries are `np.broadcast_to` views:
  `nbytes` reports the full virtual size, so accounting is honest while
  the replay allocates nothing.

Blended throughput folds the measured miss/hit rates (9.69 / 12.39
pairs/s/chip, same warm-cache session) over the simulated miss/hit
counts. The
retrieval surrogate is the one modeled component — the sweep over its
locality knobs (and a no-locality worst case) brackets the answer.

Run: python tools/cache_steady_state.py [--refposes PATH] [--json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import ml_dtypes  # ships with jax
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ncnet_tpu.evals.feature_cache import PanoFeatureCache  # noqa: E402

REFPOSES_DEFAULT = "/root/reference/lib_matlab/DUC_refposes_all.mat"

# Production feature-cache entry: resnet101 conv4 features of one pano at
# the 3072x2304 resize bucket (feat stride 16 -> 192x144, 1024 ch, bf16 —
# the miss program rounds features through bf16 before the store, lossless
# downstream because every correlation path casts to bf16 first).
ENTRY_SHAPE = (1024, 192, 144)
ENTRY_DTYPE = ml_dtypes.bfloat16

# Round-5 driver-unit rates, pairs/s/chip (2026-08-02 late-round pair on
# the same warm cache: cold 9.6916 / all-hits 12.3888 with the bf16
# feature stack; the five-run anchor scatter is 9.67-9.84).
MISS_RATE = 9.6916
HIT_RATE = 12.3888

YAWS = 12          # cutouts per scan: 12 yaw x 3 pitch (InLoc convention)
PITCHES = 3
TOP_K = 10


def load_queries(refposes_path: str):
    """[(building, name, C(3,), yaw, scan_id)] in capture order."""
    from scipy.io import loadmat

    m = loadmat(refposes_path)
    out = []
    for bld in ("DUC1_RefList", "DUC2_RefList"):
        for e in m[bld][0]:
            P = np.asarray(e["P"], np.float64)
            R, t = P[:, :3], P[:, 3]
            C = -R.T @ t
            # Camera forward axis in world frame; yaw on the floor plane.
            fwd = R.T @ np.array([0.0, 0.0, 1.0])
            yaw = math.atan2(fwd[1], fwd[0])
            out.append((bld[:4], str(e["queryname"][0]), C, yaw,
                        str(e["reldbname"][0])))
    return out


def synthetic_queries(n_per_bld=(198, 131), seed=0):
    """Fallback stream when the refposes .mat is unavailable: a random
    walk along corridors with a scan every few steps — same shape of
    locality, none of the real geometry."""
    rng = np.random.default_rng(seed)
    out = []
    for b, n in enumerate(n_per_bld):
        pos = np.zeros(2)
        heading = 0.0
        for i in range(n):
            heading += float(rng.normal(0, 0.4))
            pos = pos + 1.5 * np.array([math.cos(heading),
                                        math.sin(heading)])
            scan = f"B{b}_scan_{int(i // 3):03d}"
            out.append((f"B{b}", f"q{i:04d}",
                        np.array([pos[0], pos[1], 1.5]), heading, scan))
    return out


def build_scans(queries):
    """scan_id -> centroid position of its registered queries."""
    acc = {}
    for _, _, C, _, scan in queries:
        acc.setdefault(scan, []).append(C)
    return {s: np.mean(cs, axis=0) for s, cs in acc.items()}


def shortlist(q, scans, dist_scale=5.0, yaw_weight=1.0):
    """Top-10 cutout paths for one query under the retrieval surrogate.

    Score = distance(query, scan)/dist_scale + yaw_weight * yaw mismatch,
    where the mismatch is the smaller of the cutout-facing's wrapped
    difference to (a) the query's own viewing direction (both look at
    the same scene) and (b) the scan->query bearing (the cutout shows
    the area the query stands in) — either makes a retrieval-plausible
    cutout. dist_scale=inf, yaw_weight=0 degrades to nearest-scan-only.
    """
    _, _, C, q_yaw, _ = q

    def angdiff(a, b):
        return abs((a - b + math.pi) % (2 * math.pi) - math.pi)

    cands = []
    for scan, pos in scans.items():
        d = float(np.linalg.norm((C - pos)[:2]))
        bearing = math.atan2(C[1] - pos[1], C[0] - pos[0])
        for yi in range(YAWS):
            cut_yaw = 2 * math.pi * yi / YAWS - math.pi
            dy = min(angdiff(cut_yaw, q_yaw), angdiff(cut_yaw, bearing))
            for pi in range(PITCHES):
                score = d / dist_scale + yaw_weight * dy \
                    + 0.1 * abs(pi - 1)
                cands.append((score, f"{scan}/cutout_{yi:02d}_{pi}.jpg"))
    cands.sort()
    return [p for _, p in cands[:TOP_K]]


def build_shortlists(queries, scans, dist_scale=5.0, yaw_weight=1.0):
    """One top-10 cutout list per query (computed once per param set)."""
    lists = []
    for q in queries:
        # DUC1 and DUC2 use independent coordinate frames — retrieval
        # must only see the query's own building.
        bld_scans = {s: p for s, p in scans.items() if s.startswith(q[0])}
        lists.append(shortlist(q, bld_scans, dist_scale, yaw_weight))
    return lists


def replay(shortlists, cache_mb, disk_tier=False):
    """Drive the real cache over precomputed shortlists; return stats.

    disk_tier models eval_inloc --pano_feature_cache_dir WITHOUT the
    57 MB-per-pano npz writes: an unbounded disk tier makes every
    revisit a hit (get() promotes disk hits back into the memory LRU),
    so feeding the real cache an effectively-infinite memory budget
    reproduces the same hit/miss accounting the disk tier would see.
    """
    entry = np.broadcast_to(np.zeros((), ENTRY_DTYPE), ENTRY_SHAPE)
    shape = (3072, 2304)
    budget = (1 << 62) if disk_tier else cache_mb * 1024 * 1024
    cache = PanoFeatureCache(budget)
    uniq = set()
    for cuts in shortlists:
        for cut in cuts:
            uniq.add(cut)
            if cache.get(cut, shape) is None:
                cache.put(cut, shape, entry)
    total = cache.hits + cache.misses
    hit_frac = cache.hits / total
    blended = total / (cache.misses / MISS_RATE + cache.hits / HIT_RATE)
    return dict(
        pairs=total, unique_panos=len(uniq), hits=cache.hits,
        misses=cache.misses,
        hit_rate=round(hit_frac, 4),
        blended_pairs_per_s=round(blended, 4),
        resident_capacity=(None if disk_tier
                           else cache.max_bytes // entry.nbytes),
    )


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--refposes", default=REFPOSES_DEFAULT)
    p.add_argument("--cache_mb", type=int, nargs="*",
                   default=[4096, 8192, 16384])
    p.add_argument("--json", action="store_true",
                   help="one JSON line instead of the table")
    p.add_argument("--synthetic", action="store_true",
                   help="force the no-refposes fallback stream")
    args = p.parse_args(argv)

    if not args.synthetic and os.path.exists(args.refposes):
        queries = load_queries(args.refposes)
        source = args.refposes
    else:
        queries = synthetic_queries()
        source = "synthetic-walk"
    scans = build_scans(queries)

    results = {}
    default_lists = build_shortlists(queries, scans)
    for mb in args.cache_mb:
        results[f"mem_{mb}mb"] = replay(default_lists, mb)
    # Disk tier: every revisit hits (promotes back to mem LRU).
    results["disk_tier"] = replay(default_lists, 0, disk_tier=True)
    # Locality sensitivity at the default budget: tighter / looser
    # retrieval neighborhoods bracket the surrogate's one free knob.
    for ds, yw, label in ((2.0, 2.0, "tight"), (10.0, 0.5, "loose")):
        results[f"mem_4096mb_{label}"] = replay(
            build_shortlists(queries, scans, ds, yw), 4096)
    # Pessimistic pool: the refposes file only names scans with >=1
    # registered query (58), but the DUC database has ~277 scans —
    # unobserved scans still appear in real shortlists and dilute the
    # overlap. Interpolate distractor scans between each scan and its
    # two nearest same-building neighbors (corridor geometry) to triple
    # the pool.
    aug = dict(scans)
    for s, p in scans.items():
        bld = s[:4]
        near = sorted(
            (float(np.linalg.norm((p - p2)[:2])), s2)
            for s2, p2 in scans.items() if s2 != s and s2[:4] == bld
        )[:2]
        for i, (_, s2) in enumerate(near):
            aug[f"{bld}/distractor_{s.split('/')[-1]}_{i}"] = (
                (p + scans[s2]) / 2.0)
    results["mem_4096mb_distractors"] = replay(
        build_shortlists(queries, aug), 4096)

    out = dict(
        source=source, n_queries=len(queries), n_scans=len(scans),
        top_k=TOP_K, entry_mb=round(
            float(np.prod(ENTRY_SHAPE)) * np.dtype(ENTRY_DTYPE).itemsize
            / 1e6, 1),
        miss_rate=MISS_RATE, hit_rate_bound=HIT_RATE, results=results,
    )
    if args.json:
        print(json.dumps(out))
    else:
        print(f"stream: {out['n_queries']} queries ({source}), "
              f"{out['n_scans']} scans, top-{TOP_K} cutout shortlist")
        print(f"entry: {out['entry_mb']} MB "
              f"({ENTRY_SHAPE} {np.dtype(ENTRY_DTYPE).name})")
        for label, r in results.items():
            res = r["resident_capacity"]
            print(f"  {label:22} unique={r['unique_panos']:4d} "
                  f"hit={r['hit_rate']:.1%} "
                  f"resident={'inf' if res is None else res:>4} "
                  f"blended={r['blended_pairs_per_s']:.2f} pairs/s/chip")
    return out


if __name__ == "__main__":
    main()
