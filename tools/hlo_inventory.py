"""Static data-movement inventory of the headline block (no device needed).

The session_1128 utilization tables put the scan-batched bench block's
"other" stage at 77-99 ms/pair moving ~5.5 GB/pair at <10% HBM
efficiency — but the capture that attributes it op-by-op only exists on
hardware, and the tunnel wedges. This tool gets the STRUCTURAL half
offline: it builds the exact bench block at TPU shapes, lowers it with
jax.jit(...).lower() (abstract shapes only — works on CPU), and sums
RESULT bytes of the data-movement StableHLO ops (transpose / gather /
concatenate / pad / convert / dynamic-slice/update) grouped by the
source file:line in their location metadata. Result bytes overstate
broadcast/iota/pad (they read less than they write) and understate
gather-style ops (huge operand, tiny result); and XLA will fuse much of
this away — treat the table as "tensor volume flowing through movement
ops", a candidate list for the hardware trace to confirm, not traffic.

Usage: JAX_PLATFORMS=cpu python tools/hlo_inventory.py [--panos 10] [--bb 5]
"""

from __future__ import annotations

import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

MOVE_OPS = (
    "transpose", "gather", "scatter", "concatenate", "pad",
    "dynamic_slice", "dynamic_update_slice", "convert", "reverse",
    "broadcast_in_dim", "iota", "reshape",
)

_TY = re.compile(r"tensor<([0-9x]+)x(f32|bf16|f16|i32|s32|i8|u8|i64|s64|i1)>")
_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "i32": 4, "s32": 4, "i8": 1,
          "u8": 1, "i1": 1, "i64": 8, "s64": 8}
_LOC = re.compile(r'"([^"]+\.py)":(\d+)')
_LOC_NAME = re.compile(r'loc\("([^"]*)"')


def tensor_bytes(ty: str) -> int:
    m = _TY.search(ty)
    if not m:
        return 0
    dims, dt = m.groups()
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    return n * _BYTES[dt]


def source_of(line: str, locs: dict) -> str:
    """Resolve a (possibly nested: named-loc / callsite / alias) location
    to file:line, preferring project frames over jax-internal ones."""
    m = re.search(r"loc\(#loc(\d+)\)", line)
    if m:
        line = locs.get(m.group(1), line)
    # Expand #locN refs transitively (the table nests named locs around
    # callsites around file locs).
    for _ in range(8):
        if "#loc" not in line:
            break
        # re.sub (not str.replace): replacing "#loc1" textually would
        # corrupt longer refs like "#loc12" on the same line.
        line = re.sub(r"#loc(\d+)",
                      lambda m: locs.get(m.group(1), ""), line)
    files = _LOC.findall(line)
    if files:
        for f, n in files:
            if "/ncnet_tpu/" in f or "/tools/" in f:
                return f"{f}:{n}"
        return f"{files[0][0]}:{files[0][1]}"
    m = _LOC_NAME.search(line)
    if m:
        return m.group(1)
    return "?"


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--panos", type=int, default=10)
    p.add_argument("--bb", type=int, default=0,
                   help="pano-backbone batch (0 = current default)")
    p.add_argument("--image", type=int, default=3200)
    p.add_argument("--top", type=int, default=28)
    args = p.parse_args(argv)
    if args.bb:
        os.environ["NCNET_PANO_BACKBONE_BATCH"] = str(args.bb)

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape, resolve_feat_units
    from ncnet_tpu.evals import inloc_device_matches
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import (
        extract_features,
        ncnet_forward_from_features,
    )

    config = NCNetConfig(
        backbone=BackboneConfig(compute_dtype="bfloat16"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
        use_fused_corr_pool=True,
        fused_impl="xla",  # lowerable without Mosaic; same surrounding glue
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)
    units = resolve_feat_units(-1, args.image, 2)
    h, w = inloc_resize_shape(args.image, args.image * 3 // 4, args.image, 2,
                              h_unit=units[0], w_unit=units[1])
    print(f"block: {args.panos} panos at {h}x{w}", flush=True)

    bb = args.bb or int(os.environ.get("NCNET_PANO_BACKBONE_BATCH", "5") or 5)

    def step(params, feat_a, tgt_feat):
        corr, delta = ncnet_forward_from_features(
            config, params, feat_a, tgt_feat, final_mutual=True
        )
        return inloc_device_matches(corr, delta4d=delta, k_size=2)

    def block(params, src, tgts):
        feat_a = extract_features(config, params, src)

        # Mirror bench.py's structure: bb>1 hoists batched pano backbones
        # out of the scan; bb<=1 keeps the backbone INSIDE the scan body.
        # (A structurally different program here would make the inventory
        # incomparable to the traced bench block.)
        if bb > 1:
            from ncnet_tpu.cli.eval_inloc import _bb_group_size

            n = tgts.shape[0]
            nb = _bb_group_size(n, bb)
            groups = tgts.reshape(n // nb, nb, *tgts.shape[1:])
            # Direct batched extract over each group — the exact call
            # bench.py makes. (vmap-of-batch-1 inserts extra broadcast/
            # reshape ops into the unoptimized StableHLO and skews the
            # movement-byte inventory this tool exists to mirror.)
            feats = jax.lax.map(
                lambda g: extract_features(config, params, g), groups
            )
            feats = feats.reshape(n, 1, *feats.shape[2:])

            def body(_, tf):
                return None, step(params, feat_a, tf)

            _, ms = jax.lax.scan(body, None, feats)
            return ms

        def body_full(_, t):
            tf = extract_features(config, params, t[None])[0]
            return None, step(params, feat_a, tf[None])

        _, ms = jax.lax.scan(body_full, None, tgts)
        return ms

    src = jax.ShapeDtypeStruct((1, 3, h, w), jnp.float32)
    tgts = jax.ShapeDtypeStruct((args.panos, 3, h, w), jnp.float32)
    lowered = jax.jit(block).lower(params, src, tgts)
    try:
        text = lowered.as_text(debug_info=True)
    except TypeError:  # older jax: debug info always present
        text = lowered.as_text()
    print(f"stablehlo: {len(text) / 1e6:.1f} MB", flush=True)

    # Trailing location table (#locN = "file":line:col)
    locs = {}
    for m in re.finditer(r"#loc(\d+) = loc\((.*)\)$", text, re.M):
        locs[m.group(1)] = m.group(2)
    # alias chains: #loc5 = loc(#loc3)
    for k, v in list(locs.items()):
        m = re.fullmatch(r"#loc(\d+)", v)
        if m:
            locs[k] = locs.get(m.group(1), v)

    by_srcop = collections.Counter()
    for line in text.splitlines():
        ls = line.lstrip()
        if not ls.startswith("%"):
            continue
        m = re.search(r"stablehlo\.(\w+)", ls)
        if not m or m.group(1) not in MOVE_OPS:
            continue
        op = m.group(1)
        nbytes = tensor_bytes(ls.rsplit("->", 1)[-1] if "->" in ls else ls)
        src_file = source_of(ls, locs)
        # strip to repo-relative tail
        sf = re.sub(r"^.*/(ncnet_tpu|tools)/", r"\1/", src_file)
        sf = re.sub(r'".*', "", sf).split(";")[0]
        by_srcop[(op, sf)] += nbytes

    print("\n-- data-movement output bytes by (op, source), top "
          f"{args.top} (UNOPTIMIZED: XLA fuses much of this) --")
    for (op, sf), b in by_srcop.most_common(args.top):
        print(f"  {b / 1e9:8.2f} GB  {op:<22} {sf}")


if __name__ == "__main__":
    main()
