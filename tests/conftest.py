"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip sharding tests run on a simulated mesh
(`--xla_force_host_platform_device_count=8`), the TPU-world substitute for
multi-node fixtures (SURVEY.md §4).

Platform handling: this environment's sitecustomize registers the axon TPU
PJRT plugin in every python process and overrides the `jax_platforms` config
to "axon,cpu", which would dial the (single-session) TPU tunnel from the test
runner. Tests must run CPU-only, so the config is forced back to "cpu" before
any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache (per-user AND per-machine path: /tmp may persist
# across heterogeneous hosts, and XLA:CPU AOT entries from another CPU type
# warn and risk SIGILL). Threshold 0 caches everything — the suite is made
# of many small programs that individually compile fast but add up.
import tempfile

from ncnet_tpu.utils.profiling import machine_tag

jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "NCNET_TEST_COMPILE_CACHE",
        os.path.join(
            tempfile.gettempdir(),
            f"ncnet_tpu_test_cache_{os.getuid()}_{machine_tag()}",
        ),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
