"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip sharding tests run on a simulated mesh
(`--xla_force_host_platform_device_count=8`), the TPU-world substitute for
multi-node fixtures (SURVEY.md §4).

Platform handling: this environment's sitecustomize registers the axon TPU
PJRT plugin in every python process and overrides the `jax_platforms` config
to "axon,cpu", which would dial the (single-session) TPU tunnel from the test
runner. Tests must run CPU-only, so the config is forced back to "cpu" before
any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache (per-user AND per-machine path: /tmp may persist
# across heterogeneous hosts, and XLA:CPU AOT entries from another CPU type
# warn and risk SIGILL). Threshold 0 caches everything — the suite is made
# of many small programs that individually compile fast but add up.
import tempfile

from ncnet_tpu.utils.profiling import machine_tag

jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "NCNET_TEST_COMPILE_CACHE",
        os.path.join(
            tempfile.gettempdir(),
            f"ncnet_tpu_test_cache_{os.getuid()}_{machine_tag()}",
        ),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import json

import numpy as np
import pytest


def pytest_configure(config):
    """NCNET_RACE_CANARY=1 arms the dynamic race canary: every
    `# guarded-by:` lock / single-writer annotation in the repo becomes
    a per-write runtime assertion (docs/ANALYSIS.md "Race canary"), so
    this very suite doubles as a sanitizer pass over the annotations."""
    if os.environ.get("NCNET_RACE_CANARY") == "1":
        from ncnet_tpu.analysis.canary import install_canaries

        installed = install_canaries()
        config._ncnet_race_canaries = installed
        print(f"[race-canary] armed {len(installed)} annotated "
              f"field(s)", flush=True)


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture(scope="session", autouse=True)
def _pin_flight_dir(tmp_path_factory):
    """Pin flight-recorder dumps to a session tmp dir.

    obs/flight.py falls back NCNET_FLIGHT_DIR > run-log dir > cwd; a
    test that trips a dump outside an init_run would otherwise litter
    the repo root with flight-*.jsonl files (docs/OBSERVABILITY.md).
    Tests that assert on dumps still monkeypatch their own dir — that
    override wins per-test and restores to this pin. Also clears any
    ambient NCNET_REPLICA_ID so label assertions see only what a test
    sets itself."""
    os.environ["NCNET_FLIGHT_DIR"] = str(
        tmp_path_factory.mktemp("flight_dumps"))
    os.environ.pop("NCNET_REPLICA_ID", None)
    yield


@pytest.fixture(autouse=True)
def _reset_obs_metrics():
    """The obs default registry is process-global (one CLI run per
    process in production); zero it per test so metric assertions see
    only their own run's increments. The slow-request reservoir is
    process-global for the same reason — clear it too, or serving
    tests earlier in the suite (whose first-compile requests are the
    slowest thing the process ever sees) evict later tests' entries.
    Same story for the flight ring and its per-reason dump cooldown: a
    dump asserted by one test must contain only that test's records and
    must not be rate-limited by a breach three tests ago. And for the
    quality monitor's drift detectors: a reference window frozen from
    one test's score stream would misread every later test as drift."""
    from ncnet_tpu import obs

    obs.reset()
    obs.exemplar.reservoir().clear()
    obs.flight.recorder().clear()
    obs.quality.monitor().clear()
    yield


@pytest.fixture(autouse=True)
def _clear_failpoints():
    """The failpoint registry is process-global (armed from the env in
    production); disarm everything per test so one test's chaos cannot
    leak into another's happy path."""
    from ncnet_tpu.reliability import failpoints

    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture(scope="session")
def tiny_serving_model():
    """Session-shared tiny model for the serving tests (the eval CLI
    smoke config: k_size 2, small consensus stack, bf16 backbone).
    Session-scoped because params init is the expensive part; each test
    builds its own engine/server around these."""
    from ncnet_tpu.cli.common import build_model

    return build_model(
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=2,
        half_precision=True,
        backbone_bf16=True,
    )


def assert_valid_runlog(path, component=None):
    """Schema check for an obs run log (docs/OBSERVABILITY.md).

    Shared by the CLI flow tests (train, eval_inloc) and test_obs.py:
    every line carries the envelope (schema v1 or v2 — v2 adds the
    additive trace fields) with one run_id; the run opens with
    run_start (host/git/args metadata), records >= 1 heartbeat and
    >= 1 metrics snapshot, and closes with run_end. Traced span records
    must form a valid tree: every non-null parent_id resolves to a
    span_id in the same log — except spans marked ``remote_parent``,
    whose parent lives in the CALLER's runlog across the
    ``X-NCNet-Trace`` wire boundary by design. Rotated logs
    (NCNET_RUNLOG_MAX_MB) are validated over their whole segment set.
    Returns the parsed records (all segments, oldest first).
    """
    from ncnet_tpu.obs.events import runlog_segments

    records = []
    for seg in runlog_segments(str(path)):
        with open(seg, encoding="utf-8") as fh:
            records.extend(json.loads(line) for line in fh if line.strip())
    assert records, f"empty run log {path}"
    names = [r["event"] for r in records]
    for r in records:
        assert r["v"] in (1, 2)
        assert r["run_id"] == records[0]["run_id"]
        assert isinstance(r["event"], str)
        assert isinstance(r["t_wall"], float)
        assert isinstance(r["t_mono"], float)
    # Traced spans must form a valid tree: every span has an id, and every
    # non-root parent_id resolves. Non-span events may carry a bare trace_id
    # for correlation (e.g. serving's `request` summary event).
    span_ids = {r["span_id"] for r in records if r.get("span_id")}
    for r in records:
        if r.get("kind") == "span" and r.get("trace_id"):
            assert r.get("span_id"), f"traced span missing span_id: {r}"
            if r.get("parent_id") is not None and not r.get("remote_parent"):
                assert r["parent_id"] in span_ids, (
                    f"unresolved parent_id in {r}"
                )
    start = records[0]
    assert start["event"] == "run_start"
    assert start["schema"] in (1, 2)
    if component is not None:
        assert start["component"] == component
    for key in ("argv", "hostname", "pid", "python"):
        assert key in start
    assert names[-1] == "run_end"
    assert "status" in records[-1] and "dur_s" in records[-1]
    assert "heartbeat" in names
    snaps = [r for r in records if r["event"] == "metrics"]
    assert snaps, "no metrics snapshot in run log"
    for snap in snaps:
        assert set(snap["snapshot"]) == {"counters", "gauges", "histograms"}
    return records
