"""Bidirectional extraction-statistics kernel vs its XLA oracle.

The kernel (ops/extract_kernel.py) computes both matching directions'
max / first-wins argmax / online sumexp in one sweep; these tests pin it —
in interpret mode, which exercises the exact grid/accumulator logic —
against the straightforward XLA formulation, including ragged tile tails,
duplicate-max tie-breaking, bf16 storage rounding, and the fused
mutual-filter prologue. End-to-end: the fused inloc extraction paths must
reproduce the corr_to_matches-based formulation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.evals.inloc import (
    _raw_matches_stats,
    _raw_matches_xla,
    inloc_matches_from_consensus,
)
from ncnet_tpu.ops.extract_kernel import (
    bidir_extract_stats_pallas,
    bidir_extract_stats_xla,
    bidir_maxes_pallas,
)
from ncnet_tpu.ops.mutual import mutual_matching


def _assert_stats_equal(got, want, softmax, rtol=1e-6):
    for (gm, ga, gs), (wm, wa, ws), name in zip(got, want, ("row", "col")):
        np.testing.assert_allclose(gm, wm, rtol=rtol, err_msg=f"{name} max")
        np.testing.assert_array_equal(ga, wa, err_msg=f"{name} argmax")
        if softmax:
            np.testing.assert_allclose(
                gs, ws, rtol=1e-5, err_msg=f"{name} sumexp"
            )


@pytest.mark.parametrize("softmax", [True, False])
@pytest.mark.parametrize(
    "shape,tiles",
    [
        ((16, 128), (8, 128)),  # exact tiling
        ((50, 70), (16, 128)),  # ragged rows + block wider than the array
        ((23, 300), (8, 128)),  # ragged both axes, multi-tile columns
        ((40, 256), (16, 128)),  # multiple row and column tiles
    ],
)
def test_stats_kernel_matches_oracle(softmax, shape, tiles):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    got = bidir_extract_stats_pallas(
        x, do_softmax=softmax, tile_m=tiles[0], tile_n=tiles[1],
        interpret=True,
    )
    want = bidir_extract_stats_xla(x, do_softmax=softmax)
    _assert_stats_equal(got, want, softmax)


def test_stats_kernel_first_wins_ties():
    # Small integer values -> exact representation; plant duplicate maxima
    # within one tile and across tiles on both axes.
    x = jnp.zeros((20, 260), jnp.float32)
    x = x.at[3, 7].set(5.0).at[3, 200].set(5.0).at[3, 250].set(5.0)
    x = x.at[11, 40].set(2.0).at[17, 40].set(2.0)
    got = bidir_extract_stats_pallas(
        x, do_softmax=False, tile_m=8, tile_n=128, interpret=True
    )
    want = bidir_extract_stats_xla(x, do_softmax=False)
    _assert_stats_equal(got, want, False)
    assert int(got[0][1][3]) == 7  # first of the three row maxima
    assert int(got[1][1][40]) == 11  # first of the two column maxima


def test_stats_kernel_bf16_input():
    x = jax.random.normal(jax.random.PRNGKey(1), (30, 200), jnp.float32)
    xb = x.astype(jnp.bfloat16)
    got = bidir_extract_stats_pallas(
        xb, do_softmax=True, tile_m=8, tile_n=128, interpret=True
    )
    want = bidir_extract_stats_xla(xb, do_softmax=True)
    _assert_stats_equal(got, want, True)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_stats_kernel_fused_mutual(dtype):
    # The mutual prologue must reproduce mutual_matching -> oracle stats,
    # including the storage-dtype rounding of the filtered values.
    key = jax.random.PRNGKey(2)
    c = jax.random.uniform(key, (1, 1, 6, 5, 7, 4), jnp.float32).astype(dtype)
    x2d = c.reshape(30, 28)
    maxes = bidir_maxes_pallas(x2d, tile_m=8, tile_n=128, interpret=True)
    got = bidir_extract_stats_pallas(
        x2d, do_softmax=True, row_col_max=maxes, tile_m=8, tile_n=128,
        interpret=True,
    )
    filtered = mutual_matching(c).astype(jnp.float32).reshape(30, 28)
    want = bidir_extract_stats_xla(filtered, do_softmax=True)
    _assert_stats_equal(got, want, True, rtol=1e-5)


@pytest.mark.parametrize("softmax", [True, False])
@pytest.mark.parametrize("with_delta", [True, False])
def test_raw_matches_stats_path_equals_xla(softmax, with_delta):
    key = jax.random.PRNGKey(3)
    c = jax.random.uniform(key, (1, 1, 6, 5, 7, 4), jnp.float32)
    k_size, delta = 1, None
    if with_delta:
        k_size = 2
        delta = jax.random.randint(
            jax.random.PRNGKey(4), c.shape, 0, 16
        ).astype(jnp.int32)
    got = _raw_matches_stats(c, delta, k_size, softmax, interpret=True)
    want = _raw_matches_xla(c, delta, k_size, softmax)
    # Coordinates are exact (same integer indices); scores agree to fp
    # tolerance (1/sumexp vs exp(max - logsumexp) round differently).
    for g, w, name in zip(got[:4], want[:4], "xa ya xb yb".split()):
        np.testing.assert_array_equal(g, w, err_msg=name)
    np.testing.assert_allclose(got[4], want[4], rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_inloc_matches_from_consensus_parity(dtype):
    """Fused mutual+extraction == materialize-then-extract, end to end
    (sorted + recentered outputs), on a tie-free random tensor."""
    from ncnet_tpu.evals.inloc import inloc_device_matches

    key = jax.random.PRNGKey(5)
    consensus = jax.random.uniform(
        key, (1, 1, 4, 6, 5, 3), jnp.float32
    ).astype(dtype)
    got = inloc_matches_from_consensus(
        consensus, k_size=1, impl="pallas", interpret=True
    )
    filtered = mutual_matching(consensus).astype(jnp.float32)
    want = inloc_device_matches(filtered, k_size=1, impl="xla")
    # The sort key (score) differs in ulps between the formulations; with
    # distinct random scores the permutation is identical.
    for g, w, name in zip(got, want, "xa ya xb yb score".split()):
        np.testing.assert_allclose(
            g, w, rtol=1e-4, atol=1e-6, err_msg=name
        )


def test_inloc_device_matches_impl_knob_unknown():
    c = jnp.zeros((1, 1, 2, 2, 2, 2))
    from ncnet_tpu.evals.inloc import inloc_device_matches

    with pytest.raises(ValueError, match="unknown extraction impl"):
        inloc_device_matches(c, impl="nope")
