"""Native C++ LO-RANSAC P3P vs the numpy implementation.

Both backends implement the same Grunert minimal solver + Horn/Kabsch
pose-from-distances + object-space LO (reference stage:
lib_matlab/parfor_NC4D_PE_pnponly.m:77), so on synthetic problems they
must agree on the recovered pose and inlier set even though their RANSAC
sampling streams differ.
"""

import numpy as np
import pytest

from ncnet_tpu import native
from ncnet_tpu.localization.pnp import lo_ransac_p3p, p3p_solve

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _random_problem(seed, n=80, n_outliers=0, noise=0.0):
    rng = np.random.default_rng(seed)
    # Random proper rotation via QR.
    A = rng.normal(size=(3, 3))
    Q, R_ = np.linalg.qr(A)
    Q *= np.sign(np.diag(R_))
    if np.linalg.det(Q) < 0:
        Q[:, 0] *= -1
    t = rng.normal(size=3)
    X = rng.normal(size=(n, 3)) * 2.0
    cam = X @ Q.T + t
    # Push the cloud in front of the camera.
    shift = np.array([0.0, 0.0, 5.0 - cam[:, 2].min()])
    cam = cam + shift
    t = t + shift
    rays = cam / np.linalg.norm(cam, axis=1, keepdims=True)
    if noise:
        rays = rays + rng.normal(size=rays.shape) * noise
        rays /= np.linalg.norm(rays, axis=1, keepdims=True)
    if n_outliers:
        idx = rng.choice(n, size=n_outliers, replace=False)
        bad = rng.normal(size=(n_outliers, 3))
        rays[idx] = bad / np.linalg.norm(bad, axis=1, keepdims=True)
        inlier_mask = np.ones(n, dtype=bool)
        inlier_mask[idx] = False
    else:
        inlier_mask = np.ones(n, dtype=bool)
    return rays, X, Q, t, inlier_mask


def test_exact_recovery():
    rays, X, R, t, _ = _random_problem(0)
    res = native.lo_ransac_p3p_native(
        rays, X, inlier_thr=np.deg2rad(0.2), max_iters=1000, seed=1
    )
    assert res.ok
    assert res.num_inliers == X.shape[0]
    np.testing.assert_allclose(res.P[:, :3], R, atol=1e-9)
    np.testing.assert_allclose(res.P[:, 3], t, atol=1e-8)


def test_outlier_rejection_matches_numpy():
    rays, X, R, t, mask = _random_problem(3, n=120, n_outliers=40)
    thr = np.deg2rad(0.2)
    res_nat = native.lo_ransac_p3p_native(rays, X, thr, max_iters=2000, seed=5)
    res_np = lo_ransac_p3p(rays, X, thr, max_iters=2000, seed=5, backend="numpy")
    assert res_nat.ok and res_np.ok
    # Same inlier set (the true one) and same pose up to solver precision.
    np.testing.assert_array_equal(res_nat.inliers, mask)
    np.testing.assert_array_equal(res_np.inliers, mask)
    np.testing.assert_allclose(res_nat.P, res_np.P, atol=1e-6)
    np.testing.assert_allclose(res_nat.P[:, :3], R, atol=1e-8)


def test_noisy_problem_pose_close():
    rays, X, R, t, _ = _random_problem(7, n=200, noise=1e-4)
    thr = np.deg2rad(0.2)
    res = native.lo_ransac_p3p_native(rays, X, thr, max_iters=2000, seed=2)
    assert res.ok
    assert res.num_inliers > 150
    assert np.abs(res.P[:, :3] - R).max() < 5e-3
    assert res.inlier_error < thr


def test_minimal_solver_parity_with_numpy():
    rng = np.random.default_rng(11)
    for trial in range(20):
        rays, X, _, _, _ = _random_problem(100 + trial, n=3)
        nat = native.p3p_solve_native(rays, X)  # [k, 3, 4]
        ref = p3p_solve(rays[None], X[None])[0]  # [4, 3, 4] NaN-padded
        ref = ref[np.all(np.isfinite(ref), axis=(1, 2))]
        assert nat.shape[0] >= 1
        # Every numpy solution has a native counterpart (order-free match).
        for P in ref:
            dists = np.abs(nat - P).reshape(nat.shape[0], -1).max(axis=1)
            assert dists.min() < 1e-6, f"trial {trial}: unmatched pose"


def test_determinism_across_calls():
    rays, X, _, _, _ = _random_problem(13, n=60, n_outliers=10)
    thr = np.deg2rad(0.2)
    a = native.lo_ransac_p3p_native(rays, X, thr, max_iters=500, seed=9)
    b = native.lo_ransac_p3p_native(rays, X, thr, max_iters=500, seed=9)
    np.testing.assert_array_equal(a.P, b.P)
    np.testing.assert_array_equal(a.inliers, b.inliers)


def test_degenerate_inputs():
    res = native.lo_ransac_p3p_native(
        np.zeros((2, 3)), np.zeros((2, 3)), 0.01, max_iters=10
    )
    assert not res.ok
    # Collinear world points: solver must not crash.
    X = np.stack([np.arange(10.0)] * 3, axis=1)  # points on a line
    rays = np.tile(np.array([0.0, 0.0, 1.0]), (10, 1))
    native.lo_ransac_p3p_native(rays, X, 0.01, max_iters=50)


def test_auto_backend_dispatches_native():
    rays, X, R, t, _ = _random_problem(21)
    res = lo_ransac_p3p(rays, X, np.deg2rad(0.2), max_iters=500, seed=0)
    assert res.ok
    np.testing.assert_allclose(res.P[:, :3], R, atol=1e-8)


def test_input_validation():
    with pytest.raises(ValueError):
        native.lo_ransac_p3p_native(np.zeros((80, 3)), np.zeros((50, 3)), 0.01)
    with pytest.raises(ValueError):
        native.p3p_solve_native(np.zeros((4, 3)), np.zeros((4, 3)))
    with pytest.raises(ValueError):
        lo_ransac_p3p(np.zeros((5, 3)), np.zeros((5, 3)), 0.01, backend="numppy")


class TestNativeImageLoader:
    def _roundtrip(self, tmp_path, fmt, shape=(37, 53)):
        from ncnet_tpu.data.image_io import read_image, resize_bilinear_np

        rng = np.random.default_rng(3)
        arr = (rng.random(shape + (3,)) * 255).astype("uint8")
        from PIL import Image

        p = str(tmp_path / f"t.{fmt}")
        Image.fromarray(arr).save(p, **({"quality": 95} if fmt == "jpg" else {}))
        ref = resize_bilinear_np(read_image(p), 24, 40).transpose(2, 0, 1)
        out, orig = native.load_image_chw_native(p, 24, 40)
        assert orig == shape
        # PNG decode is bit-exact; JPEG decoders may legally differ by
        # +/-1 LSB between PIL's bundled turbo and the system libjpeg.
        np.testing.assert_allclose(out, ref, atol=2.0 if fmt == "jpg" else 1e-3)

    def test_jpeg_parity(self, tmp_path):
        self._roundtrip(tmp_path, "jpg")

    def test_png_parity(self, tmp_path):
        self._roundtrip(tmp_path, "png")

    def test_grayscale_png(self, tmp_path):
        from PIL import Image

        arr = (np.arange(40 * 30).reshape(40, 30) % 255).astype("uint8")
        p = str(tmp_path / "g.png")
        Image.fromarray(arr, mode="L").save(p)
        out, orig = native.load_image_chw_native(p, 20, 15)
        assert orig == (40, 30)
        # gray broadcast: all three channels identical
        np.testing.assert_array_equal(out[0], out[1])
        np.testing.assert_array_equal(out[1], out[2])

    def test_flip_and_normalize(self, tmp_path):
        from PIL import Image

        from ncnet_tpu.data.normalization import normalize_image

        rng = np.random.default_rng(5)
        arr = (rng.random((16, 20, 3)) * 255).astype("uint8")
        p = str(tmp_path / "f.png")
        Image.fromarray(arr).save(p)
        out, _ = native.load_image_chw_native(p, 16, 20, flip=True, normalize=True)
        ref = normalize_image(
            arr[:, ::-1].astype(np.float32).transpose(2, 0, 1) / 255.0
        )
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_missing_file(self):
        with pytest.raises(IOError):
            native.load_image_chw_native("/nonexistent.jpg", 8, 8)
