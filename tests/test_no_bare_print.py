"""Tier-1 gate: no bare ``print()`` in library code.

Thin wrapper over the engine's ``bare-print`` rule
(ncnet_tpu/analysis/rules/bare_print.py) — the AST walking that used to
live here moved into the shared analysis engine; this test pins that
the ported rule reproduces the pre-port verdict (zero bare prints
outside ``cli/``). Seeded-violation coverage (the rule actually fires
on a bad file, the cli/ exemption, pragma suppression) lives in
tests/test_analysis_engine.py.
"""

from ncnet_tpu.analysis import Repo, get_rules, run_rules


def test_no_bare_print_in_library_code():
    report = run_rules(Repo(), get_rules(["bare-print"]))
    violations = [f.location() for f in report.findings]
    assert not violations, (
        "bare print() in library code (use ncnet_tpu.obs.event or "
        f"file=sys.stderr, or pragma with a rationale): {violations}"
    )
