"""Tier-1 style gate: no bare ``print()`` in library code.

Library modules under ``ncnet_tpu/`` (everything except ``cli/``, which
IS the user-facing stdout surface) must report through the structured
run log (``ncnet_tpu.obs``) or an explicit stream (``file=sys.stderr``),
never bare ``print()``: library stdout interleaves with machine-read
contracts like bench.py's single headline JSON line
(test_bench_contract.py) and is invisible to tools/obs_report.py.

AST-based, so docstring usage examples (e.g. utils/profiling.PhaseTimer)
don't trip it. Intentional stdout contracts get an explicit allowlist
entry with a rationale, not an exemption pattern.
"""

import ast
import os

import ncnet_tpu

PKG_DIR = os.path.dirname(os.path.abspath(ncnet_tpu.__file__))

# (relative path, line) -> rationale. Every entry is a deliberate stdout
# contract; anything not listed here is a failure.
ALLOWED = {
    # e.g. ("utils/example.py", 10): "machine-read JSON contract",
}


def _bare_prints(path):
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    hits = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and not any(kw.arg == "file" for kw in node.keywords)
        ):
            hits.append(node.lineno)
    return hits


def test_no_bare_print_in_library_code():
    violations = []
    for root, dirs, files in os.walk(PKG_DIR):
        rel_root = os.path.relpath(root, PKG_DIR)
        # cli/ prints to the terminal by design; that is its job.
        if rel_root == "cli" or rel_root.startswith("cli" + os.sep):
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PKG_DIR)
            for line in _bare_prints(path):
                if ALLOWED.get((rel, line)):
                    continue
                violations.append(f"{rel}:{line}")
    assert not violations, (
        "bare print() in library code (use ncnet_tpu.obs.event or "
        f"file=sys.stderr, or allowlist with a rationale): {violations}"
    )


def test_allowlist_is_current():
    """Stale allowlist entries (code moved/removed) must be pruned."""
    for (rel, line), rationale in ALLOWED.items():
        assert rationale, f"allowlist entry {rel}:{line} needs a rationale"
        path = os.path.join(PKG_DIR, rel)
        assert os.path.exists(path), f"allowlisted file gone: {rel}"
        assert line in _bare_prints(path), (
            f"allowlisted print at {rel}:{line} no longer exists"
        )
