"""Localization pipeline tests: synthetic-scene oracles for P3P RANSAC,
backprojection, rendering, pose verification, and curves."""

import numpy as np

from ncnet_tpu.localization import (
    LocalizationParams,
    lo_ransac_p3p,
    localization_rate,
    localize_queries,
    matches_to_2d3d,
    p3p_solve,
    points_to_persp,
    pose_distance,
    pose_verification_score,
)
from ncnet_tpu.localization.driver import evaluate_poses
from ncnet_tpu.localization.pose import camera_center, make_intrinsics


def random_pose(rng):
    """Random world->camera pose with points visible in front."""
    axis = rng.normal(size=3)
    axis /= np.linalg.norm(axis)
    ang = rng.uniform(0.1, 1.0)
    K = np.array(
        [[0, -axis[2], axis[1]], [axis[2], 0, -axis[0]], [-axis[1], axis[0], 0]]
    )
    R = np.eye(3) + np.sin(ang) * K + (1 - np.cos(ang)) * (K @ K)
    t = rng.normal(size=3) * 0.5 + np.array([0, 0, 4.0])
    return np.concatenate([R, t[:, None]], axis=1)


def make_scene(rng, n, P):
    """World points in front of camera P, and their unit observation rays."""
    cam_pts = rng.uniform([-2, -2, 2], [2, 2, 8], size=(n, 3))
    R, t = P[:, :3], P[:, 3]
    world = (cam_pts - t) @ R  # R^T (x - t)
    rays = cam_pts / np.linalg.norm(cam_pts, axis=1, keepdims=True)
    return world, rays


class TestP3P:
    def test_minimal_exact(self):
        rng = np.random.default_rng(0)
        for trial in range(20):
            P = random_pose(rng)
            world, rays = make_scene(rng, 3, P)
            cands = p3p_solve(rays[None], world[None])[0]
            ok = [c for c in cands if np.all(np.isfinite(c))]
            assert ok, "no real P3P solution for a generic configuration"
            errs = [pose_distance(P, c) for c in ok]
            best = min(errs, key=lambda e: e[0])
            assert best[0] < 1e-6 and best[1] < 1e-6

    def test_ransac_with_outliers(self):
        rng = np.random.default_rng(1)
        P = random_pose(rng)
        world, rays = make_scene(rng, 200, P)
        # 40% outliers: random rays.
        n_out = 80
        bad = rng.normal(size=(n_out, 3))
        rays[:n_out] = bad / np.linalg.norm(bad, axis=1, keepdims=True)
        res = lo_ransac_p3p(rays, world, inlier_thr=np.deg2rad(0.2), max_iters=500, seed=0)
        assert res.ok
        dpos, dori = pose_distance(P, res.P)
        assert dpos < 1e-3 and np.rad2deg(dori) < 0.1
        assert res.num_inliers >= 115  # recovers (almost) all 120 inliers
        assert res.inliers[n_out:].mean() > 0.95

    def test_ransac_too_few(self):
        res = lo_ransac_p3p(np.zeros((2, 3)), np.zeros((2, 3)), 0.01)
        assert not res.ok and res.num_inliers == 0

    def test_camera_center_roundtrip(self):
        rng = np.random.default_rng(2)
        P = random_pose(rng)
        C = camera_center(P)
        # x_cam of the center is 0.
        assert np.allclose(P[:, :3] @ C + P[:, 3], 0.0, atol=1e-12)


class TestBackproject:
    def test_synthetic_lookup(self):
        h, w = 40, 60
        xx, yy = np.meshgrid(np.arange(w, dtype=float), np.arange(h, dtype=float), indexing="xy")
        xyz = np.stack([xx, yy, np.full((h, w), 5.0)], axis=-1)
        xyz[0, 0] = np.nan  # a hole
        matches = np.array(
            [
                [0.5, 0.5, 0.5, 0.5, 0.9],  # valid, center
                [0.25, 0.25, 0.005, 0.01, 0.9],  # hits the NaN hole -> dropped
                [0.1, 0.1, 0.9, 0.9, 0.1],  # below score thr -> dropped
            ]
        )
        corr = matches_to_2d3d(matches, xyz, (100, 200), focal_length=100.0, score_thr=0.75)
        assert len(corr) == 1
        assert np.allclose(corr.points[0], [w // 2, h // 2, 5.0])
        assert np.allclose(corr.query_px[0], [100.0, 50.0])
        # Ray direction reproduces the pixel through K.
        K = make_intrinsics(100.0, 100, 200)
        uv = K @ corr.rays[0]
        assert np.allclose(uv[:2] / uv[2], [100.0, 50.0])

    def test_scan_transform_applied(self):
        xyz = np.ones((4, 4, 3))
        T = np.eye(4)
        T[:3, 3] = [10.0, 0.0, 0.0]
        m = np.array([[0.5, 0.5, 0.5, 0.5, 0.9]])
        corr = matches_to_2d3d(m, xyz, (8, 8), 4.0, scan_transform=T)
        assert np.allclose(corr.points[0], [11.0, 1.0, 1.0])


class TestRender:
    def test_zbuffer_keeps_nearest(self):
        # Two points projecting to the same pixel; nearer one must win.
        K = make_intrinsics(10.0, 8, 8)
        P = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        xyz = np.array([[0.0, 0.0, 2.0], [0.0, 0.0, 1.0]])
        rgb = np.array([[1.0, 0, 0], [0, 1.0, 0]])
        rgb_p, xyz_p = points_to_persp(rgb, xyz, K @ P, 8, 8)
        assert np.allclose(rgb_p[4, 4], [0, 1.0, 0])
        assert np.allclose(xyz_p[4, 4], [0, 0, 1.0])
        # Everything else NaN.
        assert np.isnan(rgb_p).sum() == 8 * 8 * 3 - 3

    def test_behind_camera_skipped(self):
        K = make_intrinsics(10.0, 8, 8)
        P = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        _, xyz_p = points_to_persp(np.ones((1, 3)), np.array([[0.0, 0.0, -1.0]]), K @ P, 8, 8)
        assert np.all(np.isnan(xyz_p))


class TestPoseVerification:
    def _scene(self):
        rng = np.random.default_rng(3)
        h, w = 96, 128
        fl = 120.0
        # A textured fronto-parallel plane at z=4 covering the image.
        K = make_intrinsics(fl, h, w)
        ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        z = 4.0
        X = (xs - w / 2.0) * z / fl
        Y = (ys - h / 2.0) * z / fl
        xyz = np.stack([X, Y, np.full_like(X, z)], axis=-1)
        tex = rng.uniform(0, 1, size=(h, w))
        rgb = np.repeat(tex[:, :, None], 3, axis=2)
        return rgb, xyz, fl

    def test_true_pose_beats_wrong_pose(self):
        rgb, xyz, fl = self._scene()
        P_true = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        P_wrong = P_true.copy()
        P_wrong[:, 3] = [1.5, 0.8, 0.5]
        query = (rgb * 255).astype(np.uint8)
        s_true, _ = pose_verification_score(query, rgb, xyz, P_true, fl, downsample=2)
        s_wrong, _ = pose_verification_score(query, rgb, xyz, P_wrong, fl, downsample=2)
        assert s_true > s_wrong

    def test_nan_pose_scores_zero(self):
        rgb, xyz, fl = self._scene()
        s, m = pose_verification_score(rgb, rgb, xyz, np.full((3, 4), np.nan), fl)
        assert s == 0.0 and m is None


class TestCurves:
    def test_rates(self):
        pos = np.array([0.1, 0.5, 3.0, np.inf, 0.2])
        ori = np.array([1.0, 2.0, 1.0, 1.0, 45.0])  # last: ori too large
        thr = np.array([0.25, 1.0, 5.0])
        rates = localization_rate(pos, ori, thr)
        # thr 0.25: only 0.1 qualifies; thr 1.0: 0.1+0.5; thr 5.0: +3.0.
        assert np.allclose(rates, [1 / 5, 2 / 5, 3 / 5])


class TestDriver:
    def test_end_to_end_synthetic(self, tmp_path):
        rng = np.random.default_rng(7)
        fl = 100.0
        hq, wq = 80, 100
        hdb, wdb = 50, 50
        P_gt = random_pose(rng)

        # Database cutout: plane of 3-D points observed by an identity-pose
        # db camera; query observes the same points from P_gt.
        ys, xs = np.meshgrid(np.arange(hdb), np.arange(wdb), indexing="ij")
        z = 6.0
        world = np.stack(
            [(xs - wdb / 2.0) * z / 60.0, (ys - hdb / 2.0) * z / 60.0, np.full(xs.shape, z, float)],
            axis=-1,
        )
        # World -> query pixels, keep in-bounds points as matches.
        R, t = P_gt[:, :3], P_gt[:, 3]
        Kq = make_intrinsics(fl, hq, wq)
        cam = world.reshape(-1, 3) @ R.T + t
        uvw = cam @ Kq.T
        uv = uvw[:, :2] / uvw[:, 2:3]
        vis = (
            (uv[:, 0] > 1) & (uv[:, 0] < wq - 1) & (uv[:, 1] > 1) & (uv[:, 1] < hq - 1) & (cam[:, 2] > 0)
        )
        idx = np.where(vis)[0]
        assert idx.size >= 50
        idx = rng.choice(idx, size=min(200, idx.size), replace=False)
        db_xy = np.stack([(idx % wdb) + 0.5, (idx // wdb) + 0.5], axis=1)
        m = np.concatenate(
            [uv[idx] / [wq, hq], db_xy / [wdb, hdb], np.full((idx.size, 1), 0.9)], axis=1
        )

        results = localize_queries(
            queries=["q1"],
            shortlist=lambda q: ["pano_a"],
            load_matches=lambda q, j: m,
            load_cutout=lambda p: (world, None),
            query_size=lambda q: (hq, wq),
            focal_length=fl,
            params=LocalizationParams(ransac_iters=300, top_n=1),
            cache_dir=str(tmp_path / "cache"),
        )
        assert results[0].best_index == 0
        dpos, dori = pose_distance(P_gt, results[0].best_pose)
        assert dpos < 1e-2 and np.rad2deg(dori) < 0.5

        # Idempotency: second run hits the cache and gives the same pose.
        results2 = localize_queries(
            queries=["q1"],
            shortlist=lambda q: ["pano_a"],
            load_matches=lambda q, j: (_ for _ in ()).throw(AssertionError("cache not used")),
            load_cutout=lambda p: (world, None),
            query_size=lambda q: (hq, wq),
            focal_length=fl,
            params=LocalizationParams(ransac_iters=300, top_n=1),
            cache_dir=str(tmp_path / "cache"),
        )
        assert np.allclose(results2[0].best_pose, results[0].best_pose)

        # evaluate_poses + curve plumbing.
        pos_e, ori_e = evaluate_poses(results, {"q1": P_gt})
        rates = localization_rate(pos_e, ori_e, np.array([0.25]))
        assert rates[0] == 1.0

        # Parallel (num_workers > 1, the reference's parfor-over-queries)
        # must give identical results in query order.
        many = [f"q{i}" for i in range(5)]
        par = localize_queries(
            queries=many,
            shortlist=lambda q: ["pano_a"],
            load_matches=lambda q, j: m,
            load_cutout=lambda p: (world, None),
            query_size=lambda q: (hq, wq),
            focal_length=fl,
            params=LocalizationParams(ransac_iters=300, top_n=1),
            num_workers=3,
        )
        assert [r.query for r in par] == many
        for r in par:
            assert np.allclose(r.best_pose, results[0].best_pose)
