"""Localization-as-a-service + match-result cache acceptance (ISSUE 17).

* content addressing: one digest for one image regardless of arrival
  form (two paths, inline b64) — the cache can never double-store or
  path-alias an entry;
* the result cache's storage contract: bf16 canonical rounding, disk
  round-trip across cache instances, model-key namespacing, corrupt
  files read as misses, the byte-bounded LRU;
* single-flight coalescing: leader/follower/abandon protocol at the
  unit level, and the e2e proof — K concurrent identical /v1/match
  requests cost EXACTLY one engine dispatch (counter-asserted) and the
  cache-hit response is bitwise identical to the populating miss
  (evals/agreement.py comparator);
* /v1/localize fan-out: a 2-replica CPU fleet serves one query's
  shortlist legs on BOTH replicas (labeled admitted-counter deltas),
  every shortlist pano comes back as a row, ranking is by descending
  consensus mass, and a replayed shortlist answers from cache;
* deterministic ranking inputs: evals/inloc.dedup_matches breaks score
  ties canonically, so two permutations of the same device output
  produce bitwise-identical tables;
* tool contracts: bulk_match --prewarm-results (resumable disk-tier
  populator), bench_trend pass-through of the localize-bench fields,
  fleet_status's resc%% column math, ci_gate's localize_smoke
  skip-record.

The chaos gate (--localize_fanout) and localize bench contracts live
with their siblings' style here too, end-to-end and in-process.
"""

import base64
import io
import json
import os
import sys
import threading

import numpy as np
import pytest

from ncnet_tpu import obs
from ncnet_tpu.evals.agreement import match_table_agreement
from ncnet_tpu.serving.feature_store import (
    SharedFeatureStore,
    content_digest,
)
from ncnet_tpu.serving.result_cache import (
    MatchResultCache,
    request_digests,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _jpeg_bytes(h, w, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray((rng.random((h, w, 3)) * 255).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


# -- content addressing ----------------------------------------------------


def test_content_digest_one_image_one_digest(tmp_path):
    """The same image bytes under two different paths AND as an inline
    b64 body must key ONE cache entry."""
    raw = _jpeg_bytes(32, 48, 0)
    p1 = tmp_path / "a.jpg"
    p2 = tmp_path / "nested" / "b.jpg"
    p2.parent.mkdir()
    p1.write_bytes(raw)
    p2.write_bytes(raw)

    store = SharedFeatureStore(1 << 20)
    d_bytes = content_digest(raw)
    assert d_bytes == content_digest(str(p1)) == content_digest(str(p2))
    assert d_bytes == store.content_digest(str(p1))
    assert d_bytes == store.content_digest(str(p2))
    assert d_bytes == store.content_digest(raw)
    # And through the request-shaped helper: path form == b64 form.
    other = _jpeg_bytes(32, 48, 1)
    (tmp_path / "pano.jpg").write_bytes(other)
    dq1, dp1 = request_digests(
        {"query_path": str(p1), "pano_path": str(tmp_path / "pano.jpg")},
        store=store)
    dq2, dp2 = request_digests(
        {"query_b64": base64.b64encode(raw).decode(),
         "pano_b64": base64.b64encode(other).decode()})
    assert (dq1, dp1) == (dq2, dp2)
    assert dq1 == d_bytes
    assert dp1 != dq1  # different content, different digest


# -- deterministic score-tie ranking inputs --------------------------------


def test_dedup_matches_breaks_score_ties_canonically():
    """Two score-sorted permutations of the same rows (the device sort
    only orders by score, so tied rows arrive in any order) must dedup
    to bitwise-identical tables — the content-addressed cache and the
    rung-0 bitwise shadow contract both depend on it."""
    from ncnet_tpu.evals.inloc import dedup_matches

    xa = np.array([3.0, 1.0, 2.0, 1.0], np.float32)
    ya = np.array([0.0, 5.0, 4.0, 5.0], np.float32)
    xb = np.array([7.0, 6.0, 8.0, 6.0], np.float32)
    yb = np.array([9.0, 2.0, 3.0, 2.0], np.float32)
    score = np.array([0.5, 0.5, 0.5, 0.5], np.float32)  # all tied

    out_fwd = dedup_matches(xa, ya, xb, yb, score)
    perm = np.array([2, 0, 3, 1])  # still descending-score-sorted
    out_perm = dedup_matches(xa[perm], ya[perm], xb[perm], yb[perm],
                             score[perm])
    for a, b in zip(out_fwd, out_perm):
        np.testing.assert_array_equal(a, b)
    # The duplicate row (index 1 == index 3) collapsed.
    assert out_fwd[0].shape[0] == 3
    # Ties ordered by the lexicographic coordinate row.
    coords = np.stack(out_fwd[:4], axis=1)
    assert [tuple(r) for r in coords] == sorted(tuple(r) for r in coords)


# -- result cache unit contracts -------------------------------------------


def _table(n, seed):
    rng = np.random.default_rng(seed)
    # Values dense in the mantissa so bf16 rounding is OBSERVABLE.
    return (rng.random((n, 5)) * 7.0 + 0.1).astype(np.float32)


def test_result_cache_bf16_disk_roundtrip(tmp_path):
    cache = MatchResultCache(1 << 20, disk_dir=str(tmp_path),
                             model_key="mk")
    t = _table(16, 0)
    key = cache.key("dq", "dp", ("mode", 8))
    out = cache.put(key, t)
    want = cache.canonical(t)
    np.testing.assert_array_equal(out, want)
    assert not np.array_equal(out, t), "bf16 rounding must be real"

    # A fresh instance over the same dir (restarted server) serves the
    # SAME canonical table from the disk tier.
    cache2 = MatchResultCache(1 << 20, disk_dir=str(tmp_path),
                              model_key="mk")
    disk0 = obs.counter("serving.rescache.disk_hits").value
    got = cache2.get(key)
    np.testing.assert_array_equal(got, want)
    assert obs.counter("serving.rescache.disk_hits").value == disk0 + 1
    # ...and now from memory (promoted), not disk.
    got2 = cache2.get(key)
    np.testing.assert_array_equal(got2, want)
    assert obs.counter("serving.rescache.disk_hits").value == disk0 + 1

    # A different model key is a different namespace: the same digest
    # triple keys a different entry, so no cross-serve.
    other = MatchResultCache(1 << 20, disk_dir=str(tmp_path),
                             model_key="other-weights")
    assert other.get(other.key("dq", "dp", ("mode", 8))) is None

    # Corrupt entry file: a miss, never a crash.
    [path] = [os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
              if f.startswith("res1_")]
    with open(path, "wb") as fh:
        fh.write(b"not an npz")
    fresh = MatchResultCache(1 << 20, disk_dir=str(tmp_path),
                             model_key="mk")
    assert fresh.get(key) is None


def test_result_cache_lru_stays_byte_bounded():
    # bf16 entries: 100 x 5 x 2 bytes = 1000 B each; budget fits two.
    cache = MatchResultCache(2500)
    k = [cache.key("q", f"p{i}", ("op",)) for i in range(3)]
    cache.put(k[0], _table(100, 0))
    cache.put(k[1], _table(100, 1))
    assert cache.get(k[0]) is not None  # k0 is now most-recent
    cache.put(k[2], _table(100, 2))
    assert cache.nbytes <= 2500
    assert len(cache) == 2
    assert cache.get(k[1]) is None, "LRU victim was the cold entry"
    assert cache.get(k[0]) is not None
    assert cache.get(k[2]) is not None


def test_result_cache_single_flight_protocol():
    cache = MatchResultCache(1 << 20)
    key = cache.key("a", "b", ("op",))
    co0 = obs.counter("serving.rescache.coalesced").value

    verdict, fut = cache.lookup_or_begin(key)
    assert verdict == "leader"
    verdict2, fut2 = cache.lookup_or_begin(key)
    assert verdict2 == "follower" and fut2 is fut
    assert obs.counter("serving.rescache.coalesced").value == co0 + 1

    t = _table(8, 3)
    out = cache.complete(key, t)
    np.testing.assert_array_equal(out, cache.canonical(t))
    np.testing.assert_array_equal(fut2.result(timeout=5), out)
    verdict3, val3 = cache.lookup_or_begin(key)
    assert verdict3 == "hit"
    np.testing.assert_array_equal(val3, out)

    # Abandon: followers get the leader's exception, the key stays
    # uncached, and the NEXT requester starts a fresh flight.
    key2 = cache.key("a", "c", ("op",))
    assert cache.lookup_or_begin(key2)[0] == "leader"
    _, f_follow = cache.lookup_or_begin(key2)
    cache.abandon(key2, RuntimeError("device fell over"))
    with pytest.raises(RuntimeError, match="fell over"):
        f_follow.result(timeout=5)
    assert cache.lookup_or_begin(key2)[0] == "leader"


# -- e2e: coalescing proof --------------------------------------------------


def test_match_coalescing_one_dispatch_bitwise(tiny_serving_model):
    """K concurrent identical /v1/match requests = EXACTLY one engine
    dispatch (serving.batches counter delta), one populating miss, and
    every response's table bitwise identical to it."""
    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    engine.warmup([(96, 128, 96, 128)], batch_sizes=(1,))
    cache = MatchResultCache(64 * 1024 * 1024, model_key="co-test")
    server = MatchServer(engine, port=0, max_batch=4, max_delay_s=0.01,
                         default_timeout_s=120.0, slo_p99_target_s=60.0,
                         result_cache=cache).start()
    qb, pb = _jpeg_bytes(96, 128, 12), _jpeg_bytes(96, 128, 13)
    results, errors = [], []
    barrier = threading.Barrier(4)

    def hit_once():
        try:
            barrier.wait(timeout=30)
            c = MatchClient(server.url, timeout_s=120.0, retries=0)
            results.append(c.match(query_bytes=qb, pano_bytes=pb,
                                   max_matches=8))
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    try:
        batches0 = obs.counter("serving.batches").value
        threads = [threading.Thread(target=hit_once) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 4
        assert obs.counter("serving.batches").value == batches0 + 1, \
            "K identical concurrent requests must cost ONE dispatch"
        tags = sorted(r["rescache"] for r in results)
        assert tags.count("miss") == 1, tags
        assert set(tags) <= {"miss", "hit", "coalesced"}, tags
        [miss] = [r for r in results if r["rescache"] == "miss"]
        for r in results:
            cmp = match_table_agreement(miss["matches"], r["matches"])
            assert cmp["bitwise"], "coalesced/hit table diverged"

        # A later identical request is a memory hit — still bitwise
        # identical to the populating miss, still zero new dispatches.
        late = MatchClient(server.url, timeout_s=120.0, retries=0).match(
            query_bytes=qb, pano_bytes=pb, max_matches=8)
        assert late["rescache"] == "hit"
        assert match_table_agreement(miss["matches"],
                                     late["matches"])["bitwise"]
        assert obs.counter("serving.batches").value == batches0 + 1
    finally:
        server.stop()


# -- e2e: fan-out proof -----------------------------------------------------


def test_localize_fanout_spans_both_replicas(tiny_serving_model):
    """One /v1/localize query's shortlist legs land on BOTH replicas of
    a 2-replica fleet (labeled serving.admitted deltas), every pano
    comes back as a row, ranking descends by consensus mass, and a
    replayed shortlist answers from the result cache."""
    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    fleet = MatchFleet.build(
        config, params, n_replicas=2, base_id="lfo", cache_mb=0,
        engine_kwargs=dict(k_size=2, image_size=64),
        replica_kwargs=dict(max_batch=2, max_delay_s=0.01,
                            default_timeout_s=120.0))
    fleet.warmup([(96, 128, 96, 128)], batch_sizes=(1, 2))
    rids = [r.replica_id for r in fleet.replicas]
    before = {rid: obs.counter("serving.admitted",
                               labels={"replica": rid}).value
              for rid in rids}
    cache = MatchResultCache(64 * 1024 * 1024, model_key="lfo-test")
    server = MatchServer(None, port=0, fleet=fleet, result_cache=cache,
                         slo_p99_target_s=60.0).start()
    qb = _jpeg_bytes(96, 128, 20)
    panos = [_jpeg_bytes(96, 128, s) for s in range(21, 25)]
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)
        resp = client.localize(query_bytes=qb, panos=panos)

        assert resp["fanout_width"] == 4
        assert resp["n_ok"] == 4 and resp["n_failed"] == 0
        assert len(resp["panos"]) == 4, "every shortlist pano gets a row"
        assert all(r["ok"] for r in resp["panos"])
        scores = [e["score"] for e in resp["ranked"]]
        assert scores == sorted(scores, reverse=True)
        assert [e["rank"] for e in resp["ranked"]] == [0, 1, 2, 3]
        assert resp["trace_id"]
        # The fan-out proof: one query's legs were admitted on BOTH
        # replicas (the least-loaded picker spreads parallel legs).
        deltas = {rid: obs.counter("serving.admitted",
                                   labels={"replica": rid}).value
                  - before[rid] for rid in rids}
        assert all(d >= 1 for d in deltas.values()), deltas
        assert sum(deltas.values()) == 4

        # Replay the same shortlist: every leg answers from the cache
        # (no new admissions) with the SAME ranking.
        resp2 = client.localize(query_bytes=qb, panos=panos)
        assert resp2["n_ok"] == 4
        assert all(r.get("rescache") in ("hit", "coalesced")
                   for r in resp2["panos"])
        assert [e["score"] for e in resp2["ranked"]] == scores
        after2 = {rid: obs.counter("serving.admitted",
                                   labels={"replica": rid}).value
                  - before[rid] for rid in rids}
        assert after2 == deltas, "cache-served legs must not dispatch"

        # top_k truncates the ranking but never the per-pano rows.
        resp3 = client.localize(query_bytes=qb, panos=panos, top_k=2)
        assert len(resp3["ranked"]) == 2
        assert len(resp3["panos"]) == 4

        # A malformed shortlist is a 400, not a hang.
        from ncnet_tpu.serving.client import ServingError
        with pytest.raises(ServingError):
            client.localize(query_bytes=qb, panos=[])
    finally:
        server.stop()


# -- tool contracts ---------------------------------------------------------


def test_bulk_prewarm_results_contract(tmp_path, capsys):
    """tools/bulk_match.py --prewarm-results: ONE JSON line, every pair
    stored into the disk tier, and a re-run skips them all (the disk
    tier IS the resume ledger)."""
    import bulk_match

    rc_dir = str(tmp_path / "rescache")
    argv = ["--out_dir", str(tmp_path / "run"), "--engine", "echo",
            "--synthetic", "6@32x48", "--prewarm-results",
            "--rescache_dir", rc_dir, "--max_batch", "2"]
    rc = bulk_match.main(argv)
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "bulk_prewarm_results_pairs_per_s"
    assert rec["stored"] == 6 and rec["already_warm"] == 0
    assert rec["failed"] == 0
    assert any(f.startswith("res1_") for f in os.listdir(rc_dir))

    rc2 = bulk_match.main(argv)
    assert rc2 == 0
    rec2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec2["stored"] == 0 and rec2["already_warm"] == 6


def test_chaos_localize_fanout_contract(tiny_serving_model, capsys):
    """tools/chaos_serving.py --localize_fanout: a replica killed
    mid-fan-out — zero silent pano drops, zero failed legs, at least
    one redispatched leg that JOINS a localize query's trace, every
    query 200, ONE stdout JSON line."""
    import chaos_serving

    rc = chaos_serving.main([
        "--localize_fanout", "--replicas", "2", "--synthetic", "96x128",
        "--image_size", "64", "--duration_s", "4", "--threads", "2",
        "--panos", "4", "--max_batch", "2",
    ], model=tiny_serving_model)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "chaos_localize_fanout"
    assert rc == 0, f"violations: {rec['violations']}"
    assert rec["violations"] == []
    assert rec["value"] == 1.0
    assert rec["queries"]["ok"] == rec["queries"]["sent"]
    assert rec["legs"]["legs_failed"] == 0
    assert rec["silent_drops"] == 0 and rec["dropped"] == 0
    assert rec["redispatched"] >= 1
    assert rec["joined_redispatch_spans"] >= 1
    assert rec["fanout_width"] == 4 and rec["replicas"] == 2


def test_bench_localize_contract(tiny_serving_model, capsys):
    """tools/bench_serving.py --localize: ONE JSON line with the
    localize QPS headline, fan-out width, replay cache hit-rate, and
    both replicas in the per-replica admitted breakdown."""
    import bench_serving

    rc = bench_serving.main([
        "--localize", "--replicas", "2", "--synthetic", "96x128",
        "--image_size", "64", "--duration_s", "1", "--threads", "2",
        "--panos", "3", "--localize_queries", "2", "--max_batch", "2",
    ], model=tiny_serving_model)
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "serving_localize_qps"
    assert rec["unit"] == "qps" and rec["value"] > 0
    assert rec["fanout_width"] == 3 and rec["replicas"] == 2
    assert rec["queries"]["errors"] == 0
    assert rec["legs_failed"] == 0
    # Steady-state replay of a fixed shortlist set answers from cache.
    assert rec["rescache_hit_rate"] == 1.0
    assert set(rec["per_replica"]) == {"loc-d0", "loc-d1"}
    admitted = sum(v["admitted"] for v in rec["per_replica"].values())
    # The cold phase's legs all dispatched; both replicas took some.
    assert admitted == rec["fanout_width"] * 2
    assert all(v["admitted"] >= 1 for v in rec["per_replica"].values())
    for q in ("p50", "p99"):
        assert rec["replay_latency_ms"][q] > 0


def test_bench_trend_passes_localize_fields_through(tmp_path, capsys):
    """tools/bench_trend.py forwards the localize-bench context: a
    localize QPS trend is only readable next to the fan-out width it
    served and the cache hit-rate that paid for it."""
    import bench_trend

    rec = {"n": 1, "cmd": "bench", "rc": 0,
           "parsed": {"metric": "serving_localize_qps", "value": 130.0,
                      "unit": "qps", "replicas": 2, "fanout_width": 6,
                      "rescache_hit_rate": 0.98, "legs": 240,
                      "legs_failed": 0}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(rec, fh)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["metric"] == "serving_localize_qps"
    assert report["fanout_width"] == 6
    assert report["rescache_hit_rate"] == 0.98
    assert report["legs"] == 240 and report["legs_failed"] == 0


def test_fleet_status_rescache_column_math():
    import fleet_status

    assert fleet_status._rescache_pct({}) is None
    assert fleet_status._rescache_pct(
        {"serving_rescache_hits": 3.0,
         "serving_rescache_misses": 1.0}) == 75.0
    # Registered-but-untouched counters: 0/0 renders "-", not a crash.
    assert fleet_status._rescache_pct(
        {"serving_rescache_hits": 0.0,
         "serving_rescache_misses": 0.0}) is None


def test_ci_gate_localize_smoke_is_optional(capsys):
    """Off by default, never silently green: a default ci_gate run
    records localize_smoke as skipped AND optional."""
    import ci_gate

    assert "localize_smoke" in ci_gate.OPTIONAL_CHECKS
    rc = ci_gate.main(["--skip", "tier1", "--skip", "lint",
                       "--skip", "bench_trend"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["checks"]["localize_smoke"] == {
        "skipped": True, "optional": True}
