"""Image-level preprocessing parity against the reference's EXACT torch
chain — the class of silent bug (resize semantics, dim rounding,
normalization constants) that unit tests at feature level cannot catch
(VERDICT r4 missing #1: "a silent resize/BN/coord-convention bug would
pass every current test")."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def test_resize_matches_torch_bilinear_align_corners():
    """resize_bilinear_np == F.interpolate(mode='bilinear',
    align_corners=True) — the semantics of the reference's
    nn.functional.upsample on the torch-0.3/0.4 path it ships
    (eval_inloc.py:84-89, transformation.py affine resize)."""
    from ncnet_tpu.data.image_io import resize_bilinear_np

    rng = np.random.RandomState(0)
    for (h, w), (oh, ow) in [
        ((37, 53), (24, 32)),     # downscale, non-integer ratio
        ((24, 32), (37, 53)),     # upscale
        ((480, 640), (300, 400)), # the training-eval scale ratio
        ((11, 13), (11, 13)),     # identity
    ]:
        img = rng.rand(h, w, 3).astype(np.float32) * 255.0
        ours = resize_bilinear_np(img, oh, ow)
        theirs = torch.nn.functional.interpolate(
            torch.from_numpy(img.transpose(2, 0, 1))[None],
            size=(oh, ow), mode="bilinear", align_corners=True,
        )[0].numpy().transpose(1, 2, 0)
        # 0.05 on the 0-255 scale: float32 accumulation-order noise is
        # ~0.01; a semantic divergence (half-pixel shift, align_corners
        # mismatch) is O(10) on noise images and still fails loudly.
        np.testing.assert_allclose(ours, theirs, atol=5e-2, rtol=1e-4)


def test_inloc_resize_dims_match_reference_formula():
    """inloc_resize_shape at feat_unit=2 (the reference's exact-dims
    mode) must reproduce the reference's rounding arithmetic
    (eval_inloc.py:86-89) for every plausible input size: size =
    int(floor(dim/(long/image_size)*scale/k)/scale*k), scale=0.0625."""
    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape

    image_size, k, scale = 3200, 2, 0.0625
    rng = np.random.RandomState(1)
    shapes = [(1200, 1600), (1600, 1200), (2448, 3264), (3264, 2448),
              (1063, 1417), (4032, 3024)]
    shapes += [tuple(rng.randint(600, 4200, 2)) for _ in range(40)]
    for h, w in shapes:
        ratio = max(h, w) / image_size
        exp_h = int(np.floor(h / ratio * scale / k) / scale * k)
        exp_w = int(np.floor(w / ratio * scale / k) / scale * k)
        got_h, got_w = inloc_resize_shape(h, w, image_size, k,
                                          h_unit=k, w_unit=k)
        assert (got_h, got_w) == (exp_h, exp_w), (h, w)


def test_normalization_matches_reference_dict():
    """NormalizeImageDict parity: /255 then ImageNet mean/std
    (lib/normalization.py:16-27) — constants AND order."""
    from ncnet_tpu.data.normalization import normalize_image

    rng = np.random.RandomState(2)
    chw = rng.rand(3, 8, 9).astype(np.float32) * 255.0
    ours = normalize_image(chw / 255.0)
    mean = np.array([0.485, 0.456, 0.406], np.float32)[:, None, None]
    std = np.array([0.229, 0.224, 0.225], np.float32)[:, None, None]
    theirs = (chw / 255.0 - mean) / std
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-6)


def test_image_loader_end_to_end_vs_torch_chain(tmp_path):
    """load_and_resize_chw (whatever backend: native C++ or PIL+numpy)
    vs the reference's full chain on a real JPEG: imread -> CHW float ->
    /255+ImageNet normalize -> corner-aligned bilinear resize."""
    from PIL import Image

    from ncnet_tpu.data.image_io import load_and_resize_chw, read_image

    rng = np.random.RandomState(3)
    arr = (rng.rand(67, 45, 3) * 255).astype(np.uint8)
    path = str(tmp_path / "img.png")  # png: lossless, decode-identical
    Image.fromarray(arr).save(path)

    ours, im_size = load_and_resize_chw(path, 32, 24, normalize=True)
    assert tuple(im_size[:2].astype(int)) == (67, 45)

    t = torch.from_numpy(
        read_image(path).astype(np.float32).transpose(2, 0, 1))
    t = t / 255.0
    mean = torch.tensor([0.485, 0.456, 0.406])[:, None, None]
    std = torch.tensor([0.229, 0.224, 0.225])[:, None, None]
    # Reference order at InLoc is normalize THEN resize
    # (eval_inloc.py:129: resize(normalize(imreadth(q)))); both are
    # linear maps per channel, so they commute up to float assoc —
    # pin ours against normalize-then-resize explicitly.
    t = (t - mean) / std
    theirs = torch.nn.functional.interpolate(
        t[None], size=(32, 24), mode="bilinear", align_corners=True,
    )[0].numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=1e-4)
