"""Membership plane (parallel/membership.py): leases, generations, rejoin.

Everything runs on an injected fake clock — lease expiry, steal
detection and bump ordering are pure functions of the files on disk
plus the clock value, so no test sleeps.
"""

import json
import os

import pytest

from ncnet_tpu.obs.metrics import MetricsRegistry
from ncnet_tpu.parallel.membership import (
    LeaseHeartbeat,
    LeaseStolenError,
    MembershipError,
    MembershipPlane,
    StaleGenerationError,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _plane(root, host, clock, ttl=5.0):
    return MembershipPlane(str(root), host, lease_ttl_s=ttl, clock=clock)


# -- formation -------------------------------------------------------------


def test_form_is_idempotent_first_writer_wins(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    rec_a = a.form(["a", "b"])
    clock.t = 1.0
    rec_b = b.form(["b", "a"])  # second former adopts, does not rewrite
    assert rec_a == rec_b
    assert rec_a["generation"] == 1
    assert rec_a["hosts"] == ["a", "b"]
    assert rec_a["t"] == 0.0


def test_form_rejects_host_not_in_gang(tmp_path):
    with pytest.raises(ValueError, match="not in the declared host list"):
        _plane(tmp_path, "c", FakeClock()).form(["a", "b"])


# -- leases: renew / expire / steal ----------------------------------------


def test_lease_renew_keeps_host_alive_expiry_kills_it(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b"])
    a.join()
    b.join()
    clock.t = 4.0
    b.renew(1)  # b renews inside the TTL...
    clock.t = 8.0  # ...a does not: a's lease (t=0) is now 8s old
    assert b.detect_dead() == ["a"]
    assert a.detect_dead() == []  # never reports ITSELF dead
    clock.t = 8.5
    a.renew(1)  # a comes back before anyone bumped: alive again
    assert b.detect_dead() == []


def test_lease_steal_detected_by_owner_nonce(tmp_path):
    clock = FakeClock()
    a1 = _plane(tmp_path, "a", clock)
    a1.form(["a"])
    a1.join()
    # A relaunch claims the same host name and writes its own lease.
    a2 = _plane(tmp_path, "a", clock)
    a2.join()
    with pytest.raises(LeaseStolenError):
        a1.renew(1)
    # The thief itself keeps renewing fine — it owns the lease now.
    a2.renew(1)


def test_drop_lease_reads_as_departure(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b"])
    a.join()
    b.join()
    b.drop_lease()
    assert "b" not in a.live_view()
    clock.t = 6.0  # past the formation grace: a missing lease is dead
    assert a.detect_dead() == ["b"]


def test_detect_dead_formation_grace(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a", "b"])
    a.join()
    # b never joined; within one TTL of the record that is grace ...
    clock.t = 4.0
    assert a.detect_dead() == []
    # ... after it, a no-show is a death.
    clock.t = 6.0
    assert a.detect_dead() == ["b"]


def test_lease_carries_training_position(tmp_path):
    # The commit barrier (training/elastic.py) reads peers' advertised
    # (epoch, step) off their leases; the fields must round-trip.
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a"])
    a.join(step=7, epoch=2)
    lease = a.live_view()["a"]
    assert (lease["epoch"], lease["step"]) == (2, 7)
    a.renew(1, step=9, epoch=3)
    lease = a.live_view()["a"]
    assert (lease["epoch"], lease["step"]) == (3, 9)


# -- generation bumps ------------------------------------------------------


def test_bump_is_monotonic_and_idempotent_under_races(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b", "c"])
    # Two survivors race the same eviction of c: the first bump wins,
    # the second (same expected_generation) returns the winner's record
    # UNWRITTEN instead of double-bumping.
    rec_a = a.bump(["a", "b"], resume_epoch=1, resume_step=6,
                   expected_generation=1)
    assert rec_a["generation"] == 2
    rec_b = b.bump(["b"], resume_epoch=1, resume_step=6,
                   expected_generation=1)
    assert rec_b == rec_a  # b's shrink-to-solo never landed
    assert b.read_generation()["hosts"] == ["a", "b"]


def test_bump_requires_formation(tmp_path):
    with pytest.raises(MembershipError, match="form"):
        _plane(tmp_path, "a", FakeClock()).bump(
            ["a"], resume_epoch=1, resume_step=0, expected_generation=1)


def test_bump_records_resume_marker(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a", "b"])
    rec = a.bump(["a"], resume_epoch=3, resume_step=12,
                 expected_generation=1)
    assert (rec["resume_epoch"], rec["resume_step"]) == (3, 12)


# -- rejoin ----------------------------------------------------------------


def test_rejoin_after_eviction_rejected_at_old_generation(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b"])
    a.join()
    b.join()
    clock.t = 6.0
    b.renew(1)
    a.bump(["b"], resume_epoch=1, resume_step=0,  # a evicts itself out
           expected_generation=1)
    # The evicted host may not write state at the old generation ...
    with pytest.raises(StaleGenerationError):
        a.renew(1)
    with pytest.raises(StaleGenerationError):
        a.join()
    # ... re-admission is an explicit grow bump, then join works.
    rec = a.read_generation()
    new = a.bump(sorted(set(rec["hosts"]) | {"a"}), resume_epoch=1,
                 resume_step=0, expected_generation=rec["generation"])
    assert new["generation"] == 3
    assert new["hosts"] == ["a", "b"]
    a.join()
    assert "a" in b.live_view()


def test_renew_tolerates_newer_generation_that_still_lists_host(tmp_path):
    # The window between a peer's bump and this host's next generation
    # read: the record moved ahead but still lists the host — renewing
    # at the held generation must NOT raise, or the peer would evict a
    # live host mid-transition.
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b", "c"])
    a.join()
    b.join()
    b.bump(["a", "b"], resume_epoch=1, resume_step=0,
           expected_generation=1)
    a.renew(1)  # held generation is stale but a is still a member


# -- durability ------------------------------------------------------------


def test_torn_record_reads_as_none_not_garbage(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a"])
    with open(a.generation_path, "w", encoding="utf-8") as fh:
        fh.write('{"generation": 2, "hos')  # a crash mid-write
    assert a.read_generation() is None
    # A torn lease likewise drops out of the live view.
    a.join_path = a._lease_path("a")
    with open(a.join_path, "w", encoding="utf-8") as fh:
        fh.write("{")
    assert a.live_view() == {}


def test_atomic_write_leaves_no_tmp_litter(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a"])
    a.join()
    names = {n for n in os.listdir(str(tmp_path))}
    names |= {n for n in os.listdir(str(tmp_path / "hosts"))}
    assert not any(n.endswith(".tmp") for n in names)


# -- heartbeat thread ------------------------------------------------------


def test_heartbeat_parks_first_error_and_stops(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a", "b"])
    a.join()
    hb = LeaseHeartbeat(a, interval_s=0.05).start(1)
    try:
        # Evict a: the next renewal must park StaleGenerationError for
        # the training thread instead of killing the process.
        b = _plane(tmp_path, "b", clock)
        b.bump(["b"], resume_epoch=1, resume_step=0,
               expected_generation=1)
        deadline = 100
        while hb.error() is None and deadline:
            deadline -= 1
            import time as _time

            _time.sleep(0.02)
        assert isinstance(hb.error(), StaleGenerationError)
    finally:
        hb.stop()


# -- fleet view: a dead host's frozen beacon shows as lag ------------------


def test_dead_host_beacon_merge_shows_it_behind(tmp_path):
    """Two hosts' registries merged the way fleet_status merges
    scrapes: the host whose lease expired stops advancing its step
    beacon, and publish_host_lag pins it behind the survivor — the
    observability echo of what detect_dead sees on disk."""
    from ncnet_tpu import obs
    from ncnet_tpu.obs import train_watch as tw

    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    b = _plane(tmp_path, "b", clock)
    a.form(["a", "b"])
    a.join()
    b.join()
    ra, rb = MetricsRegistry(), MetricsRegistry()
    wa = tw.TrainWatch(registry=ra, host="a", clock=clock)
    wb = tw.TrainWatch(registry=rb, host="b", clock=clock)
    wa.publish_beacon(10)
    wb.publish_beacon(10)
    # b dies at step 10: its beacon freezes, its lease goes stale.
    clock.t = 8.0
    a.renew(1)
    wa.publish_beacon(40)
    assert a.detect_dead() == ["b"]
    view = obs.aggregate.merge_snapshots([ra.snapshot(), rb.snapshot()])
    out = MetricsRegistry()
    behind = tw.publish_host_lag(view, registry=out)
    assert behind == {"a": 0.0, "b": 30.0}


def test_live_view_is_json_per_lease(tmp_path):
    clock = FakeClock()
    a = _plane(tmp_path, "a", clock)
    a.form(["a"])
    a.join()
    path = os.path.join(str(tmp_path), "hosts", "a.lease.json")
    with open(path, encoding="utf-8") as fh:
        lease = json.load(fh)
    assert lease["host"] == "a"
    assert lease["generation"] == 1
    assert "owner" in lease and "pid" in lease
