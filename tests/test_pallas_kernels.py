"""Tests for the fused correlation+maxpool kernels.

The oracle is the unfused pair (feature_correlation -> maxpool4d); the
Pallas kernel runs in interpreter mode on CPU (same code path Mosaic
compiles on TPU).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.ops import feature_correlation, maxpool4d
from ncnet_tpu.ops.pallas_kernels import (
    fused_correlation_maxpool_pallas,
    fused_correlation_maxpool_xla,
)


def _oracle(fa, fb, k):
    corr = feature_correlation(fa, fb)  # bf16 contraction, f32 accum
    return maxpool4d(corr, k)


@pytest.mark.parametrize("k", [2, 4])
def test_fused_xla_matches_oracle(rng, k):
    fa = jnp.asarray(rng.randn(1, 32, 4 * k, 3 * k).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 32, 2 * k, 5 * k).astype(np.float32))
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    pooled, deltas = fused_correlation_maxpool_xla(fa, fb, k)
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(ref_pooled), atol=1e-5
    )
    for d, rd in zip(deltas, ref_deltas):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(rd))


@pytest.mark.parametrize("impl", ["bigdot", "dots"])
def test_fused_pallas_interpret_matches_oracle(rng, impl):
    k = 2
    fa = jnp.asarray(rng.randn(1, 16, 8, 6).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 16, 4, 10).astype(np.float32))
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    pooled, deltas = fused_correlation_maxpool_pallas(
        fa, fb, k, interpret=True, kernel_impl=impl
    )
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(ref_pooled), atol=1e-5
    )
    for d, rd in zip(deltas, ref_deltas):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(rd))


@pytest.mark.parametrize("impl", ["bigdot", "dots"])
def test_fused_pallas_tiling(rng, impl):
    """Multiple B tiles per row exercise the second grid dimension."""
    k = 2
    fa = jnp.asarray(rng.randn(1, 8, 4, 4).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    pooled, deltas = fused_correlation_maxpool_pallas(
        fa, fb, k, tile_b_cells=4, interpret=True, kernel_impl=impl
    )
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(ref_pooled), atol=1e-5
    )
    for d, rd in zip(deltas, ref_deltas):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(rd))


def test_fused_pallas_tile_env_override(rng, monkeypatch):
    """NCNET_PALLAS_TILE_B_CELLS (the hardware tile-sweep knob) takes the
    same path as an explicit tile_b_cells and keeps output parity."""
    from ncnet_tpu.ops import pallas_kernels

    k = 2
    fa = jnp.asarray(rng.randn(1, 8, 4, 4).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    monkeypatch.setenv("NCNET_PALLAS_TILE_B_CELLS", "4")
    # The override must actually short-circuit the auto sizing — a dead
    # knob would still pass an output-parity check.
    monkeypatch.setattr(
        pallas_kernels, "auto_tile_b_cells",
        lambda *a, **kw: (_ for _ in ()).throw(
            AssertionError("auto sizing ran despite the env override")
        ),
    )
    pooled, deltas = fused_correlation_maxpool_pallas(
        fa, fb, k, interpret=True, kernel_impl="dots"
    )
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(ref_pooled), atol=1e-5
    )
    for d, rd in zip(deltas, ref_deltas):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(rd))


@pytest.mark.parametrize("grid_order", ["ab", "ba"])
@pytest.mark.parametrize("impl", ["bigdot", "dots"])
def test_fused_pallas_ragged_tail_tile(rng, impl, grid_order):
    """A tile width that does not divide the B cell count: the padded tail
    block must not contaminate real outputs — in either grid order (both
    run in production: 'ba' is the default, 'ab' the bench A/B baseline)."""
    k = 2
    fa = jnp.asarray(rng.randn(1, 8, 4, 4).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))  # 16 B cells
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    pooled, deltas = fused_correlation_maxpool_pallas(
        fa, fb, k, tile_b_cells=6, interpret=True, kernel_impl=impl,
        grid_order=grid_order,
    )
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(ref_pooled), atol=1e-5
    )
    for d, rd in zip(deltas, ref_deltas):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(rd))


def test_fused_feeds_corr_to_matches(rng):
    """The fused outputs plug into corr_to_matches relocalization."""
    from ncnet_tpu.ops import corr_to_matches

    k = 2
    fa = jnp.asarray(rng.randn(1, 16, 8, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 16, 8, 8).astype(np.float32))
    pooled, deltas = fused_correlation_maxpool_xla(fa, fb, k)
    xa, ya, xb, yb, score = corr_to_matches(
        pooled, delta4d=deltas, k_size=k, scale="positive"
    )
    ref_pooled, ref_deltas = _oracle(fa, fb, k)
    rxa, rya, rxb, ryb, rscore = corr_to_matches(
        ref_pooled, delta4d=ref_deltas, k_size=k, scale="positive"
    )
    np.testing.assert_allclose(np.asarray(xa), np.asarray(rxa), atol=1e-6)
    np.testing.assert_allclose(np.asarray(score), np.asarray(rscore), atol=1e-5)


def test_auto_tile_b_cells_valid_at_workload_shapes():
    """The VMEM auto-sizing must yield a Mosaic-valid tile (multiple of 128
    or the whole B-cell array) with a positive grid at every shape the
    framework actually runs — a wrong size here silently demotes bench.py
    to the unfused fallback on first hardware contact."""
    from ncnet_tpu.ops.pallas_kernels import auto_tile_b_cells

    cases = [
        # (k, va, c, n_cells_b): InLoc 3200x2400 (200x150 feats, k=2),
        # InLoc portrait, PF-Pascal-ish small, square 512-bench smoke,
        # deep-channel + tall va stress.
        (2, 75, 1024, 100 * 75),
        (2, 100, 1024, 75 * 100),
        (2, 12, 512, 12 * 12),
        (2, 16, 1024, 16 * 16),
        (2, 256, 2048, 128 * 96),
        (3, 50, 1024, 66 * 50),
    ]
    for k, va, c, n_cells in cases:
        tile = auto_tile_b_cells(k, va, c, n_cells)
        assert tile > 0, (k, va, c, n_cells)
        assert tile == n_cells or tile % 128 == 0, (tile, n_cells)
        # The per-step VMEM the formula models stays under the 16 MB scoped
        # limit: fa block + double-buffered fb/output blocks + f32 slab.
        kk = k * k
        step_bytes = (
            kk * va * c * 2
            + 2 * (kk * tile * c * 2 + 2 * tile * va * 8)
            + kk * kk * va * tile * 4
        )
        assert step_bytes < 16 * 1024 * 1024, (k, va, c, n_cells, step_bytes)


def test_fused_bigdot_auto_tile_small_input_lane_alignment(rng):
    """Small inputs where auto_tile_b_cells spans all B cells (n_cells_b
    not a multiple of 128): the bigdot path must round its tile UP to a
    128 multiple — its fused-product lane slices at n*tbc are only legal
    when 128-aligned — and the resulting whole-array padded block must not
    contaminate outputs (numerics checked here; alignment enforced by the
    guard it shares with hardware lowering)."""
    from ncnet_tpu.ops.pallas_kernels import (
        fused_correlation_maxpool_pallas,
        fused_correlation_maxpool_xla,
    )

    fa = jnp.asarray(rng.randn(1, 512, 4, 24).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 512, 24, 24).astype(np.float32))  # 144 cells
    p, d = fused_correlation_maxpool_pallas(
        fa, fb, 2, interpret=True, corr_dtype=jnp.bfloat16,
        kernel_impl="bigdot",
    )
    px, dx = fused_correlation_maxpool_xla(fa, fb, 2, corr_dtype=jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(p, np.float32), np.asarray(px, np.float32)
    )
    for a, b in zip(d, dx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_kernel_ragged_tile_tail():
    """A tile that does NOT divide the B-cell count exercises the grid's
    cdiv padding path exactly as at InLoc scale (auto tile 384 vs 7500
    cells -> tail 204; here 512 vs 750 -> tail 238, same code path, test-
    sized): the padded tail must never contaminate real outputs. The full
    c=1024 auto-sizing itself is locked in
    test_auto_tile_b_cells_valid_at_workload_shapes."""
    from ncnet_tpu.ops.pallas_kernels import (
        fused_correlation_maxpool_pallas,
        fused_correlation_maxpool_xla,
    )

    tile = 512
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    fa = jax.random.normal(k1, (1, 32, 48, 20), jnp.float32)
    fb = jax.random.normal(k2, (1, 32, 100, 30), jnp.float32)  # 750 cells
    assert 750 % tile != 0  # genuinely ragged
    p, d = fused_correlation_maxpool_pallas(
        fa, fb, 2, tile_b_cells=tile, interpret=True, corr_dtype=jnp.bfloat16
    )
    px, dx = fused_correlation_maxpool_xla(fa, fb, 2, corr_dtype=jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(p, np.float32), np.asarray(px, np.float32)
    )
    for a, b in zip(d, dx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_deltas_match_decoded(rng):
    """decode_deltas=False returns the packed offset tensor whose
    corr_to_matches consumption is identical to the decoded-tuple path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.ops.matches import corr_to_matches
    from ncnet_tpu.ops.pallas_kernels import fused_correlation_maxpool_xla

    fa = jnp.asarray(rng.randn(1, 8, 8, 6).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 6, 8).astype(np.float32))
    pooled, deltas = fused_correlation_maxpool_xla(fa, fb, k_size=2)
    pooled_p, packed = fused_correlation_maxpool_xla(
        fa, fb, k_size=2, decode_deltas=False
    )
    np.testing.assert_array_equal(np.asarray(pooled), np.asarray(pooled_p))
    assert packed.shape == pooled.shape and packed.dtype == jnp.int32
    for invert in (False, True):
        ref = corr_to_matches(
            pooled, delta4d=deltas, k_size=2, do_softmax=True, invert_matching_direction=invert
        )
        out = corr_to_matches(
            pooled, delta4d=packed, k_size=2, do_softmax=True, invert_matching_direction=invert
        )
        for r, o in zip(ref, out):
            np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=1e-6)


@pytest.mark.parametrize("impl", ["bigdot", "dots"])
def test_fused_pallas_grid_orders_agree(rng, impl):
    """'ab' and 'ba' grid iteration orders are the same computation — 'ba'
    keeps the fb block resident (~9x less HBM traffic at InLoc shapes) and
    must be bit-identical. Multi-tile grid in BOTH dims so the order
    actually matters."""
    k = 2
    fa = jnp.asarray(rng.randn(1, 16, 8, 6).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 16, 4, 12).astype(np.float32))
    outs = {}
    # tile 5 does NOT divide the 12 B cells: both orders cover the padded
    # ragged-tail tile (the production shapes are ragged too — 750 cells
    # against 128-multiple tiles).
    for order in ("ab", "ba"):
        pooled, deltas = fused_correlation_maxpool_pallas(
            fa, fb, k, tile_b_cells=5, interpret=True, kernel_impl=impl,
            grid_order=order,
        )
        outs[order] = (pooled, deltas)
    np.testing.assert_array_equal(
        np.asarray(outs["ab"][0]), np.asarray(outs["ba"][0])
    )
    for da, db in zip(outs["ab"][1], outs["ba"][1]):
        np.testing.assert_array_equal(np.asarray(da), np.asarray(db))


@pytest.mark.parametrize("impl", ["bigdot", "dots"])
def test_emit_maxes_interpret_matches_reductions(rng, impl):
    """Kernel-accumulated mutual-filter maxes == reductions over the
    pooled output, including a ragged B tail and va_pad row masking
    (negative correlations must not lose to zero-feature padding)."""
    k = 2
    fa = jnp.asarray(rng.randn(1, 8, 6, 6).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 10).astype(np.float32))
    pooled, _, (row_max, col_max) = fused_correlation_maxpool_pallas(
        fa, fb, k, interpret=True, kernel_impl=impl, tile_b_cells=128,
        emit_maxes=True, grid_order="ab",
    )
    p32 = np.asarray(pooled, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(row_max), p32.max(axis=(4, 5)).reshape(-1), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(col_max), p32.max(axis=(2, 3)).reshape(-1), rtol=1e-6
    )
    # XLA fallback emits the same statistics.
    _, _, (rx, cx) = fused_correlation_maxpool_xla(
        fa, fb, k, emit_maxes=True
    )
    np.testing.assert_allclose(np.asarray(rx), np.asarray(row_max), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(cx), np.asarray(col_max), rtol=1e-6)


def test_emit_maxes_requires_ab_order(rng):
    fa = jnp.asarray(rng.randn(1, 4, 4, 4).astype(np.float32))
    with pytest.raises(ValueError, match="emit_maxes requires grid_order"):
        fused_correlation_maxpool_pallas(
            fa, fa, 2, interpret=True, emit_maxes=True, grid_order="ba"
        )


def test_mutual_matching_precomputed_maxes(rng):
    """mutual_matching(maxes=...) == the self-reducing formulation."""
    from ncnet_tpu.ops.mutual import mutual_matching

    c = jnp.asarray(
        rng.randn(1, 1, 4, 5, 6, 3).astype(np.float32)
    ).astype(jnp.bfloat16)
    c32 = c.astype(jnp.float32)
    per_a = jnp.max(c32, axis=(4, 5)).reshape(-1)
    per_b = jnp.max(c32, axis=(2, 3)).reshape(-1)
    got = mutual_matching(c, maxes=(per_a, per_b))
    want = mutual_matching(c)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_forward_fuse_corr_maxes_env_parity(rng, monkeypatch):
    """NCNET_FUSE_CORR_MAXES=1 leaves the forward output unchanged."""
    from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
    from ncnet_tpu.models.ncnet import ncnet_forward_from_features

    config = NCNetConfig(
        backbone=BackboneConfig(),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(4, 1),
        relocalization_k_size=2,
        half_precision=True,
        use_fused_corr_pool=True,
    )
    params = ncnet_init(jax.random.PRNGKey(0), config)
    fa = jnp.asarray(rng.randn(1, 1024, 8, 6).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 1024, 6, 8).astype(np.float32))
    base_corr, base_delta = ncnet_forward_from_features(config, params, fa, fb)
    monkeypatch.setenv("NCNET_FUSE_CORR_MAXES", "1")
    corr, delta = ncnet_forward_from_features(config, params, fa, fb)
    np.testing.assert_allclose(
        np.asarray(corr), np.asarray(base_corr), atol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(delta), np.asarray(base_delta))


