"""Golden tests for the unified warper + synth-pair generators.

Oracle: an inline torch re-statement of the reference semantics
(geotnf/transformation.py:14-368) — align_corners=True grids, sentinel-masked
aff∘TPS composition, symmetric padding — evaluated on CPU.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from ncnet_tpu.geometry import TpsGrid
from ncnet_tpu.geometry.transform import (
    compose_aff_tps_grid,
    composed_transform,
    geometric_transform,
    make_sampling_grid,
    symmetric_image_pad,
    synth_pair,
    synth_two_pair,
    synth_two_stage,
    synth_two_stage_two_pair,
)


def torch_affine_grid(theta, h, w):
    return F.affine_grid(
        torch.tensor(np.asarray(theta).reshape(-1, 2, 3)), (len(theta), 1, h, w),
        align_corners=True,
    )


def torch_sample(img, grid):
    return F.grid_sample(
        torch.tensor(np.asarray(img)), grid, mode="bilinear",
        padding_mode="zeros", align_corners=True,
    ).numpy()


def small_theta_aff(rng, b):
    """Random near-identity affine params [b, 6] in V2 (x-row, y-row) order."""
    base = np.array([1.0, 0, 0, 0, 1.0, 0], dtype=np.float32)
    return base + 0.2 * rng.randn(b, 6).astype(np.float32)


def small_theta_tps(rng, b, grid_size=3):
    """Near-identity TPS control displacements [b, 2*N] (X block then Y)."""
    axis = np.linspace(-1, 1, grid_size)
    py, px = np.meshgrid(axis, axis)
    base = np.concatenate([px.reshape(-1), py.reshape(-1)]).astype(np.float32)
    return base + 0.15 * rng.randn(b, 2 * grid_size**2).astype(np.float32)


def test_symmetric_image_pad_matches_np_symmetric(rng):
    img = rng.rand(2, 3, 8, 12).astype(np.float32)
    ours = np.asarray(symmetric_image_pad(jnp.asarray(img), 0.5))
    ref = np.pad(img, ((0, 0), (0, 0), (4, 4), (6, 6)), mode="symmetric")
    np.testing.assert_allclose(ours, ref)


def test_geometric_transform_identity_is_scaled_resize(rng):
    img = rng.rand(1, 3, 16, 16).astype(np.float32)
    out = geometric_transform(
        jnp.asarray(img), None, out_h=8, out_w=8,
        padding_factor=0.5, crop_factor=0.5,
    )
    theta = torch.tensor([[[1.0, 0, 0], [0, 1.0, 0]]])
    grid = F.affine_grid(theta, (1, 1, 8, 8), align_corners=True) * 0.25
    np.testing.assert_allclose(np.asarray(out), torch_sample(img, grid), atol=1e-5)


def test_affine_offset_factor_scales_translation(rng):
    theta = small_theta_aff(rng, 2)
    ours = np.asarray(
        make_sampling_grid(jnp.asarray(theta), 6, 7, "affine", offset_factor=0.5)
    )
    # Reference: base grid / f, affine, result * f == translation scaled by f.
    t = theta.reshape(2, 2, 3).copy()
    t[:, :, 2] *= 0.5
    ref = torch_affine_grid(t, 6, 7).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_tps_offset_factor_literal_semantics(rng):
    theta = small_theta_tps(rng, 1)
    f = 0.75
    ours = np.asarray(
        make_sampling_grid(jnp.asarray(theta), 5, 5, "tps", offset_factor=f)
    )
    tps = TpsGrid(3)
    xs = np.linspace(-1, 1, 5) / f
    gx, gy = np.meshgrid(xs, xs)
    pts = jnp.asarray(np.stack([gx, gy], axis=-1), dtype=jnp.float32)
    ref = np.asarray(tps.apply(jnp.asarray(theta), pts)) * f
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_composed_grid_identity_tps_equals_masked_affine(rng):
    """With identity TPS control points, composition = sentinel-masked affine.

    Uses a shrinking affine (all positions strictly in bounds) for the
    equality half — boundary pixels adjacent to sentinel regions are
    contaminated by bilinear sentinel bleed in the reference semantics too,
    so exact comparison is only meaningful when no sentinel exists.
    """
    b = 2
    axis = np.linspace(-1, 1, 3)
    py, px = np.meshgrid(axis, axis)
    theta_tps = np.tile(
        np.concatenate([px.reshape(-1), py.reshape(-1)]).astype(np.float32), (b, 1)
    )
    # strictly contracting affine: |x'|,|y'| <= 0.55 < 1 everywhere
    theta_aff = np.tile(
        np.array([0.5, 0, 0.05, 0, 0.5, -0.05], dtype=np.float32), (b, 1)
    )
    grid = np.asarray(
        compose_aff_tps_grid(jnp.asarray(theta_aff), jnp.asarray(theta_tps), 9, 9)
    )
    aff = torch_affine_grid(theta_aff.reshape(b, 2, 3), 9, 9).numpy()
    # The outermost ring of the output sits exactly at ±1 in the TPS grid and
    # fails the reference's strict (>-1, <1) bounds test, so it carries the
    # sentinel by design; compare the interior.
    np.testing.assert_allclose(grid[:, 1:-1, 1:-1], aff[:, 1:-1, 1:-1], atol=1e-4)
    assert (np.abs(grid[:, 0, :]) > 1e5).all()

    # an expanding affine leaves the valid region: corners carry the sentinel
    theta_big = np.tile(
        np.array([3.0, 0, 0, 0, 3.0, 0], dtype=np.float32), (b, 1)
    )
    grid_big = np.asarray(
        compose_aff_tps_grid(jnp.asarray(theta_big), jnp.asarray(theta_tps), 9, 9)
    )
    assert (np.abs(grid_big[:, 0, 0]) > 1e5).all()
    assert (np.abs(grid_big[:, -1, -1]) > 1e5).all()


def test_composed_transform_matches_torch_oracle(rng):
    """Full composed warp vs an inline torch oracle of the reference math."""
    b = 2
    img = rng.rand(b, 3, 20, 20).astype(np.float32)
    theta_aff = small_theta_aff(rng, b)
    theta_tps = small_theta_tps(rng, b)
    pcf = 0.5 * 9 / 16

    ours = np.asarray(
        composed_transform(
            jnp.asarray(img), jnp.asarray(theta_aff), jnp.asarray(theta_tps),
            out_h=12, out_w=12, padding_crop_factor=pcf,
        )
    )

    # torch oracle
    t = theta_aff.reshape(b, 2, 3).copy()
    t[:, :, 2] *= pcf
    grid_aff = torch_affine_grid(t, 12, 12)
    tps = TpsGrid(3)
    grid_tps = torch.tensor(
        np.asarray(tps.grid(jnp.asarray(theta_tps), 12, 12))
    ) * pcf
    inb = (
        (grid_aff[..., 0] > -1) & (grid_aff[..., 0] < 1)
        & (grid_aff[..., 1] > -1) & (grid_aff[..., 1] < 1)
    ).unsqueeze(3).float()
    grid_aff = grid_aff * inb + (inb - 1) * 1e10
    comp = F.grid_sample(
        grid_aff.permute(0, 3, 1, 2), grid_tps, align_corners=True
    ).permute(0, 2, 3, 1)
    inb2 = (
        (grid_tps[..., 0] > -1) & (grid_tps[..., 0] < 1)
        & (grid_tps[..., 1] > -1) & (grid_tps[..., 1] < 1)
    ).unsqueeze(3).float()
    comp = comp * inb2 + (inb2 - 1) * 1e10
    ref = torch_sample(img, comp)
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_synth_pair_strong_shapes_and_crop(rng):
    img = rng.rand(4, 3, 32, 32).astype(np.float32)
    theta = small_theta_aff(rng, 4)
    out = synth_pair(jnp.asarray(img), jnp.asarray(theta), output_size=(16, 16))
    assert out["source_image"].shape == (4, 3, 16, 16)
    assert out["target_image"].shape == (4, 3, 16, 16)
    # source = identity crop: padded image sampled on grid*(0.5*9/16)
    padded = np.pad(img, ((0, 0), (0, 0), (16, 16), (16, 16)), mode="symmetric")
    theta_id = torch.tensor([[[1.0, 0, 0], [0, 1.0, 0]]]).expand(4, 2, 3)
    grid = F.affine_grid(theta_id, (4, 1, 16, 16), align_corners=True) * (0.5 * 9 / 16)
    np.testing.assert_allclose(
        np.asarray(out["source_image"]), torch_sample(padded, grid), atol=1e-5
    )


def test_synth_pair_weak_negatives(rng):
    img = rng.rand(4, 3, 16, 16).astype(np.float32)
    theta = small_theta_aff(rng, 4)
    strong = synth_pair(jnp.asarray(img), jnp.asarray(theta), supervision="strong")
    weak = synth_pair(jnp.asarray(img), jnp.asarray(theta), supervision="weak")
    s, t = np.asarray(strong["source_image"]), np.asarray(strong["target_image"])
    np.testing.assert_allclose(np.asarray(weak["source_image"]),
                               np.concatenate([s[:2], s[:2]]))
    np.testing.assert_allclose(np.asarray(weak["target_image"]),
                               np.concatenate([t[:2], s[2:]]))


def test_synth_two_pair_consistency(rng):
    img = rng.rand(2, 3, 24, 24).astype(np.float32)
    theta = np.concatenate(
        [small_theta_aff(rng, 2), small_theta_tps(rng, 2)], axis=1
    )
    out = synth_two_pair(jnp.asarray(img), jnp.asarray(theta), output_size=(12, 12))
    aff_only = synth_pair(
        jnp.asarray(img), jnp.asarray(theta[:, :6]), output_size=(12, 12)
    )
    np.testing.assert_allclose(
        np.asarray(out["target_image_aff"]),
        np.asarray(aff_only["target_image"]), atol=1e-5,
    )
    tps_only = synth_pair(
        jnp.asarray(img), jnp.asarray(theta[:, 6:]), geometric_model="tps",
        output_size=(12, 12),
    )
    np.testing.assert_allclose(
        np.asarray(out["target_image_tps"]),
        np.asarray(tps_only["target_image"]), atol=1e-5,
    )


def test_synth_two_stage_keys(rng):
    img = rng.rand(2, 3, 24, 24).astype(np.float32)
    theta = np.concatenate(
        [small_theta_aff(rng, 2), small_theta_tps(rng, 2)], axis=1
    )
    out = synth_two_stage(jnp.asarray(img), jnp.asarray(theta), output_size=(12, 12))
    assert set(out) == {
        "source_image", "target_image", "theta_GT_aff", "theta_GT_tps"
    }
    assert out["target_image"].shape == (2, 3, 12, 12)
    assert np.isfinite(np.asarray(out["target_image"])).all()


def test_synth_two_stage_two_pair_keys(rng):
    img = rng.rand(2, 3, 24, 24).astype(np.float32)
    theta = np.concatenate(
        [small_theta_aff(rng, 2), small_theta_tps(rng, 2)], axis=1
    )
    out = synth_two_stage_two_pair(
        jnp.asarray(img), jnp.asarray(theta), output_size=(12, 12)
    )
    assert set(out) == {
        "source_image_aff", "target_image_aff", "source_image_tps",
        "target_image_tps", "theta_GT_aff", "theta_GT_tps",
    }
    for k in ("source_image_aff", "target_image_aff", "source_image_tps",
              "target_image_tps"):
        assert out[k].shape == (2, 3, 12, 12)


def test_tps_grid_batch_equals_out_h(rng):
    """Regression: b == out_h must not trip TpsGrid.apply's batch inference."""
    b = 12
    theta = small_theta_tps(rng, b)
    grid = make_sampling_grid(jnp.asarray(theta), b, 7, "tps")
    assert grid.shape == (b, 12, 7, 2)
    # every batch element is transformed by its own theta
    grid1 = make_sampling_grid(jnp.asarray(theta[:1]), b, 7, "tps")
    np.testing.assert_allclose(np.asarray(grid[:1]), np.asarray(grid1), atol=1e-6)


def test_synth_pair_weak_odd_batch_raises(rng):
    img = rng.rand(3, 3, 16, 16).astype(np.float32)
    theta = small_theta_aff(rng, 3)
    with pytest.raises(ValueError):
        synth_pair(jnp.asarray(img), jnp.asarray(theta), supervision="weak")
