"""Utils tests: batching helpers, profiling, plotting, file helpers."""


import numpy as np
import pytest

from ncnet_tpu.utils import (
    PhaseTimer,
    collate_ragged,
    create_file_path,
    expand_dim,
    phase,
    softmax_1d,
    str_to_bool,
    trace_context,
)
from ncnet_tpu.utils.plot import denormalize_for_display, plot_matches_horizontal, save_image


def test_create_file_path(tmp_path):
    target = tmp_path / "a" / "b" / "c.txt"
    create_file_path(str(target))
    assert target.parent.is_dir()
    create_file_path("no_dir_component.txt")  # no-op, no crash


def test_collate_ragged():
    samples = [
        {"img": np.zeros((3, 4)), "pts": np.zeros((2, 5)), "name": "a", "n": 1},
        {"img": np.ones((3, 4)), "pts": np.zeros((2, 7)), "name": "b", "n": 2},
    ]
    out = collate_ragged(samples)
    assert out["img"].shape == (2, 3, 4)
    assert isinstance(out["pts"], list) and len(out["pts"]) == 2  # ragged -> list
    assert out["name"] == ["a", "b"]
    assert np.array_equal(out["n"], [1, 2])
    assert collate_ragged([]) == {}


def test_softmax_and_expand():
    x = np.array([[1.0, 2.0, 3.0]])
    s = np.asarray(softmax_1d(x))
    assert np.allclose(s.sum(axis=-1), 1.0)
    assert np.all(np.diff(s[0]) > 0)
    e = np.asarray(expand_dim(np.zeros((2, 3)), 0, 4))
    assert e.shape == (4, 2, 3)


def test_str_to_bool():
    assert str_to_bool("yes") and str_to_bool("True") and str_to_bool(True)
    assert not str_to_bool("0") and not str_to_bool("no")
    with pytest.raises(ValueError):
        str_to_bool("maybe")


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"), t.phase("b"):
        pass
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert "a" in t.report()
    d = t.as_dict()
    assert d["a"]["calls"] == 2
    with phase("global_phase"):
        pass
    with trace_context(None):  # no-op path
        pass


def test_phase_timer_sync():
    import jax.numpy as jnp

    t = PhaseTimer()
    with t.phase("matmul", sync=jnp.ones((8, 8)) @ jnp.ones((8, 8))):
        pass
    assert t.totals["matmul"] > 0


def test_plot_helpers(tmp_path):
    img = np.random.default_rng(0).normal(size=(3, 32, 48)).astype(np.float32)
    disp = denormalize_for_display(img)
    assert disp.shape == (32, 48, 3) and disp.min() >= 0 and disp.max() <= 1

    out = tmp_path / "img.png"
    save_image(img, str(out))
    assert out.stat().st_size > 0

    out2 = tmp_path / "matches.png"
    a = np.random.default_rng(1).uniform(size=(32, 48, 3))
    b = np.random.default_rng(2).uniform(size=(40, 48, 3))
    pa = np.array([[5.0, 6.0], [10.0, 12.0]])
    pb = np.array([[7.0, 8.0], [11.0, 13.0]])
    plot_matches_horizontal(a, b, pa, pb, str(out2), inliers=np.array([True, False]))
    assert out2.stat().st_size > 0


def test_run_with_alarm_timeout_and_value():
    import time

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    assert run_with_alarm(5, lambda: 42) == 42
    import pytest as _pytest

    with _pytest.raises(AlarmTimeout):
        run_with_alarm(1, time.sleep, 10)


def test_run_with_alarm_flies_past_except_exception():
    """AlarmTimeout must not be swallowed by the bench tools' broad
    per-candidate `except Exception` handlers (it is a BaseException)."""
    import time

    import pytest as _pytest

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    def swallowing():
        try:
            time.sleep(10)
        except Exception:  # noqa: BLE001 — the pattern under test
            return "swallowed"

    with _pytest.raises(AlarmTimeout):
        run_with_alarm(1, swallowing)


def test_run_with_alarm_inner_fence_restores_outer():
    """A nested (per-candidate) fence must re-arm the outer (phase) fence
    on exit — the 2026-07-31 session-starvation regression guard."""
    import time

    import pytest as _pytest

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    def body():
        run_with_alarm(30, lambda: None)  # fast inner fence
        time.sleep(10)  # outer 2 s fence must still fire here

    with _pytest.raises(AlarmTimeout):
        run_with_alarm(2, body)


def test_run_with_alarm_inner_cannot_extend_outer():
    """Inner fences longer than the outer's remaining budget are clamped:
    a phase of candidates whose handlers swallow AlarmTimeout (the bench
    tools' pattern) drains in ~1 s per candidate once the outer budget is
    spent, instead of running each candidate to its own full bound."""
    import time

    from ncnet_tpu.utils.profiling import AlarmTimeout, run_with_alarm

    done = []

    def body():
        for i in range(4):
            try:
                run_with_alarm(30, time.sleep, 3)
                done.append(i)
            except AlarmTimeout:
                pass

    t0 = time.monotonic()
    try:
        run_with_alarm(2, body)
    except AlarmTimeout:
        pass
    elapsed = time.monotonic() - t0
    # Unclamped, body would sleep 4 x 3 s = 12 s; the 2 s outer fence must
    # bound it to ~2 s + ~1 s per remaining clamped candidate.
    assert elapsed < 9, f"outer fence failed to bound nested fences: {elapsed:.1f}s"
    assert len(done) < 4
