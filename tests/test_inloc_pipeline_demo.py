"""End-to-end pipeline demo test: eval_inloc -> localize -> rate curve.

The composed user-facing flow the reference splits across Python AND
Matlab (eval_inloc.py + compute_densePE_NCNet.m), here one in-process run
on a synthetic scene with identity ground truth (see
examples/inloc_pipeline_demo.py for the construction).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))


def test_pipeline_demo_localizes_identity(tmp_path):
    import inloc_pipeline_demo

    rc = inloc_pipeline_demo.main(
        ["--out", str(tmp_path), "--size", "128", "--ransac_iters", "500"]
    )
    assert rc == 0  # recovered translation error < 0.25 m
    assert (tmp_path / "out" / "localization_curve.png").exists()
