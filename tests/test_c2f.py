"""Coarse-to-fine matching: ops bookkeeping, factor-1 equivalence, eval path.

The c2f mode's quality story rests on two invariants this file pins:

* Degenerate knobs (factor 1, top-K >= all cells) route through the
  UNMODIFIED one-shot program — bit-identical outputs, relocalization
  included — so turning the mode on with neutral knobs can never change
  a result (the exact quality gate of docs/PERF.md).
* The live path's crop/splice bookkeeping is exact: window starts equal
  what was sliced, refined rows land on their aligned fine-grid blocks,
  and every non-refined cell carries its coarse fallback — checked here
  on hand-built tensors and on ragged, non-square grids.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.evals import c2f_device_matches, inloc_device_matches
from ncnet_tpu.models import BackboneConfig, NCNetConfig, ncnet_init
from ncnet_tpu.models.ncnet import (
    c2f_coarse_from_features,
    c2f_is_degenerate,
    c2f_raw_matches_from_features,
    c2f_stride,
    ncnet_forward_from_features,
)
from ncnet_tpu.ops import avgpool2d_features
from ncnet_tpu.ops.c2f import coarse_gate, gather_windows, splice_matches


def _cfg(**kw):
    base = dict(
        backbone=BackboneConfig(cnn="vgg", last_layer="pool3"),
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(4, 1),
        relocalization_k_size=2,
        mode="c2f",
        c2f_coarse_factor=2,
        c2f_topk=4,
        c2f_radius=1,
    )
    base.update(kw)
    return NCNetConfig(**base)


def _feats(key, c, h, w):
    f = jax.random.normal(key, (1, c, h, w), jnp.float32)
    return f / jnp.linalg.norm(f, axis=1, keepdims=True)


# -- config ---------------------------------------------------------------


def test_config_validates_c2f_knobs():
    with pytest.raises(ValueError):
        _cfg(mode="bogus")
    with pytest.raises(ValueError):
        _cfg(c2f_coarse_factor=0)
    with pytest.raises(ValueError):
        _cfg(c2f_radius=-1)
    assert c2f_stride(_cfg()) == 4                # factor 2 x reloc k 2
    assert c2f_stride(_cfg(relocalization_k_size=1)) == 2


def test_degenerate_predicate():
    shp = (1, 8, 8, 8)
    # Factor 1 + keep-everything gate -> one-shot by construction.
    assert c2f_is_degenerate(_cfg(c2f_coarse_factor=1, c2f_topk=0),
                             shp, shp)
    # k=2 relocalization: 8x8 features -> 16 coarse cells per direction.
    assert c2f_is_degenerate(_cfg(c2f_coarse_factor=1, c2f_topk=16),
                             shp, shp)
    assert not c2f_is_degenerate(_cfg(c2f_coarse_factor=1, c2f_topk=15),
                                 shp, shp)
    # Ragged: the gate must keep all cells in BOTH probe directions.
    assert not c2f_is_degenerate(_cfg(c2f_coarse_factor=1, c2f_topk=16),
                                 shp, (1, 8, 8, 10))
    # Any real pooling is never degenerate.
    assert not c2f_is_degenerate(_cfg(c2f_topk=0), shp, shp)


# -- ops ------------------------------------------------------------------


def test_avgpool2d_features():
    f = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 8, 12), jnp.float32)
    p = avgpool2d_features(f, 2)
    assert p.shape == (1, 6, 4, 6)
    norms = jnp.linalg.norm(p, axis=1)
    np.testing.assert_allclose(np.asarray(norms), 1.0, atol=1e-5)
    raw = avgpool2d_features(f, 2, renorm=False)
    np.testing.assert_allclose(
        np.asarray(raw[0, :, 0, 0]),
        np.asarray(f[0, :, :2, :2].mean(axis=(1, 2))), rtol=1e-5)
    assert avgpool2d_features(f, 1) is f
    with pytest.raises(ValueError):
        avgpool2d_features(f, 3)  # 8 % 3 != 0


def test_coarse_gate_statistics_and_topk():
    flat = jnp.asarray([
        [0.1, 0.9, 0.0, 0.2],
        [0.5, 0.1, 0.3, 0.0],
        [0.2, 0.2, 0.8, 0.1],
        [0.0, 0.3, 0.1, 0.7],
    ], jnp.float32)
    coarse4d = flat.reshape(1, 1, 2, 2, 2, 2)
    top_s, top_c, cell_s, mb = coarse_gate(coarse4d, 2)
    np.testing.assert_allclose(np.asarray(top_s), [0.9, 0.8])
    assert np.asarray(top_c).tolist() == [0, 2]
    np.testing.assert_allclose(np.asarray(cell_s), [0.9, 0.5, 0.8, 0.7])
    assert np.asarray(mb).tolist() == [1, 0, 2, 3]
    # topk <= 0 keeps every cell; topk > n clamps.
    for k in (0, 9):
        top_s, top_c, _, _ = coarse_gate(coarse4d, k)
        assert top_c.shape == (4,)
        assert np.asarray(top_c).tolist() == [0, 2, 3, 1]
    with pytest.raises(ValueError):
        coarse_gate(jnp.zeros((2, 1, 2, 2, 2, 2)), 2)


def test_gather_windows_starts_and_content():
    ka, kb = jax.random.split(jax.random.PRNGKey(1))
    feat_a = _feats(ka, 3, 8, 8)
    feat_b = _feats(kb, 3, 8, 8)
    top_cells = jnp.asarray([3], jnp.int32)        # coarse A cell (1, 1)
    matched_b = jnp.asarray([0, 0, 0, 2], jnp.int32)  # -> B cell (1, 0)
    win_a, win_b, sbi, sbj = gather_windows(
        feat_a, feat_b, top_cells, matched_b, stride=4, radius=0,
        coarse_shape=(2, 2, 2, 2),
    )
    assert win_a.shape == (1, 3, 4, 4) and win_b.shape == (1, 3, 4, 4)
    # A window: the aligned fine block of coarse cell (1, 1), exact.
    np.testing.assert_array_equal(
        np.asarray(win_a[0]), np.asarray(feat_a[0, :, 4:8, 4:8]))
    # B window: centered on B cell (1, 0), clipped into the grid — the
    # returned starts must equal what was sliced.
    assert (int(sbi[0]), int(sbj[0])) == (4, 0)
    np.testing.assert_array_equal(
        np.asarray(win_b[0]), np.asarray(feat_b[0, :, 4:8, 0:4]))
    # radius 1 covers the whole 8-cell grid: starts clip to 0.
    _, win_b, sbi, sbj = gather_windows(
        feat_a, feat_b, top_cells, matched_b, stride=4, radius=1,
        coarse_shape=(2, 2, 2, 2),
    )
    assert win_b.shape == (1, 3, 8, 8)
    assert (int(sbi[0]), int(sbj[0])) == (0, 0)


def test_splice_matches_bookkeeping():
    """Refined rows land exactly on their aligned fine block; every other
    row carries the coarse fallback (matched coarse-B cell center +
    coarse score)."""
    s, k = 2, 1
    top_cells = jnp.asarray([3], jnp.int32)        # coarse A cell (1, 1)
    cell_scores = jnp.asarray([0.1, 0.2, 0.3, 0.4], jnp.float32)
    matched_b = jnp.asarray([0, 1, 2, 3], jnp.int32)
    refined = jnp.zeros((k, 1, s, s, 4, 4), jnp.float32)
    refined = refined.at[0, 0, 0, 0, 2, 3].set(5.0)
    i_a, j_a, i_b, j_b, score = splice_matches(
        refined, top_cells, cell_scores, matched_b,
        jnp.asarray([0], jnp.int32), jnp.asarray([0], jnp.int32),
        coarse_shape=(2, 2, 2, 2), fine_shape=(4, 4, 4, 4), stride=s,
    )
    i_a, j_a, i_b, j_b, score = (np.asarray(v)[0]
                                 for v in (i_a, j_a, i_b, j_b, score))
    assert i_a.tolist() == np.repeat(np.arange(4), 4).tolist()
    assert j_a.tolist() == np.tile(np.arange(4), 4).tolist()
    # Refined block: fine rows {10, 11, 14, 15} = coarse cell (1,1)*s.
    # Subcell (0,0) (row 10) took the planted max at window B (2, 3).
    assert (i_b[10], j_b[10], score[10]) == (2, 3, 5.0)
    # Its siblings saw all-zero windows: argmax 0 -> window origin.
    for row in (11, 14, 15):
        assert (i_b[row], j_b[row], score[row]) == (0, 0, 0.0)
    # Fallbacks: fine (0,0) -> coarse cell 0, matched B cell 0, whose
    # fine-grid center is (1, 1); fine (0,3) -> coarse cell 1 -> B cell
    # 1 -> center (1, 3). Scores are the coarse cell scores.
    assert (i_b[0], j_b[0], score[0]) == (1, 1, np.float32(0.1))
    assert (i_b[3], j_b[3], score[3]) == (1, 3, np.float32(0.2))


# -- factor-1 equivalence (the exact quality gate) ------------------------


@pytest.mark.parametrize("k_size", [1, 2])
def test_factor1_topk_all_bit_identical_to_oneshot(k_size):
    config = _cfg(relocalization_k_size=k_size, c2f_coarse_factor=1,
                  c2f_topk=0)
    params = ncnet_init(jax.random.PRNGKey(0), config)
    ka, kb = jax.random.split(jax.random.PRNGKey(2))
    feat_a = _feats(ka, 8, 8, 8)
    feat_b = _feats(kb, 8, 8, 8)

    oneshot = dataclasses.replace(config, mode="oneshot")
    corr, delta = ncnet_forward_from_features(oneshot, params,
                                              feat_a, feat_b)
    ref = jax.jit(inloc_device_matches, static_argnames=("k_size",))(
        corr, delta4d=delta, k_size=max(k_size, 1))
    got = jax.jit(c2f_device_matches, static_argnums=0)(
        config, params, feat_a, feat_b)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))

    # Stage 1 at factor 1 IS the one-shot forward, bitwise.
    c_corr, c_delta = c2f_coarse_from_features(config, params,
                                               feat_a, feat_b)
    np.testing.assert_array_equal(np.asarray(c_corr), np.asarray(corr))
    if delta is None:
        assert c_delta is None
    else:
        np.testing.assert_array_equal(np.asarray(c_delta),
                                      np.asarray(delta))


# -- live path on ragged, non-square grids --------------------------------


def test_c2f_live_ragged_grids():
    config = _cfg(c2f_topk=3)
    params = ncnet_init(jax.random.PRNGKey(0), config)
    ka, kb = jax.random.split(jax.random.PRNGKey(3))
    feat_a = _feats(ka, 6, 16, 12)   # 16x12 vs 12x20: ragged AND
    feat_b = _feats(kb, 6, 12, 20)   # non-square on both sides
    outs = c2f_raw_matches_from_features(
        config, params, feat_a, feat_b, both_directions=True,
        scale="positive",
    )
    n = 12 * 20 + 16 * 12  # per-B field + per-A field
    for o in outs:
        assert o.shape == (1, n)
        assert np.isfinite(np.asarray(o)).all()
    xa, ya, xb, yb, _ = (np.asarray(o) for o in outs)
    for v in (xa, ya, xb, yb):
        assert (v >= 0.0).all() and (v <= 1.0).all()

    # The sorted device-matches wrapper: descending scores, same count.
    got = jax.jit(c2f_device_matches, static_argnums=0)(
        config, params, feat_a, feat_b)
    score = np.asarray(got[4])
    assert score.shape == (n,)
    assert (np.diff(score) <= 1e-6).all()

    # Batch > 1 is a contract violation, not a silent wrong answer.
    with pytest.raises(ValueError):
        c2f_raw_matches_from_features(
            config, params, jnp.concatenate([feat_a, feat_a]), feat_b)


# -- eval harness ---------------------------------------------------------


def test_evaluate_pck_c2f_modes(tmp_path):
    """evaluate_pck under mode='c2f': the degenerate route scores
    IDENTICALLY to one-shot, and the live route runs end to end on a
    real (synthetic) dataset through the batched lax.map path."""
    from tests.test_evals_data import _write_synthetic_dataset

    from ncnet_tpu.cli.eval_pck import evaluate_pck
    from ncnet_tpu.data import PFPascalDataset

    root = str(tmp_path)
    _write_synthetic_dataset(root, n_pairs=2, size=64)
    dataset = PFPascalDataset(os.path.join(root, "eval.csv"), root,
                              output_size=(64, 64))
    config = _cfg()                 # vgg pool3: 64 px -> 8x8 features
    params = ncnet_init(jax.random.PRNGKey(0), config)

    oneshot = dataclasses.replace(config, mode="oneshot")
    _, per_os = evaluate_pck(oneshot, params, dataset, batch_size=2,
                             num_workers=1, verbose=False)
    degen = dataclasses.replace(config, c2f_coarse_factor=1, c2f_topk=0)
    _, per_deg = evaluate_pck(degen, params, dataset, batch_size=2,
                              num_workers=1, verbose=False)
    np.testing.assert_array_equal(per_os, per_deg)

    _, per_c2f = evaluate_pck(config, params, dataset, batch_size=2,
                              num_workers=1, verbose=False)
    assert per_c2f.shape == per_os.shape
    assert np.isfinite(per_c2f).all()
    assert ((per_c2f >= 0) & (per_c2f <= 1)).all()
