"""Multi-tenant QoS (ncnet_tpu/serving/qos.py, ISSUE 12).

Three layers of coverage:

* Unit — TokenBucket / TenantTable / ladder grammar / QosController
  state machine on fake clocks: admission budgets, priority-hint
  lowering, bounded tenant cardinality, step-down rate limiting,
  step-up hysteresis, and bottom-priority-first shed order are pure
  control flow and must be testable at microsecond cost.
* Batcher — the per-tenant queue-slot cap (fairness isolation inside
  DeadlineBatcher, scope="tenant" rejections, slot release after run).
* CPU end-to-end — a real MatchServer with a quality ladder under
  synthetic pressure: low-priority traffic degrades then sheds while
  interactive traffic keeps serving; tenant budgets surface as 429s;
  an idle QoS layer is bit-identical to the plain path (the
  degenerate-ladder contract); draining refusals carry their kind.
"""

import threading

import pytest

from ncnet_tpu import obs
from ncnet_tpu.serving.batcher import DeadlineBatcher, RejectedError
from ncnet_tpu.serving.qos import (
    PRIORITY_CLASSES,
    QosController,
    QosDecision,
    Rung,
    TenantPolicy,
    TenantTable,
    TokenBucket,
    parse_ladder,
    parse_tenant_spec,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- token bucket ----------------------------------------------------------


def test_token_bucket_rate_and_refill():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=2.0, clock=clk)
    assert b.try_take() is None
    assert b.try_take() is None
    wait = b.try_take()
    assert wait == pytest.approx(0.5)  # 1 token at 2/s = 0.5 s away
    clk.t += 0.5
    assert b.try_take() is None, "refilled token admits"
    # Refill never exceeds burst: a long idle spell buys burst, not more.
    clk.t += 100.0
    assert b.try_take() is None
    assert b.try_take() is None
    assert b.try_take() is not None


def test_token_bucket_unlimited_and_default_burst():
    clk = FakeClock()
    assert TokenBucket(0.0, clock=clk).try_take() is None
    assert TokenBucket(-1.0, clock=clk).try_take() is None
    # Default burst = max(rate, 1): rate 0.5 still admits one request.
    b = TokenBucket(0.5, clock=clk)
    assert b.try_take() is None
    assert b.try_take() is not None


# -- tenant specs and table ------------------------------------------------


def test_parse_tenant_spec_grammar():
    p = parse_tenant_spec("acme:batch")
    assert (p.tenant, p.priority, p.rate, p.burst) == ("acme", "batch",
                                                       0.0, 0.0)
    p = parse_tenant_spec("acme:interactive:5:10")
    assert (p.rate, p.burst) == (5.0, 10.0)
    for bad in ("acme", ":batch", "a:b:c:d:e", "acme:nope",
                "acme:batch:notanumber"):
        with pytest.raises(ValueError):
            parse_tenant_spec(bad)


def test_tenant_table_resolve_and_priority_hint_only_lowers():
    clk = FakeClock()
    table = TenantTable([TenantPolicy("acme", "batch", rate=1.0)],
                        clock=clk)
    # Unlabeled traffic folds into the default (interactive) tenant.
    name, prio, bucket = table.resolve(None)
    assert (name, prio) == ("default", "interactive")
    assert bucket.try_take() is None  # default rate 0 = unlimited
    # Declared tenant gets its declared class and its own budget.
    name, prio, bucket = table.resolve("acme")
    assert (name, prio) == ("acme", "batch")
    assert bucket is table.resolve("acme")[2], "bucket is stable"
    # The hint can self-LOWER below the declared class...
    assert table.resolve("acme", "best_effort")[1] == "best_effort"
    # ...but never self-UPGRADE, and garbage hints are ignored.
    assert table.resolve("acme", "interactive")[1] == "batch"
    assert table.resolve("acme", "platinum")[1] == "batch"


def test_tenant_table_strangers_bounded_by_overflow():
    clk = FakeClock()
    table = TenantTable(max_tenants=2, clock=clk)
    assert table.resolve("x1")[0] == "x1"
    assert table.resolve("x2")[0] == "x2"
    # Past the bound, newcomers share one overflow identity (bounded
    # state and metric cardinality)...
    assert table.resolve("x3")[0] == "other"
    assert table.resolve("x4")[0] == "other"
    assert table.resolve("x3")[2] is table.resolve("x4")[2]
    # ...but already-seen names and the default keep their identity.
    assert table.resolve("x1")[0] == "x1"
    assert table.resolve(None)[0] == "default"


# -- quality ladder grammar ------------------------------------------------


def test_parse_ladder_grammar():
    ladder = parse_ladder("c2f:factor=2,topk=16; c2f:coarse_factor=4,"
                          "topk=8,radius=1")
    assert ladder == (Rung(2, 16), Rung(4, 8, radius=1))
    assert ladder[0].knobs() == {"coarse_factor": 2, "topk": 16}
    assert ladder[1].knobs() == {"coarse_factor": 4, "topk": 8,
                                 "radius": 1}
    assert parse_ladder("") == ()
    assert parse_ladder(" ; ") == ()
    for bad in ("oneshot:factor=2", "c2f:topk=8", "c2f:factor=2",
                "c2f:factor=x,topk=8", "c2f:factor=2,topk=8,zoom=3"):
        with pytest.raises(ValueError):
            parse_ladder(bad)


def test_rung_validation():
    with pytest.raises(ValueError):
        Rung(0, 8)
    with pytest.raises(ValueError):
        Rung(2, 8, radius=-1)


def test_qos_decision_apply_rewrites_request():
    req = {"query_b64": "x", "pano_b64": "y"}
    assert QosDecision(position=0).apply(dict(req)) == req, "rung 0 no-op"
    out = QosDecision(position=2, rung_index=2,
                      rung=Rung(4, 8)).apply(dict(req))
    assert out["mode"] == "c2f"
    assert out["c2f"] == {"coarse_factor": 4, "topk": 8}


# -- controller state machine ----------------------------------------------


def make_controller(clk, ladder=parse_ladder("c2f:factor=2,topk=16;"
                                             "c2f:factor=4,topk=8"),
                    **kw):
    depth = {"d": 0}
    kw.setdefault("step_down_interval_s", 1.0)
    kw.setdefault("step_up_hold_s", 5.0)
    ctl = QosController(ladder, depth_fn=lambda: depth["d"], max_queue=10,
                        high_water_frac=0.5, clock=clk, **kw)
    return ctl, depth


def test_controller_steps_down_rate_limited_on_queue_pressure():
    clk = FakeClock()
    ctl, depth = make_controller(clk)
    assert ctl.update() == 0, "no pressure, no transition"
    depth["d"] = 5  # == high_water_frac * max_queue
    assert ctl.update() == 1
    assert ctl.update() == 1, "rate-limited: one step per interval"
    clk.t += 1.0
    assert ctl.update() == 2
    assert ctl.transitions == 2
    assert obs.gauge("serving.qos.rung").value == 2.0
    assert obs.counter("serving.qos.transitions").value == 2.0
    # Pressure forever still bottoms out at max_position.
    for _ in range(10):
        clk.t += 1.0
        ctl.update()
    assert ctl.position == ctl.max_position == 2 + len(PRIORITY_CLASSES)
    events = [r for r in obs.flight.recorder().snapshot()
              if r.get("event") == "qos_transition"]
    assert events and events[0]["reason"] == "queue"
    assert (events[0]["rung_from"], events[0]["rung_to"]) == (0, 1)


def test_controller_burn_signal_steps_down():
    class StubSlo:
        paging = False

        def maybe_evaluate(self):
            return {"availability": {"paging": self.paging}}

    clk = FakeClock()
    slo = StubSlo()
    ctl = QosController(parse_ladder("c2f:factor=2,topk=8"), slo=slo,
                        clock=clk, step_down_interval_s=1.0)
    assert ctl.update() == 0
    slo.paging = True
    assert ctl.update() == 1
    events = [r for r in obs.flight.recorder().snapshot()
              if r.get("event") == "qos_transition"]
    assert events[-1]["reason"] == "burn"


def test_controller_recovery_hysteresis_rearms_per_step():
    clk = FakeClock()
    ctl, depth = make_controller(clk)
    depth["d"] = 10
    for _ in range(3):
        ctl.update()
        clk.t += 1.0
    assert ctl.position == 3
    depth["d"] = 0
    ctl.update()  # arms the cool timer, no step yet
    assert ctl.position == 3
    clk.t += 4.9
    assert ctl.update() == 3, "hold not yet satisfied"
    clk.t += 0.2
    assert ctl.update() == 2, "sustained cool steps up ONE"
    assert ctl.update() == 2, "hold re-arms per step (no free-fall up)"
    clk.t += 5.1
    assert ctl.update() == 1
    # A pressure blip during recovery resets the cool timer.
    depth["d"] = 10
    clk.t += 1.0
    assert ctl.update() == 2
    depth["d"] = 0
    clk.t += 4.0
    assert ctl.update() == 2, "cool restarted by the blip"


def test_controller_resolve_shed_order_bottom_priority_first():
    clk = FakeClock()
    ladder = parse_ladder("c2f:factor=2,topk=16;c2f:factor=4,topk=8")
    ctl, depth = make_controller(clk, ladder=ladder)
    n = len(ladder)

    def verdicts():
        return {p: ctl.resolve(p) for p in PRIORITY_CLASSES}

    # Position 0: everyone runs as requested.
    assert all(d.rung is None and not d.shed
               for d in verdicts().values())
    depth["d"] = 10
    ctl.update()  # pos 1
    v = verdicts()
    assert v["interactive"].rung is None, "interactive never degraded"
    assert v["batch"].rung == ladder[0] and not v["batch"].shed
    assert v["best_effort"].rung == ladder[0]
    clk.t += 1.0
    ctl.update()  # pos 2 = last quality rung
    v = verdicts()
    assert v["batch"].rung == ladder[1]
    clk.t += 1.0
    ctl.update()  # pos n+1: shed best_effort only
    v = verdicts()
    assert v["best_effort"].shed
    assert v["batch"].rung == ladder[1] and not v["batch"].shed
    assert v["interactive"].rung is None and not v["interactive"].shed
    assert ctl.snapshot()["shedding"] == ["best_effort"]
    clk.t += 1.0
    ctl.update()  # pos n+2: shed batch too
    v = verdicts()
    assert v["batch"].shed and v["best_effort"].shed
    assert not v["interactive"].shed
    clk.t += 1.0
    ctl.update()  # pos n+3 = the LAST rung: interactive sheds
    v = verdicts()
    assert all(d.shed for d in v.values())
    assert ctl.position == ctl.max_position == n + 3
    assert ctl.snapshot()["shedding"] == list(PRIORITY_CLASSES)
    assert ctl.snapshot()["shed_total"] >= 4
    # Unknown priority strings resolve as the lowest class.
    assert ctl.resolve("platinum").shed


def test_controller_degenerate_empty_ladder_sheds_only():
    clk = FakeClock()
    ctl, depth = make_controller(clk, ladder=())
    assert ctl.max_position == len(PRIORITY_CLASSES)
    depth["d"] = 10
    ctl.update()
    assert ctl.resolve("best_effort").shed
    d = ctl.resolve("batch")
    assert d.rung is None and not d.shed, "no ladder = no degradation"
    assert ctl.snapshot()["quality_rungs"] == 0


# -- batcher per-tenant queue slots ----------------------------------------


def echo_runner(calls):
    def runner(bucket_key, payloads):
        calls.append((bucket_key, list(payloads)))
        return [f"r:{p}" for p in payloads]

    return runner


def test_batcher_tenant_slot_cap_and_release():
    clk, calls = FakeClock(), []
    b = DeadlineBatcher(echo_runner(calls), clock=clk, max_batch=8,
                        max_queue=8, max_delay_s=0.05,
                        tenant_queue_frac=0.25)
    # cap = max(1, int(8 * 0.25)) = 2 slots per tenant.
    f1 = b.submit("a", "p1", tenant="loud")
    f2 = b.submit("a", "p2", tenant="loud")
    with pytest.raises(RejectedError) as ei:
        b.submit("a", "p3", tenant="loud")
    assert ei.value.scope == "tenant"
    assert ei.value.retry_after_s > 0
    assert obs.counter("serving.tenant.rejected",
                       labels={"tenant": "loud"}).value == 1.0
    # Other tenants and untagged riders are untouched by loud's cap.
    f3 = b.submit("a", "q1", tenant="quiet")
    f4 = b.submit("a", "n1")
    # The run releases the slots: loud can queue again afterwards.
    clk.t += 0.06
    assert b.poll() == 1
    assert b._tenant_pending == {}
    f5 = b.submit("a", "p3", tenant="loud")
    clk.t += 0.06
    assert b.poll() == 1
    for f in (f1, f2, f3, f4, f5):
        assert f.result(0).result.startswith("r:")


def test_batcher_queue_full_rejection_keeps_queue_scope():
    clk, calls = FakeClock(), []
    b = DeadlineBatcher(echo_runner(calls), clock=clk, max_batch=4,
                        max_queue=1, max_delay_s=0.05,
                        tenant_queue_frac=0.5)
    b.submit("a", "p1", tenant="t")
    with pytest.raises(RejectedError) as ei:
        b.submit("a", "p2", tenant="t")
    assert ei.value.scope == "queue", "capacity rejection, not fairness"


def test_batcher_tenant_frac_validation():
    with pytest.raises(ValueError):
        DeadlineBatcher(lambda k, p: p, tenant_queue_frac=0.0)
    with pytest.raises(ValueError):
        DeadlineBatcher(lambda k, p: p, tenant_queue_frac=1.5)


# -- engine: per-op c2f knob parsing ---------------------------------------


def _jpeg_b64(h, w, seed):
    import base64
    import io

    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(seed)
    img = Image.fromarray(
        rng.randint(0, 255, size=(h, w, 3), dtype="uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return base64.b64encode(buf.getvalue()).decode()


def test_engine_prepare_c2f_knobs_and_bucket_keys(tiny_serving_model):
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    req = {"query_b64": _jpeg_b64(96, 128, 0),
           "pano_b64": _jpeg_b64(96, 128, 1)}
    # Default-op requests (no knobs, or knobs equal to the config)
    # keep the pre-QoS 3-tuple bucket key — warmups and logs unchanged.
    p0 = engine.prepare(dict(req, mode="c2f"))
    assert len(p0.bucket_key) == 3 and p0.c2f_op is None
    pd = engine.prepare(dict(req, mode="c2f", c2f={}))
    assert len(pd.bucket_key) == 3 and pd.c2f_op is None
    # A non-default operating point extends the key with its op tuple.
    factor = int(config.c2f_coarse_factor) * 2
    p1 = engine.prepare(dict(req, mode="c2f",
                             c2f={"coarse_factor": factor, "topk": 8}))
    assert p1.c2f_op == (factor, 8, int(config.c2f_radius))
    assert len(p1.bucket_key) == 4 and p1.bucket_key[3] == p1.c2f_op
    # Malformed knob payloads are 400-class ValueErrors, not 500s.
    with pytest.raises(ValueError, match="require mode='c2f'"):
        engine.prepare(dict(req, c2f={"topk": 8}))
    with pytest.raises(ValueError, match="JSON object"):
        engine.prepare(dict(req, mode="c2f", c2f=[8]))
    with pytest.raises(ValueError, match="unknown c2f knobs"):
        engine.prepare(dict(req, mode="c2f", c2f={"zoom": 2}))
    with pytest.raises(ValueError, match="integers"):
        engine.prepare(dict(req, mode="c2f", c2f={"topk": "lots"}))


# -- end-to-end over HTTP --------------------------------------------------


class _QuietSlo:
    """Stub SLO feed: never paging. The e2e tests drive the controller
    from queue pressure alone — the server's real SloEngine would page
    on first-compile latency (seconds against a 0.5 s p99 target) and
    correctly pin the ladder down, which is the behavior under test in
    the chaos gate, not here."""

    def maybe_evaluate(self):
        return {}


def _start_server(engine, **kw):
    from ncnet_tpu.serving.server import MatchServer

    kw.setdefault("port", 0)
    kw.setdefault("max_batch", 1)
    kw.setdefault("max_queue", 16)
    kw.setdefault("max_delay_s", 0.01)
    kw.setdefault("default_timeout_s", 300.0)
    return MatchServer(engine, **kw).start()


def _client(url, **kw):
    from ncnet_tpu.serving.client import MatchClient

    kw.setdefault("timeout_s", 600.0)
    kw.setdefault("retries", 0)
    return MatchClient(url, **kw)


def test_serving_e2e_qos_degrade_then_shed_then_recover(
        tiny_serving_model):
    """The tentpole contract end to end: under pressure low-priority
    traffic first runs degraded, then sheds bottom-first; interactive
    keeps serving until the very last position; recovery climbs back
    to rung 0."""
    from ncnet_tpu.serving.client import OverCapacityError
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    pressure = {"on": True}
    ladder = parse_ladder("c2f:factor=2,topk=8")
    qos = QosController(
        ladder,
        slo=_QuietSlo(),
        depth_fn=lambda: 100 if pressure["on"] else 0,
        max_queue=10,
        step_down_interval_s=0.0,  # one step per request, deterministic
        step_up_hold_s=0.05,
    )
    tenants = TenantTable([TenantPolicy("victim", "interactive"),
                           TenantPolicy("lowpri", "best_effort")])
    server = _start_server(engine, qos=qos, tenants=tenants)
    try:
        client = _client(server.url)
        qb = _jpeg_b64(96, 128, 0)
        pano = _jpeg_b64(96, 128, 1)
        import base64

        kwargs = dict(query_bytes=base64.b64decode(qb),
                      pano_bytes=base64.b64decode(pano), max_matches=8)
        # Request 1 (pos 0 -> 1): lowpri runs, but degraded onto rung 1.
        r1 = client.match(tenant="lowpri", **kwargs)
        assert r1["qos"] == {"rung": 1, "degraded": True}
        assert r1["n_matches"] >= 1
        # Request 2 (pos 2 = quality rungs exhausted): best_effort sheds.
        with pytest.raises(OverCapacityError) as ei:
            client.match(tenant="lowpri", **kwargs)
        assert ei.value.status == 503
        assert ei.value.payload["kind"] == "shed"
        assert ei.value.payload["qos_rung"] == 2
        # Request 3 (pos 3, batch shed too): interactive still serves.
        r3 = client.match(tenant="victim", **kwargs)
        assert r3["qos"] == {"rung": 3, "degraded": False}
        # Request 4 (pos 4 = the LAST position): even interactive sheds
        # — 503 + Retry-After really is the bottom of the ladder.
        with pytest.raises(OverCapacityError) as ei:
            client.match(tenant="victim", **kwargs)
        assert ei.value.payload["kind"] == "shed"
        assert qos.position == qos.max_position == 4
        health = client.healthz()
        assert health["qos"]["rung"] == 4
        assert health["qos"]["shedding"] == list(PRIORITY_CLASSES)
        assert obs.counter("serving.qos.degraded").value >= 1.0
        assert obs.counter(
            "serving.qos.shed",
            labels={"priority": "best_effort"}).value >= 1.0
        assert obs.counter(
            "serving.tenant.shed", labels={"tenant": "victim"}).value \
            == 1.0
        assert obs.counter(
            "serving.tenant.requests",
            labels={"tenant": "lowpri",
                    "priority": "best_effort"}).value == 2.0
        # Recovery: pressure off, hysteresis climbs back to rung 0.
        pressure["on"] = False
        import time

        deadline = time.monotonic() + 30.0
        while client.healthz()["qos"]["rung"] > 0:
            assert time.monotonic() < deadline, "never recovered"
            time.sleep(0.06)
        r5 = client.match(tenant="lowpri", **kwargs)
        assert r5["qos"] == {"rung": 0, "degraded": False}
    finally:
        server.stop()


def test_serving_e2e_tenant_budget_429(tiny_serving_model):
    """A tenant over its admission budget gets 429 + Retry-After with
    kind=tenant_budget — its own limit, not service pressure."""
    from ncnet_tpu.serving.client import OverCapacityError
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    tenants = TenantTable([
        TenantPolicy("capped", "interactive", rate=0.01, burst=1.0)])
    server = _start_server(engine, tenants=tenants)
    try:
        client = _client(server.url)
        import base64

        kwargs = dict(query_bytes=base64.b64decode(_jpeg_b64(96, 128, 0)),
                      pano_bytes=base64.b64decode(_jpeg_b64(96, 128, 1)))
        r = client.match(tenant="capped", **kwargs)
        assert r["n_matches"] >= 1
        assert "qos" not in r, "no controller, no qos block"
        with pytest.raises(OverCapacityError) as ei:
            client.match(tenant="capped", **kwargs)
        assert ei.value.status == 429
        assert ei.value.payload["kind"] == "tenant_budget"
        assert ei.value.payload["tenant"] == "capped"
        assert obs.counter("serving.tenant.throttled",
                           labels={"tenant": "capped"}).value == 1.0
        # Unlabeled traffic is accounted as the default tenant and is
        # not touched by capped's budget.
        r = client.match(**kwargs)
        assert r["n_matches"] >= 1
        assert obs.counter(
            "serving.tenant.requests",
            labels={"tenant": "default",
                    "priority": "interactive"}).value == 1.0
    finally:
        server.stop()


def test_serving_e2e_qos_idle_is_bit_identical(tiny_serving_model):
    """The degenerate-ladder contract: a QoS layer that never engages
    (controller pinned at rung 0) serves bit-identical matches to the
    plain admission path."""
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    import base64

    kwargs = dict(query_bytes=base64.b64decode(_jpeg_b64(96, 128, 0)),
                  pano_bytes=base64.b64decode(_jpeg_b64(96, 128, 1)),
                  max_matches=8)
    plain = _start_server(engine)
    try:
        r_plain = _client(plain.url).match(**kwargs)
    finally:
        plain.stop()
    qos = QosController(parse_ladder("c2f:factor=2,topk=8"),
                        slo=_QuietSlo(), depth_fn=lambda: 0,
                        max_queue=16)
    servered = _start_server(engine, qos=qos)
    try:
        r_qos = _client(servered.url).match(**kwargs)
    finally:
        servered.stop()
    assert r_qos["qos"] == {"rung": 0, "degraded": False}
    assert r_qos["matches"] == r_plain["matches"]
    assert r_qos["n_matches"] == r_plain["n_matches"]


def test_serving_e2e_draining_503_carries_kind(tiny_serving_model):
    """The shutdown drain window refuses with kind=draining and counts
    a labeled serving.errors increment — not a bare unexplained 503."""
    from ncnet_tpu.serving.client import OverCapacityError
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    server = _start_server(engine)
    try:
        client = _client(server.url)
        import base64

        kwargs = dict(query_bytes=base64.b64decode(_jpeg_b64(96, 128, 0)),
                      pano_bytes=base64.b64decode(_jpeg_b64(96, 128, 1)))
        assert client.match(**kwargs)["n_matches"] >= 1
        # Close admission while HTTP still serves — the drain window.
        server.batcher.close()
        with pytest.raises(OverCapacityError) as ei:
            client.match(**kwargs)
        assert ei.value.status == 503
        assert ei.value.payload["kind"] == "draining"
        assert obs.counter("serving.errors",
                           labels={"kind": "draining"}).value == 1.0
    finally:
        server.stop()


def test_qos_threaded_update_and_resolve_are_safe():
    """Smoke the controller's locking: concurrent update/resolve from
    many threads never crashes and lands on a valid position."""
    clk = FakeClock()
    ctl, depth = make_controller(clk, step_down_interval_s=0.0)
    depth["d"] = 10
    errs = []

    def hammer():
        try:
            for _ in range(200):
                ctl.update()
                ctl.resolve("batch")
                ctl.snapshot()
        except Exception as exc:  # noqa: BLE001 — the assertion surface
            errs.append(exc)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert 0 <= ctl.position <= ctl.max_position
