"""Weight-conversion tests: torch state dicts -> ncnet_tpu pytrees.

The numeric oracle is a functional torch re-implementation of the
torchvision ResNet/VGG forward driven directly by the state dict, so
conversion AND our backbone forward are pinned end-to-end without needing
torchvision itself.
"""

import numpy as np
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from ncnet_tpu.models.backbone import (
    BackboneConfig,
    RESNET_SPECS,
    backbone_apply,
    backbone_init,
)
from ncnet_tpu.models.convert import (
    convert_resnet_state_dict,
    convert_vgg_state_dict,
    convert_conv4d_weight,
    convert_neigh_consensus_state_dict,
)
from ncnet_tpu.ops import conv4d


def make_resnet_state_dict(arch="resnet50", stages=3, seed=0):
    """Random torchvision-style ResNet state dict (truncated at `stages`)."""
    g = torch.Generator().manual_seed(seed)
    sd = {}

    def add_bn(prefix, c):
        sd[f"{prefix}.weight"] = torch.randn(c, generator=g) * 0.1 + 1
        sd[f"{prefix}.bias"] = torch.randn(c, generator=g) * 0.1
        sd[f"{prefix}.running_mean"] = torch.randn(c, generator=g) * 0.1
        sd[f"{prefix}.running_var"] = torch.rand(c, generator=g) + 0.5
        sd[f"{prefix}.num_batches_tracked"] = torch.tensor(1)

    sd["conv1.weight"] = torch.randn(64, 3, 7, 7, generator=g) * 0.05
    add_bn("bn1", 64)
    cin = 64
    for s in range(1, stages + 1):
        planes = 64 * 2 ** (s - 1)
        cout = planes * 4
        for b in range(RESNET_SPECS[arch][s - 1]):
            p = f"layer{s}.{b}"
            sd[f"{p}.conv1.weight"] = torch.randn(planes, cin, 1, 1, generator=g) * 0.05
            add_bn(f"{p}.bn1", planes)
            sd[f"{p}.conv2.weight"] = torch.randn(planes, planes, 3, 3, generator=g) * 0.05
            add_bn(f"{p}.bn2", planes)
            sd[f"{p}.conv3.weight"] = torch.randn(cout, planes, 1, 1, generator=g) * 0.05
            add_bn(f"{p}.bn3", cout)
            if b == 0:
                sd[f"{p}.downsample.0.weight"] = (
                    torch.randn(cout, cin, 1, 1, generator=g) * 0.05
                )
                add_bn(f"{p}.downsample.1", cout)
            cin = cout
    return sd


def torch_resnet_forward(sd, x, arch="resnet50", stages=3):
    """Functional torchvision-ResNet forward from a raw state dict."""

    def bn(t, p):
        return F.batch_norm(
            t,
            sd[f"{p}.running_mean"],
            sd[f"{p}.running_var"],
            sd[f"{p}.weight"],
            sd[f"{p}.bias"],
            training=False,
        )

    x = F.conv2d(x, sd["conv1.weight"], stride=2, padding=3)
    x = F.relu(bn(x, "bn1"))
    x = F.max_pool2d(x, 3, 2, 1)
    for s in range(1, stages + 1):
        for b in range(RESNET_SPECS[arch][s - 1]):
            p = f"layer{s}.{b}"
            stride = 2 if (b == 0 and s > 1) else 1
            identity = x
            out = F.relu(bn(F.conv2d(x, sd[f"{p}.conv1.weight"]), f"{p}.bn1"))
            out = F.relu(
                bn(
                    F.conv2d(out, sd[f"{p}.conv2.weight"], stride=stride, padding=1),
                    f"{p}.bn2",
                )
            )
            out = bn(F.conv2d(out, sd[f"{p}.conv3.weight"]), f"{p}.bn3")
            if f"{p}.downsample.0.weight" in sd:
                identity = bn(
                    F.conv2d(x, sd[f"{p}.downsample.0.weight"], stride=stride),
                    f"{p}.downsample.1",
                )
            x = F.relu(out + identity)
    return x


def test_resnet_conversion_numerical_parity(rng):
    config = BackboneConfig(cnn="resnet50", last_layer="layer2")
    sd = make_resnet_state_dict("resnet50", stages=2)
    params = convert_resnet_state_dict(sd, config)
    x = rng.randn(1, 3, 64, 64).astype(np.float32)
    ref = torch_resnet_forward(sd, torch.tensor(x), "resnet50", stages=2).numpy()
    ours = np.asarray(backbone_apply(config, params, jnp.asarray(x)))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-3)


def test_resnet_conversion_shapes_match_init():
    config = BackboneConfig(cnn="resnet50", last_layer="layer3")
    sd = make_resnet_state_dict("resnet50", stages=3)
    converted = convert_resnet_state_dict(sd, config)
    inited = backbone_init(jax.random.PRNGKey(0), config)
    c_shapes = [x.shape for x in jax.tree.leaves(jax.tree.map(np.asarray, converted))]
    i_shapes = [x.shape for x in jax.tree.leaves(jax.tree.map(np.asarray, inited))]
    assert c_shapes == i_shapes


def make_vgg_state_dict(seed=0):
    g = torch.Generator().manual_seed(seed)
    cfg = [
        (0, 3, 64), (2, 64, 64), (5, 64, 128), (7, 128, 128),
        (10, 128, 256), (12, 256, 256), (14, 256, 256),
        (17, 256, 512), (19, 512, 512), (21, 512, 512),
    ]
    sd = {}
    for idx, cin, cout in cfg:
        sd[f"{idx}.weight"] = torch.randn(cout, cin, 3, 3, generator=g) * 0.05
        sd[f"{idx}.bias"] = torch.randn(cout, generator=g) * 0.1
    return sd


def torch_vgg_forward(sd, x):
    order = [0, 2, "M", 5, 7, "M", 10, 12, 14, "M", 17, 19, 21, "M"]
    for o in order:
        if o == "M":
            x = F.max_pool2d(x, 2, 2)
        else:
            x = F.relu(F.conv2d(x, sd[f"{o}.weight"], sd[f"{o}.bias"], padding=1))
    return x


def test_vgg_conversion_numerical_parity(rng):
    config = BackboneConfig(cnn="vgg", last_layer="pool4")
    sd = make_vgg_state_dict()
    params = convert_vgg_state_dict(sd, config)
    x = rng.randn(1, 3, 64, 64).astype(np.float32)
    ref = torch_vgg_forward(sd, torch.tensor(x)).numpy()
    ours = np.asarray(backbone_apply(config, params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_conv4d_weight_conversion(rng):
    """Native torch Conv4d layout converts to a weight our conv4d agrees on."""
    from tests.test_ops import torch_conv4d

    cin, cout, k = 2, 3, 3
    w_native = rng.randn(cout, cin, k, k, k, k).astype(np.float32) * 0.1
    bias = rng.randn(cout).astype(np.float32)
    x = rng.randn(1, cin, 4, 4, 4, 4).astype(np.float32)

    ours_w = convert_conv4d_weight(w_native, pre_permuted=False)
    ours = np.asarray(conv4d(jnp.asarray(x), jnp.asarray(ours_w), jnp.asarray(bias)))
    ref = torch_conv4d(
        torch.tensor(x), torch.tensor(ours_w), torch.tensor(bias)
    ).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-4)

    # pre-permuted layout (what reference checkpoints store: [kI,O,I,kJ,kK,kL])
    w_pre = w_native.transpose(2, 0, 1, 3, 4, 5)
    ours_w2 = convert_conv4d_weight(w_pre, pre_permuted=True)
    np.testing.assert_array_equal(ours_w, ours_w2)


def test_neigh_consensus_state_dict_conversion(rng):
    sd = {
        "NeighConsensus.conv.0.weight": torch.tensor(
            rng.randn(3, 4, 1, 3, 3, 3).astype(np.float32)
        ),
        "NeighConsensus.conv.0.bias": torch.tensor(rng.randn(4).astype(np.float32)),
        "NeighConsensus.conv.2.weight": torch.tensor(
            rng.randn(3, 1, 4, 3, 3, 3).astype(np.float32)
        ),
        "NeighConsensus.conv.2.bias": torch.tensor(rng.randn(1).astype(np.float32)),
    }
    params = convert_neigh_consensus_state_dict(sd, (3, 3))
    assert params[0]["weight"].shape == (3, 3, 3, 3, 1, 4)
    assert params[1]["weight"].shape == (3, 3, 3, 3, 4, 1)
