"""Streaming video-session matching (ISSUE 13).

Unit half: the seed ops' geometry (dilate/select) and the
full-coverage bitwise-equality contract — a seed covering every coarse
cell makes :func:`~ncnet_tpu.ops.c2f.refine_from_seed` reproduce the
coarse-gated refinement exactly, so seeding can only ever *restrict*
the nomination set, never change the refinement math. Session-table
half: TTL eviction, the seed-quality re-seed threshold, and the
table/tenant seat caps, all on a fake clock.

E2E half: the ``/v1/session`` verb over HTTP on a two-replica fleet —
steady-state frames run seeded (no coarse stage in the timing block),
a mid-stream kill of the seed-holding replica re-seeds on a survivor
(the "re-seed, not die" contract), a lost session id answers 410 and
the client transparently re-opens, and a full session table answers
429 ``session_slots``.
"""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops import neigh_consensus_init
from ncnet_tpu.ops.c2f import (
    coarse_gate,
    dilate_seed,
    refine_from_gate,
    refine_from_seed,
    seed_gate,
)
from ncnet_tpu.serving.session import (
    SessionCapError,
    SessionLostError,
    SessionManager,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _jpeg_bytes(h, w, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray((rng.random((h, w, 3)) * 255).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


# -- seed ops ---------------------------------------------------------------


def test_dilate_seed_radius_zero_is_identity():
    mask = dilate_seed(jnp.array([5], dtype=jnp.int32), grid=(4, 4),
                       radius=0)
    expect = np.zeros((4, 4), bool)
    expect[1, 1] = True
    assert np.array_equal(np.asarray(mask), expect)


def test_dilate_seed_chebyshev_radius_one():
    # Cell 5 = (1, 1) on a 4x4 grid: radius 1 covers the 3x3 block
    # around it; a corner seed (cell 15 = (3, 3)) clips at the edge.
    mask = dilate_seed(jnp.array([5, 15], dtype=jnp.int32), grid=(4, 4),
                      radius=1)
    expect = np.zeros((4, 4), bool)
    expect[0:3, 0:3] = True
    expect[2:4, 2:4] = True
    assert np.array_equal(np.asarray(mask), expect)


def test_seed_gate_full_coverage_equals_coarse_gate(rng):
    # A seed containing every coarse cell reduces seed_gate EXACTLY to
    # coarse_gate's selection over the same cell_scores (the docstring
    # contract in ops/c2f.py).
    ha = wa = hb = wb = 3
    coarse4d = jnp.asarray(
        rng.rand(1, 1, ha, wa, hb, wb).astype(np.float32))
    topk = 4
    ts, tc, cs, mb = coarse_gate(coarse4d, topk)
    all_cells = jnp.arange(ha * wa, dtype=jnp.int32)
    s_ts, s_tc, s_cs, s_mb = seed_gate(
        all_cells, cs, mb, grid=(ha, wa), seed_radius=0, topk=topk)
    assert np.array_equal(np.asarray(ts), np.asarray(s_ts))
    assert np.array_equal(np.asarray(tc), np.asarray(s_tc))
    assert np.array_equal(np.asarray(cs), np.asarray(s_cs))
    assert np.array_equal(np.asarray(mb), np.asarray(s_mb))


def test_refine_from_seed_full_coverage_bitwise(rng):
    # Full pipeline equality: refine_from_seed with a full-coverage
    # seed produces bit-identical match fields to coarse_gate +
    # refine_from_gate (same gather, same consensus, same splice).
    stride, radius, topk = 2, 1, 4
    ha = wa = hb = wb = 2  # coarse grids; fine = coarse * stride
    c = 8
    feat_a = jnp.asarray(
        rng.rand(1, c, ha * stride, wa * stride).astype(np.float32))
    feat_b = jnp.asarray(
        rng.rand(1, c, hb * stride, wb * stride).astype(np.float32))
    coarse4d = jnp.asarray(
        rng.rand(1, 1, ha, wa, hb, wb).astype(np.float32))
    consensus = neigh_consensus_init(
        jax.random.PRNGKey(0), (3, 3), (16, 1))

    _ts, tc, cs, mb = coarse_gate(coarse4d, topk)
    kw = dict(coarse_shape=(ha, wa, hb, wb), stride=stride, radius=radius,
              symmetric=True, corr_dtype=jnp.float32)
    base = refine_from_gate(consensus, tc, cs, mb, feat_a, feat_b, **kw)
    seeded, new_gate = refine_from_seed(
        consensus, jnp.arange(ha * wa, dtype=jnp.int32), cs, mb,
        feat_a, feat_b, seed_radius=0, topk=topk, **kw)
    for a, b in zip(base, seeded):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # The updated gate has coarse_gate's tuple shape — next frame's
    # nominator stays structurally identical frame over frame (the
    # engine's seeded program relies on this to avoid retraces).
    assert len(new_gate) == 4
    assert np.asarray(new_gate[1]).shape == (topk,)
    assert np.asarray(new_gate[2]).shape == (ha * wa,)
    assert np.asarray(new_gate[3]).shape == (ha * wa,)


# -- session table ----------------------------------------------------------


def _gates():
    """Minimal well-formed gates payload (numpy, both directions)."""
    one = (np.arange(4, dtype=np.int32), np.ones(4, np.float32),
           np.zeros(4, np.int32))
    return (one, one)


def test_session_ttl_eviction_fake_clock():
    clock = FakeClock()
    mgr = SessionManager(max_sessions=4, ttl_s=10.0, clock=clock)
    s = mgr.open("default", "interactive", "digest", ref_b64="x")
    assert mgr.get(s.session_id) is s
    clock.t = 9.0
    assert mgr.get(s.session_id) is s  # touch resets idleness
    clock.t = 19.5
    assert mgr.evict_idle() == 1
    with pytest.raises(SessionLostError):
        mgr.get(s.session_id)
    assert mgr.active() == 0


def test_session_get_unknown_and_closed_raise():
    mgr = SessionManager(max_sessions=2, clock=FakeClock())
    with pytest.raises(SessionLostError):
        mgr.get("nope")
    s = mgr.open("default", "interactive", "digest", ref_b64="x")
    mgr.close(s.session_id)
    with pytest.raises(SessionLostError):
        mgr.get(s.session_id)
    with pytest.raises(SessionLostError):
        mgr.close(s.session_id)


def test_seed_quality_threshold_drives_reseed():
    mgr = SessionManager(max_sessions=2, reseed_frac=0.5,
                         clock=FakeClock())
    s = mgr.open("default", "interactive", "digest", ref_b64="x")
    # record_frame's contract (and the race canary under
    # NCNET_RACE_CANARY=1): callers hold the session lock across each
    # frame, like the server's prepare -> submit -> record window.
    with s.lock:
        # Full-coarse frame mints the seed; coarse-scale mass is not a
        # reference (refined-scale masses are not comparable to it).
        mgr.record_frame(s, seeded=False, gates=_gates(),
                         replica_id="d0", bucket=("b",))
        assert s.seed is not None and s.seed.mass_ref is None
        # First seeded frame establishes the refined-scale reference.
        mgr.record_frame(s, seeded=True, gates=_gates(), mass=10.0)
        assert s.seed.mass_ref == 10.0
        # At/above the threshold the seed rolls forward (mass_ref
        # sticks).
        mgr.record_frame(s, seeded=True, gates=_gates(), mass=6.0)
        assert s.seed is not None and s.reseeds == 0
        assert s.seed.mass_ref == 10.0
        # Below reseed_frac * mass_ref: the seed drops, the NEXT frame
        # re-runs the coarse pass.
        mgr.record_frame(s, seeded=True, gates=_gates(), mass=4.0)
        assert s.seed is None
        assert s.reseeds == 1
        assert s.frames == 4 and s.seeded_frames == 3
        # Gate-less frame (degenerate op path): the session simply
        # never seeds, without counting a re-seed.
        mgr.record_frame(s, seeded=False, gates=None)
        assert s.seed is None and s.reseeds == 1


def test_session_table_and_tenant_caps():
    mgr = SessionManager(max_sessions=2, tenant_frac=0.5,
                         clock=FakeClock())
    mgr.open("t1", "interactive", "d", ref_b64="x")
    with pytest.raises(SessionCapError) as exc:
        mgr.open("t1", "interactive", "d", ref_b64="x")
    assert exc.value.scope == "tenant" and exc.value.limit == 1
    mgr.open("t2", "interactive", "d", ref_b64="x")
    with pytest.raises(SessionCapError) as exc:
        mgr.open("t3", "interactive", "d", ref_b64="x")
    assert exc.value.scope == "table" and exc.value.limit == 2
    snap = mgr.snapshot()
    assert snap["active"] == 2 and snap["max_sessions"] == 2


# -- HTTP e2e ---------------------------------------------------------------


def _session_fleet_server(model, **server_kw):
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    config, params = model
    fleet = MatchFleet.build(
        config, params, n_replicas=2, base_id="sess", cache_mb=0,
        engine_kwargs=dict(k_size=2, image_size=64),
        replica_kwargs=dict(max_batch=2, max_delay_s=0.01,
                            default_timeout_s=120.0),
    )
    server_kw.setdefault("slo_p99_target_s", 60.0)
    server = MatchServer(None, port=0, fleet=fleet, **server_kw).start()
    return fleet, server


def test_session_stream_kill_reseeds_and_reopen(tiny_serving_model):
    """The acceptance scenario over real HTTP: seeded steady state,
    replica kill mid-stream re-seeds on the survivor with a 200 (never
    a dead session), and a server-side close answers 410 which the
    client absorbs with one transparent re-open."""
    from ncnet_tpu.serving.client import MatchClient

    fleet, server = _session_fleet_server(tiny_serving_model)
    ref = _jpeg_bytes(96, 128, 1)
    frames = [_jpeg_bytes(96, 128, s) for s in range(2, 6)]
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)
        with client.session(ref_bytes=ref) as s:
            first = s.frame(query_bytes=frames[0])
            assert first["n_matches"] >= 1
            assert first["session"]["seeded"] is False
            assert first["session"]["frame"] == 1

            second = s.frame(query_bytes=frames[1])
            assert second["session"]["seeded"] is True
            # Steady state: the coarse stage never dispatched.
            assert "coarse_ms" not in second["timing"]
            assert "refine_ms" in second["timing"]

            # Kill the replica holding the seed: the next frame must
            # answer 200 on a survivor and report the re-seed.
            sess = server.sessions.get(s.session_id)
            holder = sess.seed.replica_id
            assert holder in {"sess-d0", "sess-d1"}
            fleet.kill(holder)
            third = s.frame(query_bytes=frames[2])
            assert third["n_matches"] >= 1
            assert third["session"]["reseeded"] is True
            assert third["session"]["seeded"] is False  # full coarse pass
            fleet.revive(holder)

            # Seed re-establishes on the survivor's full-coarse gates.
            fourth = s.frame(query_bytes=frames[3])
            assert fourth["session"]["seeded"] is True

            sess = server.sessions.get(s.session_id)
            assert sess.frames == 4
            assert sess.reseeds >= 1

            # Server-side loss (TTL eviction stand-in): the client
            # absorbs the 410 with exactly one transparent re-open.
            server.sessions.close(s.session_id)
            fifth = s.frame(query_bytes=frames[0])
            assert fifth["n_matches"] >= 1
            assert s.reopens == 1

            hz = client.healthz()
            assert hz["sessions"]["active"] == 1

            # close() answers for the RE-OPENED session (the original
            # died server-side): one frame, no re-seeds yet.
            stats = s.close()
            assert stats is not None
            assert stats["frames"] == 1
    finally:
        server.stop()


def test_session_table_full_answers_429_session_slots(tiny_serving_model):
    from ncnet_tpu.serving.client import MatchClient, OverCapacityError

    fleet, server = _session_fleet_server(tiny_serving_model,
                                          max_sessions=1)
    ref = _jpeg_bytes(96, 128, 1)
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)
        with client.session(ref_bytes=ref):
            with pytest.raises(OverCapacityError):
                with client.session(ref_bytes=ref):
                    pass
    finally:
        server.stop()
