"""ISSUE 6 acceptance: the two-replica fleet demo.

Two real MatchServers with distinct replica ids share one process (and
therefore one obs registry — the hardest aliasing case for label
identity), serve real load over HTTP, and:

* ``aggregate.fleet_view`` over both ``/metrics`` endpoints produces
  one fleet view whose summed counters equal the per-replica totals
  and whose fleet p99 is consistent with the merged buckets;
* ``tools/fleet_status.py`` polls the same endpoints and emits the
  house one-JSON-line record with matching numbers;
* an induced failure window (failpoint-armed, the PR-5 sites) flips
  the availability SLO's fast-burn alert and writes exactly one flight
  dump; recovery clears the page and the error-budget readout climbs
  back — all on a fake clock.
"""

import glob
import io
import json
import os
import sys

import numpy as np
import pytest

from ncnet_tpu import obs
from ncnet_tpu.obs import aggregate, flight
from ncnet_tpu.reliability import failpoints
from ncnet_tpu.serving.client import MatchClient, ServingError

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _jpeg_bytes(h, w, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray((rng.random((h, w, 3)) * 255).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def _make_server(model, rid, **kw):
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_delay_s", 0.01)
    kw.setdefault("default_timeout_s", 120.0)
    # CPU-tier latency (first requests pay a compile) must not burn the
    # latency SLO's budget; these tests drive the availability SLO.
    kw.setdefault("slo_p99_target_s", 60.0)
    return MatchServer(engine, port=0, replica_id=rid, **kw).start()


def test_two_replica_fleet_view_and_dashboard(tiny_serving_model, capsys):
    """The fleet-equality demo: load through two labeled replicas, one
    merged view, summed counters == per-replica totals, fleet p99
    consistent with the merged bucket ladder, fleet_status contract."""
    s0 = _make_server(tiny_serving_model, "r0")
    s1 = _make_server(tiny_serving_model, "r1")
    kwargs = dict(query_bytes=_jpeg_bytes(96, 128, 0),
                  pano_bytes=_jpeg_bytes(96, 128, 1), max_matches=8)
    n0, n1 = 5, 3
    try:
        c0 = MatchClient(s0.url, timeout_s=120.0, retries=0)
        c1 = MatchClient(s1.url, timeout_s=120.0, retries=0)
        for _ in range(n0):
            assert c0.match(**kwargs)["n_matches"] >= 1
        for _ in range(n1):
            assert c1.match(**kwargs)["n_matches"] >= 1

        # /healthz carries the replica identity and the SLO budget
        # readout (the balancer-facing fields).
        hz = c0.healthz()
        assert hz["replica"] == "r0"
        assert set(hz["slo"]) == {"availability", "deadline_hit",
                                  "latency_p99", "quality_drift"}
        for r in hz["slo"].values():
            assert not r["paging"]
            assert r["budget_remaining_frac"] == 1.0

        view = aggregate.fleet_view([s0.url, s1.url])
        assert view["errors"] == {}
        assert view["replicas"] == ["r0", "r1"]

        # Summed counters == per-replica totals (replica-labeled
        # series: exact, no double count).
        per = view["per_replica"]
        assert per["r0"]["counters"]["serving_requests"] == float(n0)
        assert per["r1"]["counters"]["serving_requests"] == float(n1)
        assert view["counters"]["serving_requests"] == float(n0 + n1)
        assert view["counters"]["serving_responses"] == float(n0 + n1)
        # (per_replica may also hold synthetic source<i> idents for
        # unlabeled series, e.g. process-global jit.* telemetry — the
        # fleet equality is over the replica-labeled series.)
        assert view["counters"]["serving_requests"] == sum(
            per[rid]["counters"]["serving_requests"]
            for rid in view["replicas"])

        # Fleet p99: consistent with the merged cumulative buckets —
        # p99 sits inside the first bucket whose cumulative count
        # covers 99% of the fleet's observations.
        merged = view["histograms"]["serving_e2e_latency_s"]
        assert merged["count"] == float(n0 + n1)
        assert merged["count"] == sum(
            per[rid]["histograms"]["serving_e2e_latency_s"]["count"]
            for rid in view["replicas"])
        assert merged["min"] <= merged["p50"] <= merged["p95"] \
            <= merged["p99"] <= merged["max"]
        target = 0.99 * merged["count"]
        lo = 0.0
        for le, cum in merged["buckets"]:
            if cum >= target:
                assert lo <= merged["p99"] <= max(le, merged["min"])
                break
            lo = le
        else:
            pytest.fail("merged buckets never cover the p99 target")

        # The build-info gauge carries both identities: the replica
        # label became the aggregation dimension, the other identity
        # labels (version/backend/...) stay in the series key.
        info_ids = set()
        for key, entry in view["gauges"].items():
            if key.startswith("ncnet_build_info"):
                info_ids |= set(entry["per_replica"])
        assert info_ids >= {"r0", "r1"}

        # The dashboard over the same endpoints: one stdout JSON line.
        import fleet_status

        rc = fleet_status.main([s0.url, s1.url, "--iterations", "2",
                                "--interval_s", "0"])
        assert rc == 0
        out_lines = [l for l in capsys.readouterr().out.splitlines()
                     if l.strip()]
        assert len(out_lines) == 1, out_lines
        rec = json.loads(out_lines[0])
        assert rec["metric"] == "fleet_status"
        assert rec["unit"] == "requests"
        assert rec["value"] == float(n0 + n1)
        assert rec["fleet"]["requests"] == float(n0 + n1)
        assert rec["replicas"]["r0"]["requests"] == float(n0)
        assert rec["replicas"]["r1"]["requests"] == float(n1)
        assert rec["polls"] == 2
        assert rec["unreachable"] == []
    finally:
        s0.stop()
        s1.stop()


def test_fleet_status_isolates_unreachable_replica(tiny_serving_model,
                                                   capsys):
    s0 = _make_server(tiny_serving_model, "r0")
    dead = "http://127.0.0.1:9"  # discard port: connection refused
    try:
        import fleet_status

        rc = fleet_status.main([s0.url, dead, "--iterations", "1"])
        assert rc == 1  # nonzero: somebody was unreachable
        out_lines = [l for l in capsys.readouterr().out.splitlines()
                     if l.strip()]
        rec = json.loads(out_lines[0])
        assert rec["unreachable"] == [dead]
        assert rec["fleet"]["n_sources"] == 1  # the live one still merged
    finally:
        s0.stop()


def test_slo_burn_page_and_recovery_e2e(tiny_serving_model, tmp_path,
                                        monkeypatch):
    """The induced-failure acceptance: a failpoint-armed 500 window
    flips the availability fast-burn alert through the REAL server path
    (healthz -> slo_status -> SloEngine over the live registry), writes
    exactly one flight dump, and recovery clears the page and restores
    the budget readout. Fake SLO clock; breaker threshold set high so
    errors stay 500s (breaker 503s are excluded from availability by
    design)."""
    flight_dir = str(tmp_path / "flight")
    monkeypatch.setenv("NCNET_FLIGHT_DIR", flight_dir)
    flight.recorder().clear()

    server = _make_server(tiny_serving_model, "r0",
                          breaker_threshold=1000)
    clock = FakeClock()
    # Same engine the server built, re-clocked for determinism: short
    # windows so the page fits in a few evaluation steps.
    server.slo = obs.SloEngine(
        obs.default_serving_slos(p99_target_s=60.0, fast_window_s=10.0,
                                 slow_window_s=60.0),
        labels=server.labels, clock=clock, min_interval_s=0.0,
    )
    kwargs = dict(query_bytes=_jpeg_bytes(96, 128, 0),
                  pano_bytes=_jpeg_bytes(96, 128, 1), max_matches=8)
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)

        def tick(n=1):
            """Advance the SLO clock and evaluate via the server path."""
            for _ in range(n):
                clock.t += 2.0
                hz = client.healthz()
            return hz["slo"]["availability"]

        # A healthy baseline fills both windows with good history.
        for _ in range(6):
            assert client.match(**kwargs)["n_matches"] >= 1
            tick()
        assert not server.slo.paging

        # Failure window: every device dispatch 500s (PR-5 site).
        failpoints.set_failpoint("engine.device", "error")
        avail = None
        for i in range(20):
            with pytest.raises(ServingError) as exc_info:
                client.match(**kwargs)
            assert exc_info.value.status == 500
            avail = tick()
            if avail["paging"]:
                break
        assert avail is not None and avail["paging"], \
            "sustained 500s never flipped the burn alert"
        assert avail["burn_fast"] >= 14.0 and avail["burn_slow"] >= 6.0
        burned = avail["budget_remaining_frac"]
        assert burned < 1.0
        pages = obs.counter("slo.availability.pages",
                            labels=server.labels).value
        assert pages == 1.0
        dumps = glob.glob(
            flight_dir + "/flight-slo-burn-availability-*.jsonl")
        assert len(dumps) == 1, "exactly one dump per page episode"
        header = json.loads(open(dumps[0]).readline())
        assert header["reason"] == "slo-burn-availability"

        # Recovery: disarm, serve good traffic, age the failure window
        # out. The page clears, no second dump, and the budget readout
        # climbs off its low as good volume accumulates.
        failpoints.clear("engine.device")
        for i in range(30):
            assert client.match(**kwargs)["n_matches"] >= 1
            avail = tick()
            if not avail["paging"]:
                break
        assert not avail["paging"], "recovery never cleared the page"
        assert not server.slo.paging
        for _ in range(10):
            assert client.match(**kwargs)["n_matches"] >= 1
            avail = tick()
        assert avail["budget_remaining_frac"] >= burned
        assert obs.counter("slo.availability.pages",
                           labels=server.labels).value == 1.0
        assert len(glob.glob(
            flight_dir + "/flight-slo-burn-availability-*.jsonl")) == 1
        assert client.healthz()["status"] == "ok"
    finally:
        failpoints.clear()
        server.stop()
