"""Cross-process tracing plane: wire propagation, head sampling,
bounded runlogs, multi-runlog assembly (obs/trace.py, obs/events.py,
tools/trace_export.py, tools/obs_report.py — docs/OBSERVABILITY.md,
"Cross-process tracing").

Unit layer: the ``X-NCNet-Trace`` header grammar (inject/extract
round-trip, malformed values rejected to None), trace continuation
with the ``remote_parent`` marker and the ``trace.*`` counters,
sample-rate-0 suppression with the error/force escape hatches, runlog
size rotation (segment sets read identically to an unrotated
reference), clock-skew recovery on synthetic records, the redispatch
hop landing in the request's own tree, and obs_report's
``<remote ...>`` vs ``<orphaned>`` grouping.

E2e layer: a real stdlib client and a 2-replica fleet server share a
process but write SEPARATE runlogs (the client gets an explicit
``run_log`` sink); the exported join of the two logs must be ONE tree
per request rooted at the client span, with the response ``trace_id``
equal to the id the client injected.
"""

import json
import os
import sys
import threading

import pytest

from conftest import assert_valid_runlog
from ncnet_tpu import obs
from ncnet_tpu.obs import trace
from ncnet_tpu.obs.events import RunLog, runlog_segments

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import obs_report  # noqa: E402
import trace_export  # noqa: E402


def _load(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- wire grammar ---------------------------------------------------------


def test_inject_extract_roundtrip():
    ctx = trace.SpanCtx("ab" * 8, "cd" * 8, sampled=True)
    value = trace.inject(ctx)
    assert value == f"{'ab' * 8}-{'cd' * 8}-01"
    back = trace.extract(value)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled is True
    # An extracted context is marked remote: its span lives in the
    # caller's runlog, and trace() counts the continuation.
    assert back.remote is True

    unsampled = trace.inject(trace.SpanCtx("ab" * 8, "cd" * 8,
                                           sampled=False))
    assert unsampled.endswith("-00")
    assert trace.extract(unsampled).sampled is False

    # inject() with no argument serializes the ambient context.
    assert trace.inject() is None
    with trace.trace("request") as root:
        hdr = trace.inject()
    assert trace.extract(hdr).trace_id == root.trace_id
    assert trace.extract(hdr).span_id == root.span_id


@pytest.mark.parametrize("bad", [
    None,
    "",
    "justonechunk",
    "two-chunks",
    "a" * 16 + "-" + "b" * 16 + "-01-extra",
    "zz!" + "-" + "b" * 16 + "-01",          # non-hex trace id
    "a" * 16 + "-" + "b!" * 8 + "-01",       # non-hex span id
    "a" * 16 + "-" + "b" * 16 + "-xx",       # non-hex flags
    "-" + "b" * 16 + "-01",                  # empty trace id
    42,                                       # not a string at all
])
def test_extract_rejects_malformed(bad):
    # Malformed propagation is best-effort-dropped, never an error:
    # the server roots a fresh trace instead of failing the request.
    assert trace.extract(bad) is None


# -- continuation + counters ----------------------------------------------


def test_trace_continuation_counters_and_remote_marker(tmp_path):
    path = tmp_path / "t.jsonl"
    run = obs.init_run("unit", str(path), heartbeat_s=0)
    try:
        with trace.trace("client.request") as croot:
            wire = trace.inject()
        remote = trace.extract(wire)
        with trace.trace("request", parent=remote, kind="server") as sroot:
            pass
    finally:
        run.close()
    # Continuation: same trace, parented onto the wire span.
    assert sroot.trace_id == croot.trace_id
    records = _load(path)
    req = next(r for r in records if r["event"] == "request")
    assert req["trace_id"] == croot.trace_id
    assert req["parent_id"] == croot.span_id
    assert req["remote_parent"] is True
    assert req["span_kind"] == "server"
    assert obs.counter("trace.remote_spans").value == 1
    assert obs.counter("trace.sampled").value == 2
    assert obs.counter("trace.dropped").value == 0


def test_sample_rate_zero_suppresses_spans_but_records_errors(tmp_path):
    path = tmp_path / "s.jsonl"
    run = obs.init_run("unit", str(path), heartbeat_s=0)
    try:
        trace.set_sample_rate(0.0)
        assert obs.gauge("trace.sample_rate").value == 0.0
        # Happy path: root + child write NOTHING.
        with trace.trace("request") as root:
            assert not root.sampled
            with trace.span("child"):
                pass
            # inject propagates the negative decision downstream.
            assert trace.inject().endswith("-00")
        # Instant events are never sampling-gated.
        obs.event("request_summary", trace_id=root.trace_id)
        # An exception inside an unsampled trace still leaves a trail.
        with pytest.raises(RuntimeError):
            with trace.trace("boom"):
                raise RuntimeError("x")
        # force(): the handler discovers a 4xx/5xx outcome after the
        # fact; the root must land with the forced fields.
        with trace.trace("forced_req") as froot:
            trace.force(froot, status=503, error_kind="over_capacity")
    finally:
        trace.set_sample_rate(1.0)
        run.close()
    records = _load(path)
    spans = [r for r in records if r.get("kind") == "span"]
    names = {r["event"] for r in spans}
    assert "request" not in names and "child" not in names
    assert any(r["event"] == "request_summary" for r in records)
    boom = next(r for r in spans if r["event"] == "boom")
    assert boom["error"].startswith("RuntimeError")
    assert boom["sampled"] is False
    forced = next(r for r in spans if r["event"] == "forced_req")
    assert forced["status"] == 503
    assert forced["error_kind"] == "over_capacity"
    assert forced["sampled"] is False
    # Counters reconcile: every root decision counted, all dropped.
    assert obs.counter("trace.dropped").value == 3
    assert obs.counter("trace.sampled").value == 0


# -- runlog rotation ------------------------------------------------------


def test_runlog_rotation_segment_set_reads_as_one_log(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, "unit", max_bytes=4000)
    for i in range(60):
        log.event("tick", i=i, pad="x" * 40)
    log.event("heartbeat", idle_s=0.0)
    log.flush_metrics()
    log.close("ok")
    segs = runlog_segments(path)
    assert len(segs) >= 3, "4 kB cap over ~10 kB of events must rotate"
    assert segs[-1] == path, "active base file is always the newest"
    mids = [os.path.basename(s) for s in segs[:-1]]
    assert mids == sorted(mids)
    # conftest's schema check reads the segment set transparently and
    # sees the full ordered stream.
    records = assert_valid_runlog(path, component="unit")
    assert [r["i"] for r in records
            if r["event"] == "tick"] == list(range(60))

    # Reader equivalence: the rotated set exports identically to a
    # hand-merged unrotated reference file.
    merged = str(tmp_path / "merged.jsonl")
    with open(merged, "w", encoding="utf-8") as out:
        for seg in segs:
            with open(seg, encoding="utf-8") as fh:
                out.write(fh.read())
    ta = trace_export.export(path, str(tmp_path / "a.trace.json"))
    tb = trace_export.export(merged, str(tmp_path / "b.trace.json"))
    assert ta["traceEvents"] == tb["traceEvents"]
    assert obs_report.load_run(path) == obs_report.load_run(merged)


def test_runlog_rotation_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("NCNET_RUNLOG_MAX_MB", "0.002")  # 2000 bytes
    path = str(tmp_path / "e.jsonl")
    log = RunLog(path, "unit")
    assert log.max_bytes == 2000
    for i in range(40):
        log.event("tick", i=i, pad="y" * 60)
    log.close("ok")
    assert len(runlog_segments(path)) >= 2

    # A garbage value degrades to unbounded, never takes the run down.
    monkeypatch.setenv("NCNET_RUNLOG_MAX_MB", "junk")
    log2 = RunLog(str(tmp_path / "j.jsonl"), "unit")
    assert log2.max_bytes == 0
    log2.close("ok")


# -- clock-skew pairing ---------------------------------------------------


def _span(tw, dur, sid, pid=None, **fields):
    return {"kind": "span", "event": "s", "t_wall": tw, "dur_s": dur,
            "span_id": sid, "parent_id": pid, **fields}


def test_clock_offsets_recover_skew_from_remote_edges():
    t0 = 1000.0
    skew = 12.5  # server wall clock runs this far AHEAD of the client
    client = [_span(t0 + 1.0, 1.0, "a"),
              _span(t0 + 0.9, 0.8, "b", "a")]
    # The server span covers the same instant as its client parent, but
    # timestamped on the skewed clock.
    server = [_span(t0 + skew + 0.85, 0.7, "c", "b", remote_parent=True)]
    offs = trace_export.clock_offsets([client, server])
    assert offs[0] == 0.0, "file 0 is the reference timebase"
    assert offs[1] == pytest.approx(-skew, abs=0.2)

    # A file with no remote edge to the reference keeps offset 0.
    lonely = [_span(t0 + 99.0, 1.0, "z")]
    offs = trace_export.clock_offsets([client, server, lonely])
    assert offs[1] == pytest.approx(-skew, abs=0.2)
    assert offs[2] == 0.0

    # Transitive correction: a third file hanging off the SERVER's
    # spans corrects through the chain back to the client's timebase.
    skew2 = -5.0
    replica = [_span(t0 + skew + skew2 + 0.75, 0.5, "d", "c",
                     remote_parent=True)]
    offs = trace_export.clock_offsets([client, server, replica])
    assert offs[2] == pytest.approx(-(skew + skew2), abs=0.4)


def test_trace_export_selftest_passes(capsys):
    assert trace_export._selftest() == 0
    line = capsys.readouterr().out.strip()
    report = json.loads(line)
    assert report["metric"] == "trace_export_selftest"
    assert report["ok"] is True
    assert report["clock_offset_s"] == pytest.approx(-30.0, abs=0.5)


# -- obs_report grouping --------------------------------------------------


def test_obs_report_remote_vs_orphaned_grouping():
    recs = [
        {"kind": "span", "event": "request", "dur_s": 0.5, "t_wall": 1.0,
         "trace_id": "t1", "span_id": "s1", "parent_id": "w" * 16,
         "remote_parent": True},
        {"kind": "span", "event": "admit", "dur_s": 0.1, "t_wall": 1.0,
         "trace_id": "t1", "span_id": "s2", "parent_id": "s1"},
        {"kind": "span", "event": "lost_child", "dur_s": 0.1,
         "t_wall": 1.0, "trace_id": "t2", "span_id": "s3",
         "parent_id": "gone"},
    ]
    tree = obs_report.span_tree(recs)
    remote_root = f"<remote {'w' * 8}>"
    # The wire-parented span roots under <remote ...> (join the
    # caller's log to resolve it), NOT under <orphaned> — which stays
    # reserved for genuinely lost parents.
    assert (remote_root, "request") in tree
    assert (remote_root, "request", "admit") in tree
    assert ("<orphaned>", "lost_child") in tree
    assert not any("<orphaned>" in p and "request" in p for p in tree)


def test_obs_report_join_renders_one_tree(tmp_path, capsys):
    client = [
        {"v": 2, "run_id": "c", "event": "run_start", "t_wall": 1.0,
         "t_mono": 0.0, "component": "client", "pid": 11},
        {"v": 2, "run_id": "c", "event": "client.request", "kind": "span",
         "t_wall": 2.0, "t_mono": 1.0, "dur_s": 1.0,
         "trace_id": "t" * 16, "span_id": "a" * 16, "parent_id": None},
        {"v": 2, "run_id": "c", "event": "client.attempt", "kind": "span",
         "t_wall": 1.95, "t_mono": 0.95, "dur_s": 0.9,
         "trace_id": "t" * 16, "span_id": "b" * 16, "parent_id": "a" * 16},
    ]
    server = [
        {"v": 2, "run_id": "s", "event": "run_start", "t_wall": 1.0,
         "t_mono": 0.0, "component": "serving", "pid": 12},
        {"v": 2, "run_id": "s", "event": "request", "kind": "span",
         "t_wall": 1.9, "t_mono": 0.9, "dur_s": 0.8,
         "trace_id": "t" * 16, "span_id": "c" * 16, "parent_id": "b" * 16,
         "remote_parent": True},
    ]
    paths = [str(tmp_path / "c.jsonl"), str(tmp_path / "s.jsonl")]
    for path, recs in zip(paths, (client, server)):
        with open(path, "w", encoding="utf-8") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
    assert obs_report.main(["--join"] + paths) == 0
    out = capsys.readouterr().out
    assert "cross-process span tree" in out
    assert "client.request" in out and "client.attempt" in out
    # The remote edge RESOLVED across the join: no synthetic roots.
    assert "<remote" not in out and "<orphaned>" not in out
    assert "joined traces: 1" in out


# -- redispatch hop in the request's tree ---------------------------------


def _echo(bucket_key, batch):
    return [{"payload": p, "bucket": bucket_key} for p in batch]


def test_redispatch_span_lands_in_request_trace(tmp_path):
    from ncnet_tpu.serving.dispatcher import FleetDispatcher
    from ncnet_tpu.serving.fleet import Replica

    path = tmp_path / "d.jsonl"
    run = obs.init_run("unit", str(path), heartbeat_s=0)
    clock = FakeClock()
    pool = [Replica(f"r{i}", runner=_echo, clock=clock, max_batch=2,
                    max_queue=4, max_delay_s=0.05) for i in range(2)]
    disp = FleetDispatcher(pool)
    try:
        with trace.trace("request") as root:
            fut = disp.submit("b", "x")
        victim = next(r for r in pool if r.load > 0)
        survivor = next(r for r in pool if r is not victim)
        victim.kill()
        clock.t += 0.1
        victim.batcher.poll()  # refusal -> done-callback redispatches
        clock.t += 0.1
        survivor.batcher.poll()
        assert fut.result(timeout=1).result["payload"] == "x"
    finally:
        run.close()
    records = _load(path)
    # The flat `redispatch` instant event predates the trace plane and
    # stays; the SPAN record is the new tree-linked hop.
    red = [r for r in records if r.get("event") == "redispatch"
           and r.get("kind") == "span"]
    assert len(red) == 1
    # The hop parents onto the submitting request's root — a cross-
    # replica retry stays visible inside the request's own tree.
    assert red[0]["trace_id"] == root.trace_id
    assert red[0]["parent_id"] == root.span_id
    assert "error" in red[0]
    assert red[0]["attempt"] >= 1
    assert red[0]["replica"] == victim.replica_id


# -- e2e: client + 2-replica fleet, separate runlogs, one joined tree -----


def _jpeg_bytes(h, w, seed):
    import io

    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray((rng.random((h, w, 3)) * 255).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def test_cross_process_trace_e2e(tiny_serving_model, tmp_path):
    from ncnet_tpu.serving.client import MatchClient
    from ncnet_tpu.serving.fleet import MatchFleet
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    server_log_path = str(tmp_path / "server.jsonl")
    client_log_path = str(tmp_path / "client.jsonl")
    # Server logs through the ambient run; the client gets an EXPLICIT
    # sink — in-process client+server must not interleave one file, or
    # the join below would be vacuous.
    run_log = obs.init_run("serving", server_log_path)
    client_log = RunLog(client_log_path, "client")
    fleet = MatchFleet.build(
        config, params, n_replicas=2, base_id="e2e", cache_mb=64,
        cache_model_key="trace-e2e",
        engine_kwargs=dict(k_size=2, image_size=64),
        replica_kwargs=dict(max_batch=2, max_delay_s=0.01,
                            default_timeout_s=120.0))
    server = MatchServer(None, port=0, fleet=fleet,
                         slo_p99_target_s=60.0).start()
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0,
                             run_log=client_log)
        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)
        r1 = client.match(query_bytes=qb, pano_bytes=pb, max_matches=8)
        assert r1["n_matches"] >= 1

        # Kill a replica mid-stream: traffic keeps flowing on the
        # survivor and the trace plane keeps propagating.
        fleet.kill("e2e-d1")
        r2 = client.match(query_bytes=qb, pano_bytes=pb, max_matches=8)
        assert r2["n_matches"] >= 1
        fleet.revive("e2e-d1")

        # Head sampling off: the request succeeds, writes NO span
        # events anywhere, but the root decision is still counted.
        sampled0 = obs.counter("trace.sampled").value
        dropped0 = obs.counter("trace.dropped").value
        trace.set_sample_rate(0.0)
        try:
            r3 = client.match(query_bytes=qb, pano_bytes=pb,
                              max_matches=8)
        finally:
            trace.set_sample_rate(1.0)
        assert r3["trace_id"]
        assert obs.counter("trace.sampled").value == sampled0
        assert obs.counter("trace.dropped").value == dropped0 + 1
    finally:
        server.stop()
        run_log.close("ok")
        client_log.close("ok")

    server_records = assert_valid_runlog(server_log_path,
                                         component="serving")
    client_records = _load(client_log_path)

    # The response trace_id IS the client-injected id: the client log's
    # request roots carry exactly the ids the server echoed back.
    creqs = [r for r in client_records
             if r.get("event") == "client.request"]
    assert len(creqs) == 2, "unsampled r3 must not write a client root"
    assert {r["trace_id"] for r in creqs} == {r1["trace_id"],
                                              r2["trace_id"]}
    for r in creqs:
        assert r["span_kind"] == "client"
        assert r["parent_id"] is None
        assert r["attempts"] == 1 and r["status"] == 200

    # The server CONTINUED those traces across the wire.
    sreqs = [r for r in server_records
             if r.get("event") == "request" and r.get("kind") == "span"]
    assert {r["trace_id"] for r in sreqs} == {r1["trace_id"],
                                              r2["trace_id"]}
    for r in sreqs:
        assert r["remote_parent"] is True
        assert r["span_kind"] == "server"

    # r3 (unsampled) left no span record in EITHER log.
    assert all(r.get("trace_id") != r3["trace_id"]
               for r in server_records + client_records
               if r.get("kind") == "span")

    # The join: every span of each request walks up to ONE root — the
    # client.request span — across the two files.
    by_id = {r["span_id"]: r
             for r in client_records + server_records
             if r.get("kind") == "span" and r.get("span_id")}
    for resp in (r1, r2):
        tspans = [r for r in by_id.values()
                  if r.get("trace_id") == resp["trace_id"]]
        assert len(tspans) >= 4, (
            "expected client root + attempt + server request + "
            f"lifecycle children, got {[r['event'] for r in tspans]}")
        roots = [r for r in tspans if r.get("parent_id") is None]
        assert [r["event"] for r in roots] == ["client.request"]
        for r in tspans:
            node, hops = r, 0
            while node.get("parent_id") is not None:
                node = by_id[node["parent_id"]]
                hops += 1
                assert hops < 50, "cycle in joined span tree"
            assert node["event"] == "client.request"

    # And the exporter agrees: 2 cross-file traces, near-zero skew
    # (same host clock), output written.
    out = str(tmp_path / "joined.trace.json")
    data = trace_export.export([client_log_path, server_log_path], out)
    assert os.path.exists(out)
    assert trace_export._cross_file_traces(
        [client_records, server_records]) == 2
    off = data["otherData"]["clock_offsets_s"][server_log_path]
    assert abs(off) < 2.0
