"""True multi-host training test: two coordinated CPU processes.

The reference has no distributed capability at all (SURVEY.md §2.8); here
the multi-host path (parallel/multihost.py + cli/train.py) is validated
end-to-end by launching TWO separate Python processes that form a
2-host x 2-device global mesh over the JAX distributed runtime (Gloo
collectives on CPU), each decoding only its host-local slice of every
global batch. Per-epoch losses must agree across hosts (same global
computation) and the run must produce a checkpoint on each host.
"""

import csv
import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _write_dataset(root):
    rng = np.random.default_rng(0)
    (root / "images").mkdir()
    (root / "image_pairs").mkdir()
    names = []
    for i in range(10):
        n = f"images/im{i}.jpg"
        Image.fromarray((rng.random((48, 48, 3)) * 255).astype("uint8")).save(
            root / n
        )
        names.append(n)
    for split, rows in (("train_pairs", range(0, 8, 2)), ("val_pairs", [8])):
        with open(root / "image_pairs" / f"{split}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["source_image", "target_image", "class", "flip"])
            for i in rows:
                w.writerow([names[i], names[i + 1], 1, 0])


def _run_pair(cmds_env, timeout):
    """Launch the per-process commands, reap BOTH even when one fails —
    a surviving peer otherwise blocks forever in the coordinator handshake
    or a cross-process collective and leaks across retried runs."""
    procs = [
        subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for cmd, env in cmds_env
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return procs, outs


def _proc_env(extra=None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        # The probe scripts import ncnet_tpu; python puts the *script's*
        # directory (tests/) on sys.path, not the cwd, so the repo root must
        # travel explicitly — the suite must not depend on a venv install.
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        **(extra or {}),
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


@pytest.mark.slow
def test_two_process_sharded_consensus():
    """The sharded match pipeline over a mesh spanning two PROCESSES: the
    Conv4d halo exchange (ppermute) crosses the host boundary — the
    DCN-analogue path. Each process pins its addressable shards against the
    unsharded reference (tests/_mh_sharded_probe.py)."""
    port = _free_port()
    probe = os.path.join(REPO, "tests", "_mh_sharded_probe.py")
    procs, outs = _run_pair(
        [
            ([sys.executable, probe, f"localhost:{port}", str(pid)],
             _proc_env())
            for pid in range(2)
        ],
        timeout=300,
    )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"probe failed:\n{out}"
        assert "cross-host sharded consensus OK" in out


@pytest.mark.slow
def test_two_process_train(tmp_path):
    _write_dataset(tmp_path)
    port = _free_port()
    procs, outs = _run_pair(
        [
            (
                [
                    sys.executable, "-m", "ncnet_tpu.cli.train",
                    "--dataset_image_path", str(tmp_path),
                    "--dataset_csv_path", str(tmp_path / "image_pairs"),
                    "--num_epochs", "2", "--batch_size", "4",
                    "--image_size", "48", "--backbone", "vgg",
                    "--ncons_kernel_sizes", "3", "--ncons_channels", "1",
                    "--result_model_dir", str(tmp_path / f"models_h{pid}"),
                    "--num_workers", "0",
                ],
                _proc_env({
                    "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
                    "JAX_NUM_PROCESSES": "2",
                    "JAX_PROCESS_ID": str(pid),
                }),
            )
            for pid in range(2)
        ],
        timeout=600,
    )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"host process failed:\n{out}"

    # Both hosts saw the global mesh and agreed on every epoch loss.
    epoch_re = re.compile(r"Epoch \d+: train (\S+)\s+val (\S+)")
    losses = [epoch_re.findall(o) for o in outs]
    assert losses[0] and losses[0] == losses[1], (
        f"per-host losses diverged:\n{losses}\n--- host0:\n{outs[0]}"
    )
    for out in outs:
        assert "hosts: 2" in out
    # Only host 0 writes checkpoints (replicated params; concurrent writes
    # on shared storage would race).
    runs = os.listdir(tmp_path / "models_h0")
    assert len(runs) == 1
    assert (tmp_path / "models_h0" / runs[0] / "epoch_2").is_dir()
    assert not os.path.exists(tmp_path / "models_h1") or not os.listdir(
        tmp_path / "models_h1"
    )


@pytest.mark.slow
def test_two_process_sharded_consensus_real_extent():
    """The cross-process halo-exchange consensus at the production sharded
    extent: iA=96 rows over a 4-way mesh spanning two processes, with the
    real 16-channel consensus (VERDICT r2 item 6's multihost variant).
    The B plane is halved (48x36) to keep two CPU processes feasible —
    the sharded axis and channel geometry are the production values."""
    port = _free_port()
    probe = os.path.join(REPO, "tests", "_mh_sharded_probe.py")
    shape = "96,72,48,36,16"
    procs, outs = _run_pair(
        [
            ([sys.executable, probe, f"localhost:{port}", str(pid), shape],
             _proc_env())
            for pid in range(2)
        ],
        timeout=560,
    )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"probe failed:\n{out}"
        assert "cross-host sharded consensus OK" in out
