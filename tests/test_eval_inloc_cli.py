"""End-to-end test of the InLoc matching CLI (cli/eval_inloc.py).

Synthetic fixture: a shortlist .mat (ImgList rows of query name + pano
names), query/pano JPEGs. Checks the written per-query match .mat
(layout parity with the reference writer, eval_inloc.py:199-221) and the
--resume skip behavior.
"""

import os

import numpy as np
import pytest
from PIL import Image
from scipy.io import loadmat, savemat

from ncnet_tpu.cli import eval_inloc


@pytest.fixture()
def fixture_dir(tmp_path):
    rng = np.random.default_rng(0)
    qdir = tmp_path / "query"
    pdir = tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    for name, d in [("q0.jpg", qdir), ("q1.jpg", qdir)]:
        Image.fromarray((rng.random((96, 128, 3)) * 255).astype("uint8")).save(d / name)
    pano_names = [f"p{i}.jpg" for i in range(2)]
    for name in pano_names:
        Image.fromarray((rng.random((96, 128, 3)) * 255).astype("uint8")).save(
            pdir / name
        )
    # ImgList struct array: each row (queryname, topNname cell array).
    img_list = np.zeros((1, 2), dtype=[("queryname", "O"), ("topNname", "O")])
    for q, qn in enumerate(["q0.jpg", "q1.jpg"]):
        img_list[0, q]["queryname"] = qn
        img_list[0, q]["topNname"] = np.array(pano_names, dtype=object).reshape(1, -1)
    savemat(tmp_path / "shortlist.mat", {"ImgList": img_list})
    return tmp_path


def _run(fixture_dir, size=64):
    out_dir = fixture_dir / "matches"
    eval_inloc.main(
        [
            "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
            "--query_path", str(fixture_dir / "query"),
            "--pano_path", str(fixture_dir / "pano"),
            "--output_dir", str(out_dir),
            "--image_size", str(size),
            "--n_queries", "2",
            "--n_panos", "2",
            "--k_size", "2",
        ]
    )
    exp = [d for d in os.listdir(out_dir)]
    assert len(exp) == 1
    return out_dir / exp[0]


def test_write_matches_mat_reference_contract(tmp_path):
    """Key-by-key contract with the reference writer (eval_inloc.py:126,
    199-221): the unchanged Matlab pipeline must see identical field names,
    dtypes, shapes, and values from both writers.

    The reference side is generated here with scipy from the reference
    code's documented layout: float64 `matches` [1, Npanos, N, 5] filled
    rows-first with (xA, yA, xB, yB, score), `query_fn` str,
    `pano_fn` object array, do_compression=True.
    """
    from scipy.io import savemat as scipy_savemat

    from ncnet_tpu.evals.inloc import (
        fill_matches,
        matches_buffer,
        write_matches_mat,
    )

    rng = np.random.default_rng(3)
    n_panos, n_cap = 3, 7
    pano_fn_all = np.vstack(
        [
            np.array([f"pano_{q}_{i}.jpg" for i in range(n_panos)], dtype=object
                     ).reshape(1, -1)
            for q in range(2)
        ]
    )

    # Reference writer emulation (eval_inloc.py:126,199-203,221).
    matches_ref = np.zeros((1, n_panos, n_cap, 5))
    per_pano = []
    for idx in range(n_panos):
        npts = [5, 7, 0][idx]  # fewer-than-N, exactly-N, and empty panos
        tup = tuple(rng.random(npts) for _ in range(5))
        per_pano.append(tup)
        xa, ya, xb, yb, score = tup
        if npts > 0:
            matches_ref[0, idx, :npts, 0] = xa
            matches_ref[0, idx, :npts, 1] = ya
            matches_ref[0, idx, :npts, 2] = xb
            matches_ref[0, idx, :npts, 3] = yb
            matches_ref[0, idx, :npts, 4] = score
    ref_path = tmp_path / "ref" / "1.mat"
    os.makedirs(ref_path.parent)
    scipy_savemat(
        ref_path,
        {"matches": matches_ref, "query_fn": "q0.jpg", "pano_fn": pano_fn_all},
        do_compression=True,
    )

    # Our writer on the same data.
    buf = matches_buffer(n_panos, n_cap)
    for idx, tup in enumerate(per_pano):
        fill_matches(buf, idx, tup)
    ours_path = tmp_path / "ours" / "1.mat"
    write_matches_mat(str(ours_path), buf, "q0.jpg", pano_fn_all)

    ref = loadmat(ref_path)
    ours = loadmat(ours_path)
    ref_keys = {k for k in ref if not k.startswith("__")}
    assert {k for k in ours if not k.startswith("__")} == ref_keys
    for k in sorted(ref_keys):
        assert ours[k].dtype == ref[k].dtype, k
        assert ours[k].shape == ref[k].shape, k
        if ref[k].dtype == object:
            np.testing.assert_array_equal(ours[k], ref[k])
        else:
            np.testing.assert_array_equal(ours[k], ref[k], err_msg=k)


def test_inloc_resize_shape_alignment():
    """Pin the reference's resize-alignment arithmetic (eval_inloc.py:84-89):
    long side scaled to ~image_size with feature dims (stride 16) divisible
    by k_size, and the height unit additionally by shards*k for the sharded
    forward."""
    from ncnet_tpu.cli.eval_inloc import inloc_resize_shape

    # Canonical InLoc sizes: iPhone7 query 4032x3024 -> 3200x2400.
    assert inloc_resize_shape(4032, 3024, 3200, 2) == (3200, 2400)
    assert inloc_resize_shape(3024, 4032, 3200, 2) == (2400, 3200)
    # Non-standard aspect: alignment trims, never exceeds the long side.
    assert inloc_resize_shape(3000, 2000, 3200, 2) == (3200, 2112)
    for h, w in [(4032, 3024), (999, 1501), (3000, 2000), (480, 640)]:
        for k in (1, 2):
            for shards in (1, 4):
                oh, ow = inloc_resize_shape(
                    h, w, 3200, k, h_unit=k * shards
                )
                assert oh <= 3200 and ow <= 3200
                assert (oh // 16) % (k * shards) == 0, (h, w, k, shards)
                assert (ow // 16) % k == 0
                assert oh % 16 == 0 and ow % 16 == 0


def test_writes_match_files(fixture_dir):
    exp_dir = _run(fixture_dir)
    files = sorted(os.listdir(exp_dir))
    assert [f for f in files if f.endswith(".mat")] == ["1.mat", "2.mat"]
    # The run's telemetry log (docs/OBSERVABILITY.md) lands alongside —
    # one file per run, nothing else in the experiment dir.
    runlogs = [f for f in files if not f.endswith(".mat")]
    assert len(runlogs) == 1 and runlogs[0].startswith("runlog-eval_inloc-")
    assert runlogs[0].endswith(".jsonl")
    from conftest import assert_valid_runlog

    records = assert_valid_runlog(exp_dir / runlogs[0],
                                  component="eval_inloc")
    names = [r["event"] for r in records]
    # The demo run records per-query progress and the dispatch counters.
    assert names.count("query") == 2
    final = [r for r in records if r["event"] == "metrics"][-1]["snapshot"]
    assert final["counters"]["eval_inloc.pairs"] == 4.0
    assert records[-1]["status"] == "ok"
    m = loadmat(exp_dir / "1.mat")["matches"]
    # [1, n_panos, N, 5] with normalized coords + score rows filled.
    assert m.shape[0] == 1 and m.shape[1] == 2 and m.shape[3] == 5
    filled = m[0, 0]
    assert np.isfinite(filled).all()
    assert (filled[:, :4] >= 0).all() and (filled[:, :4] <= 1).all()


def test_resume_skips_existing(fixture_dir):
    exp_dir = _run(fixture_dir)
    mtimes = {f: os.path.getmtime(exp_dir / f) for f in os.listdir(exp_dir)}
    _run(fixture_dir)  # --resume is default-on; nothing rewritten
    for f, t in mtimes.items():
        assert os.path.getmtime(exp_dir / f) == t


@pytest.mark.parametrize(
    "shards", [2, pytest.param(4, marks=pytest.mark.slow)]
)
def test_spatial_shards_cli(fixture_dir, shards):
    """--spatial_shards N runs the sharded forward on the CPU mesh and writes
    the same .mat layout (N=4 exercises the h_unit=N*k input bucketing)."""
    out_dir = fixture_dir / f"matches_sharded_{shards}"
    eval_inloc.main(
        [
            "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
            "--query_path", str(fixture_dir / "query"),
            "--pano_path", str(fixture_dir / "pano"),
            "--output_dir", str(out_dir),
            "--image_size", "128",
            "--n_queries", "1",
            "--n_panos", "2",
            "--k_size", "2",
            "--spatial_shards", str(shards),
        ]
    )
    exp = os.listdir(out_dir)
    m = loadmat(out_dir / exp[0] / "1.mat")["matches"]
    assert m.shape[0] == 1 and m.shape[3] == 5
    assert np.isfinite(m[0, 0]).all()


@pytest.mark.slow
@pytest.mark.parametrize("backbone_batch", ["1", "2"])
def test_pano_batch_matches_unbatched(fixture_dir, backbone_batch,
                                      monkeypatch):
    """--pano_batch (scanned same-shape stacks, incl. ragged padding) writes
    the same .mat contents as the per-pano dispatch path."""
    from scipy.io import loadmat

    ref_dir = _run(fixture_dir)
    # backbone_batch="2" covers the NCNET_PANO_BACKBONE_BATCH path:
    # group backbones run batched before the per-pano scan.
    monkeypatch.setenv("NCNET_PANO_BACKBONE_BATCH", backbone_batch)
    out_b = fixture_dir / ("matches_batched" + backbone_batch)
    eval_inloc.main(
        [
            "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
            "--query_path", str(fixture_dir / "query"),
            "--pano_path", str(fixture_dir / "pano"),
            "--output_dir", str(out_b),
            "--image_size", "64",
            "--n_queries", "2",
            "--n_panos", "2",
            "--k_size", "2",
            # 3 > n_panos: exercises the ragged-group repeat padding.
            "--pano_batch", "3",
        ]
    )
    exp = os.listdir(out_b)
    assert len(exp) == 1
    got_dir = out_b / exp[0]
    names = sorted(f for f in os.listdir(ref_dir) if f.endswith(".mat"))
    got_names = sorted(f for f in os.listdir(got_dir) if f.endswith(".mat"))
    assert got_names == names and names
    for fn in names:
        want = loadmat(ref_dir / fn)["matches"]
        got = loadmat(got_dir / fn)["matches"]
        # The scanned program is a DIFFERENT compiled artifact: XLA fusion
        # choices shift bf16 rounding by ~1e-4, which flips near-tied
        # argmax winners on these noise-image fixtures — exact coordinate
        # equality is not a property of the batching. Assert the stable
        # contract instead: same layout, same filled rows, coordinates in
        # range, and the descending score columns equal to rounding.
        assert got.shape == want.shape
        filled_w = np.any(want != 0, axis=-1)
        filled_g = np.any(got != 0, axis=-1)
        np.testing.assert_array_equal(filled_g, filled_w)
        assert np.all((got[..., :4] >= 0) & (got[..., :4] <= 1))
        np.testing.assert_allclose(
            got[..., 4], want[..., 4], atol=2e-3,
            err_msg="score column diverged beyond bf16 rounding",
        )


@pytest.mark.slow
def test_pano_batch_mixed_shapes(tmp_path):
    """Batched pano mode with HETEROGENEOUS pano shapes: the incremental
    grouper must split same-bucket stacks correctly (portrait + landscape
    panos in one shortlist) and still fill every pano's slot."""
    rng = np.random.default_rng(3)
    qdir = tmp_path / "query"
    pdir = tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    Image.fromarray(
        (rng.random((96, 128, 3)) * 255).astype("uint8")
    ).save(qdir / "q0.jpg")
    # Two landscape + two portrait panos -> two shape buckets.
    shapes = [(96, 128), (128, 96), (96, 128), (128, 96)]
    pano_names = []
    for i, (h, w) in enumerate(shapes):
        n = f"p{i}.jpg"
        Image.fromarray(
            (rng.random((h, w, 3)) * 255).astype("uint8")
        ).save(pdir / n)
        pano_names.append(n)
    img_list = np.zeros((1, 1), dtype=[("queryname", "O"), ("topNname", "O")])
    img_list[0, 0]["queryname"] = "q0.jpg"
    img_list[0, 0]["topNname"] = np.array(
        pano_names, dtype=object
    ).reshape(1, -1)
    savemat(tmp_path / "shortlist.mat", {"ImgList": img_list})

    out_dir = tmp_path / "matches"
    eval_inloc.main(
        [
            "--inloc_shortlist", str(tmp_path / "shortlist.mat"),
            "--query_path", str(qdir),
            "--pano_path", str(pdir),
            "--output_dir", str(out_dir),
            "--image_size", "64",
            "--n_queries", "1",
            "--n_panos", "4",
            "--k_size", "2",
            "--pano_batch", "2",
        ]
    )
    exp = os.listdir(out_dir)
    assert len(exp) == 1
    from scipy.io import loadmat

    m = loadmat(out_dir / exp[0] / "1.mat")["matches"]
    assert m.shape[1] == 4
    # Every pano slot must carry real matches (nonzero scores).
    for idx in range(4):
        assert np.any(m[0, idx, :, 4] > 0), f"pano {idx} slot empty"


def test_pano_feature_cache_parity_and_hits(fixture_dir, capsys):
    """Cross-query pano-feature cache (VERDICT r3 item 2): both queries
    share the same 2 panos, so the second query's panos are pure cache
    hits — and every written .mat must be BIT-IDENTICAL to the uncached
    run (a hit replays the identical feature tensor through the identical
    match program)."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "2",
        "--n_panos", "2",
        "--k_size", "2",
    ]
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "m_off"),
        "--pano_feature_cache_mb", "0",
    ])
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "m_on"),
        "--pano_feature_cache_dir", str(fixture_dir / "fc_parity"),
    ])
    out = capsys.readouterr().out
    # q0: 2 misses; q1: the same panos -> 2 hits.
    assert "2/4 hits (50%" in out

    # Entries are stored bf16 (half the bytes of the f32 features; the
    # parity assertions below prove the rounding is output-lossless).
    # On disk that's a uint16 view + dtype tag — npz can't round-trip
    # the ml_dtypes bf16 dtype itself.
    npzs = [f for f in os.listdir(fixture_dir / "fc_parity")
            if f.endswith(".npz")]
    assert npzs
    with np.load(fixture_dir / "fc_parity" / npzs[0]) as z:
        assert str(z["dtype"][()]) == "bfloat16"
        assert z["feats"].dtype == np.uint16

    exp_off = os.listdir(fixture_dir / "m_off")[0]
    exp_on = os.listdir(fixture_dir / "m_on")[0]
    for q in ("1.mat", "2.mat"):
        a = loadmat(fixture_dir / "m_off" / exp_off / q)
        b = loadmat(fixture_dir / "m_on" / exp_on / q)
        np.testing.assert_array_equal(a["matches"], b["matches"])
        assert a["query_fn"] == b["query_fn"]


def test_pano_feature_cache_with_pano_batch(fixture_dir, capsys):
    """--pano_batch composed with the cache: query 1's misses run the
    batched-backbone miss program (stacks of --pano_batch, features
    returned for the store), query 2's panos are pure hits. Contract
    mirrors test_pano_batch_matches_unbatched: batching already trades
    bit-exactness for throughput (different compiled artifacts shift
    bf16 rounding), so the cached-batched run must match the uncached
    batched run at the same layout/filled-rows/score-rounding level."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "2",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_batch", "2",
    ]
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "mb_off"),
        "--pano_feature_cache_mb", "0",
    ])
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "mb_on"),
    ])
    out = capsys.readouterr().out
    # q0: 2 misses (one batched stack); q1: the same panos -> 2 hits.
    assert "2/4 hits (50%" in out

    exp_off = os.listdir(fixture_dir / "mb_off")[0]
    exp_on = os.listdir(fixture_dir / "mb_on")[0]
    for q in ("1.mat", "2.mat"):
        want = loadmat(fixture_dir / "mb_off" / exp_off / q)["matches"]
        got = loadmat(fixture_dir / "mb_on" / exp_on / q)["matches"]
        assert got.shape == want.shape
        filled_w = np.any(want != 0, axis=-1)
        filled_g = np.any(got != 0, axis=-1)
        np.testing.assert_array_equal(filled_g, filled_w)
        assert np.all((got[..., :4] >= 0) & (got[..., :4] <= 1))
        np.testing.assert_allclose(
            got[..., 4], want[..., 4], atol=2e-3,
            err_msg="score column diverged beyond bf16 rounding",
        )


def test_ragged_miss_stacks(fixture_dir, capsys, monkeypatch):
    """NCNET_RAGGED_MISS_STACKS=1: a drain-time partial miss group
    dispatches at its TRUE size — here 2 misses under --pano_batch 3
    run one 2-stack program instead of a repeat-padded 3-stack — both
    plain and composed with the feature cache (q1's panos are hits, and
    the ragged producer key's "-r" suffix keeps its entries out of
    padded-mode tiers). Contract mirrors the batched tests: padding was
    never bit-exact across program shapes, so the ragged run must match
    the padded run at the layout/filled-rows/score-rounding level.

    Ragged is the promoted default (v5e steady state 10.75 vs 9.59
    pairs/s/chip); the padded baseline is forced explicitly."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "2",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_batch", "3",
    ]
    monkeypatch.setenv("NCNET_RAGGED_MISS_STACKS", "0")
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "rg_pad"),
        "--pano_feature_cache_mb", "0",
    ])
    monkeypatch.setenv("NCNET_RAGGED_MISS_STACKS", "1")
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "rg_off"),
        "--pano_feature_cache_mb", "0",
    ])
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "rg_on"),
    ])
    out = capsys.readouterr().out
    # Cached run: q0 misses both panos (one ragged 2-stack), q1 hits.
    assert "2/4 hits (50%" in out

    exp_pad = os.listdir(fixture_dir / "rg_pad")[0]
    for mode_dir in ("rg_off", "rg_on"):
        exp = os.listdir(fixture_dir / mode_dir)[0]
        for q in ("1.mat", "2.mat"):
            want = loadmat(fixture_dir / "rg_pad" / exp_pad / q)["matches"]
            got = loadmat(fixture_dir / mode_dir / exp / q)["matches"]
            assert got.shape == want.shape
            np.testing.assert_array_equal(
                np.any(got != 0, axis=-1), np.any(want != 0, axis=-1)
            )
            np.testing.assert_allclose(
                got[..., 4], want[..., 4], atol=2e-3,
                err_msg=f"{mode_dir}/{q} scores diverged beyond bf16 "
                        "rounding vs the padded run",
            )
            # Coordinates are grid-cell centers — score rounding may
            # flip near-tied argmax winners on noise fixtures, but the
            # overwhelming majority of rows must pick the SAME cell in
            # both modes (a systematic coordinate shift would pass the
            # score check while silently breaking localization).
            same = np.all(
                np.isclose(got[..., :4], want[..., :4], atol=1e-6), axis=-1
            )
            frac = same[np.any(want != 0, axis=-1)].mean()
            assert frac >= 0.9, (
                f"{mode_dir}/{q}: only {frac:.0%} of filled rows agree on "
                "match coordinates between ragged and padded dispatch"
            )


@pytest.mark.slow
def test_pano_feature_cache_producer_key_isolation(fixture_dir, capsys):
    """Disk entries are keyed by the PROGRAM that produced them: a tier
    populated by a sequential run must MISS in a --pano_batch run (and
    vice versa), because the batched backbone is a different XLA
    artifact (bf16 rounding differs) and a cross-producer hit would
    silently break each mode's hit/miss parity contract."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "1",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_feature_cache_dir", str(fixture_dir / "fc_prod"),
    ]
    eval_inloc.main(base + ["--output_dir", str(fixture_dir / "mp_seq")])
    capsys.readouterr()
    # Batched run, same disk dir: the seq-produced entries must not hit.
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "mp_bat"),
        "--pano_batch", "2",
    ])
    out = capsys.readouterr().out
    assert "0/2 hits" in out
    # Same batched config again: now ITS OWN disk entries hit.
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "mp_bat2"),
        "--pano_batch", "2",
    ])
    out = capsys.readouterr().out
    assert "2/2 hits (100%" in out


@pytest.mark.slow
def test_pano_feature_cache_disk_tier(fixture_dir, capsys):
    """Disk tier: a SECOND process-run with an empty memory cache serves
    every pano from disk (no backbone recompute), still bit-identical."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "2",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_feature_cache_dir", str(fixture_dir / "featcache"),
    ]
    eval_inloc.main(base + ["--output_dir", str(fixture_dir / "m_d1")])
    capsys.readouterr()
    # New run dir, fresh memory cache: all 4 probes hit the disk tier.
    eval_inloc.main(base + ["--output_dir", str(fixture_dir / "m_d2")])
    out = capsys.readouterr().out
    assert "4/4 hits (100%" in out
    assert "from disk" in out
    exp1 = os.listdir(fixture_dir / "m_d1")[0]
    exp2 = os.listdir(fixture_dir / "m_d2")[0]
    for q in ("1.mat", "2.mat"):
        a = loadmat(fixture_dir / "m_d1" / exp1 / q)
        b = loadmat(fixture_dir / "m_d2" / exp2 / q)
        np.testing.assert_array_equal(a["matches"], b["matches"])


@pytest.mark.slow
def test_pano_dp_fanout_parity(fixture_dir):
    """--pano_dp 8: each virtual device runs the complete batch-1 per-pano
    program on a different pano (shard_map fan-out) — written matches must
    be identical to the sequential path's. Full-mesh (8-way) variant;
    the tier-1 lane covers the same property with a smaller mesh in
    test_pano_dp_fanout_parity_fast below."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "2",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_feature_cache_mb", "0",
    ]
    eval_inloc.main(base + ["--output_dir", str(fixture_dir / "m_seq")])
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "m_dp"),
        "--pano_dp", "8",
    ])
    exp_a = os.listdir(fixture_dir / "m_seq")[0]
    exp_b = os.listdir(fixture_dir / "m_dp")[0]
    for q in ("1.mat", "2.mat"):
        a = loadmat(fixture_dir / "m_seq" / exp_a / q)
        b = loadmat(fixture_dir / "m_dp" / exp_b / q)
        np.testing.assert_array_equal(a["matches"], b["matches"])


def test_pano_dp_fanout_parity_fast(fixture_dir):
    """Tier-1 shrunk --pano_dp parity: 4-way mesh, one query, two panos.

    Kept in the default lane since the ragged-dispatch default broke this
    mode once (a drain-time partial group is not divisible by the mesh, so
    --pano_dp must force padded dispatch — ADVICE r5 high): the 2-pano
    group here is NOT divisible by the 4-way mesh, so the drain path is
    exactly the regression shape, at a fraction of the full-mesh cost."""
    base = [
        "--inloc_shortlist", str(fixture_dir / "shortlist.mat"),
        "--query_path", str(fixture_dir / "query"),
        "--pano_path", str(fixture_dir / "pano"),
        "--image_size", "64",
        "--n_queries", "1",
        "--n_panos", "2",
        "--k_size", "2",
        "--pano_feature_cache_mb", "0",
    ]
    eval_inloc.main(base + ["--output_dir", str(fixture_dir / "f_seq")])
    eval_inloc.main(base + [
        "--output_dir", str(fixture_dir / "f_dp"),
        "--pano_dp", "4",
    ])
    exp_a = os.listdir(fixture_dir / "f_seq")[0]
    exp_b = os.listdir(fixture_dir / "f_dp")[0]
    a = loadmat(fixture_dir / "f_seq" / exp_a / "1.mat")
    b = loadmat(fixture_dir / "f_dp" / exp_b / "1.mat")
    np.testing.assert_array_equal(a["matches"], b["matches"])
