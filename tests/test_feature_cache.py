"""Unit tests for the cross-query pano feature cache (evals/feature_cache)."""

import numpy as np

from ncnet_tpu.evals.feature_cache import PanoFeatureCache, model_cache_key


def _feat(seed, mb=1):
    rng = np.random.default_rng(seed)
    n = mb * 1024 * 1024 // 4
    return rng.random(n).astype(np.float32)


def test_lru_byte_bound_eviction():
    c = PanoFeatureCache(max_bytes=3 * 1024 * 1024)
    for i in range(4):  # 4 x 1 MB into a 3 MB budget
        c.put(f"p{i}", (8, 8), _feat(i))
    assert c.nbytes <= 3 * 1024 * 1024
    assert c.get("p0", (8, 8)) is None  # oldest evicted
    assert c.get("p3", (8, 8)) is not None

    # get() refreshes recency: p1 survives the next insertion, p2 goes.
    assert c.get("p1", (8, 8)) is not None
    c.put("p4", (8, 8), _feat(4))
    assert c.get("p1", (8, 8)) is not None
    assert c.get("p2", (8, 8)) is None


def test_keying_separates_shape_and_model():
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m1")
    f = _feat(0)
    c.put("p", (8, 8), f)
    assert c.get("p", (16, 8)) is None  # different resize bucket
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m2")
    assert c2.get("p", (8, 8)) is None  # different weights

    got = c.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)


def test_oversized_entry_not_cached_in_memory():
    c = PanoFeatureCache(max_bytes=1024)
    c.put("p", (8, 8), _feat(0))  # 1 MB > 1 KB budget
    assert c.nbytes == 0


def test_disk_tier_promote_and_truncation_tolerance(tmp_path):
    d = str(tmp_path / "cache")
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                         model_key="m")
    f = _feat(1)
    c.put("p", (8, 8), f)

    # Fresh instance (new process): memory empty, disk serves + promotes.
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    got = c2.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)
    assert c2.disk_hits == 1
    assert c2.nbytes == f.nbytes

    # Truncated disk entry (killed run) is a miss, not a crash.
    import glob
    import os

    path = glob.glob(os.path.join(d, "feat2_*.npz"))[0]
    with open(path, "r+b") as fh:
        fh.truncate(100)
    c3 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    assert c3.get("p", (8, 8)) is None


def test_store_dtype_roundtrip_and_legacy_migration(tmp_path):
    """store_dtype=bf16 (what eval_inloc passes): fresh entries store and
    round-trip bf16 through disk; a pre-bf16 untagged f32 disk entry is
    rounded to bf16 on load instead of occupying a double-size slot and
    forcing a second hit-program dtype specialization."""
    import ml_dtypes

    d = str(tmp_path / "cache")
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                         model_key="m", store_dtype=ml_dtypes.bfloat16)
    f = _feat(1)
    c.put("p", (8, 8), f)
    got = c.get("p", (8, 8))
    assert got.dtype == ml_dtypes.bfloat16
    assert got.nbytes == f.nbytes // 2
    np.testing.assert_array_equal(got, f.astype(ml_dtypes.bfloat16))

    # Disk round-trip preserves bf16 (uint16 view + tag inside the npz).
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got2 = c2.get("p", (8, 8))
    assert got2.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got2, got)

    # Legacy entry written by a pre-bf16 build: raw f32 npz under the
    # unversioned feat_ name, the way the old np.savez(fh, feats=feats)
    # did.
    import os

    f_old = _feat(2)
    legacy_path = c2._legacy_disk_path(c2._key("q", (8, 8)))
    with open(legacy_path, "wb") as fh:
        np.savez(fh, feats=f_old)
    c3 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got3 = c3.get("q", (8, 8))
    assert got3.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got3, f_old.astype(ml_dtypes.bfloat16))
    # The migration moves the entry to the versioned half-size format
    # (feat2_) and drops the legacy file, so a pre-bf16 reader sharing
    # this dir misses instead of misreading the uint16 view as features.
    assert not os.path.exists(legacy_path)
    feat2_path = c3._disk_path(c3._key("q", (8, 8)))
    with np.load(feat2_path) as z:
        assert str(z["dtype"][()]) == "bfloat16"
        assert z["feats"].dtype == np.uint16

    # A corrupt versioned file must not shadow an intact legacy entry:
    # the probe falls through to the legacy format and serves it.
    with open(feat2_path, "r+b") as fh:
        fh.truncate(10)
    with open(legacy_path, "wb") as fh:
        np.savez(fh, feats=f_old)
    c4 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got4 = c4.get("q", (8, 8))
    np.testing.assert_array_equal(got4, f_old.astype(ml_dtypes.bfloat16))


def test_model_cache_key_checkpoint_vs_seed(tmp_path):
    assert model_cache_key("", seed=3) == "init-seed-3"
    ck = tmp_path / "ckpt"
    ck.mkdir()
    (ck / "params.npz").write_bytes(b"x")
    k1 = model_cache_key(str(ck))
    assert str(ck) in k1 and "@" in k1
    import os
    import time

    os.utime(ck / "params.npz", (time.time() + 5, time.time() + 5))
    assert model_cache_key(str(ck)) != k1  # re-save invalidates
