"""Unit tests for the cross-query pano feature cache (evals/feature_cache)."""

import numpy as np

from ncnet_tpu.evals.feature_cache import PanoFeatureCache, model_cache_key


def _feat(seed, mb=1):
    rng = np.random.default_rng(seed)
    n = mb * 1024 * 1024 // 4
    return rng.random(n).astype(np.float32)


def test_lru_byte_bound_eviction():
    c = PanoFeatureCache(max_bytes=3 * 1024 * 1024)
    for i in range(4):  # 4 x 1 MB into a 3 MB budget
        c.put(f"p{i}", (8, 8), _feat(i))
    assert c.nbytes <= 3 * 1024 * 1024
    assert c.get("p0", (8, 8)) is None  # oldest evicted
    assert c.get("p3", (8, 8)) is not None

    # get() refreshes recency: p1 survives the next insertion, p2 goes.
    assert c.get("p1", (8, 8)) is not None
    c.put("p4", (8, 8), _feat(4))
    assert c.get("p1", (8, 8)) is not None
    assert c.get("p2", (8, 8)) is None


def test_keying_separates_shape_and_model():
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m1")
    f = _feat(0)
    c.put("p", (8, 8), f)
    assert c.get("p", (16, 8)) is None  # different resize bucket
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m2")
    assert c2.get("p", (8, 8)) is None  # different weights

    got = c.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)


def test_oversized_entry_not_cached_in_memory():
    c = PanoFeatureCache(max_bytes=1024)
    c.put("p", (8, 8), _feat(0))  # 1 MB > 1 KB budget
    assert c.nbytes == 0


def test_disk_tier_promote_and_truncation_tolerance(tmp_path):
    d = str(tmp_path / "cache")
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                         model_key="m")
    f = _feat(1)
    c.put("p", (8, 8), f)

    # Fresh instance (new process): memory empty, disk serves + promotes.
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    got = c2.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)
    assert c2.disk_hits == 1
    assert c2.nbytes == f.nbytes

    # Truncated disk entry (killed run) is a miss, not a crash.
    import glob
    import os

    path = glob.glob(os.path.join(d, "feat2_*.npz"))[0]
    with open(path, "r+b") as fh:
        fh.truncate(100)
    c3 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    assert c3.get("p", (8, 8)) is None


def test_store_dtype_roundtrip_and_legacy_migration(tmp_path):
    """store_dtype=bf16 (what eval_inloc passes): fresh entries store and
    round-trip bf16 through disk; a pre-bf16 untagged f32 disk entry is
    rounded to bf16 on load instead of occupying a double-size slot and
    forcing a second hit-program dtype specialization."""
    import ml_dtypes

    d = str(tmp_path / "cache")
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                         model_key="m", store_dtype=ml_dtypes.bfloat16)
    f = _feat(1)
    c.put("p", (8, 8), f)
    got = c.get("p", (8, 8))
    assert got.dtype == ml_dtypes.bfloat16
    assert got.nbytes == f.nbytes // 2
    np.testing.assert_array_equal(got, f.astype(ml_dtypes.bfloat16))

    # Disk round-trip preserves bf16 (uint16 view + tag inside the npz).
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got2 = c2.get("p", (8, 8))
    assert got2.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got2, got)

    # Legacy entry written by a pre-bf16 build: raw f32 npz under the
    # unversioned feat_ name, the way the old np.savez(fh, feats=feats)
    # did.
    import os

    f_old = _feat(2)
    legacy_path = c2._legacy_disk_path(c2._key("q", (8, 8)))
    with open(legacy_path, "wb") as fh:
        np.savez(fh, feats=f_old)
    c3 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got3 = c3.get("q", (8, 8))
    assert got3.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got3, f_old.astype(ml_dtypes.bfloat16))
    # The migration moves the entry to the versioned half-size format
    # (feat2_) and drops the legacy file, so a pre-bf16 reader sharing
    # this dir misses instead of misreading the uint16 view as features.
    assert not os.path.exists(legacy_path)
    feat2_path = c3._disk_path(c3._key("q", (8, 8)))
    with np.load(feat2_path) as z:
        assert str(z["dtype"][()]) == "bfloat16"
        assert z["feats"].dtype == np.uint16

    # A corrupt versioned file must not shadow an intact legacy entry:
    # the probe falls through to the legacy format and serves it.
    with open(feat2_path, "r+b") as fh:
        fh.truncate(10)
    with open(legacy_path, "wb") as fh:
        np.savez(fh, feats=f_old)
    c4 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m", store_dtype=ml_dtypes.bfloat16)
    got4 = c4.get("q", (8, 8))
    np.testing.assert_array_equal(got4, f_old.astype(ml_dtypes.bfloat16))


def test_model_cache_key_checkpoint_vs_seed(tmp_path):
    assert model_cache_key("", seed=3) == "init-seed-3"
    ck = tmp_path / "ckpt"
    ck.mkdir()
    (ck / "params.npz").write_bytes(b"x")
    k1 = model_cache_key(str(ck))
    assert str(ck) in k1 and "@" in k1
    import os
    import time

    os.utime(ck / "params.npz", (time.time() + 5, time.time() + 5))
    assert model_cache_key(str(ck)) != k1  # re-save invalidates


# -- disk-tier concurrency (serving fleet regression) --------------------


def test_disk_tier_concurrent_writers_and_migration(tmp_path):
    """Two cache instances sharing one disk_dir (the fleet / multi-
    process sweep shape) under racing gets and puts — including both
    racing the SAME legacy-entry migration: every read returns correct
    values, the legacy file migrates to exactly one versioned entry,
    and every file on disk stays loadable (no torn writes, no vanished
    entries)."""
    import os
    import threading

    import ml_dtypes

    d = str(tmp_path / "shared")
    os.makedirs(d)
    f32 = _feat(7)
    probe = PanoFeatureCache(max_bytes=4 * 1024 * 1024, disk_dir=d,
                             model_key="m",
                             store_dtype=ml_dtypes.bfloat16)
    # Plant a pre-bf16 legacy entry (raw untagged f32 npz).
    legacy = probe._legacy_disk_path(probe._key("pano_legacy", (8, 8)))
    with open(legacy, "wb") as fh:
        np.savez(fh, feats=f32)
    expect = f32.astype(ml_dtypes.bfloat16)

    caches = [PanoFeatureCache(max_bytes=2 * 1024 * 1024, disk_dir=d,
                               model_key="m",
                               store_dtype=ml_dtypes.bfloat16)
              for _ in range(2)]
    errors = []

    def work(c):
        try:
            for i in range(10):
                got = c.get("pano_legacy", (8, 8))
                assert got is not None, "legacy entry vanished mid-race"
                np.testing.assert_array_equal(np.asarray(got), expect)
                key = f"pano{i % 4}"
                if c.get(key, (8, 8)) is None:
                    c.put(key, (8, 8), _feat(i % 4))
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=work, args=(c,))
               for c in caches for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    # The migration landed exactly once: versioned entry present and
    # tagged, the legacy file gone.
    new_path = probe._disk_path(probe._key("pano_legacy", (8, 8)))
    assert os.path.exists(new_path) and not os.path.exists(legacy)
    with np.load(new_path) as z:
        assert str(z["dtype"][()]) == "bfloat16"
        np.testing.assert_array_equal(
            z["feats"].view(ml_dtypes.bfloat16), expect)
    # Every racing writer's entry loads clean from a fresh instance.
    fresh = PanoFeatureCache(max_bytes=8 * 1024 * 1024, disk_dir=d,
                             model_key="m",
                             store_dtype=ml_dtypes.bfloat16)
    for i in range(4):
        got = fresh.get(f"pano{i}", (8, 8))
        assert got is not None
        np.testing.assert_array_equal(
            np.asarray(got), _feat(i).astype(ml_dtypes.bfloat16))
    assert not [p for p in os.listdir(d) if p.endswith(".tmp")], \
        "torn temp files left behind"


# -- SharedFeatureStore (serving/feature_store.py) -----------------------


def test_shared_store_content_addressed_identity(tmp_path):
    from ncnet_tpu.serving.feature_store import SharedFeatureStore

    store = SharedFeatureStore(8 * 1024 * 1024, model_key="m")
    p1, p2 = tmp_path / "a.bin", tmp_path / "b.bin"
    p1.write_bytes(b"x" * 100)
    p2.write_bytes(b"x" * 100)  # same bytes, different path
    f = _feat(0)
    store.put(str(p1), (8, 8), f)
    got = store.get(str(p2), (8, 8))
    assert got is not None, "byte-identical copy missed"
    np.testing.assert_array_equal(got, f)
    assert store.hits == 1 and store.misses == 0

    p3 = tmp_path / "c.bin"
    p3.write_bytes(b"y" * 100)  # same size, different content
    assert store.get(str(p3), (8, 8)) is None
    assert store.misses == 1
    # Unreadable path: literal-path fallback, a miss, never a crash.
    assert store.get(str(tmp_path / "ghost.bin"), (8, 8)) is None


def test_shared_store_rehashes_on_content_change(tmp_path):
    import os

    from ncnet_tpu.serving.feature_store import SharedFeatureStore

    store = SharedFeatureStore(8 * 1024 * 1024, model_key="m")
    p = tmp_path / "a.bin"
    p.write_bytes(b"x" * 100)
    os.utime(p, ns=(1_000_000_000, 1_000_000_000))
    store.put(str(p), (8, 8), _feat(0))
    assert store.get(str(p), (8, 8)) is not None
    # New content under the SAME path and size: the (size, mtime_ns)
    # memo signature changes, the store re-hashes, the old entry no
    # longer answers for this path.
    p.write_bytes(b"z" * 100)
    os.utime(p, ns=(2_000_000_000, 2_000_000_000))
    assert store.get(str(p), (8, 8)) is None


def test_shared_store_prewarm_promotes_disk_tier(tmp_path):
    from ncnet_tpu.serving.feature_store import SharedFeatureStore

    d = str(tmp_path / "disk")
    pano = tmp_path / "a.bin"
    pano.write_bytes(b"x" * 100)
    cold = tmp_path / "cold.bin"
    cold.write_bytes(b"q" * 100)

    seed = SharedFeatureStore(8 * 1024 * 1024, disk_dir=d, model_key="m")
    seed.put(str(pano), (8, 8), _feat(0))

    # A fresh store (restarted server) sharing the disk dir: prewarm
    # promotes the on-disk entry into memory, misses compute nothing.
    store = SharedFeatureStore(8 * 1024 * 1024, disk_dir=d, model_key="m")
    warm = store.prewarm([str(pano), str(cold), str(tmp_path / "nope")],
                         lambda path: (8, 8))
    assert warm == 1
    assert store.disk_hits == 1 and store.nbytes > 0
    got = store.get(str(pano), (8, 8))
    np.testing.assert_array_equal(got, _feat(0))
    assert store.disk_hits == 1  # second get served from memory
