"""Unit tests for the cross-query pano feature cache (evals/feature_cache)."""

import numpy as np

from ncnet_tpu.evals.feature_cache import PanoFeatureCache, model_cache_key


def _feat(seed, mb=1):
    rng = np.random.default_rng(seed)
    n = mb * 1024 * 1024 // 4
    return rng.random(n).astype(np.float32)


def test_lru_byte_bound_eviction():
    c = PanoFeatureCache(max_bytes=3 * 1024 * 1024)
    for i in range(4):  # 4 x 1 MB into a 3 MB budget
        c.put(f"p{i}", (8, 8), _feat(i))
    assert c.nbytes <= 3 * 1024 * 1024
    assert c.get("p0", (8, 8)) is None  # oldest evicted
    assert c.get("p3", (8, 8)) is not None

    # get() refreshes recency: p1 survives the next insertion, p2 goes.
    assert c.get("p1", (8, 8)) is not None
    c.put("p4", (8, 8), _feat(4))
    assert c.get("p1", (8, 8)) is not None
    assert c.get("p2", (8, 8)) is None


def test_keying_separates_shape_and_model():
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m1")
    f = _feat(0)
    c.put("p", (8, 8), f)
    assert c.get("p", (16, 8)) is None  # different resize bucket
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, model_key="m2")
    assert c2.get("p", (8, 8)) is None  # different weights

    got = c.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)


def test_oversized_entry_not_cached_in_memory():
    c = PanoFeatureCache(max_bytes=1024)
    c.put("p", (8, 8), _feat(0))  # 1 MB > 1 KB budget
    assert c.nbytes == 0


def test_disk_tier_promote_and_truncation_tolerance(tmp_path):
    d = str(tmp_path / "cache")
    c = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                         model_key="m")
    f = _feat(1)
    c.put("p", (8, 8), f)

    # Fresh instance (new process): memory empty, disk serves + promotes.
    c2 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    got = c2.get("p", (8, 8))
    np.testing.assert_array_equal(got, f)
    assert c2.disk_hits == 1
    assert c2.nbytes == f.nbytes

    # Truncated disk entry (killed run) is a miss, not a crash.
    import glob
    import os

    path = glob.glob(os.path.join(d, "feat_*.npz"))[0]
    with open(path, "r+b") as fh:
        fh.truncate(100)
    c3 = PanoFeatureCache(max_bytes=64 * 1024 * 1024, disk_dir=d,
                          model_key="m")
    assert c3.get("p", (8, 8)) is None


def test_model_cache_key_checkpoint_vs_seed(tmp_path):
    assert model_cache_key("", seed=3) == "init-seed-3"
    ck = tmp_path / "ckpt"
    ck.mkdir()
    (ck / "params.npz").write_bytes(b"x")
    k1 = model_cache_key(str(ck))
    assert str(ck) in k1 and "@" in k1
    import os
    import time

    os.utime(ck / "params.npz", (time.time() + 5, time.time() + 5))
    assert model_cache_key(str(ck)) != k1  # re-save invalidates
