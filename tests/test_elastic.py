"""Elastic training driver (training/elastic.py): batch adjustment,
membership-change surfacing, resume accounting, commit barrier, and the
checkpoint fallback walk it resumes through.

Driver tests run with ``heartbeat_s=0`` (inline renewals from
step_check) on fake clocks — no thread, no sleeps, fully deterministic.
"""

import os

import numpy as np
import pytest

from ncnet_tpu import obs
from ncnet_tpu.parallel import multihost
from ncnet_tpu.parallel.membership import (
    MembershipPlane,
    StaleGenerationError,
)
from ncnet_tpu.training import elastic


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _events(name):
    return [r for r in obs.flight.recorder().snapshot()
            if r.get("event") == name]


def _driver(root, host, clock, ttl=5.0, **kw):
    plane = MembershipPlane(str(root), host, lease_ttl_s=ttl, clock=clock)
    kw.setdefault("heartbeat_s", 0)
    kw.setdefault("check_interval_s", 0.0)
    return elastic.ElasticDriver(plane, clock=clock, **kw)


# -- host_local_slice rank/n_hosts (satellite 1) ---------------------------


def test_host_local_slice_explicit_rank_and_hosts():
    assert multihost.host_local_slice(12, rank=0, n_hosts=3) == (0, 4)
    assert multihost.host_local_slice(12, rank=2, n_hosts=3) == (8, 12)
    # Defaults still resolve from the JAX process grid (single process
    # on CPU: the whole batch).
    assert multihost.host_local_slice(12) == (0, 12)


def test_host_local_slice_rejects_bad_shapes():
    with pytest.raises(ValueError, match="host count must be >= 1"):
        multihost.host_local_slice(12, rank=0, n_hosts=0)
    with pytest.raises(ValueError, match="rank 3 out of range"):
        multihost.host_local_slice(12, rank=3, n_hosts=3)
    # The indivisible message must name the remainder AND the way out
    # (the elastic round-down) — it fires mid-incident.
    with pytest.raises(ValueError, match="remainder 1.*adjusted_global_batch"):
        multihost.host_local_slice(13, rank=0, n_hosts=3)


# -- adjusted_global_batch -------------------------------------------------


def test_adjusted_global_batch_rounds_down_and_says_so():
    before = len(_events("train_batch_adjusted"))
    assert elastic.adjusted_global_batch(16, 3) == 15
    evs = _events("train_batch_adjusted")
    assert len(evs) == before + 1
    assert evs[-1]["requested"] == 16
    assert evs[-1]["adjusted"] == 15
    assert evs[-1]["hosts"] == 3


def test_adjusted_global_batch_exact_is_silent():
    before = len(_events("train_batch_adjusted"))
    assert elastic.adjusted_global_batch(12, 3) == 12
    assert len(_events("train_batch_adjusted")) == before


def test_adjusted_global_batch_rejects_impossible():
    with pytest.raises(ValueError, match="cannot cover 5 hosts"):
        elastic.adjusted_global_batch(3, 5)
    with pytest.raises(ValueError, match="host count must be >= 1"):
        elastic.adjusted_global_batch(8, 0)


# -- driver membership view ------------------------------------------------


def test_driver_rank_writer_and_slice(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    assert (da.rank, db.rank) == (0, 1)
    assert da.is_writer and not db.is_writer
    assert da.slice_for(8) == (0, 4)
    assert db.slice_for(8) == (4, 8)
    assert da.n_hosts == 2 and da.generation == 1


def test_step_check_detects_death_bumps_and_raises(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock, ledger_dir=str(tmp_path))
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    da.note_commit(1, 6)  # last committed checkpoint position
    clock.t = 6.0  # b's lease (t=0) expires; a renews inline in check
    with pytest.raises(elastic.MembershipChange) as exc:
        da.step_check(1, 9, force=True)
    chg = exc.value
    assert chg.dead == ["b"]
    assert (chg.epoch, chg.step) == (1, 9)
    assert chg.record["generation"] == 2
    assert chg.record["hosts"] == ["a"]
    # The bump advertised the commit marker as the resume point.
    assert (chg.record["resume_epoch"], chg.record["resume_step"]) == (1, 6)
    # Writer takeover is automatic once the driver adopts the record.
    da.resume(chg.record, 1, 6, chg.epoch, chg.step, steps_per_epoch=24)
    assert da.generation == 2 and da.is_writer and da.n_hosts == 1
    assert da.resumes == 1
    assert da.lost_steps == 3  # detected (1,9) minus resumed (1,6)
    evs = _events("elastic_resume")
    assert evs and evs[-1]["lost_steps"] == 3


def test_step_check_surfaces_peer_bump_before_detection(tmp_path):
    # A peer already bumped (grow or shrink): this host must adopt the
    # NEWER record, not renew/detect at the generation it still holds.
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    new = db.plane.bump(["a", "b", "c"], resume_epoch=1, resume_step=0,
                        expected_generation=1)
    with pytest.raises(elastic.MembershipChange) as exc:
        da.step_check(1, 3, force=True)
    assert exc.value.record == new
    assert exc.value.dead == []


def test_step_check_raises_stale_when_evicted(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    db.plane.bump(["b"], resume_epoch=1, resume_step=0,
                  expected_generation=1)
    with pytest.raises(StaleGenerationError):
        da.step_check(1, 3, force=True)


def test_step_check_is_time_gated(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock, check_interval_s=0.25)
    da.plane.form(["a"])
    da.start()
    da.step_check(1, 0)  # first check runs (gate starts at -inf)
    t0 = da.check_time_s
    da.step_check(1, 1)  # within the interval: fast path, no probe
    assert da.check_time_s == t0
    clock.t = 0.3
    da.step_check(1, 2)
    assert da.check_time_s >= t0


def test_resume_lost_steps_across_epoch_boundary(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    da.plane.form(["a"])
    da.start()
    rec = dict(da.record)
    da.resume(rec, resumed_epoch=1, resumed_step=20, detected_epoch=2,
              detected_step=4, steps_per_epoch=24)
    assert da.lost_steps == 8  # (2-1)*24 + 4 - 20


# -- commit barrier --------------------------------------------------------


def test_commit_barrier_waits_for_every_live_member(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    # b advertises (1, 5): the writer may commit positions up to it ...
    db.plane.renew(1, step=5, epoch=1)
    assert da.commit_barrier(1, 5, wait_s=0) is True
    assert da.commit_barrier(1, 4, wait_s=0) is True
    # ... but not past it — a commit the gang has not reached is the
    # silent-step-loss window the barrier exists to close.
    assert da.commit_barrier(1, 6, wait_s=0) is False
    assert da.commit_barrier(2, 0, wait_s=0) is False
    db.plane.renew(1, step=6, epoch=1)
    assert da.commit_barrier(1, 6, wait_s=0) is True


def test_commit_barrier_fails_on_missing_peer_lease(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    db.plane.drop_lease()  # dead peer: no advertised position at all
    assert da.commit_barrier(1, 1, wait_s=0) is False


def test_commit_barrier_solo_is_immediate(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    da.plane.form(["a"])
    da.start()
    assert da.commit_barrier(7, 100, wait_s=0) is True


def test_finish_barrier_releases_on_peer_finish_depart_or_death(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    db = _driver(tmp_path, "b", clock)
    da.plane.form(["a", "b"])
    da.start()
    db.start()
    # b still mid-run with a fresh lease: the finisher must wait.
    db.plane.renew(1, step=3, epoch=1)
    assert da.finish_barrier(2, wait_s=0) is False
    # b finished too (advertises past any trainable position): release.
    db.plane.renew(1, step=0, epoch=3)
    assert da.finish_barrier(2, wait_s=0) is True
    # b departed cleanly (lease dropped): release.
    db.plane.drop_lease()
    assert da.finish_barrier(2, wait_s=0) is True
    # b dead mid-run (stale lease): nothing to wait for — release.
    db.plane.renew(1, step=3, epoch=1)
    clock.t = 6.0
    assert da.finish_barrier(2, wait_s=0) is True


def test_advertise_writes_through_without_heartbeat(tmp_path):
    clock = FakeClock()
    da = _driver(tmp_path, "a", clock)
    da.plane.form(["a"])
    da.start()
    da.advertise(3, 11)
    lease = da.plane.live_view()["a"]
    assert (lease["epoch"], lease["step"]) == (3, 11)


# -- step ledger -----------------------------------------------------------


def test_record_step_ledger_lines(tmp_path):
    import json as _json

    clock = FakeClock()
    da = _driver(tmp_path, "a", clock, ledger_dir=str(tmp_path / "led"))
    da.plane.form(["a"])
    da.start()
    da.record_step(1, 0, (0, 4))
    da.record_step(1, 1, (0, 4))
    da.stop()
    path = tmp_path / "led" / "steps-a.jsonl"
    lines = [_json.loads(l) for l in path.read_text().splitlines()]
    assert lines == [
        {"gen": 1, "epoch": 1, "step": 0, "host": "a", "slice": [0, 4]},
        {"gen": 1, "epoch": 1, "step": 1, "host": "a", "slice": [0, 4]},
    ]


# -- checkpoint fallback walk (satellite 2) --------------------------------


def _save_tiny(directory, epoch, tag=None):
    from ncnet_tpu.models import BackboneConfig, NCNetConfig
    from ncnet_tpu.training.checkpoint import save_checkpoint

    config = NCNetConfig(backbone=BackboneConfig(cnn="vgg"),
                         ncons_kernel_sizes=(3,),
                         ncons_channels=(1,))
    return save_checkpoint(
        directory, {"neigh_consensus": np.zeros(4, np.float32)}, config,
        epoch=epoch, extra={"step_in_epoch": 0}, tag=tag)


def test_load_latest_checkpoint_walks_past_truncation(tmp_path):
    from ncnet_tpu.training.checkpoint import load_latest_checkpoint

    root = str(tmp_path / "run")
    _save_tiny(root, epoch=1)
    _save_tiny(root, epoch=2, tag="step")
    # Truncate the newest candidate's params mid-file (disk-full /
    # mid-write kill): complete by the meta.json marker, torn inside.
    with open(os.path.join(root, "step", "params.npz"), "wb") as fh:
        fh.write(b"\x50\x4b")  # a 2-byte "zip"
    before = obs.metrics.snapshot()["counters"].get(
        "train.checkpoint_fallbacks", 0)
    path, result = load_latest_checkpoint(root)
    assert path.endswith("epoch_1")
    assert result["meta"]["epoch"] == 1
    after = obs.metrics.snapshot()["counters"].get(
        "train.checkpoint_fallbacks", 0)
    assert after == before + 1
    evs = _events("checkpoint_fallback")
    assert evs and evs[-1]["path"].endswith("step")
    assert "Error" in evs[-1]["error"] or "error" in evs[-1]["error"]


def test_load_latest_checkpoint_raises_when_nothing_loads(tmp_path):
    from ncnet_tpu.training.checkpoint import load_latest_checkpoint

    with pytest.raises(FileNotFoundError, match="no loadable checkpoint"):
        load_latest_checkpoint(str(tmp_path / "empty"))
