"""Online matching service (ncnet_tpu/serving, ISSUE 2).

Two layers of coverage:

* DeadlineBatcher unit tests — fake clock, no threads, no jax: the
  flush policy (max-batch, max-delay, deadline), bucket isolation,
  admission control, the drain contract, and error propagation are all
  pure control flow and must be testable at microsecond cost.
* CPU end-to-end — a real MatchServer on an ephemeral port with a tiny
  model, driven over HTTP by MatchClient: concurrent requests share a
  batch, the feature cache replays bit-identically, /healthz and
  /metrics serve, the run log validates, and shutdown drains cleanly.
"""

import io
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from conftest import assert_valid_runlog
from ncnet_tpu import obs
from ncnet_tpu.serving.batcher import DeadlineBatcher, RejectedError
from ncnet_tpu.serving.client import (
    MatchClient,
    OverCapacityError,
    ServingError,
)

# -- batcher (fake clock, threadless) -------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def echo_runner(calls):
    def runner(bucket_key, payloads):
        calls.append((bucket_key, list(payloads)))
        return [f"r:{p}" for p in payloads]

    return runner


def make_batcher(clock, calls, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_queue", 8)
    kw.setdefault("max_delay_s", 0.05)
    return DeadlineBatcher(echo_runner(calls), clock=clock, **kw)


def test_batcher_max_batch_flush():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls)
    f1 = b.submit("a", "p1")
    f2 = b.submit("a", "p2")
    # Full bucket dispatches without any clock advance.
    assert b.poll() == 1
    assert f1.result(0).result == "r:p1"
    assert f2.result(0).result == "r:p2"
    assert f1.result(0).batch_size == 2
    assert calls == [("a", ["p1", "p2"])]


def test_batcher_max_delay_flush():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, max_delay_s=0.05)
    f = b.submit("a", "p1")
    assert b.poll() == 0, "partial bucket, not due yet"
    clock.t += 0.04
    assert b.poll() == 0, "still inside the linger window"
    clock.t += 0.02
    assert b.poll() == 1, "oldest lingered past max_delay_s"
    assert f.result(0).batch_size == 1
    assert f.result(0).queue_wait_s == pytest.approx(0.06)


def test_batcher_deadlines_off_mode():
    """default_timeout_s=None: no rider ever gets a deadline — bulk
    riders flush on size/linger only, across arbitrarily long waits."""
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, default_timeout_s=None,
                     max_delay_s=0.05, deadline_slack_s=10.0)
    f = b.submit("a", "p1")
    # With any finite deadline, a 10s slack would force an immediate
    # deadline-near flush; deadline-free riders must not.
    for pend in b._buckets.groups["a"]:
        assert pend.deadline is None
    assert b.poll() == 0, "no deadline flush in deadlines-off mode"
    # A simulated *month* of waiting expires nothing — the rider is
    # still served by the ordinary linger flush, never deadline-killed.
    clock.t += 30 * 24 * 3600.0
    assert b.poll() == 1
    assert f.result(0).result == "r:p1"
    # An explicit per-request timeout still opts a rider back in: with
    # deadline 3s and slack 10s the deadline-near flush fires at once.
    f2 = b.submit("a", "p2", timeout_s=3.0)
    assert b._buckets.groups["a"][0].deadline is not None
    assert b.poll() == 1
    assert f2.result(0).result == "r:p2"


def test_batcher_deadline_flush_beats_max_delay():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, max_delay_s=10.0, deadline_slack_s=0.005)
    f = b.submit("a", "p1", timeout_s=0.02)
    clock.t += 0.01
    assert b.poll() == 0, "deadline minus slack not reached"
    clock.t += 0.006  # now 0.016 >= 0.02 - 0.005
    assert b.poll() == 1, "deadline-near flush fires long before max_delay"
    assert f.result(0).result == "r:p1"


def test_batcher_bucket_isolation_by_shape():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, max_batch=2)
    b.submit(("64x48", "img"), "p1")
    b.submit(("96x64", "img"), "p2")
    clock.t += 0.06
    assert b.poll() == 2, "different shapes never share a batch"
    assert sorted(len(ps) for _, ps in calls) == [1, 1]
    b.submit(("64x48", "img"), "q1")
    b.submit(("64x48", "img"), "q2")
    assert b.poll() == 1, "same shape batches together"
    assert calls[-1] == (("64x48", "img"), ["q1", "q2"])


def test_batcher_backpressure_rejects_with_retry_after():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, max_batch=4, max_queue=3)
    futs = [b.submit("a", f"p{i}") for i in range(3)]
    with pytest.raises(RejectedError) as exc_info:
        b.submit("a", "overflow")
    assert exc_info.value.depth == 3
    assert exc_info.value.retry_after_s > 0
    snap = obs.snapshot()
    assert snap["counters"]["serving.rejected"] == 1.0
    assert snap["counters"]["serving.admitted"] == 3.0
    # The rejected request is NOT in any bucket: a later poll runs only
    # the three admitted ones.
    clock.t += 0.06
    assert b.poll() == 1
    assert [f.result(0).result for f in futs] == ["r:p0", "r:p1", "r:p2"]


def test_batcher_drain_on_close_completes_all_admitted():
    clock, calls = FakeClock(), []
    b = make_batcher(clock, calls, max_batch=4)
    futs = [b.submit("a", f"p{i}") for i in range(3)]
    futs.append(b.submit("b", "q0"))
    b.close()  # threadless: drains synchronously on the caller
    for f in futs:
        assert f.done(), "drain contract: every admitted request completes"
    assert {f.result(0).result for f in futs} == {"r:p0", "r:p1", "r:p2",
                                                  "r:q0"}
    with pytest.raises(RuntimeError):
        b.submit("a", "late")


def test_batcher_runner_exception_propagates_per_request():
    # isolate_poison=False: the pre-bisection contract — a failed batch
    # forwards the raw runner exception to every rider. The bisection
    # semantics of the default path live in test_reliability.py.
    clock = FakeClock()

    def boom(bucket_key, payloads):
        raise ValueError("device on fire")

    b = DeadlineBatcher(boom, max_batch=2, clock=clock,
                        isolate_poison=False)
    f1 = b.submit("a", "p1")
    f2 = b.submit("a", "p2")
    assert b.poll() == 1
    for f in (f1, f2):
        with pytest.raises(ValueError, match="device on fire"):
            f.result(0)
    assert obs.snapshot()["counters"]["serving.batch_errors"] == 1.0


def test_batcher_worker_thread_real_clock():
    """The threaded path: full-bucket and linger flushes both complete
    without any explicit poll() from the test."""
    calls = []
    b = DeadlineBatcher(echo_runner(calls), max_batch=2,
                        max_delay_s=0.02).start()
    try:
        f1 = b.submit("a", "p1")
        f2 = b.submit("a", "p2")
        assert f1.result(timeout=5).batch_size == 2
        assert f2.result(timeout=5).result == "r:p2"
        f3 = b.submit("a", "p3")  # partial: linger flush on the worker
        assert f3.result(timeout=5).batch_size == 1
    finally:
        b.close()


# -- client backoff (stub HTTP server, no jax) ----------------------------


def _stub_server(handler_cls):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_client_retries_503_then_succeeds():
    state = {"hits": 0, "always_503": False}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            state["hits"] += 1
            if state["always_503"] or state["hits"] < 2:
                body = b'{"error": "over capacity"}'
                self.send_response(503)
                self.send_header("Retry-After", "0.01")
            else:
                body = b'{"n_matches": 0, "matches": [], "batch_size": 1}'
                self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd, url = _stub_server(Handler)
    try:
        client = MatchClient(url, retries=2)
        resp = client.match(query_path="q.jpg", pano_path="p.jpg")
        assert resp["n_matches"] == 0
        assert state["hits"] == 2, "one 503 then one retry"

        state["always_503"] = True
        with pytest.raises(OverCapacityError) as exc_info:
            MatchClient(url, retries=0).match(
                query_path="q.jpg", pano_path="p.jpg"
            )
        assert exc_info.value.status == 503
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- end to end (tiny model, real HTTP, CPU) ------------------------------


def _jpeg_bytes(h, w, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray((rng.random((h, w, 3)) * 255).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def test_serving_e2e_cpu(tiny_serving_model, tmp_path):
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    log_path = str(tmp_path / "serving_run.jsonl")
    run_log = obs.init_run("serving", log_path)
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=64)
    server = MatchServer(
        engine, port=0, max_batch=2, max_queue=16,
        max_delay_s=0.3, default_timeout_s=300.0, run_log=run_log,
    ).start()
    try:
        client = MatchClient(server.url, timeout_s=600.0)
        assert client.healthz()["status"] == "ok"

        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)

        # Two concurrent same-shape requests share one batch (the
        # acceptance criterion: a response served from a batch of > 1).
        results = [None, None]

        def call(i):
            results[i] = client.match(query_bytes=qb, pano_bytes=pb,
                                      max_matches=8)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r is not None for r in results)
        assert any(r["batch_size"] == 2 for r in results), results
        for r in results:
            assert r["n_matches"] >= 1
            assert len(r["matches"]) == r["n_matches"] <= 8
            assert all(len(row) == 5 for row in r["matches"])
            assert r["latency_ms"] >= r["queue_wait_ms"]
            # Per-request lifecycle timing (schema v2): every stage
            # present, totals consistent with the e2e latency.
            timing = r["timing"]
            assert set(timing) == {"admit_ms", "queue_wait_ms",
                                   "batch_assemble_ms", "device_ms",
                                   "respond_ms", "total_ms"}
            assert all(v >= 0.0 for v in timing.values())
            assert timing["device_ms"] > 0.0
            assert timing["total_ms"] == r["latency_ms"]
            assert r["trace_id"]
        assert results[0]["trace_id"] != results[1]["trace_id"]

        # Path-referenced pano: miss populates the feature cache, the
        # repeat hits it and replays bit-identically.
        pano_path = str(tmp_path / "pano.jpg")
        with open(pano_path, "wb") as fh:
            fh.write(pb)
        r_miss = client.match(query_bytes=qb, pano_path=pano_path)
        r_hit = client.match(query_bytes=qb, pano_path=pano_path)
        assert engine.cache.hits >= 1
        assert r_miss["matches"] == r_hit["matches"]

        # Malformed requests map to 400, not 500.
        for bad in ({}, {"query_b64": "!!", "pano_b64": "!!"},
                    {"query_path": "/nonexistent.jpg",
                     "pano_path": pano_path}):
            status, payload, _ = client._request("POST", "/v1/match", bad)
            assert status == 400, (bad, payload)
            assert "error" in payload

        # /metrics: Prometheus text of the default registry, including
        # cumulative histogram _bucket lines (schema v2 satellite).
        metrics = client.metrics()
        assert "# TYPE serving_batches_total counter" in metrics
        assert "serving_e2e_latency_s_count" in metrics
        assert "serving_batch_size_max 2" in metrics
        assert "# TYPE serving_e2e_latency_s histogram" in metrics
        assert 'serving_e2e_latency_s_bucket{le="+Inf"}' in metrics
        assert 'serving_queue_wait_s_bucket{le="+Inf"}' in metrics
        assert "serving_device_time_s_count" in metrics

        # Drain contract over the real engine: admit directly, then
        # stop() — every admitted request still completes.
        prepared = engine.prepare({"query_b64": _b64(qb),
                                   "pano_b64": _b64(pb)})
        futs = [server.batcher.submit(prepared.bucket_key, prepared)
                for _ in range(3)]
    finally:
        server.stop()
    for f in futs:
        assert f.done(), "drain: admitted request dropped at shutdown"
        assert f.result(0).result["n_matches"] >= 1
    with pytest.raises(RuntimeError):
        server.batcher.submit(prepared.bucket_key, prepared)

    run_log.close("ok")
    records = assert_valid_runlog(log_path, component="serving")
    names = [r["event"] for r in records]
    assert "serving_start" in names and "serving_stop" in names
    assert "request" in names

    # Request spans form a valid tree (the schema-v2 acceptance
    # contract): every HTTP-served request root nests queue_wait +
    # batch_assemble + device children booked from the worker thread.
    # MatchClient injects X-NCNet-Trace, so HTTP-served roots CONTINUE
    # the client's trace (remote_parent; the parent span lives in the
    # caller's runlog) — only the raw _request 400 probes are local
    # roots with parent_id None.
    spans = [r for r in records
             if r.get("kind") == "span" and r.get("trace_id")]
    roots = [r for r in spans
             if r["event"] == "request"
             and (r.get("parent_id") is None or r.get("remote_parent"))]
    children = {}
    for r in spans:
        if r.get("parent_id") is not None:
            children.setdefault(r["parent_id"], set()).add(r["event"])
    # 400-path roots carry only an admit child; the served requests
    # (2 concurrent + miss + hit) carry the full lifecycle.
    full = [root for root in roots
            if {"admit", "queue_wait", "respond"}
            <= children.get(root["span_id"], set())]
    assert len(full) >= 4, [children.get(r["span_id"]) for r in roots]
    # Device-side spans fan out from the worker into request trees.
    got = set().union(*children.values())
    assert {"batch_assemble", "device"} <= got
    # The batched pair of requests shares ONE device dispatch: their
    # device spans carry batch_size 2 in two distinct trees.
    dev2 = [r for r in spans
            if r["event"] == "device" and r.get("batch_size") == 2]
    assert len({r["trace_id"] for r in dev2}) >= 2

    # The exporter turns this log into structurally valid Chrome-trace
    # JSON (ph/ts/pid/tid; ts monotone within each tid).
    import json as _json
    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__),
                                      "..", "tools"))
    import trace_export

    out = str(tmp_path / "serving.trace.json")
    data = trace_export.export(log_path, out)
    with open(out, encoding="utf-8") as fh:
        assert _json.load(fh) == data
    by_tid = {}
    for e in data["traceEvents"]:
        assert e["ph"] in ("X", "i", "M")
        if e["ph"] != "M":
            by_tid.setdefault(e["tid"], []).append(e["ts"])
    for tid, ts in by_tid.items():
        assert ts == sorted(ts), f"non-monotone ts in tid {tid}"
    x_names = {e["name"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert {"request", "admit", "queue_wait", "device"} <= x_names


def _b64(data):
    import base64

    return base64.b64encode(data).decode()


# -- chaos e2e: breaker, poison isolation, env-armed failpoints ------------


def test_serving_e2e_breaker_opens_and_recovers(tiny_serving_model,
                                                tmp_path, monkeypatch):
    """ISSUE-5 acceptance: with engine.device=error:1.0 injected, the
    breaker opens (503 + Retry-After, zero hung requests), /healthz
    degrades, the flight dump is written exactly once; after the fault
    clears and the reset window passes, the half-open probe closes it
    and requests succeed again."""
    import glob
    import time

    from ncnet_tpu.obs import flight
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    flight_dir = str(tmp_path / "flight")
    monkeypatch.setenv("NCNET_FLIGHT_DIR", flight_dir)
    flight.recorder().clear()  # resets the per-reason dump cooldown too

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    server = MatchServer(
        engine, port=0, max_batch=1, max_queue=16, max_delay_s=0.01,
        default_timeout_s=60.0, breaker_threshold=2, breaker_reset_s=2.0,
    ).start()
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)
        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)
        kwargs = dict(query_bytes=qb, pano_bytes=pb, max_matches=8)
        assert client.match(**kwargs)["n_matches"] >= 1, "warm request"

        failpoints.set_failpoint("engine.device", "error")
        # Threshold consecutive dispatch failures: each is a structured
        # 500 (the request is answered, not dropped)...
        for _ in range(2):
            with pytest.raises(ServingError) as exc_info:
                client.match(**kwargs)
            assert exc_info.value.status == 500
        # ...then the breaker is open: immediate 503 + Retry-After from
        # the front door, no device work attempted.
        with pytest.raises(OverCapacityError) as exc_info:
            client.match(**kwargs)
        assert exc_info.value.status == 503
        assert exc_info.value.payload["retry_after_s"] > 0
        hz = client.healthz()
        assert hz["status"] == "degraded"
        assert hz["breaker"]["state"] == "open"
        assert hz["failpoints"] == {"engine.device": "error"}
        dumps = glob.glob(
            flight_dir + "/flight-breaker-open-engine-*.jsonl")
        assert len(dumps) == 1, "exactly one flight dump per open episode"
        assert obs.snapshot()["counters"]["breaker.engine.opens"] == 1.0

        # Fault cleared + reset window passed: the next request is the
        # half-open probe; its success closes the breaker.
        failpoints.clear("engine.device")
        time.sleep(2.1)
        assert client.match(**kwargs)["n_matches"] >= 1
        assert server.breaker.state == "closed"
        assert client.healthz()["status"] == "ok"
    finally:
        server.stop()


def test_serving_e2e_poison_rider_isolated(tiny_serving_model):
    """ISSUE-5 acceptance: one poison rider in a shared batch of 4
    fails alone (structured PoisonRequestError) while the other three
    riders return correct matches."""
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.reliability.failpoints import InjectedFault
    from ncnet_tpu.serving.batcher import PoisonRequestError
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    server = MatchServer(
        engine, port=0, max_batch=4, max_queue=16, max_delay_s=0.5,
        default_timeout_s=300.0, breaker_threshold=50,
    ).start()
    try:
        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)
        body = {"query_b64": _b64(qb), "pano_b64": _b64(pb),
                "max_matches": 8}
        prepared = [server.engine.prepare(body) for _ in range(4)]
        prepared[1].poison = True
        # The per-rider chaos site: only the marked payload faults, so
        # the dispatch fails exactly when rider 1 is in the batch.
        failpoints.set_failpoint(
            "engine.rider", "error",
            match=lambda p: getattr(p, "poison", False),
        )
        futs = [server.batcher.submit(p.bucket_key, p) for p in prepared]
        results, errors = {}, {}
        for i, f in enumerate(futs):
            try:
                results[i] = f.result(timeout=300)
            except Exception as exc:  # noqa: BLE001 — sorted below
                errors[i] = exc
        assert set(errors) == {1}, f"only the poison rider fails: {errors}"
        assert isinstance(errors[1], PoisonRequestError)
        assert isinstance(errors[1].cause, InjectedFault)
        reference = None
        for i in (0, 2, 3):
            br = results[i]
            assert br.result["n_matches"] >= 1
            assert br.batch_size < 4, "innocents completed post-bisection"
            if reference is None:
                reference = br.result["matches"].tolist()
            else:
                assert br.result["matches"].tolist() == reference, (
                    "identical innocent requests must return identical "
                    "matches after isolation"
                )
        snap = obs.snapshot()["counters"]
        assert snap["serving.poison_isolated"] == 1.0
        assert snap["serving.poison_survivors"] == 3.0
        assert snap["serving.poison_bisects"] >= 1.0
    finally:
        server.stop()


def test_serving_e2e_env_failpoints_no_silent_drops(tiny_serving_model,
                                                    monkeypatch):
    """ISSUE-5 satellite: with NCNET_FAILPOINTS armed from the
    environment, every request still gets a structured response — the
    injected ones a 500 tagged kind=injected_fault, the rest correct
    matches; nothing hangs or vanishes."""
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    monkeypatch.setenv("NCNET_FAILPOINTS", "server.handle=error:1.0x2")
    armed = failpoints.configure_from_env()
    assert set(armed) == {"server.handle"}

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    server = MatchServer(
        engine, port=0, max_batch=1, max_queue=16, max_delay_s=0.01,
        default_timeout_s=60.0,
    ).start()
    try:
        client = MatchClient(server.url, timeout_s=120.0, retries=0)
        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)
        outcomes = []
        for _ in range(4):
            try:
                outcomes.append(
                    ("ok", client.match(query_bytes=qb, pano_bytes=pb,
                                        max_matches=8)))
            except ServingError as exc:
                outcomes.append(("error", exc))
        assert len(outcomes) == 4, "no silent drops"
        injected = [o for kind, o in outcomes if kind == "error"]
        served = [o for kind, o in outcomes if kind == "ok"]
        assert len(injected) == 2, "x2 cap: exactly two injected faults"
        for exc in injected:
            assert exc.status == 500
            assert exc.payload["kind"] == "injected_fault"
        assert len(served) == 2
        for resp in served:
            assert resp["n_matches"] >= 1
        assert obs.snapshot()["counters"]["failpoint.server.handle"] == 2.0
    finally:
        server.stop()


def test_serving_e2e_c2f_mode(tiny_serving_model, monkeypatch):
    """Coarse-to-fine over HTTP: mode='c2f' requests run the two-stage
    engine path (coarse/refine stage timings in the response), land in
    their own mode-keyed bucket, degrade cleanly under the engine.refine
    failpoint, and leave one-shot requests on the same server untouched.
    Degenerate knobs are covered engine-side: factor 1 + keep-all top-K
    must dispatch the unmodified one-shot program bit-identically."""
    from ncnet_tpu.reliability import failpoints
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=96,
                         cache_mb=0, c2f_topk=4)
    server = MatchServer(
        engine, port=0, max_batch=2, max_queue=16, max_delay_s=0.05,
        default_timeout_s=600.0,
    ).start()
    try:
        client = MatchClient(server.url, timeout_s=600.0, retries=0)
        qb = _jpeg_bytes(96, 128, 0)
        pb = _jpeg_bytes(96, 128, 1)

        r = client.match(query_bytes=qb, pano_bytes=pb, mode="c2f")
        assert r["n_matches"] >= 1
        assert all(len(row) == 5 for row in r["matches"])
        # Two-stage path: per-stage timings rode the response, and the
        # c2f stage metrics recorded the run.
        assert r["timing"]["coarse_ms"] >= 0.0
        assert r["timing"]["refine_ms"] >= 0.0
        snap = obs.snapshot()["histograms"]
        assert any(k.startswith("engine.c2f.coarse_s") for k in snap)
        assert any(k.startswith("engine.c2f.survivors") for k in snap)

        # One-shot on the same server: untouched timing schema.
        r_os = client.match(query_bytes=qb, pano_bytes=pb)
        assert r_os["n_matches"] >= 1
        assert "coarse_ms" not in r_os["timing"]

        # Unknown mode is the request's own fault: 400, not 500.
        with pytest.raises(ServingError) as exc:
            client.match(query_bytes=qb, pano_bytes=pb, mode="fine2coarse")
        assert exc.value.status == 400

        # The stage-2 failpoint (docs/RELIABILITY.md planted sites):
        # injected fault surfaces as a structured error, and the very
        # next c2f request serves normally.
        monkeypatch.setenv("NCNET_FAILPOINTS", "engine.refine=error:1.0x1")
        assert set(failpoints.configure_from_env()) == {"engine.refine"}
        with pytest.raises(ServingError) as exc:
            client.match(query_bytes=qb, pano_bytes=pb, mode="c2f")
        assert exc.value.status == 500
        r2 = client.match(query_bytes=qb, pano_bytes=pb, mode="c2f")
        assert r2["n_matches"] >= 1
    finally:
        server.stop()


def test_engine_c2f_degenerate_routes_oneshot(tiny_serving_model):
    """Factor-1 + keep-everything knobs: the c2f bucket is degenerate,
    run_batch dispatches the one-shot program (bit-identical matches),
    and the refine_skipped counter records the routing decision."""
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0, c2f_coarse_factor=1, c2f_topk=0)
    qb = _jpeg_bytes(96, 128, 0)
    pb = _jpeg_bytes(96, 128, 1)
    import base64

    req = {"query_b64": base64.b64encode(qb).decode(),
           "pano_b64": base64.b64encode(pb).decode()}
    p_c2f = engine.prepare(dict(req, mode="c2f"))
    p_os = engine.prepare(req)
    assert p_c2f.bucket_key != p_os.bucket_key  # mode keys the bucket
    assert engine._c2f_bucket_degenerate(p_c2f.bucket_key)
    out_c2f = engine.run_batch(p_c2f.bucket_key, [p_c2f])
    out_os = engine.run_batch(p_os.bucket_key, [p_os])
    np.testing.assert_array_equal(out_c2f[0]["matches"],
                                  out_os[0]["matches"])
    assert "coarse_ms" not in out_c2f[0]["timing"]
    counters = obs.snapshot()["counters"]
    assert any(k.startswith("engine.c2f.refine_skipped")
               for k in counters)
