"""Concurrency regression for the PR 13 backbone layout fix.

The channels-last trace flag was once a module global: one replica
thread entering an NHWC scope flipped every other thread's in-flight
trace into mixed-layout convs. It is now ``threading.local`` state
(models/backbone.py ``_LAYOUT_STATE``), and this test pins that down:
N threads trace channels-first and channels-last CONCURRENTLY - a
barrier inside each thread's layout scope guarantees every scope is
simultaneously open - and each thread must see its own layout, both in
the flag and in the conv output shape. Deterministic (the barrier
forces the overlap; no sleeps) and fast (tiny eager convs, no jit).
"""

import threading

import numpy as np

from ncnet_tpu.models.backbone import (
    _channels_last,
    _channels_last_on,
    conv2d,
)

N_THREADS = 8
ROUNDS = 3


def test_concurrent_layout_scopes_never_mix():
    cin, cout, hw = 3, 5, 8
    w = np.zeros((3, 3, cin, cout), np.float32)
    x_nchw = np.zeros((1, cin, hw, hw), np.float32)
    x_nhwc = np.zeros((1, hw, hw, cin), np.float32)
    barrier = threading.Barrier(N_THREADS)
    errors = []

    def worker(idx):
        nhwc = idx % 2 == 1
        try:
            for _ in range(ROUNDS):
                with _channels_last(nhwc):
                    # Every thread sits here with its scope OPEN until
                    # all N scopes are open: a module-global flag would
                    # now hold the last writer's layout for everyone.
                    barrier.wait(timeout=30)
                    assert _channels_last_on() is nhwc
                    out = conv2d(x_nhwc if nhwc else x_nchw, w,
                                 stride=1, padding=1)
                    want = ((1, hw, hw, cout) if nhwc
                            else (1, cout, hw, hw))
                    assert out.shape == want, (
                        f"thread {idx}: mixed-layout conv "
                        f"(got {out.shape}, want {want})")
                assert _channels_last_on() is False  # scope restored
        except Exception as exc:  # noqa: BLE001 - reported by the main thread
            errors.append((idx, exc))
            barrier.abort()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
