"""Algebraic consensus arms (ncnet_tpu/ops/cp4d.py, ISSUE 18).

Coverage, per the arms' declared contracts:

* rank-full CP is BITWISE identical to conv4d_reference in f32 (the
  delta-basis lowering replays the reference loop: same pads, same
  slices, same einsum, same accumulation order) — per conv, which is
  the claim; the tuned dense stack is a different formulation.
* truncated ranks clear their declared agreement floors
  (DECLARED_AGREEMENT_FLOOR — the number quality_report gates cp QoS
  rungs against).
* the FFT arm matches the direct conv within f32 tolerance, and within
  a looser tolerance from bf16 inputs.
* the ALS factorization cache round-trips through its JSON file and
  invalidates by checkpoint digest, never by mtime; exact (delta)
  factorizations are never persisted.
* the autotuner's winner selection respects measured time across the
  dense/cp/fft kinds (injected timer — no device compiles).
* end to end: a MatchServer with a ``cp:rank=8`` QoS rung serves the
  cp arm under pressure and stays bitwise-identical to the plain
  admission path at rung 0.
"""

import base64
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops import autotune, cp4d
from ncnet_tpu.ops.conv4d import (
    conv4d_reference,
    neigh_consensus_apply,
    neigh_consensus_init,
)

SHAPE = (1, 1, 6, 5, 7, 6)
TAPS = 3 ** 4  # every kernel below is (3,3,3,3,...)


@pytest.fixture
def params():
    return neigh_consensus_init(jax.random.PRNGKey(0), (3, 3), (8, 1))


@pytest.fixture
def corr():
    r = np.random.RandomState(1)
    return jnp.asarray(r.randn(*SHAPE).astype(np.float32))


@pytest.fixture
def clean_env(monkeypatch, tmp_path):
    """Hermetic knobs: no ambient plan env, both caches at tmp paths,
    fresh in-process factor memo."""
    for k in autotune.PLAN_ENV_KEYS + ("NCNET_CONV4D_STRATEGY",
                                       "NCNET_CONSENSUS_CL"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("NCNET_STRATEGY_CACHE",
                       str(tmp_path / "consensus_autotune.json"))
    cache = tmp_path / "consensus_cp.json"
    monkeypatch.setenv("NCNET_CP_FACTOR_CACHE", str(cache))
    monkeypatch.setattr(cp4d, "_FACTOR_MEMO", {})
    return cache


# -- exactness -------------------------------------------------------------


def test_rank_full_cp_bitwise_vs_reference(params, clean_env):
    """Tier-1 acceptance: at rank >= the tap count the CP arm is not
    'close' — it is the same f32 bits as conv4d_reference, layer by
    layer (delta factors lower to the reference's own slice/einsum/add
    program)."""
    r = np.random.RandomState(2)
    cin = 1
    for layer in params:
        x = jnp.asarray(
            r.randn(1, cin, 5, 4, 6, 5).astype(np.float32))
        ref = np.asarray(conv4d_reference(x, layer["weight"],
                                          layer["bias"]))
        full = np.asarray(cp4d.cp_conv4d(x, layer["weight"],
                                         layer["bias"], rank=TAPS))
        assert full.dtype == np.float32
        assert np.array_equal(ref, full), "full-rank CP is not bitwise"
        # Over-asking is clamped to the tap count, same bits.
        over = np.asarray(cp4d.cp_conv4d(x, layer["weight"],
                                         layer["bias"], rank=TAPS * 4))
        assert np.array_equal(ref, over)
        cin = int(layer["weight"].shape[5])


def test_swap_factors_full_rank_bitwise(params, clean_env):
    """The symmetric branch's role-swapped factors accumulate in the
    SWAPPED kernel's reference order — bitwise again, not just equal."""
    from ncnet_tpu.ops.conv4d import swap_ab_weight

    w = params[0]["weight"]
    x = jnp.asarray(np.random.RandomState(3)
                    .randn(1, 1, 5, 4, 6, 5).astype(np.float32))
    ref = np.asarray(conv4d_reference(x, swap_ab_weight(w), None))
    swapped = cp4d.swap_factors(cp4d.cp_decompose(w, TAPS))
    got = np.asarray(cp4d._cp_apply_one(x, swapped))
    assert np.array_equal(ref, got)


def test_truncated_ranks_clear_declared_floors(params, corr, clean_env):
    """Every declared (rank, floor) pair holds on the random-init stack
    — the WORST case the floors were calibrated against."""
    dense = np.asarray(jax.jit(
        lambda c: neigh_consensus_apply(params, c, symmetric=True))(corr))
    for rank, floor in sorted(cp4d.DECLARED_AGREEMENT_FLOOR.items()):
        out = np.asarray(cp4d.consensus_cp_apply(
            params, corr, rank=rank, symmetric=True))
        agreement = cp4d.output_agreement(dense, out)
        assert agreement >= floor, (
            f"rank {rank} agreement {agreement:.4f} below declared "
            f"floor {floor}")


def test_fft_parity_f32_and_bf16(params, clean_env):
    """FFT arm vs direct conv: exact-tolerance in f32; from bf16 inputs
    both arms compute in f32 from the same rounded input, so the gap
    stays FFT-roundoff-sized, gated looser."""
    r = np.random.RandomState(4)
    layer = params[0]
    x32 = jnp.asarray(r.randn(1, 1, 5, 4, 6, 5).astype(np.float32))
    ref = np.asarray(conv4d_reference(x32, layer["weight"],
                                      layer["bias"]))
    fft = np.asarray(cp4d.fft_conv4d(x32, layer["weight"],
                                     layer["bias"]))
    scale = float(np.max(np.abs(ref)))
    assert float(np.max(np.abs(fft - ref))) < 1e-5 * scale

    xbf = x32.astype(jnp.bfloat16)
    ref_bf = np.asarray(conv4d_reference(xbf, layer["weight"],
                                         layer["bias"]), np.float32)
    fft_bf = np.asarray(cp4d.fft_conv4d(xbf, layer["weight"],
                                        layer["bias"]))
    scale = max(float(np.max(np.abs(ref_bf))), 1e-30)
    assert float(np.max(np.abs(fft_bf - ref_bf))) < 1e-2 * scale


def test_fft_stack_agreement_near_exact(params, corr, clean_env):
    """The full symmetric fft stack tracks the dense stack at ~f32
    precision (agreement, not bitwise — different reduction orders)."""
    dense = np.asarray(jax.jit(
        lambda c: neigh_consensus_apply(params, c, symmetric=True))(corr))
    fft = np.asarray(cp4d.consensus_fft_apply(
        params, corr, symmetric=True))
    assert cp4d.output_agreement(dense, fft) > 0.9999


# -- factor cache ----------------------------------------------------------


def _boom(*a, **k):
    raise AssertionError("ALS ran when the factor cache should serve")


def test_factor_cache_round_trip_and_digest_invalidation(
        clean_env, monkeypatch):
    w = np.asarray(jax.random.normal(
        jax.random.PRNGKey(5), (3, 3, 3, 3, 2, 2)), np.float32)
    f1 = cp4d.cp_decompose(w, 8)
    data = json.loads(clean_env.read_text())
    digest = cp4d.weight_digest(w)
    assert f"{digest}|rank=8" in data["entries"]

    # Round trip: fresh memo (a new process), ALS forbidden — the JSON
    # cache must serve the identical factors.
    monkeypatch.setattr(cp4d, "_FACTOR_MEMO", {})
    monkeypatch.setattr(cp4d, "_als_factors", _boom)
    f2 = cp4d.cp_decompose(w, 8)
    for k in ("a", "b", "c", "d", "core"):
        np.testing.assert_array_equal(f1[k], f2[k])

    # Checkpoint change invalidates by CONTENT digest: the perturbed
    # kernel must not be served the stale factors (ALS is reached).
    with pytest.raises(AssertionError, match="ALS ran"):
        cp4d.cp_decompose(w + 0.5, 8)
    # A different rank of the same weight is its own entry too.
    with pytest.raises(AssertionError, match="ALS ran"):
        cp4d.cp_decompose(w, 4)

    # Exact full-rank factors never touch ALS or the JSON cache.
    cp4d.cp_decompose(w, TAPS)
    data = json.loads(clean_env.read_text())
    assert list(data["entries"]) == [f"{digest}|rank=8"]


def test_factor_cache_disabled_by_empty_env(monkeypatch, tmp_path):
    monkeypatch.setenv("NCNET_CP_FACTOR_CACHE", "")
    monkeypatch.setattr(cp4d, "_FACTOR_MEMO", {})
    assert cp4d.factor_cache_path() is None
    w = np.asarray(jax.random.normal(
        jax.random.PRNGKey(6), (3, 3, 3, 3, 1, 2)), np.float32)
    f = cp4d.cp_decompose(w, 4)
    assert f["rank"] == 4 and not (tmp_path / "consensus_cp.json").exists()


# -- autotuner arm selection ----------------------------------------------


def test_autotune_picks_dense_when_cp_loses(params, corr, clean_env):
    """A cp/fft candidate that measures slower must not win on novelty:
    the tuner is time-ordered across kinds."""

    def timer(params_, corr_, sym_, plan, *, reps, iters):
        kind = autotune.normalize_plan(plan)["kind"]
        return 0.0, 1.0 if kind == "dense" else 50.0

    best, ms, results = autotune.autotune(
        params, corr, timer=timer, save=False)
    assert autotune.normalize_plan(best)["kind"] == "dense"
    assert ms == 1.0
    labels = {autotune.plan_label(p) for p, _ in results}
    assert "fft" in labels and any(
        l.startswith("cp:rank=") for l in labels), \
        "algebraic arms missing from the candidate space"


def test_autotune_picks_cp_when_it_wins(params, corr, clean_env):
    def timer(params_, corr_, sym_, plan, *, reps, iters):
        p = autotune.normalize_plan(plan)
        if p["kind"] == "cp" and p["cp_rank"] == 8:
            return 0.0, 0.5
        return 0.0, 5.0

    best, ms, _ = autotune.autotune(params, corr, timer=timer,
                                    save=False)
    p = autotune.normalize_plan(best)
    assert (p["kind"], p["cp_rank"], ms) == ("cp", 8, 0.5)


# -- serving end-to-end ----------------------------------------------------


class _QuietSlo:
    """Never-paging SLO stub (the e2e drives the controller from queue
    pressure alone — same posture as tests/test_qos.py)."""

    def maybe_evaluate(self):
        return {}


def _jpeg_b64(h, w, seed):
    import io

    from PIL import Image

    rng = np.random.RandomState(seed)
    img = Image.fromarray(
        rng.randint(0, 255, size=(h, w, 3), dtype="uint8"))
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return base64.b64encode(buf.getvalue()).decode()


def _start_server(engine, **kw):
    from ncnet_tpu.serving.server import MatchServer

    kw.setdefault("port", 0)
    kw.setdefault("max_batch", 1)
    kw.setdefault("max_queue", 16)
    kw.setdefault("max_delay_s", 0.01)
    kw.setdefault("default_timeout_s", 300.0)
    return MatchServer(engine, **kw).start()


def _client(url):
    from ncnet_tpu.serving.client import MatchClient

    return MatchClient(url, timeout_s=600.0, retries=0)


def test_serving_e2e_cp_rung_degrades_and_rung0_stays_bitwise(
        tiny_serving_model, clean_env):
    """The QoS acceptance end to end: a ladder whose only rung is
    ``cp:rank=8`` serves full quality at rung 0 — bitwise-identical to
    a server with no QoS layer at all — and under queue pressure the
    SAME request runs degraded on the cp arm (its own program, its own
    bucket key) instead of shedding."""
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.qos import (
        QosController,
        TenantPolicy,
        TenantTable,
        parse_ladder,
    )

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    kwargs = dict(
        query_bytes=base64.b64decode(_jpeg_b64(96, 128, 0)),
        pano_bytes=base64.b64decode(_jpeg_b64(96, 128, 1)),
        max_matches=8)

    plain = _start_server(engine)
    try:
        r_plain = _client(plain.url).match(**kwargs)
    finally:
        plain.stop()

    pressure = {"on": False}
    ladder = parse_ladder("cp:rank=8")
    assert ladder[0].knobs() == {"kind": "cp", "rank": 8}
    qos = QosController(
        ladder, slo=_QuietSlo(),
        depth_fn=lambda: 100 if pressure["on"] else 0,
        max_queue=10,
        step_down_interval_s=0.0,
        step_up_hold_s=60.0,  # never climbs back during the test
    )
    # Degradation applies to degradable classes only (interactive runs
    # as requested until the shed positions) — drive a best_effort
    # tenant onto the cp rung.
    tenants = TenantTable([TenantPolicy("lowpri", "best_effort")])
    server = _start_server(engine, qos=qos, tenants=tenants)
    try:
        client = _client(server.url)
        # Idle: rung 0 is the full-quality dense arm, same bits as the
        # no-QoS server (the degenerate-ladder contract, now with a cp
        # rung in the ladder).
        r0 = client.match(tenant="lowpri", **kwargs)
        assert r0["qos"] == {"rung": 0, "degraded": False}
        assert r0["matches"] == r_plain["matches"]
        assert r0["n_matches"] == r_plain["n_matches"]
        # Pressure: the controller steps onto the cp rung and the
        # request still serves (degraded), on the rank-8 arm.
        pressure["on"] = True
        r1 = client.match(tenant="lowpri", **kwargs)
        assert r1["qos"] == {"rung": 1, "degraded": True}
        assert r1["n_matches"] >= 1
        # /healthz itself re-evaluates the controller (pressure is
        # still on, so it may have stepped further by now) — assert
        # the ladder exposure, not an exact position.
        health = client.healthz()
        assert health["qos"]["rung"] >= 1
        assert health["qos"]["ladder"] == [{"kind": "cp", "rank": 8}]
    finally:
        server.stop()


def test_engine_cp_plan_extends_bucket_key(tiny_serving_model,
                                           clean_env):
    """A forced cp plan can never share a compiled program or a result-
    cache namespace with default traffic: the plan extends the bucket
    key; default requests keep the pre-plan key shape."""
    from ncnet_tpu.serving.engine import MatchEngine

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    req = {"query_b64": _jpeg_b64(96, 128, 0),
           "pano_b64": _jpeg_b64(96, 128, 1)}
    p0 = engine.prepare(dict(req))
    assert p0.plan is None
    p1 = engine.prepare(dict(req, consensus={"kind": "cp", "rank": 8}))
    assert p1.plan == ("cp", 8)
    assert p1.bucket_key == p0.bucket_key + (("plan", "cp", 8),)
    # An explicit dense knob is still a FORCED plan (the default is ''
    # = defer to env/cache/auto), so it gets its own key too — a pinned
    # dense response never shares cache with auto-resolved traffic.
    pd = engine.prepare(dict(req, consensus={"kind": "dense"}))
    assert pd.plan == ("dense", 0)
    assert pd.bucket_key == p0.bucket_key + (("plan", "dense", 0),)
    with pytest.raises(ValueError, match="rank"):
        engine.prepare(dict(req, consensus={"kind": "cp"}))
    with pytest.raises(ValueError, match="unknown consensus"):
        engine.prepare(dict(req, consensus={"rankk": 8}))
