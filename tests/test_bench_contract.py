"""bench.py output contract: exactly one JSON line with the driver's keys.

The round driver records bench.py stdout as the benchmark result; a stray
print or a changed key silently breaks the recording. Runs the real bench
end to end on CPU at a tiny smoke size.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_emits_one_json_line():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        NCNET_BENCH_SMOKE_SIZE="96",
        NCNET_BENCH_DIAL_TIMEOUT="60",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["metric"].startswith("inloc_dense_match_pairs_per_s_per_chip")
    assert rec["value"] > 0


def test_bench_serving_emits_one_json_line(tiny_serving_model, capsys):
    """tools/bench_serving.py stdout contract (ISSUE 2): the load
    generator, run in-process against a real tiny server, prints ONE
    JSON line with the throughput metric and latency percentiles."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import bench_serving
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    # Precompile the exact bucket the synthetic load hits so the bench
    # measures serving, not XLA.
    engine.warmup([(96, 128, 96, 128)], batch_sizes=(1, 2))
    server = MatchServer(engine, port=0, max_batch=2, max_delay_s=0.05,
                         default_timeout_s=120.0).start()
    try:
        rc = bench_serving.main([
            "--url", server.url, "--synthetic", "96x128",
            "--rate", "8", "--duration_s", "1", "--threads", "4",
        ])
    finally:
        server.stop()
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "serving_match_throughput_rps"
    assert rec["unit"] == "req/s"
    assert rec["value"] > 0
    for q in ("p50", "p95", "p99"):
        assert rec["latency_ms"][q] > 0
    assert rec["sent"] == 8
    assert rec["ok"] + rec["rejected"] == rec["sent"]
    assert rec["errors"] == 0


def test_bench_serving_fleet_mode_contract(tiny_serving_model, capsys):
    """tools/bench_serving.py --replicas N (ISSUE 7 satellite): the
    weak-scaling fleet bench — in-process 1-replica baseline, then an
    N-replica fleet at N x the offered rate — prints ONE JSON line with
    the fleet headline, the per-replica breakdown, and an HONEST
    scaling_efficiency (structure asserted, not a speedup number: these
    CPU replicas time-slice one host)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import bench_serving

    rc = bench_serving.main([
        "--replicas", "2", "--synthetic", "96x128",
        "--rate", "4", "--duration_s", "1", "--baseline_duration_s", "1",
        "--threads", "4", "--max_batch", "2",
    ], model=tiny_serving_model)
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "serving_fleet_pairs_per_s"
    assert rec["unit"] == "pairs/s"
    assert rec["value"] > 0
    assert rec["replicas"] == 2
    assert rec["single_replica_pairs_per_s"] > 0
    assert rec["scaling_x"] > 0
    assert rec["scaling_efficiency"] == pytest.approx(
        rec["scaling_x"] / 2, rel=1e-3)
    assert rec["errors"] == 0
    assert rec["sent"] == rec["ok"] + rec["rejected"]
    # Per-replica accounting: both fleet replicas exist in the
    # breakdown and their admissions cover every ok request.
    assert set(rec["per_replica"]) == {"fleet-d0", "fleet-d1"}
    admitted = sum(v["admitted"] for v in rec["per_replica"].values())
    assert admitted >= rec["ok"]
    assert all(v["batches"] >= 0 for v in rec["per_replica"].values())
    assert rec["redispatched"] == 0  # nobody was killed
    # The --url and --replicas modes are mutually exclusive.
    with pytest.raises(SystemExit):
        bench_serving.main(["--url", "http://x", "--replicas", "2",
                            "--synthetic", "96x128"])


def test_chaos_serving_kill_replica_contract(tiny_serving_model, capsys):
    """tools/chaos_serving.py kill_replica verb (ISSUE 7 satellite): a
    two-replica fleet with one replica killed mid-window — zero silent
    drops (the exit gate), the fault log records the window, and the
    output carries the fleet fields."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import chaos_serving

    rc = chaos_serving.main([
        "--replicas", "2", "--synthetic", "96x128",
        "--rate", "4", "--duration_s", "2", "--threads", "4",
        "--max_batch", "2", "--breaker_reset_s", "0.4",
        "--fault", "kill_replica:0@0.4-1.2",
    ], model=tiny_serving_model)
    assert rc == 0, "a nonzero rc means a request was silently dropped"
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "chaos_serving_survival"
    assert rec["dropped"] == 0
    assert rec["replicas"] == 2
    assert rec["redispatched"] >= 0
    assert rec["sent"] == 8
    assert (rec["ok"] + rec["rejected"] + rec["poison"] + rec["errors"]
            == rec["sent"])
    assert rec["ok"] >= 1, "the surviving replica kept serving"
    assert rec["faults"]["kill_replica:0"] == [
        {"t_s": 0.4, "action": "arm"}, {"t_s": 1.2, "action": "disarm"},
    ]
    # kill_replica without a fleet is a usage error, not a hang.
    with pytest.raises(SystemExit):
        chaos_serving.main(["--fault", "kill_replica@0.1-0.2"],
                           model=tiny_serving_model)


def test_chaos_serving_emits_one_json_line(tiny_serving_model, capsys):
    """tools/chaos_serving.py stdout contract (ISSUE 5): the chaos
    harness — in-process server, open-loop load, a timed engine.device
    fault window — prints ONE JSON line with the survival metric,
    per-outcome accounting that sums to every scheduled request (no
    silent drops), and the observed breaker transitions."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import chaos_serving

    rc = chaos_serving.main([
        "--synthetic", "96x128", "--rate", "4", "--duration_s", "2",
        "--threads", "4", "--max_batch", "2",
        "--breaker_threshold", "2", "--breaker_reset_s", "0.4",
        "--fault", "engine.device=error:1.0@0.4-1.2",
    ], model=tiny_serving_model)
    assert rc == 0, "a nonzero rc means a request was silently dropped"
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "chaos_serving_survival"
    assert rec["unit"] == "frac"
    assert 0.0 <= rec["value"] <= 1.0
    assert rec["dropped"] == 0
    assert rec["sent"] == 8
    assert (rec["ok"] + rec["rejected"] + rec["poison"] + rec["errors"]
            == rec["sent"])
    assert rec["ok"] >= 1, "requests outside the fault window succeed"
    assert rec["faults"]["engine.device"] == [
        {"t_s": 0.4, "action": "arm"}, {"t_s": 1.2, "action": "disarm"},
    ]
    assert isinstance(rec["breaker_transitions"], list)
    assert rec["duration_s"] > 0


def test_chaos_serving_tenant_flood_contract(tiny_serving_model, capsys):
    """tools/chaos_serving.py --tenant_flood (ISSUE 12): victim /
    lowpri / flood tenants against a laddered server with a pinned-slow
    device — the gate passes (victims 100% available, rung transitions
    recorded, low-priority traffic ran degraded, no over_capacity 503
    while a coarser rung was untried) and the JSON line carries the
    per-tenant accounting."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import chaos_serving

    rc = chaos_serving.main([
        "--tenant_flood", "--synthetic", "96x128",
        "--duration_s", "4", "--threads", "8",
        "--max_batch", "2", "--flood_x", "10",
    ], model=tiny_serving_model)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rc == 0, f"gate violations: {rec['violations']}"
    assert rec["metric"] == "chaos_tenant_flood"
    assert rec["unit"] == "frac"
    assert rec["value"] == 1.0, "every victim request served"
    assert rec["violations"] == []
    assert rec["dropped"] == 0
    assert rec["transitions"] >= 1, "the ladder engaged"
    assert rec["quality_rungs"] == 2  # the default two-rung ladder
    # Self-calibration (measured capacity -> offered load) is reported.
    assert rec["capacity_rps"] > 0
    assert rec["base_rate_rps"] == pytest.approx(
        rec["capacity_rps"] / 4, rel=1e-2)
    t = rec["tenants"]
    assert set(t) == {"victim", "lowpri", "flood"}
    assert t["victim"]["ok"] == t["victim"]["sent"]
    assert (t["lowpri"]["degraded"] + t["flood"]["degraded"]) >= 1
    # Per-tenant outcome accounting covers every scheduled request.
    for st in t.values():
        assert (st["ok"] + st["shed"] + st["over_capacity"]
                + st["tenant_budget"] + st["tenant_slots"]
                + st["breaker"] + st["errors"]) == st["sent"]
    # An empty ladder is a usage error, not a silent no-op run.
    with pytest.raises(SystemExit):
        chaos_serving.main(["--tenant_flood", "--qos_ladder", ""],
                           model=tiny_serving_model)


def test_bench_serving_tenants_mode_contract(tiny_serving_model, capsys):
    """tools/bench_serving.py --tenants (ISSUE 12): concurrent
    per-tenant open-loop loads against one server, ONE JSON line with
    per-tenant availability / p99 / rungs visited."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import bench_serving
    from ncnet_tpu.serving.engine import MatchEngine
    from ncnet_tpu.serving.server import MatchServer

    config, params = tiny_serving_model
    engine = MatchEngine(config, params, k_size=2, image_size=64,
                         cache_mb=0)
    engine.warmup([(96, 128, 96, 128)], batch_sizes=(1, 2))
    server = MatchServer(engine, port=0, max_batch=2, max_delay_s=0.05,
                         default_timeout_s=120.0).start()
    try:
        rc = bench_serving.main([
            "--url", server.url, "--synthetic", "96x128",
            "--duration_s", "1", "--threads", "4",
            "--tenants", "alpha:interactive:4",
            "--tenants", "beta:batch:2",
        ])
    finally:
        server.stop()
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "serving_tenant_mix_rps"
    assert rec["unit"] == "req/s"
    assert rec["value"] > 0
    assert set(rec["tenants"]) == {"alpha", "beta"}
    for name, expect_rate in (("alpha", 4.0), ("beta", 2.0)):
        tr = rec["tenants"][name]
        assert tr["rate"] == expect_rate
        assert tr["sent"] >= 1 and tr["errors"] == 0
        assert tr["availability"] == 1.0
        assert tr["p99_ms"] > 0
        assert tr["rungs_visited"] == []  # no QoS layer on this server
        assert tr["degraded"] == 0
    # --tenants drives ONE server over HTTP; the in-process fleet
    # bench is a different mode.
    with pytest.raises(SystemExit):
        bench_serving.main(["--replicas", "2", "--synthetic", "96x128",
                            "--tenants", "a:batch:1"])


def test_bench_serving_session_mode_contract(tiny_serving_model, capsys):
    """tools/bench_serving.py --session (ISSUE 13): one streaming
    session (open -> frames -> close) against a one-shot c2f baseline
    of the SAME frames; ONE JSON line with frames/s, the seeded /
    unseeded / full-c2f latency split, and the seed hit accounting
    (structure asserted, not the speedup number: CPU boxes jitter)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import bench_serving

    rc = bench_serving.main([
        "--replicas", "1", "--session", "--synthetic", "96x128",
        "--frames", "6", "--warmup_frames", "1", "--max_batch", "2",
    ], model=tiny_serving_model)
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rec["metric"] == "serving_session_fps"
    assert rec["unit"] == "frames/s"
    assert rec["value"] > 0
    assert rec["frames"] == 6
    assert rec["warmup_frames"] == 1
    assert rec["errors"] == 0
    # Frame 1 runs the full coarse pass; every later frame rides the
    # previous frame's seed (no kills in this run -> no re-seeds).
    assert rec["seeded_frames"] >= 4
    assert rec["seed_hit_frac"] > 0
    assert rec["reseeds"] == 0
    lat = rec["latency_ms"]
    assert lat["full_c2f"]["n"] == 5 and lat["full_c2f"]["p50"] > 0
    assert lat["seeded"]["n"] >= 3 and lat["seeded"]["p50"] > 0
    # Post-warmup session frames are all accounted seeded-or-not.
    assert lat["seeded"]["n"] + lat["unseeded"]["n"] == 5
    assert rec["seeded_speedup_p50"] is not None
    assert rec["seeded_speedup_p50"] > 0
    # Frames are generated client-side: --session without --synthetic
    # is a usage error, not a silent fallback.
    with pytest.raises(SystemExit):
        bench_serving.main(["--session", "--replicas", "1"],
                           model=tiny_serving_model)


def test_chaos_serving_session_stream_contract(tiny_serving_model, capsys):
    """tools/chaos_serving.py --session_stream (ISSUE 13): streams over
    a two-replica fleet with a kill window over EACH replica in turn —
    whichever replica holds a stream's seed gets killed, so the gate
    (a kill mid-stream must re-seed on a survivor, never kill the
    session, drop a frame, or answer non-200) is exercised
    deterministically."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import json as _json

    import chaos_serving

    rc = chaos_serving.main([
        "--session_stream", "--replicas", "2", "--sessions", "2",
        "--synthetic", "96x128", "--duration_s", "6",
        "--fault", "kill_replica:0@1.0-2.5",
        "--fault", "kill_replica:1@3.5-5.0",
    ], model=tiny_serving_model)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = _json.loads(lines[0])
    assert rc == 0, f"gate violations: {rec['violations']}"
    assert rec["metric"] == "chaos_session_stream"
    assert rec["unit"] == "frac"
    assert rec["value"] == 1.0, "every frame answered 200"
    assert rec["violations"] == []
    assert rec["session_deaths"] == []
    assert rec["dropped"] == 0
    assert rec["sessions"] == 2 and rec["replicas"] == 2
    f = rec["frames"]
    assert f["ok"] + f["rejected"] + f["errors"] == f["sent"]
    assert f["errors"] == 0
    assert f["seeded"] >= 1, "the stream rode its seed"
    assert f["reseeded"] >= 1, "a kill window forced a re-seed"
    assert rec["reseeds"] >= 1
    # Both kill windows armed and disarmed on schedule.
    for site, t0, t1 in (("kill_replica:0", 1.0, 2.5),
                         ("kill_replica:1", 3.5, 5.0)):
        assert rec["faults"][site] == [
            {"t_s": t0, "action": "arm"}, {"t_s": t1, "action": "disarm"},
        ]
    # Every stream survived to a clean close with its counters.
    assert len(rec["session_close"]) == 2
    assert all(cs["frames"] >= 1 for cs in rec["session_close"])
    # One replica is not a streaming fleet: there must be a survivor
    # to re-seed on.
    with pytest.raises(SystemExit):
        chaos_serving.main(["--session_stream", "--replicas", "1",
                            "--synthetic", "96x128",
                            "--fault", "kill_replica:0@0.1-0.2"],
                           model=tiny_serving_model)


def test_autotune_cli_emits_one_json_line(tmp_path, capsys, monkeypatch):
    """tools/autotune_consensus.py stdout contract (ISSUE 3): run
    in-process with the fake timer (no device dial, no compiles) and a
    tmp cache; ONE stdout JSON line with the best-plan metric, and the
    winner persisted to the cache file."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import autotune_consensus
    from ncnet_tpu.ops import autotune

    cache = tmp_path / "cache.json"
    monkeypatch.setenv("NCNET_AUTOTUNE_FAKE_TIMER", "1")
    monkeypatch.setenv("NCNET_STRATEGY_CACHE", str(cache))
    for k in autotune.PLAN_ENV_KEYS:
        monkeypatch.delenv(k, raising=False)
    rc = autotune_consensus.main([
        "--shape", "1,1,6,5,7,6", "--dtype", "float32",
        "--kernel_sizes", "3", "3", "--channels", "16", "1",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "consensus_autotune_best_ms"
    assert rec["unit"] == "ms"
    assert rec["value"] > 0
    assert rec["backend"] == "fake"
    assert rec["measured"] == rec["candidates"] and rec["failed"] == 0
    assert rec["cache_path"] == str(cache)
    # The winner round-trips: the cache now resolves for this signature.
    import jax

    from ncnet_tpu.ops.conv4d import neigh_consensus_init

    params = neigh_consensus_init(jax.random.PRNGKey(0), (3, 3), (16, 1))
    looked = autotune.lookup_plan((1, 1, 6, 5, 7, 6), "float32", params,
                                  symmetric=True)
    assert looked is not None
    assert autotune.plan_key(looked) == autotune.plan_key(rec["plan"])


def test_traceagg_on_committed_round2_trace():
    """traceagg ground truth against the committed round-2 device trace:
    whole-step totals and the stage rollup must reproduce the numbers in
    docs/NEXT.md's round-3 attribution table (backbone ~174 ms/step for
    the double pass, consensus ~110, corr+pool ~10-15)."""
    from ncnet_tpu.utils.traceagg import aggregate, stage_rollup

    agg = aggregate(os.path.join(REPO, "docs/tpu_r02/trace"), steps=2)
    assert agg is not None
    assert 250 < agg["total_ms"] < 350
    assert 0.05 < agg["mfu"] < 0.12
    assert 0.3 < agg["hbm_frac"] < 0.5
    stages = stage_rollup(agg)
    assert 150 < stages["backbone"]["ms"] < 200
    assert 90 < stages["consensus"]["ms"] < 125
    assert 5 < stages["corr_pool"]["ms"] < 20
    for s in stages.values():
        for k in ("ms", "tflops", "gbs", "mfu", "hbm_frac"):
            assert k in s


def test_traceagg_on_committed_round5_trace():
    """Self-time ground truth against the committed round-5 bb5 capture
    (the REAL nested-`while` artifact, not the synthetic fixture): one
    op line, attributed total == the 0.962 s op-line span (not the
    1.89 s flat sum), and the honest stage split that closed VERDICT r4
    item 2 — consensus 502 / backbone 243 / corr_pool 92 / extract 64 /
    other 62 ms per 10-pair block (docs/NEXT.md round-5 ledger)."""
    from ncnet_tpu.utils.traceagg import aggregate, stage_rollup

    agg = aggregate(os.path.join(REPO, "docs/tpu_r05/bench_trace"),
                    steps=1)
    assert agg is not None
    assert agg["op_lines"] == 1
    assert 950 < agg["total_ms"] < 975
    stages = stage_rollup(agg)
    assert 490 < stages["consensus"]["ms"] < 515
    assert 230 < stages["backbone"]["ms"] < 255
    assert 85 < stages["corr_pool"]["ms"] < 100
    assert 55 < stages["extract"]["ms"] < 75
    # The fabricated-"other" regression guard: flat summing booked the
    # scan container's whole body here (993 ms); self time leaves only
    # real glue.
    assert stages["other"]["ms"] < 80


def test_traceagg_returns_none_for_cpu_trace(tmp_path):
    """A CPU trace has no accelerator op metadata: aggregate must return
    None (bench emits util=null), never fabricated zeros."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x.T).sum()

    x = jnp.ones((64, 64))
    f(x).block_until_ready()
    with jax.profiler.trace(str(tmp_path)):
        f(x).block_until_ready()
    from ncnet_tpu.utils.traceagg import aggregate

    assert aggregate(str(tmp_path), steps=1) is None


def test_traceagg_excludes_umbrella_rows(tmp_path):
    """The session_1128 capture artifact (docs/NEXT.md): a converter that
    attaches long_name/cost args to the "XLA Modules" umbrella line must
    not double the attributed total — the umbrella spans the very ops it
    contains and its sourceless share masquerades as an "other" stage
    equal to the whole wall. op_tids pins aggregation to the op line."""
    import gzip
    import json

    from ncnet_tpu.utils.traceagg import aggregate, stage_rollup

    d = tmp_path / "plugins" / "profile" / "2026_08_02_00_00_00"
    d.mkdir(parents=True)
    meta = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]
    op = {"ph": "X", "pid": 3, "tid": 3, "ts": 0, "dur": 100.0,
          "name": "fusion.1",
          "args": {"long_name": "fusion.1", "model_flops": 1000,
                   "bytes_accessed": 2000, "hlo_category": "fusion",
                   "source": "ncnet_tpu/ops/conv4d.py"}}
    op2 = dict(op, ts=100, dur=60.0, name="conv.2",
               args=dict(op["args"], long_name="conv.2",
                         source="ncnet_tpu/models/backbone.py"))
    # The umbrella: ONE event spanning both ops, same metadata shape,
    # no ncnet source file.
    umbrella = {"ph": "X", "pid": 3, "tid": 2, "ts": 0, "dur": 160.0,
                "name": "jit_block", "args": {"long_name": "jit_block",
                "model_flops": 2000, "bytes_accessed": 4000,
                "hlo_category": "module"}}
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": meta + [op, op2, umbrella]}, f)

    agg = aggregate(str(tmp_path), steps=1)
    assert agg is not None
    assert abs(agg["total_ms"] - 0.160) < 1e-9  # ops only, not 0.320
    stages = stage_rollup(agg)
    assert "other" not in stages
    assert set(stages) == {"consensus", "backbone"}


def test_traceagg_self_time_for_nested_containers(tmp_path):
    """The round-5 capture artifact: the op line nests flame-graph
    style — a `while` container (the bb5 scan block, source bench.py)
    spans the per-iteration body ops emitted on the SAME tid and carries
    device_duration/model_flops for its whole body. Summing events flat
    double-counts every looped op (observed: Σdur 1.89 s over a 0.96 s
    span) and books the body's cost a second time under the container's
    sourceless "other" stage. aggregate must charge each event only its
    SELF share (duration/flops/bytes minus same-line children)."""
    import gzip
    import json

    from ncnet_tpu.utils.traceagg import aggregate, stage_rollup

    d = tmp_path / "plugins" / "profile" / "2026_08_02_00_00_00"
    d.mkdir(parents=True)
    meta = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        # An "Async XLA Ops" line must NOT count as a second op line
        # (substring match made op_lines=2 on a single-core capture).
        {"ph": "M", "pid": 3, "tid": 4, "name": "thread_name",
         "args": {"name": "Async XLA Ops"}},
    ]
    body = {"ph": "X", "pid": 3, "tid": 3, "ts": 10, "dur": 80.0,
            "name": "fusion.7",
            "args": {"long_name": "fusion.7", "model_flops": 800,
                     "bytes_accessed": 1600, "hlo_category": "fusion",
                     "source": "ncnet_tpu/models/backbone.py"}}
    body2 = dict(body, ts=95, dur=40.0, name="fusion.8",
                 args=dict(body["args"], long_name="fusion.8",
                           model_flops=400, bytes_accessed=800))
    # The container: spans both body ops on the same line, metadata
    # totals its body, source is the scan wrapper (stage "other").
    outer = {"ph": "X", "pid": 3, "tid": 3, "ts": 0, "dur": 160.0,
             "name": "while.5",
             "args": {"long_name": "while.5", "model_flops": 1200,
                      "bytes_accessed": 2400, "hlo_category": "while",
                      "source": "bench.py"}}
    tail = dict(body, ts=170, dur=40.0, name="conv.9",
                args=dict(body["args"], long_name="conv.9",
                          model_flops=100, bytes_accessed=200,
                          source="ncnet_tpu/ops/conv4d.py"))
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": meta + [outer, body, body2, tail]}, f)

    agg = aggregate(str(tmp_path), steps=1)
    assert agg is not None
    assert agg["op_lines"] == 1  # Async line excluded
    # Top-level coverage: container 160 + tail 40, NOT 160+80+40+40.
    assert abs(agg["total_ms"] - 0.200) < 1e-9
    # FLOPs de-duplicated the same way: the bodies keep their 800+400
    # under their OWN stages, the container's self share is
    # 1200-800-400 = 0, and the tail adds 100 — total 1300, not
    # 1200+800+400+100.
    assert abs(agg["total_gflops"] * 1e9 - 1300.0) < 1e-6
    stages = stage_rollup(agg)
    # Container self time = 160 - 120 = 40 -> "other"; body ops keep
    # their own stages at full duration.
    assert abs(stages["backbone"]["ms"] - 0.120) < 1e-9
    assert abs(stages["other"]["ms"] - 0.040) < 1e-9
    assert abs(stages["consensus"]["ms"] - 0.040) < 1e-9


def test_bulk_match_emits_one_json_line(tmp_path, capsys):
    """tools/bulk_match.py stdout contract (ISSUE 8): a synthetic echo
    corpus run prints ONE JSON line with the throughput metric and the
    completion/health counters tools/bench_trend.py passes through."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bulk_match

    rc = bulk_match.main([
        "--out_dir", str(tmp_path / "run"), "--engine", "echo",
        "--synthetic", "8@32x48", "--replicas", "2", "--max_batch", "2",
        "--checkpoint_every", "4",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "bulk_match_pairs_per_s"
    assert rec["unit"] == "pairs/s"
    assert rec["value"] > 0
    for key in ("pairs_done", "pairs_this_run", "pairs_s", "quarantined",
                "retries", "resumes", "duration_s", "ledger"):
        assert key in rec, rec
    assert rec["pairs_done"] == 8
    assert rec["resumes"] == 0


def test_bulk_match_chaos_contract(tmp_path, capsys):
    """`--chaos` gate contract (ISSUE 8): two SIGKILL-resume legs plus
    a faulted final leg over the default synthetic corpus; rc 0 only
    when the audit finds zero lost/duplicated pairs and every poison
    pair quarantined — and ONE stdout JSON line says so."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bulk_match

    rc = bulk_match.main(["--chaos", "--out_dir", str(tmp_path / "run")])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "bulk_chaos_survival"
    assert rec["unit"] == "frac"
    assert rc == 0, f"chaos gate failed: {rec}"
    assert rec["value"] == 1.0
    assert rec["lost"] == 0 and rec["duplicates"] == 0
    assert rec["poison_quarantined"] == rec["poison_expected"] == 3
    assert rec["wrongly_quarantined"] == 0
    assert rec["kills"] == 2
    assert rec["resumes"] >= 2


def test_ncnet_lint_emits_one_json_line(capsys):
    """tools/ncnet_lint.py stdout contract (ISSUE 10): the full-repo
    lint, run in-process, prints ONE JSON line with the findings/new
    counts and the rule list, and exits 0 on the clean repo."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import ncnet_lint

    rc = ncnet_lint.main([])
    assert rc == 0, capsys.readouterr().err
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("findings", "new", "rules", "files", "suppressed",
                "duration_s"):
        assert key in rec, rec
    assert rec["new"] == 0
    assert set(rec["rules"]) == {
        "bare-print", "failpoint-docs", "lock-order", "metrics-docs",
        "recompile-hazard", "shared-state-race", "trace-purity",
    }
    # Unknown rules are a usage error (rc 2), not a silent pass.
    assert ncnet_lint.main(["--rule", "nope"]) == 2
    capsys.readouterr()


def test_ncnet_lint_nonzero_on_seeded_fixtures(tmp_path, capsys):
    """ISSUE 10 acceptance: the tool (not just the engine) exits
    nonzero on each seeded violation class, driven through --root."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import textwrap

    import ncnet_lint

    fixtures = {
        "trace-purity": ("ncnet_tpu/bad.py", """
            import time

            import jax


            @jax.jit
            def step(x):
                return x + time.time()
        """),
        "lock-order": ("ncnet_tpu/serving/bad.py", """
            import threading


            class A:
                def __init__(self):
                    self._l1 = threading.Lock()
                    self._l2 = threading.Lock()

                def f(self):
                    with self._l1:
                        with self._l2:
                            pass

                def g(self):
                    with self._l2:
                        with self._l1:
                            pass
        """),
        "recompile-hazard": ("ncnet_tpu/bad.py", """
            def f(h, w):
                bucket_key = [h, w]
                return bucket_key
        """),
        "bare-print": ("ncnet_tpu/bad.py", """
            def f(x):
                print("x", x)
        """),
    }
    for rule, (rel, src) in fixtures.items():
        root = tmp_path / rule
        path = root / rel
        path.parent.mkdir(parents=True)
        path.write_text(textwrap.dedent(src))
        rc = ncnet_lint.main(["--root", str(root), "--rule", rule])
        err = capsys.readouterr()
        assert rc == 1, f"{rule} fixture should fail the lint: {err.err}"
        rec = json.loads(err.out.strip())
        assert rec["new"] >= 1, (rule, rec)


def test_trace_export_selftest_emits_one_json_line():
    """tools/trace_export.py --selftest stdout contract: the multi-
    runlog join verification (synthetic client + skewed server logs)
    prints ONE JSON line and exits 0 — the shape ci_gate's optional
    --with-trace-join check records."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         "--selftest"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "trace_export_selftest"
    assert rec["ok"] is True
    for key in ("single_tree", "skew_recovered", "nested",
                "remote_marked", "clock_offset_s"):
        assert key in rec, rec


def test_bench_trend_passes_quality_fields_through(tmp_path, capsys):
    """tools/bench_trend.py forwards the quality-observatory fields
    (ISSUE 14): a throughput trend earned by walking tenants down QoS
    rungs is only honest next to the measured shadow agreement and the
    drift state that licensed it (tools/quality_report.py)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_trend

    rec = {"n": 1, "cmd": "bench", "rc": 0,
           "parsed": {"metric": "serving_match_throughput_rps",
                      "value": 24.0, "unit": "req/s",
                      "shadow_agreement": 0.97,
                      "quality_drift_psi": 0.04}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(rec, fh)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["metric"] == "serving_match_throughput_rps"
    assert report["shadow_agreement"] == 0.97
    assert report["quality_drift_psi"] == 0.04


def test_bench_trend_passes_consensus_plan_fields_through(tmp_path,
                                                          capsys):
    """tools/bench_trend.py forwards the algebraic-arm fields (ISSUE
    18): a consensus trend won by a CP-truncated or spectral plan is
    only honest next to the plan kind/rank and the measured
    agreement-vs-dense."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_trend

    rec = {"n": 1, "cmd": "bench", "rc": 0,
           "parsed": {"metric": "match_pairs_per_s",
                      "value": 12.5, "unit": "pairs/s",
                      "consensus_plan_kind": "cp",
                      "cp_rank": 8,
                      "cp_agreement": 0.93}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(rec, fh)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["consensus_plan_kind"] == "cp"
    assert report["cp_rank"] == 8
    assert report["cp_agreement"] == 0.93


def test_chaos_train_emits_one_json_verdict_line(tmp_path):
    """tools/chaos_train.py stdout contract (ISSUE 20): the elastic
    chaos gate prints ONE JSON line carrying the full verdict — every
    acceptance check named, the ledger audit, the strict-curve gate —
    and exits 0 iff all of them hold. Tiny deterministic config: 2
    hosts, failpoint-armed victim death at its 3rd lease renewal."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_train.py"),
         "--hosts", "2", "--epochs", "2", "--steps", "20",
         "--batch", "8", "--step-s", "0.04", "--save-interval", "5",
         "--lease-ttl-s", "0.5", "--check-interval-s", "0.08",
         "--kill", "failpoint", "--kill-after-renewals", "2",
         "--resume-budget-steps", "40", "--dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO)
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "chaos_train"
    assert res.returncode == 0, (rec, res.stderr[-2000:])
    assert rec["ok"] is True
    assert rec["kill_mode"] == "failpoint"
    assert rec["killed"] not in rec["live_hosts"]
    assert rec["generation"] >= 2
    assert rec["resumes"] >= 1
    for check, passed in rec["checks"].items():
        assert passed, (check, rec)
    # The ledger audit is the headline: no step of the final curve may
    # go untrained by every generation.
    assert rec["ledger_ok"] is True
    assert rec["strict_ok"] is True


@pytest.mark.slow
def test_bench_train_hosts_emits_scaling_line(tmp_path):
    """tools/bench_train.py --hosts stdout contract (ISSUE 20): the
    elastic scaling mode prints ONE JSON line with the efficiency
    headline, the lease-overhead share (< 2% acceptance) and the
    resume count, and never imports jax in the parent."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_train.py"),
         "--hosts", "2", "--batch", "8", "--elastic-steps", "16"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "train_elastic_scaling"
    assert rec["unit"] == "scaling_efficiency"
    assert rec["hosts"] == 2
    assert rec["value"] == rec["scaling_efficiency"] > 0
    assert rec["lease_overhead_frac"] < 0.02
    assert rec["elastic_resumes"] == 0  # no-kill fleets must not churn
    assert rec["synthetic"] is True


def test_bench_trend_passes_elastic_fields_through(tmp_path, capsys):
    """tools/bench_trend.py forwards the elastic-scaling fields (ISSUE
    20): an efficiency trend is only comparable at one host count, and
    a number earned mid-eviction-recovery is not steady-state."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_trend

    rec = {"n": 1, "cmd": "bench", "rc": 0,
           "parsed": {"metric": "train_elastic_scaling",
                      "value": 0.97, "unit": "scaling_efficiency",
                      "hosts": 3, "scaling_efficiency": 0.97,
                      "elastic_resumes": 0}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(rec, fh)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["hosts"] == 3
    assert report["scaling_efficiency"] == 0.97
    assert report["elastic_resumes"] == 0
