"""bench.py output contract: exactly one JSON line with the driver's keys.

The round driver records bench.py stdout as the benchmark result; a stray
print or a changed key silently breaks the recording. Runs the real bench
end to end on CPU at a tiny smoke size.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_emits_one_json_line():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        NCNET_BENCH_SMOKE_SIZE="96",
        NCNET_BENCH_DIAL_TIMEOUT="60",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["metric"].startswith("inloc_dense_match_pairs_per_s_per_chip")
    assert rec["value"] > 0
