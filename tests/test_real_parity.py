"""tools/real_parity.py offline: the fetch->convert->eval->compare path on
a real torch-serialized surrogate checkpoint + synthetic dataset (the
committed fallback while the published weights are unfetchable —
VERDICT r3 item 7b)."""

import json
import os
import shutil
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.mark.slow
def test_real_parity_runner_on_surrogate(tmp_path, capsys):
    from tests.test_evals_data import _write_synthetic_dataset
    from tests.test_pth_tar_surrogate import (
        _sequential_resnet_keys,
        make_reference_pth_tar,
        make_resnet_state_dict,
    )
    import real_parity

    # Surrogate reference checkpoint in the exact published layout (tiny
    # consensus so CPU eval stays fast; arch travels inside the file).
    named_sd = make_resnet_state_dict("resnet101", stages=3, seed=3)
    pth = tmp_path / "ncnet_surrogate.pth.tar"
    make_reference_pth_tar(
        pth, _sequential_resnet_keys(named_sd), (3,), (1,)
    )

    root = str(tmp_path / "pf")
    os.makedirs(root)
    _write_synthetic_dataset(root, n_pairs=4, size=64)
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(csv_dir)
    shutil.copy(os.path.join(root, "eval.csv"),
                os.path.join(csv_dir, "test_pairs.csv"))

    rc = real_parity.main([
        "--suite", "pfpascal",
        "--pth", str(pth),
        "--dataset_path", root,
        "--expected_pck", "-1",  # surrogate: no published number to match
        "--image_size", "64",
        "--batch_size", "2",
        "--num_workers", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "pf_pascal_pck_at_0.1"
    assert rec["n_pairs"] == 4
    assert 0.0 <= rec["value"] <= 1.0
    assert "parity" not in rec

    # Second run reuses the existing conversion (idempotent).
    rc = real_parity.main([
        "--suite", "pfpascal",
        "--pth", str(pth),
        "--dataset_path", root,
        "--expected_pck", "-1",
        "--image_size", "64",
        "--batch_size", "2",
        "--num_workers", "2",
    ])
    assert rc == 0
    assert "using existing conversion" in capsys.readouterr().out


def test_real_parity_records_failed_fetch(tmp_path, capsys, monkeypatch):
    """A missing .pth with no egress exits 3 and echoes the fetch failure
    verbatim (the evidence trail). Hermetic: a stub download.sh stands in
    for wget so the test never touches the network."""
    import real_parity

    tm = tmp_path / "trained_models"
    tm.mkdir()
    (tm / "download.sh").write_text(
        "#!/bin/sh\n"
        "echo \"wget: unable to resolve host address 'www.di.ens.fr'\" >&2\n"
        "exit 4\n"
    )
    monkeypatch.setattr(real_parity, "REPO", str(tmp_path))
    with pytest.raises(SystemExit) as ei:
        real_parity.main([
            "--pth", str(tm / "missing.pth.tar"),
            "--dataset_path", str(tmp_path),
            "--expected_pck", "-1",
        ])
    assert ei.value.code == 3
    out = capsys.readouterr().out
    assert "unable to resolve host" in out
    assert "FETCH FAILED" in out


def _surrogate_pth(tmp_path, seed=3):
    from tests.test_pth_tar_surrogate import (
        _sequential_resnet_keys,
        make_reference_pth_tar,
        make_resnet_state_dict,
    )

    named_sd = make_resnet_state_dict("resnet101", stages=3, seed=seed)
    pth = tmp_path / "ncnet_surrogate.pth.tar"
    make_reference_pth_tar(
        pth, _sequential_resnet_keys(named_sd), (3,), (1,)
    )
    return pth


@pytest.mark.slow
def test_real_parity_willow_suite(tmp_path, capsys):
    """pfwillow suite on a staged Willow-layout dataset: report-only (no
    gate), bbox PCK in [0, 1]."""
    import csv as csvmod

    from PIL import Image

    import real_parity

    pth = _surrogate_pth(tmp_path)
    rng = np.random.default_rng(1)
    root = tmp_path / "willow"
    (root / "images").mkdir(parents=True)
    names = []
    for i in range(4):
        n = f"images/w{i}.png"
        Image.fromarray(
            (rng.random((60, 80, 3)) * 255).astype("uint8")
        ).save(root / n)
        names.append(n)
    px = ";".join(str(v) for v in np.linspace(8, 70, 10))
    py = ";".join(str(v) for v in np.linspace(6, 52, 10))
    with open(root / "test_pairs.csv", "w", newline="") as f:
        w = csvmod.writer(f)
        w.writerow(["imageA", "imageB", "XA", "YA", "XB", "YB"])
        for i in range(0, 4, 2):
            w.writerow([names[i], names[i + 1], px, py, px, py])

    rc = real_parity.main([
        "--suite", "pfwillow",
        "--pth", str(pth),
        "--willow_dataset_path", str(root),
        "--image_size", "64", "--batch_size", "2", "--num_workers", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["metric"] == "pf_willow_pck_at_0.1"
    assert rec["suite"] == "pfwillow"
    assert 0.0 <= rec["value"] <= 1.0
    assert "parity" not in rec  # report-only


@pytest.mark.slow
def test_real_parity_tss_suite(tmp_path, capsys):
    """tss suite: flows written AND scored against staged GT .flo (mean
    EPE + flow-PCK fields present)."""
    import csv as csvmod

    from PIL import Image

    import real_parity
    from ncnet_tpu.geometry.flow_io import write_flo_file

    pth = _surrogate_pth(tmp_path)
    rng = np.random.default_rng(0)
    root = tmp_path / "tss"
    rows = []
    # pair3 exercises the flip_img_A=1 scoring path (prediction
    # re-indexed from the mirrored source grid before GT comparison).
    for pair, flip in [("pair1", 0), ("pair2", 0), ("pair3", 1)]:
        d = root / pair
        d.mkdir(parents=True)
        for name in ["image1.png", "image2.png"]:
            Image.fromarray(
                (rng.random((48, 64, 3)) * 255).astype("uint8")
            ).save(d / name)
        # GT flow at the source resolution: zero flow (self-consistent
        # fixture; the surrogate net scores whatever it scores).
        write_flo_file(np.zeros((48, 64, 2), np.float32),
                       str(d / "flow1.flo"))
        rows.append([f"{pair}/image1.png", f"{pair}/image2.png", 1, flip,
                     "car"])
    with open(root / "test_pairs.csv", "w", newline="") as f:
        w = csvmod.writer(f)
        w.writerow(["source", "target", "flow_direction", "flip",
                    "category"])
        w.writerows(rows)

    rc = real_parity.main([
        "--suite", "tss",
        "--pth", str(pth),
        "--tss_dataset_path", str(root),
        "--flow_output_dir", str(tmp_path / "flows"),
        "--image_size", "64", "--batch_size", "2", "--num_workers", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["metric"] == "tss_flow"
    assert rec["n_pairs"] == 3
    assert rec["n_scored_vs_gt"] == 3
    assert rec["mean_epe_px"] >= 0.0
    assert 0.0 <= rec["flow_pck_at_0.05"] <= 1.0


def test_real_parity_blocked_suites_record_and_continue(
        tmp_path, capsys, monkeypatch):
    """With no egress and nothing staged, every suite records a verbatim
    'blocked' entry, the runner visits ALL suites, and exits 3."""
    import real_parity

    # Hermetic: REPO points at tmp (no trained_models/download.sh there),
    # so every fetch fails fast without touching the network.
    monkeypatch.setattr(real_parity, "REPO", str(tmp_path))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit) as exc:
        real_parity.main([
            "--pth", str(tmp_path / "absent.pth.tar"),
            "--ivd_pth", str(tmp_path / "absent_ivd.pth.tar"),
            "--dataset_path", str(empty),
            "--willow_dataset_path", str(empty),
            "--tss_dataset_path", str(empty),
            "--inloc_dataset_path", str(empty),
        ])
    assert exc.value.code == 3
    out = capsys.readouterr().out
    recs = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    summary = recs[-1]
    assert summary["summary"] is True
    assert set(summary["suites_blocked"]) == {"pfpascal", "pfwillow",
                                              "tss", "inloc"}
    blocked = [r for r in recs if "blocked" in r]
    assert len(blocked) == 4


@pytest.mark.slow
def test_real_parity_inloc_suite(tmp_path, capsys):
    """inloc suite full chain offline: staged shortlist + query/pano
    images + cutout .mats + GT poses -> match stage -> localization ->
    rate@ fields in the record."""
    from PIL import Image
    from scipy.io import savemat

    import real_parity

    pth = _surrogate_pth(tmp_path)
    rng = np.random.default_rng(0)
    root = tmp_path / "inloc"
    for d in ("query", "pano", "cutouts"):
        (root / d).mkdir(parents=True)
    qnames = ["q0.jpg", "q1.jpg"]
    pnames = ["p0.jpg", "p1.jpg"]
    for n in qnames:
        Image.fromarray((rng.random((96, 128, 3)) * 255).astype("uint8")
                        ).save(root / "query" / n)
    for n in pnames:
        Image.fromarray((rng.random((96, 128, 3)) * 255).astype("uint8")
                        ).save(root / "pano" / n)
    img_list = np.zeros((1, 2), dtype=[("queryname", "O"),
                                       ("topNname", "O")])
    for q, qn in enumerate(qnames):
        img_list[0, q]["queryname"] = qn
        img_list[0, q]["topNname"] = np.array(
            pnames, dtype=object).reshape(1, -1)
    savemat(root / "shortlist.mat", {"ImgList": img_list})
    # Cutout XYZ planes (named <pano>.mat as cli.localize expects).
    ys, xs = np.meshgrid(np.arange(50), np.arange(50), indexing="ij")
    world = np.stack([(xs - 25) * 0.1, (ys - 25) * 0.1,
                      np.full(xs.shape, 6.0)], axis=-1)
    for n in pnames:
        savemat(root / "cutouts" / f"{n}.mat", {"XYZcut": world})
    np.savez(tmp_path / "gt.npz",
             queries=np.array(qnames),
             poses=np.stack([np.eye(3, 4), np.eye(3, 4)]))

    rc = real_parity.main([
        "--suite", "inloc",
        "--ivd_pth", str(pth),
        "--inloc_shortlist", str(root / "shortlist.mat"),
        "--inloc_query_path", str(root / "query"),
        "--inloc_pano_path", str(root / "pano"),
        "--inloc_cutout_path", str(root / "cutouts"),
        "--inloc_transform_path", "none",
        "--inloc_matches_dir", str(tmp_path / "matches"),
        "--inloc_gt_poses", str(tmp_path / "gt.npz"),
        "--inloc_image_size", "64",
        "--inloc_n_queries", "2",
        "--inloc_n_panos", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["metric"] == "inloc_localization"
    assert rec["n_queries"] == 2
    assert "rate@0.25m" in rec and "rate@1.0m" in rec
