"""tools/real_parity.py offline: the fetch->convert->eval->compare path on
a real torch-serialized surrogate checkpoint + synthetic dataset (the
committed fallback while the published weights are unfetchable —
VERDICT r3 item 7b)."""

import json
import os
import shutil
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.mark.slow
def test_real_parity_runner_on_surrogate(tmp_path, capsys):
    from tests.test_evals_data import _write_synthetic_dataset
    from tests.test_pth_tar_surrogate import (
        _sequential_resnet_keys,
        make_reference_pth_tar,
        make_resnet_state_dict,
    )
    import real_parity

    # Surrogate reference checkpoint in the exact published layout (tiny
    # consensus so CPU eval stays fast; arch travels inside the file).
    named_sd = make_resnet_state_dict("resnet101", stages=3, seed=3)
    pth = tmp_path / "ncnet_surrogate.pth.tar"
    make_reference_pth_tar(
        pth, _sequential_resnet_keys(named_sd), (3,), (1,)
    )

    root = str(tmp_path / "pf")
    os.makedirs(root)
    _write_synthetic_dataset(root, n_pairs=4, size=64)
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(csv_dir)
    shutil.copy(os.path.join(root, "eval.csv"),
                os.path.join(csv_dir, "test_pairs.csv"))

    rc = real_parity.main([
        "--pth", str(pth),
        "--dataset_path", root,
        "--expected_pck", "-1",  # surrogate: no published number to match
        "--image_size", "64",
        "--batch_size", "2",
        "--num_workers", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "pf_pascal_pck_at_0.1"
    assert rec["n_pairs"] == 4
    assert 0.0 <= rec["value"] <= 1.0
    assert "parity" not in rec

    # Second run reuses the existing conversion (idempotent).
    rc = real_parity.main([
        "--pth", str(pth),
        "--dataset_path", root,
        "--expected_pck", "-1",
        "--image_size", "64",
        "--batch_size", "2",
        "--num_workers", "2",
    ])
    assert rc == 0
    assert "using existing conversion" in capsys.readouterr().out


def test_real_parity_records_failed_fetch(tmp_path, capsys, monkeypatch):
    """A missing .pth with no egress exits 3 and echoes the fetch failure
    verbatim (the evidence trail). Hermetic: a stub download.sh stands in
    for wget so the test never touches the network."""
    import real_parity

    tm = tmp_path / "trained_models"
    tm.mkdir()
    (tm / "download.sh").write_text(
        "#!/bin/sh\n"
        "echo \"wget: unable to resolve host address 'www.di.ens.fr'\" >&2\n"
        "exit 4\n"
    )
    monkeypatch.setattr(real_parity, "REPO", str(tmp_path))
    with pytest.raises(SystemExit) as ei:
        real_parity.main([
            "--pth", str(tm / "missing.pth.tar"),
            "--dataset_path", str(tmp_path),
            "--expected_pck", "-1",
        ])
    assert ei.value.code == 3
    out = capsys.readouterr().out
    assert "unable to resolve host" in out
    assert "FETCH FAILED" in out
