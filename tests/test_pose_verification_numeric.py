"""Numerical (not ordinal) pin of the dense pose-verification stage.

Cross-checks `pose_verification_score` against a HAND-COMPUTED trace of the
reference recipe (lib_matlab/parfor_nc4d_PV.m:15-34) on a fixture whose
SIFT math is analytic, so the expected numbers hold for vl_phow and for any
correct dense-SIFT implementation alike:

* query = intensity ramp along x, rendered view = ramp along y. Constant
  gradients put ALL descriptor energy into one orientation bin (bin 0 for
  the query, bin 2 for the render — orthogonal orientations are 2 of 8 bins
  apart under any SIFT convention). After the SIFT normalize -> clamp 0.2 ->
  renormalize -> rootSIFT chain, every fully-interior descriptor is exactly
  0.25 on its 16 active components (16 x 0.25^2 = 1), and any two unit-L2
  descriptors with disjoint support are exactly sqrt(2) apart — regardless
  of spatial-window shape, smoothing, or downsample filtering. Hence
  err == sqrt(2) at every frame, median sqrt(2), and
  score = quantile(err, 0.5)^-1 = 1/sqrt(2) (parfor_nc4d_PV.m:34).

Deliberate divergences from vl_phow, which the fixture is invariant to
(documented per VERDICT r1 item 6): single scale (the reference calls
vl_phow with 'sizes' 8 only, so this is cosmetic), box-mean downsample
instead of Matlab imresize antialiasing, soft two-bin orientation
assignment without vl_dsift's Gaussian gradient smoothing, and a
triangular (non-fast-mode) spatial window.
"""

import numpy as np

from ncnet_tpu.localization.dsift import dense_root_sift
from ncnet_tpu.localization.pose_verification import pose_verification_score

H = W = 64          # downsampled render size
DS = 8              # reference dslevel = 8^-1
FOCAL_FULL = DS * 64.0  # -> f = 64 px at the downsampled size


def _cloud_rendering_y_ramp():
    """One 3-D point per downsampled pixel, colored gray = row index, placed
    so K @ [I|0] projects it exactly onto that pixel (z = 1 plane)."""
    f = FOCAL_FULL / DS
    vv, uu = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    x = (uu - W / 2.0) / f
    y = (vv - H / 2.0) / f
    xyz = np.stack([x, y, np.ones_like(x)], axis=-1).reshape(-1, 3)
    gray = vv.astype(np.float64)
    rgb = np.repeat(gray.reshape(-1, 1), 3, axis=1)  # luma weights sum to 1
    return rgb, xyz


P_IDENTITY = np.hstack([np.eye(3), np.zeros((3, 1))])


def test_pv_score_matches_hand_computed_trace():
    rgb, xyz = _cloud_rendering_y_ramp()
    # Full-resolution x-ramp query; the box mean over 8x8 blocks keeps it a
    # ramp along x, so its gradient stays constant.
    query = np.tile(np.arange(W * DS, dtype=np.float64), (H * DS, 1))

    score, err_map = pose_verification_score(
        query, rgb, xyz, P_IDENTITY, focal_length=FOCAL_FULL
    )

    # Hand-computed: every frame errs by exactly sqrt(2) -> score 1/sqrt(2).
    assert err_map is not None
    errs = err_map[np.isfinite(err_map)]
    assert errs.size > 0
    np.testing.assert_allclose(errs, np.sqrt(2.0), atol=1e-4)
    np.testing.assert_allclose(score, 1.0 / np.sqrt(2.0), atol=1e-4)


def test_central_descriptor_components_are_exact():
    """The 16 active components of a fully-interior ramp descriptor are
    exactly 0.25 after the normalize -> clamp -> renormalize -> rootSIFT
    chain (and live in a single orientation bin)."""
    ramp = np.tile(np.arange(W, dtype=np.float64), (H, 1))  # x-ramp
    frames, desc = dense_root_sift(ramp, step=4, bin_size=8)

    center = np.argmin(np.abs(frames - np.array([32, 32])).sum(axis=1))
    d = desc[center].reshape(16, 8)  # [spatial cell, orientation bin]
    np.testing.assert_allclose(d[:, 0], 0.25, atol=1e-5)
    np.testing.assert_allclose(d[:, 1:], 0.0, atol=1e-6)

    # Orthogonal ramp: same energy, two bins over (90 deg = 2 of 8 bins).
    frames_y, desc_y = dense_root_sift(ramp.T, step=4, bin_size=8)
    dy = desc_y[center].reshape(16, 8)
    np.testing.assert_allclose(dy[:, 2], 0.25, atol=1e-5)


def test_pv_identical_images_score_inf():
    """Query whose downsample equals the render exactly: zero descriptor
    error everywhere -> score Inf (Matlab: quantile(0,.5)^-1 = Inf)."""
    rgb, xyz = _cloud_rendering_y_ramp()
    # Constant within each 8x8 block, value = downsampled row index -> the
    # box mean reproduces the render's y-ramp EXACTLY.
    query = np.repeat(np.repeat(
        np.tile(np.arange(H, dtype=np.float64).reshape(-1, 1), (1, W)),
        DS, axis=0), DS, axis=1)

    score, err_map = pose_verification_score(
        query, rgb, xyz, P_IDENTITY, focal_length=FOCAL_FULL
    )
    assert np.isinf(score)


def test_pv_nan_pose_scores_zero():
    """NaN candidate poses short-circuit to score 0 (parfor_nc4d_PV.m:8,55)."""
    rgb, xyz = _cloud_rendering_y_ramp()
    bad = np.full((3, 4), np.nan)
    score, err_map = pose_verification_score(
        np.zeros((H * DS, W * DS)), rgb, xyz, bad, focal_length=FOCAL_FULL
    )
    assert score == 0.0 and err_map is None
