"""The analysis engine itself: seeded violations, suppression, and the
tier-1 repo-wide gate.

Fixture repos are built under tmp_path (a ``ncnet_tpu/`` tree the
:class:`~ncnet_tpu.analysis.engine.Repo` discovers like the real one)
with one known-bad file per rule — the lint must FIRE on each of them
and stay quiet on the clean counterparts, or a refactor could silently
empty a rule and every downstream gate would pass trivially.

The repo-wide test at the bottom is the actual tier-1 gate: all rules
over the real repo, zero new findings, acyclic lock graph, every
baseline entry justified. Fast, ``JAX_PLATFORMS=cpu``-safe, no model
build — it never imports jax.
"""

import json
import os
import textwrap

import pytest

from ncnet_tpu.analysis import (
    Baseline,
    Repo,
    all_rules,
    get_rules,
    run_rules,
)
from ncnet_tpu.analysis.rules.lock_order import build_graph


def make_repo(tmp_path, files):
    """A fixture repo: {relpath: source} -> Repo rooted at tmp_path."""
    for rel, text in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    return Repo(root=str(tmp_path))


def run_rule(repo, rule_id, baseline=None):
    return run_rules(repo, get_rules([rule_id]), baseline)


# -- trace-purity ---------------------------------------------------------


TRACED_BAD = {
    "ncnet_tpu/bad_jit.py": """
        import time
        import numpy as np
        import jax


        @jax.jit
        def step(x):
            t = time.time()
            print("step", t)
            return _helper(x)


        def _helper(x):
            return float(np.asarray(x).mean())


        def body(c, x):
            return c, x.item()


        def run(xs):
            return jax.lax.scan(body, 0, xs)
    """,
}

TRACED_CLEAN = {
    "ncnet_tpu/good_jit.py": """
        import jax
        import jax.numpy as jnp


        @jax.jit
        def step(key, x):
            noise = jax.random.normal(key, x.shape)
            return jnp.asarray(x) + noise


        def host_driver(x):
            # Host-side code may sync freely: not reached from a trace.
            print("result", float(x.mean()))
    """,
}


def test_trace_purity_fires_on_seeded_jit_host_sync(tmp_path):
    repo = make_repo(tmp_path, TRACED_BAD)
    report = run_rule(repo, "trace-purity")
    msgs = {(f.line, f.symbol) for f in report.findings}
    lines = [l.rstrip() for l in (tmp_path / "ncnet_tpu/bad_jit.py")
             .read_text().splitlines()]
    def line_of(snippet):
        return next(i for i, l in enumerate(lines, 1) if snippet in l)
    assert (line_of("time.time()"), "step") in msgs          # direct
    assert (line_of('print("step"'), "step") in msgs         # print
    assert (line_of("float(np.asarray"), "step") in msgs     # via helper
    assert (line_of("x.item()"), "body") in msgs             # scan body
    assert len(report.findings) >= 5  # float + asarray on the same line


def test_trace_purity_quiet_on_pure_traced_code(tmp_path):
    repo = make_repo(tmp_path, TRACED_CLEAN)
    report = run_rule(repo, "trace-purity")
    assert report.findings == [], [f.message for f in report.findings]


# -- lock-order -----------------------------------------------------------


LOCK_CYCLE = {
    "ncnet_tpu/serving/locks.py": """
        import threading


        class A:
            def __init__(self):
                self._l1 = threading.Lock()
                self._l2 = threading.Lock()

            def forward(self):
                with self._l1:
                    with self._l2:
                        pass

            def backward(self):
                with self._l2:
                    self._grab_l1()

            def _grab_l1(self):
                with self._l1:
                    pass
    """,
}

LOCK_SELF = {
    "ncnet_tpu/serving/selflock.py": """
        import threading


        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """,
}

LOCK_CLEAN = {
    "ncnet_tpu/serving/ordered.py": """
        import threading


        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def both(self):
                with self._a:
                    with self._b:
                        pass

            def also_both(self):
                with self._a:
                    self._take_b()

            def _take_b(self):
                with self._b:
                    pass
    """,
}


def _cycle_findings(report):
    return [f for f in report.findings if f.symbol != "docs-block"]


def test_lock_order_detects_two_lock_cycle(tmp_path):
    repo = make_repo(tmp_path, LOCK_CYCLE)
    report = run_rule(repo, "lock-order")
    cycles = _cycle_findings(report)
    assert cycles, "two-lock cycle not detected"
    assert any("A._l1" in f.message and "A._l2" in f.message
               for f in cycles)
    g = build_graph(repo)
    assert ("A._l1", "A._l2") in g.edges  # nested with
    assert ("A._l2", "A._l1") in g.edges  # via call resolution


def test_lock_order_detects_nonreentrant_self_acquire(tmp_path):
    repo = make_repo(tmp_path, LOCK_SELF)
    report = run_rule(repo, "lock-order")
    assert any("re-acquired" in f.message
               for f in _cycle_findings(report))


def test_lock_order_quiet_on_consistent_order(tmp_path):
    repo = make_repo(tmp_path, LOCK_CLEAN)
    report = run_rule(repo, "lock-order")
    assert _cycle_findings(report) == [], (
        [f.message for f in report.findings])
    g = build_graph(repo)
    assert ("C._a", "C._b") in g.edges
    assert not g.cycles()


# -- recompile-hazard -----------------------------------------------------


KEY_BAD = {
    "ncnet_tpu/keys.py": """
        import time


        def submit(x, h, w, d):
            bucket_key = [h, w]
            cache_key = (time.time(), x)
            table_key = tuple(d.items())
            return bucket_key, cache_key, table_key
    """,
}

KEY_CLEAN = {
    "ncnet_tpu/goodkeys.py": """
        import hashlib


        def submit(x, h, w, d):
            bucket_key = (h, w)
            table_key = tuple(sorted(d.items()))
            blob_key = hashlib.sha256(repr([h, w]).encode()).hexdigest()
            return bucket_key, table_key, blob_key
    """,
}

STATIC_BAD = {
    "ncnet_tpu/statics.py": """
        from functools import partial

        import jax


        @partial(jax.jit, static_argnums=(1,))
        def f(x, cfg=[1, 2]):
            return x
    """,
}


def test_recompile_hazard_fires_on_seeded_keys(tmp_path):
    repo = make_repo(tmp_path, KEY_BAD)
    report = run_rule(repo, "recompile-hazard")
    by_symbol = {f.symbol: f.message for f in report.findings}
    assert "unhashable" in by_symbol["bucket_key"]
    assert "nondeterministic time.time" in by_symbol["cache_key"]
    assert "iteration order" in by_symbol["table_key"]


def test_recompile_hazard_quiet_on_sanitized_keys(tmp_path):
    repo = make_repo(tmp_path, KEY_CLEAN)
    report = run_rule(repo, "recompile-hazard")
    assert report.findings == [], [f.message for f in report.findings]


def test_recompile_hazard_flags_unhashable_static_default(tmp_path):
    repo = make_repo(tmp_path, STATIC_BAD)
    report = run_rule(repo, "recompile-hazard")
    assert any("static arg" in f.message and f.symbol == "f"
               for f in report.findings)


# -- bare-print -----------------------------------------------------------


PRINT_FILES = {
    "ncnet_tpu/libmod.py": """
        import sys


        def report(x):
            print("bad", x)
            print("fine", x, file=sys.stderr)
    """,
    "ncnet_tpu/cli/tool.py": """
        def main():
            print("cli stdout is the contract")
    """,
}


def test_bare_print_flags_library_not_cli(tmp_path):
    repo = make_repo(tmp_path, PRINT_FILES)
    report = run_rule(repo, "bare-print")
    paths = [f.path for f in report.findings]
    assert paths == ["ncnet_tpu/libmod.py"], paths


# -- pragma + baseline suppression ---------------------------------------


def test_pragma_suppresses_same_line_and_line_above(tmp_path):
    repo = make_repo(tmp_path, {
        "ncnet_tpu/pragmas.py": """
            def f(x):
                print("same-line")  # ncnet-lint: disable=bare-print
                # ncnet-lint: disable=bare-print
                print("line-above")
                # ncnet-lint: disable=all
                print("disable-all")
                print("still flagged")
        """,
    })
    report = run_rule(repo, "bare-print")
    assert len(report.findings) == 1
    assert report.suppressed == 3
    assert "still flagged" in repo.file("ncnet_tpu/pragmas.py").lines[
        report.findings[0].line - 1]


def test_file_pragma_only_in_header(tmp_path):
    header = make_repo(tmp_path / "hdr", {
        "ncnet_tpu/wholefile.py": """
            # ncnet-lint: disable-file=bare-print
            def f():
                print("a")
                print("b")
        """,
    })
    assert run_rule(header, "bare-print").findings == []
    buried = make_repo(tmp_path / "buried", {
        "ncnet_tpu/late.py": "\n" * 30 + textwrap.dedent("""
            # ncnet-lint: disable-file=bare-print
            def f():
                print("a")
        """),
    })
    assert len(run_rule(buried, "bare-print").findings) == 1


def test_baseline_round_trip(tmp_path):
    repo = make_repo(tmp_path, PRINT_FILES)
    first = run_rule(repo, "bare-print")
    assert first.new and not first.ok
    bl = Baseline.from_findings(first.findings)
    path = str(tmp_path / "baseline.json")
    bl.save(path)
    second = run_rule(repo, "bare-print", Baseline.load(path))
    assert second.ok
    assert len(second.findings) == len(first.findings)  # still counted
    assert second.new == []
    data = json.loads((tmp_path / "baseline.json").read_text())
    assert data["version"] == 1 and data["entries"]


def test_baseline_symbol_match_survives_line_churn(tmp_path):
    bl = Baseline([{"rule": "trace-purity", "path": "ncnet_tpu/x.py",
                    "line": 999, "symbol": "step", "reason": "ok"}])
    from ncnet_tpu.analysis import Finding
    moved = Finding("trace-purity", "ncnet_tpu/x.py", 12, "msg",
                    symbol="step")
    other = Finding("trace-purity", "ncnet_tpu/x.py", 12, "msg",
                    symbol="other")
    assert bl.matches(moved)
    assert not bl.matches(other)


def test_changed_only_selection_cannot_fake_docs_verdicts(tmp_path):
    """full_repo rules must see every file even when a selection narrows
    the per-file set — otherwise --changed-only on an unrelated file
    would report every docs row stale (or none)."""
    repo_all = Repo()
    repo_narrow = Repo(selected=["ncnet_tpu/version.py"])
    full = run_rules(repo_all, get_rules(["metrics-docs"]))
    narrow = run_rules(repo_narrow, get_rules(["metrics-docs"]))
    assert ([f.message for f in full.findings]
            == [f.message for f in narrow.findings])
    # while a per-file rule genuinely narrows:
    assert len(repo_narrow.selected()) <= 1


# -- the tier-1 repo-wide gate -------------------------------------------


def test_repo_passes_full_analysis():
    """The gate: all rules, real repo, zero new findings. A real
    violation must be FIXED (or pragma'd with a justification) — the
    baseline is for deliberate exceptions only."""
    repo = Repo()
    report = run_rules(repo, all_rules(),
                       Baseline.load(Baseline.default_path(repo)))
    assert report.ok, "\n".join(
        f"{f.rule} {f.location()} {f.message}" for f in report.new)


def test_repo_lock_graph_is_acyclic():
    """ISSUE 10 acceptance: the serving+obs+pipeline lock set admits a
    total acquisition order (no deadlock hazard)."""
    g = build_graph(Repo())
    assert g.cycles() == []
    # the graph is non-trivial: the known held-across-call edges exist
    assert ("DeadlineBatcher._cond", "MetricsRegistry._lock") in g.edges
    assert ("MatchEngine._store_lock", "PanoFeatureCache._lock") in g.edges


def test_baseline_entries_are_justified():
    """Every committed baseline entry carries a nonempty reason, and
    none hide serving/ or obs/ findings (ISSUE 10 satellite: zero
    unexplained entries in those trees)."""
    repo = Repo()
    bl = Baseline.load(Baseline.default_path(repo))
    for e in bl.entries:
        assert e.get("reason"), f"baseline entry needs a reason: {e}"
        assert not e.get("path", "").startswith(
            ("ncnet_tpu/serving/", "ncnet_tpu/obs/")), (
            f"serving/obs findings must be fixed or pragma'd in code, "
            f"not baselined: {e}")
