"""tools/train_eval_pipeline.py: train -> checkpoint -> eval -> export
-> reconvert as one run (VERDICT r4 missing #2 offline half)."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_pipeline_end_to_end(tmp_path, capsys):
    path = os.path.join(REPO, "tools", "train_eval_pipeline.py")
    spec = importlib.util.spec_from_file_location("train_eval_pipeline",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    rc = mod.main([
        "--out", str(tmp_path / "run"),
        "--size", "48", "--image_size", "48",
        "--epochs", "1", "--n_train", "8", "--batch_size", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads(
        [l for l in out.splitlines() if l.startswith('{"pipeline"')][-1]
    )
    assert rec["roundtrip_exact"] is True
    # The reconverted checkpoint must score IDENTICALLY — any resize/BN/
    # layout divergence in export->convert would break this.
    assert rec["pck"] == rec["pck_reconverted"]
    assert rec["train_s"] > 0
