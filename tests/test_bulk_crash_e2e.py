"""Process-level crash-resume proof for the bulk pipeline (ISSUE 8).

Each case runs tools/bulk_match.py in a real subprocess with a ``kill``
failpoint armed (``NCNET_FAILPOINTS="site=kill:+N"`` → ``os.kill(...,
SIGKILL)`` at the Nth+1 evaluation), confirms the process actually died
mid-run, resumes it with no faults armed, and asserts the resumed
ledger is **byte-identical** to an uninterrupted reference run over
the same corpus. Kill sites cover the whole commit window:

* ``bulk.commit``      — before a ledger append;
* ``bulk.checkpoint``  — between the checkpoint tmp's fsync and its
  ``os.replace`` (the classic torn-rename window);
* ``bulk.read`` / ``bulk.dispatch`` — mid manifest streaming.

The echo engine keeps each subprocess jax-free (~a second per leg)
while still exercising the real Replica/DeadlineBatcher/dispatcher
stack. Corpus is tier-1 sized; determinism comes from the synth seed
and the digest-based ledger records.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "bulk_match.py")

# Small inflight window + tight checkpoint cadence => many commit and
# checkpoint evaluations, so every +N kill lands mid-run.
RUN_FLAGS = ["--engine", "echo", "--replicas", "2", "--max_inflight",
             "2", "--checkpoint_every", "2", "--shard_size", "4"]


def run_tool(out_dir, manifest=None, synthetic=None, failpoints="",
             expect_kill=False):
    cmd = [sys.executable, TOOL, "--out_dir", str(out_dir)] + RUN_FLAGS
    if manifest:
        cmd += ["--manifest", str(manifest)]
    if synthetic:
        cmd += ["--synthetic", synthetic]
    env = dict(os.environ)
    env.pop("NCNET_FAILPOINTS", None)
    if failpoints:
        env["NCNET_FAILPOINTS"] = failpoints
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120)
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected a SIGKILL death under {failpoints!r}, got "
            f"rc={proc.returncode}\nstderr:\n{proc.stderr}")
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstderr:\n{proc.stderr}")
    return proc


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """One synthesized corpus + the uninterrupted reference ledger."""
    root = tmp_path_factory.mktemp("bulk_e2e")
    ref_dir = root / "ref"
    run_tool(ref_dir, synthetic="10@32x48")
    manifest = ref_dir / "corpus" / "manifest.jsonl"
    ledger = (ref_dir / "ledger.jsonl").read_bytes()
    rows = [json.loads(line) for line in ledger.splitlines()]
    assert [r["row"] for r in rows] == list(range(10))
    return {"root": root, "manifest": manifest, "ledger": ledger}


@pytest.mark.parametrize("spec", [
    "bulk.commit=kill:+1",
    "bulk.checkpoint=kill:+2",
    "bulk.read=kill:+4",
    "bulk.dispatch=kill:+5",
], ids=["commit", "checkpoint-rename", "read", "dispatch"])
def test_sigkill_then_resume_is_byte_identical(corpus, spec):
    site = spec.partition("=")[0].replace(".", "_")
    out = corpus["root"] / f"kill_{site}"
    run_tool(out, manifest=corpus["manifest"], failpoints=spec,
             expect_kill=True)
    killed_bytes = (out / "ledger.jsonl").read_bytes() \
        if (out / "ledger.jsonl").exists() else b""
    assert killed_bytes != corpus["ledger"], (
        "the kill fired too late to interrupt anything — tighten +N")
    proc = run_tool(out, manifest=corpus["manifest"])
    assert (out / "ledger.jsonl").read_bytes() == corpus["ledger"], (
        "resumed ledger differs from the uninterrupted reference")
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["resumes"] == 1
    assert line["quarantined"] == 0
    ck = json.loads((out / "checkpoint.json").read_text())
    assert ck["next_row"] == 10


def test_double_kill_double_resume(corpus):
    """Crash → resume → crash again → resume: the ledger still converges
    byte-identically, and the resume count survives in the checkpoint."""
    out = corpus["root"] / "double"
    run_tool(out, manifest=corpus["manifest"],
             failpoints="bulk.commit=kill:+1", expect_kill=True)
    run_tool(out, manifest=corpus["manifest"],
             failpoints="bulk.commit=kill:+2", expect_kill=True)
    run_tool(out, manifest=corpus["manifest"])
    assert (out / "ledger.jsonl").read_bytes() == corpus["ledger"]
    ck = json.loads((out / "checkpoint.json").read_text())
    assert ck["resumes"] == 2
