"""Training observatory (ncnet_tpu/obs/train_watch.py): per-step
telemetry, the bounded-lag divergence sentinel, heartbeat/watchdog
armor, per-host beacons, and the train_report gate
(docs/OBSERVABILITY.md "Training observatory")."""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ncnet_tpu import obs
from ncnet_tpu.obs import events as obs_events
from ncnet_tpu.obs import train_watch as tw
from ncnet_tpu.obs.metrics import MetricsRegistry
from ncnet_tpu.obs.quality import DriftDetector
from ncnet_tpu.reliability import failpoints

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive(watch, clock, n, *, wait_s=0.01, device_s=0.1, loss=0.5,
           grad_norm=1.0, epoch=1):
    """Run n fake steps through watch.steps/book with known timings."""

    def batches():
        for i in range(n):
            clock.t += wait_s  # the next() wait = data_wait share
            yield {"_indices": np.array([2 * i, 2 * i + 1])}

    for i, batch in watch.steps(batches()):
        clock.t += device_s  # dispatch-to-book = forward_backward share
        watch.book(epoch=epoch, step=i, loss=np.float32(loss),
                   grad_norm=np.float32(grad_norm),
                   update_ratio=np.float32(0.01),
                   batch_ids=batch["_indices"])


# -- per-step telemetry ----------------------------------------------------


def test_step_telemetry_fake_clock():
    clock = FakeClock()
    watch = tw.TrainWatch(policy="skip", lag=1, lr=5e-4, clock=clock,
                          host="hA")
    _drive(watch, clock, 5)
    watch.drain()

    snap = obs.snapshot()
    hists, gauges = snap["histograms"], snap["gauges"]
    assert hists["train.step_time_s"]["count"] == 5
    # Every step is 0.01 wait + 0.1 device: the split histograms carry
    # exactly those shares.
    assert hists["train.data_wait_s"]["sum"] == pytest.approx(0.05)
    assert hists["train.device_s"]["sum"] == pytest.approx(0.5)
    assert hists["train.step_time_s"]["sum"] == pytest.approx(0.55)
    assert snap["counters"]["train.steps"] == 5
    assert gauges["train.lr"] == pytest.approx(5e-4)
    assert gauges["train.loss"] == pytest.approx(0.5)
    assert gauges["train.grad_norm"] == pytest.approx(1.0)
    assert gauges["train.update_ratio"] == pytest.approx(0.01)
    # The per-host beacon: last booked step, replica-labeled.
    assert gauges['train.step_index{replica="hA"}'] == 4.0
    assert watch.divergent_steps == []


def test_step_spans_and_events_land_in_runlog(tmp_path):
    path = str(tmp_path / "runlog-train-unit.jsonl")
    run = obs.init_run("train", path, heartbeat_s=0)
    clock = FakeClock()
    watch = tw.TrainWatch(policy="skip", lag=0, clock=clock)
    _drive(watch, clock, 3)
    watch.close()
    run.close()

    with open(path) as fh:
        records = [json.loads(l) for l in fh]
    roots = [r for r in records
             if r["event"] == "train.step" and r.get("kind") == "span"]
    assert len(roots) == 3
    assert {r["step"] for r in roots} == {0, 1, 2}
    # Each root's trace carries the data_wait/forward_backward/update
    # children — the request-shaped tree trace_export renders.
    for root in roots:
        kids = [r for r in records if r.get("kind") == "span"
                and r.get("trace_id") == root["trace_id"]
                and r.get("parent_id") == root["span_id"]]
        assert {k["event"] for k in kids} == {
            "data_wait", "forward_backward", "update"}
    steps = [r for r in records if r["event"] == "train_step"]
    assert len(steps) == 3
    assert all(np.isfinite(r["loss"]) for r in steps)
    assert all("grad_norm" in r for r in steps)


# -- divergence sentinel ---------------------------------------------------


def test_corrupt_failpoint_one_dump_skip_policy(tmp_path):
    """The acceptance drill: NCNET_FAILPOINTS=train.step=corrupt:x1
    must produce EXACTLY ONE train-divergence dump whose ring names
    the offending step's batch manifest ids, and the run must survive
    under the skip policy."""
    failpoints.configure("train.step=corrupt:x1")
    clock = FakeClock()
    watch = tw.TrainWatch(policy="skip", lag=2, clock=clock,
                          flight_dir=str(tmp_path))
    _drive(watch, clock, 6)
    watch.drain()  # the run survives: every step resolved, no raise

    assert watch.divergent_steps == [(1, 0)]
    dumps = glob.glob(str(tmp_path / "flight-train-divergence-*.jsonl"))
    assert len(dumps) == 1, dumps
    with open(dumps[0]) as fh:
        dumped = [json.loads(l) for l in fh]
    div = [r for r in dumped if r.get("event") == "train_divergence"]
    assert len(div) == 1
    assert div[0]["kind"] == "nonfinite"
    assert div[0]["policy"] == "skip"
    # Step 0's batch rode ids [0, 1] (see _drive) — the dump names it.
    assert div[0]["batch_ids"] == [0, 1]
    ring = div[0]["ring"]
    assert any(e["step"] == 0 and e.get("nonfinite")
               and e["batch_ids"] == [0, 1] for e in ring)


def test_halt_policy_raises_dump_only_records(tmp_path):
    failpoints.configure("train.step=corrupt:x1")
    clock = FakeClock()
    watch = tw.TrainWatch(policy="halt", lag=0, clock=clock,
                          flight_dir=str(tmp_path / "halt"))
    os.makedirs(tmp_path / "halt")
    with pytest.raises(tw.TrainDivergence) as exc:
        _drive(watch, clock, 2)
    assert exc.value.kind == "nonfinite"
    assert (exc.value.epoch, exc.value.step) == (1, 0)

    failpoints.clear()
    failpoints.configure("train.step=corrupt:x1")
    obs.flight.recorder().clear()
    clock2 = FakeClock()
    quiet = tw.TrainWatch(policy="dump-only", lag=0, clock=clock2,
                          flight_dir=str(tmp_path / "dumponly"))
    os.makedirs(tmp_path / "dumponly")
    _drive(quiet, clock2, 3)  # records, never raises
    quiet.drain()
    assert quiet.divergent_steps == [(1, 0)]
    assert glob.glob(str(tmp_path / "dumponly" / "flight-*.jsonl"))


def test_sustained_nan_is_one_episode_one_dump(tmp_path):
    """Every corrupted step is counted, but a sustained NaN run is ONE
    episode: one train_divergence event, one dump — not a dump storm."""
    failpoints.configure("train.step=corrupt:x4")
    clock = FakeClock()
    watch = tw.TrainWatch(policy="dump-only", lag=0, clock=clock,
                          flight_dir=str(tmp_path))
    _drive(watch, clock, 6)
    watch.drain()
    assert len(watch.divergent_steps) == 4
    assert len(glob.glob(str(tmp_path / "flight-train-divergence-*"))) == 1
    reg_snap = obs.snapshot()
    assert reg_snap["counters"]["train.divergence.events"] == 4


def test_grad_norm_drift_triggers_divergence(tmp_path):
    drift = DriftDetector(window=8, threshold=0.25, sustain=2,
                          check_every=4)
    clock = FakeClock()
    watch = tw.TrainWatch(policy="dump-only", lag=0, clock=clock,
                          drift=drift, flight_dir=str(tmp_path))

    def batches(n):
        for _ in range(n):
            clock.t += 0.01
            yield {}

    step = 0
    # Freeze the reference window at grad_norm ~0.01 ...
    for i, _b in watch.steps(batches(8)):
        clock.t += 0.1
        watch.book(epoch=1, step=i, loss=np.float32(0.1),
                   grad_norm=np.float32(0.01))
        step = i
    # ... then a sustained 1000x grad-norm shift: PSI crosses the
    # ladder and the sentinel flags a grad_norm_drift divergence.
    for i, _b in watch.steps(batches(16), start=step + 1):
        clock.t += 0.1
        watch.book(epoch=1, step=i, loss=np.float32(0.1),
                   grad_norm=np.float32(10.0))
    watch.drain()
    assert watch.divergent_steps, "drift never flagged"
    assert obs.snapshot()["gauges"]["train.grad_norm_psi"] > 0.25
    dumps = glob.glob(str(tmp_path / "flight-train-divergence-*"))
    assert len(dumps) == 1
    with open(dumps[0]) as fh:
        div = [json.loads(l) for l in fh
               if "train_divergence" in l][0]
    assert div["kind"] == "grad_norm_drift"


# -- hang armor ------------------------------------------------------------


class FakeWatchdog:
    def __init__(self):
        self.calls = []

    def arm(self, timeout_s):
        self.calls.append(("arm", timeout_s))

    def disarm(self):
        self.calls.append(("disarm", None))

    def stop(self):
        self.calls.append(("stop", None))


def test_watchdog_armed_per_step():
    wd = FakeWatchdog()
    clock = FakeClock()
    watch = tw.TrainWatch(policy="skip", lag=0, clock=clock,
                          step_timeout_s=30.0, watchdog=wd)
    _drive(watch, clock, 3)
    watch.close()
    arms = [c for c in wd.calls if c[0] == "arm"]
    assert len(arms) == 3 and all(t == 30.0 for _, t in arms)
    # Every armed deadline is disarmed by its book() before the next
    # arm — a long epoch never trips the dog, only a hung step does.
    seq = [c[0] for c in wd.calls]
    for i, op in enumerate(seq):
        if op == "arm":
            assert "disarm" in seq[i + 1:], "arm without a later disarm"
    assert seq[-1] == "stop"


def test_heartbeat_flags_hung_step(tmp_path):
    """A device step that stops making progress shows up as a stall
    episode: stall event + a flight dump next to the runlog — the
    soft armor around the step loop (the Watchdog is the hard one)."""
    clock = FakeClock()
    run = obs_events.RunLog(str(tmp_path / "runlog-train-hb.jsonl"),
                            "train", clock=clock)
    hb = obs.Heartbeat(run, interval_s=10.0, stall_after_s=25.0,
                       clock=clock)
    run.event("train_step", step=0, loss=0.1)  # healthy progress
    clock.t = 10.0
    assert hb.beat_once()["stalled"] is False
    clock.t = 40.0  # the next step hung: no progress for 30s
    assert hb.beat_once()["stalled"] is True
    assert hb.stalls == 1
    run.close()
    with open(run.path) as fh:
        records = [json.loads(l) for l in fh]
    assert any(r["event"] == "stall" for r in records)
    assert glob.glob(str(tmp_path / "flight-stall-*.jsonl"))


# -- per-host beacons ------------------------------------------------------


def test_two_host_beacon_merge_shows_lag():
    """Two processes' registries, merged the way fleet_status merges
    scrapes: the straggler's train.host_behind_steps is positive."""
    r0, r1 = MetricsRegistry(), MetricsRegistry()
    clock = FakeClock()
    w0 = tw.TrainWatch(registry=r0, host="host0", clock=clock)
    w1 = tw.TrainWatch(registry=r1, host="host1", clock=clock)
    w0.publish_beacon(100)
    w1.publish_beacon(92)

    view = obs.aggregate.merge_snapshots([r0.snapshot(), r1.snapshot()])
    out = MetricsRegistry()
    behind = tw.publish_host_lag(view, registry=out)
    assert behind == {"host0": 0.0, "host1": 8.0}
    gauges = out.snapshot()["gauges"]
    assert gauges['train.host_behind_steps{replica="host1"}'] == 8.0
    assert gauges['train.host_behind_steps{replica="host0"}'] == 0.0
    # No beacons -> no lag rows, not a crash.
    assert tw.publish_host_lag({"gauges": {}}, registry=out) == {}


# -- checkpoint health -----------------------------------------------------


def test_checkpoint_health_bookkeeping(tmp_path):
    ck = tmp_path / "run" / "epoch_1"
    ck.mkdir(parents=True)
    (ck / "params.npz").write_bytes(b"x" * 1000)
    (ck / "meta.json").write_text("{}")
    tw.book_checkpoint_save(str(ck), str(tmp_path / "run"), 0.25)
    tw.book_checkpoint_load(str(ck), 0.5)
    snap = obs.snapshot()
    assert snap["histograms"]["train.ckpt.save_s"]["sum"] == \
        pytest.approx(0.25)
    assert snap["histograms"]["train.ckpt.load_s"]["sum"] == \
        pytest.approx(0.5)
    assert snap["gauges"]["train.ckpt.bytes"] >= 1000
    assert snap["gauges"]["train.ckpt.chain_depth"] == 1.0


# -- train_report ----------------------------------------------------------


def _make_runlog(tmp_path, final_loss):
    """A miniature but schema-true training runlog: step events, span
    trees, an epoch record, and a final metrics snapshot."""
    path = str(tmp_path / "runlog-train-rep.jsonl")
    run = obs.init_run("train", path, heartbeat_s=0)
    clock = FakeClock()
    watch = tw.TrainWatch(policy="skip", lag=0, clock=clock)
    _drive(watch, clock, 4, loss=final_loss)
    watch.close()
    obs.event("epoch", epoch=1, train_loss=final_loss, val_loss=0.0,
              pairs_per_s=8.0, dur_s=0.5)
    run.close()
    return path


def test_train_report_strict_green_on_reference(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import train_report

    path = _make_runlog(tmp_path, final_loss=0.001)
    rc = train_report.main([path, "--strict"])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    rec = json.loads(out[0])
    assert rc == 0 and rec["ok"] is True
    assert rec["steps"] == 4 and rec["spans"] == 4
    assert rec["divergence_events"] == 0
    assert all(rec["strict"].values()), rec["strict"]
    assert rec["step_time_hist_count"] == 4
    assert rec["grad_norm_points"] == 4


def test_train_report_strict_red_on_worse_curve(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import train_report

    # Final loss 1.0 sits far above the committed reference's
    # 0.0 +/- 0.05 margin: the gate must go red, and must SAY why.
    path = _make_runlog(tmp_path, final_loss=1.0)
    rc = train_report.main([path, "--strict"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and rec["ok"] is False
    assert rec["strict"]["final_loss_vs_reference"] is False
    # The rest of the evidence is intact — only the curve regressed.
    assert rec["strict"]["train_step_spans"] is True
    assert rec["strict"]["step_time_histogram"] is True


def test_train_report_empty_runlog_is_an_error(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import train_report

    empty = tmp_path / "runlog-train-empty.jsonl"
    empty.write_text("")
    rc = train_report.main([str(empty)])
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and "error" in rec


# -- bench/gate contracts --------------------------------------------------


def test_bench_trend_passes_train_fields_through(tmp_path, capsys):
    """tools/bench_trend.py forwards the train-bench shape fields: a
    train_step_pairs_per_s trend is only comparable within one device
    count / batch / remat-accum configuration."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_trend

    rec = {"n": 1, "cmd": "bench_train", "rc": 0,
           "parsed": {"metric": "train_step_pairs_per_s",
                      "value": 6.4, "unit": "pairs/s",
                      "step_ms": 312.5, "devices": 4, "batch": 16,
                      "accum": 2, "remat_policy": "dots"}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(rec, fh)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["metric"] == "train_step_pairs_per_s"
    assert report["step_ms"] == 312.5
    assert report["devices"] == 4 and report["batch"] == 16
    assert report["accum"] == 2 and report["remat_policy"] == "dots"


def test_ci_gate_train_smoke_skipped_not_green(capsys):
    """ci_gate without --with-train-smoke records the check as
    {"skipped": true, "optional": true} — never silently green."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import ci_gate

    assert "train_smoke" in ci_gate.OPTIONAL_CHECKS
    rc = ci_gate.main(["--skip", "tier1", "--skip", "lint",
                       "--skip", "bench_trend"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert rec["checks"]["train_smoke"] == {
        "skipped": True, "optional": True}


def test_bench_train_error_path_one_json_line():
    """bench_train.py's early-error paths keep the one-JSON-line
    stdout contract: a bad --accum/--batch shape prints exactly one
    parseable {"error": ...} line and exits 2."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_train.py"),
         "--batch", "4", "--accum", "3", "--backbone", "vgg",
         "--image-size", "48", "--iters", "1"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert res.returncode == 2, res.stderr[-1000:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "train_step_pairs_per_s"
    assert "--accum" in rec["error"]
